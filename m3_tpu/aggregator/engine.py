"""Aggregator engine: host control plane over the device arenas.

Re-design of the reference's object-per-metric engine
(``src/aggregator/aggregator/aggregator.go:263`` AddUntimed →
``shard.go:171`` → ``map.go:149`` find-or-create Entry →
``entry.go:264`` resolve metadata → per-(id, aggregation key) element →
``generic_elem.go:181`` AddUnion; flush via ``list.go:289``
baseMetricList.Flush → ``generic_elem.go:271`` Consume).

Here the per-shard state is three fixed-capacity device arenas (counter /
gauge / timer) per storage-policy resolution.  The host owns:

* ``MetricMap`` — metric ID bytes → (type, slot, aggregation bitmask),
  the analogue of map.go's entry map + shard_insert_queue slot creation;
* window bookkeeping — ring index = (aligned_nanos // resolution) % W,
  the analogue of generic_elem's startAligned-keyed values list;
* ``consume`` — drains every window whose end <= target, computes the
  (C, lanes) output matrix on device, masks each slot's requested
  aggregation types, and emits (id, type, time, value) tuples through a
  flush handler, the analogue of Consume + flushLocalFn.

Batched adds take numpy arrays; ID→slot resolution is vectorized through
a Python dict once per unique ID (new series only), then cached in the
caller-visible ``resolve`` arrays — mirroring how the reference amortizes
entry lookup with rate-limited entry creation.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Callable, Dict, List, Sequence

import jax.numpy as jnp
import numpy as np

from m3_tpu.aggregator.arena import make_arenas
from m3_tpu.core.hash import shard_for
from m3_tpu.metrics.aggregation import AggregationID, AggregationType
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.transformation import TransformationType
from m3_tpu.metrics.types import MetricType

# Transform tails a MetricList can execute at consume.  RESET
# (unary_multi.go transformReset: the datapoint unchanged plus a forced
# zero half a resolution later) emits a SECOND FlushedMetric per consume
# carrying the zero rows at ts + max(resolution//2, 1) — multi-datapoint
# emission, the HA-failover counter-reset signal for PromQL rate().
_SUPPORTED_TAIL = frozenset({
    TransformationType.ABSOLUTE, TransformationType.ADD,
    TransformationType.PER_SECOND, TransformationType.INCREASE,
    TransformationType.RESET,
})


@dataclasses.dataclass(frozen=True)
class ForwardSpec:
    """Next pipeline stage for a forwarded metric (reference
    forwarded_writer.go:186 Register / aggregator.go:395 AddForwarded):
    the resolved next-stage output ID, its aggregation, and whatever
    ops remain after it."""

    id: bytes
    aggregation_id: "AggregationID"
    tail: tuple  # ops after this rollup (transforms / applied rollups)


@dataclasses.dataclass(frozen=True)
class AggregatorOptions:
    """Sizing knobs (reference aggregator/options.go, collapsed to the
    arena geometry that matters on device)."""

    capacity: int = 1 << 20  # metric slots per type per shard
    num_windows: int = 2  # ring of open resolution windows
    timer_sample_capacity: int = 1 << 24
    quantiles: tuple = (0.5, 0.95, 0.99)
    # Timer drain sort mode: packed32 sorts ONE i64 (slot<<32 |
    # orderable-f32) key instead of the (i32, f64) lex pair — ~4x
    # faster drain on CPU, avoids software-emulated f64 compares on
    # TPU; quantile/min/max lanes carry f32 precision (~1e-7 rel on
    # f32's finite normal range — values beyond ±3.4e38 saturate,
    # below ~1.2e-38 flush; see arena.timer_consume), moments stay
    # f64-exact.
    timer_packed32: bool = False
    # Arena layout: "packed" (sort/segment formulation + adaptive-width
    # counters, aggregator/packed.py), "f64" (the scatter arenas — the
    # bit-exact parity oracle), or None = the M3_ARENA_LAYOUT seam
    # (auto -> packed).  Packed counter stats are exact; gauge
    # sum/sum_sq and timer value lanes carry the documented <=1e-6
    # envelopes (see arena.resolved_arena_layout).
    layout: str | None = None
    storage_policies: tuple = (StoragePolicy.parse("10s:2d"),)
    # New-metric creation rate cap, entries/sec across the aggregator
    # (reference entry.go rate limits; 0 = unlimited).  Samples whose
    # series creation exceeds it are dropped with a typed counter —
    # churn degrades gracefully instead of filling the slot maps.
    new_series_limit_per_sec: float = 0.0


@dataclasses.dataclass
class FlushedMetric:
    """One flushed aggregate batch: parallel arrays."""

    policy: StoragePolicy
    timestamp_nanos: int
    slots: np.ndarray  # int32
    types: np.ndarray  # int8 AggregationType values
    values: np.ndarray  # float64
    metric_type: MetricType = MetricType.GAUGE  # which map owns `slots`


FlushHandler = Callable[["MetricList", FlushedMetric], None]


class MetricMap:
    """(ID, aggregation key) → slot allocator for one metric type.

    The reference keys aggregation elements by (id, aggregation key)
    (map.go:149 entry map; entry.go:264 one elem per key), so the same
    metric ID written with two different aggregation sets produces both
    sets of outputs — mirrored here by keying slots on (id, mask).

    Slots are dense int32; freed slots recycle through a free list (the
    reference GCs idle entries via lastAccess; expiry here drains the
    arena's device-side last_at column through MetricList.expire).
    """

    def __init__(self, capacity: int, use_native: bool | None = None,
                 limiter=None):
        self.capacity = capacity
        # Optional shared NewSeriesLimiter (storage/limits.py): entry
        # creations past the rate resolve to slot -1; callers drop
        # those samples and count them (reference entry.go
        # errWriteNewMetricRateLimitExceeded).
        self.limiter = limiter
        self._slots: Dict[tuple, int] = {}
        self._ids: List[bytes | None] = []
        self._free: List[int] = []
        self.agg_mask = np.zeros(capacity, np.uint64)
        # Per-slot pipeline-tail signature (0 = no tail).  The reference
        # keys a separate element per FULL aggregation key including the
        # pipeline (map.go:149); this engine keys slots on (id, mask),
        # so a tail/no-tail or tail/other-tail collision on one slot
        # would silently mis-aggregate — resolve() rejects it loudly
        # instead (MetricList.add_batch's loud-failure contract).
        self.tail_sig = np.zeros(capacity, np.int32)
        # Native batch resolver (native/idmap.cc): the per-sample dict
        # probe is the engine's host bottleneck at 1M-series scale
        # (reference map.go:149 is a sharded concurrent map for the
        # same reason).  The Python path remains as oracle + fallback.
        self._native = None
        if use_native is not False:
            try:
                from m3_tpu.native.idmap import NativeIdMap, available

                if available():
                    self._native = NativeIdMap(capacity)
                    self._native_ids: List[bytes | None] = [None] * capacity
                elif use_native is True:
                    raise RuntimeError("native idmap unavailable")
            except Exception:
                # Opportunistic mode (None) degrades silently to the
                # Python path; an EXPLICIT use_native=True must not —
                # silent 5x-slower fallback would corrupt perf numbers.
                if use_native is True:
                    raise
                self._native = None

    def __len__(self) -> int:
        return (len(self._native) if self._native is not None
                else len(self._slots))

    def id_of(self, slot: int) -> bytes | None:
        if self._native is not None:
            return (self._native_ids[slot]
                    if slot < len(self._native_ids) else None)
        return self._ids[slot] if slot < len(self._ids) else None

    def resolve(self, ids: Sequence[bytes], agg_id: AggregationID,
                mt: MetricType, tail_sig: int = 0) -> np.ndarray:
        """Find-or-create slots for a batch of IDs.  ``tail_sig`` is the
        MetricList-assigned signature of the batch's pipeline tail (0 =
        none); a resolve that lands on a live slot carrying a DIFFERENT
        signature raises rather than letting two rules with different
        tails (or one with, one without) silently share an aggregate."""
        mask = self._mask_for(agg_id, mt)
        if self._native is not None:
            try:
                slots, new_pos = self._native.resolve(ids, mask)
            except RuntimeError as e:
                raise RuntimeError(
                    f"metric map capacity {self.capacity} exhausted"
                ) from e
            if len(new_pos) and self.limiter is not None:
                # The native resolver allocated eagerly; release the
                # over-budget creations and mark EVERY occurrence of a
                # released id rejected (an in-batch duplicate resolved
                # to the now-freed slot and must not write into it).
                granted = self.limiter.acquire_up_to(len(new_pos))
                released = set()
                for i in new_pos[granted:]:
                    self._native.release(ids[i], mask)
                    released.add(ids[i])
                if released:
                    for j in range(len(ids)):
                        if ids[j] in released:
                            slots[j] = -1
                new_pos = new_pos[:granted]
            for i in new_pos:
                s = int(slots[i])
                self._native_ids[s] = ids[i]
                self.agg_mask[s] = np.uint64(mask)
                self.tail_sig[s] = tail_sig
            self._check_tails(ids, slots, tail_sig)
            return slots
        slots = np.empty(len(ids), np.int32)
        get = self._slots.get
        missing: List[int] = []
        for i, mid in enumerate(ids):
            s = get((mid, mask))
            if s is None:
                missing.append(i)
                slots[i] = -1
            else:
                slots[i] = s
        # Charge per CREATION, not per occurrence (in-batch duplicates
        # of one new id take a single token).
        n_new = len({ids[i] for i in missing})
        budget = (n_new if self.limiter is None
                  else self.limiter.acquire_up_to(n_new))
        allocated: List[int] = []
        try:
            for i in missing:
                mid = ids[i]
                s = self._slots.get((mid, mask))
                if s is None:
                    if budget <= 0:
                        continue  # stays -1: rejected creation
                    budget -= 1
                    s = self._allocate(mid, mask)
                    self.agg_mask[s] = np.uint64(mask)
                    self.tail_sig[s] = tail_sig
                    allocated.append(s)
                slots[i] = s
        except RuntimeError:
            # All-or-nothing like the native resolver: roll this batch's
            # allocations back so both paths leave identical state after
            # a capacity-exhausted resolve.
            for s in allocated:
                self.release(s)
            raise
        self._check_tails(ids, slots, tail_sig)
        return slots

    def _check_tails(self, ids, slots: np.ndarray, tail_sig: int) -> None:
        valid = slots >= 0
        bad = np.nonzero(valid & (self.tail_sig[slots] != np.int32(tail_sig)))[0]
        if bad.size:
            i = int(bad[0])
            raise ValueError(
                f"metric {ids[i]!r} resolves to a slot whose pipeline "
                f"tail signature {int(self.tail_sig[slots[i]])} differs "
                f"from this batch's {tail_sig}; two rules producing the "
                "same output ID need distinct rollup IDs per tail")

    def _mask_for(self, agg_id: AggregationID, mt: MetricType) -> int:
        """Compressed mask of the requested types that are valid for this
        metric type (the reference validates per type: aggregation
        type.go IsValidForCounter/Timer/Gauge)."""
        m = 0
        for t in agg_id.types_for(mt):
            if t.is_valid_for(mt):
                m |= 1 << int(t)
        return m

    def _allocate(self, mid: bytes, mask: int) -> int:
        if self._free:
            s = self._free.pop()
            self._ids[s] = mid
        else:
            s = len(self._ids)
            if s >= self.capacity:
                raise RuntimeError(
                    f"metric map capacity {self.capacity} exhausted"
                )
            self._ids.append(mid)
        self._slots[(mid, mask)] = s
        return s

    def to_entries(self) -> dict:
        """Checkpoint form: exact (slot, id, mask, tail_sig) rows plus
        the python path's free list (aggregator/checkpoint.py)."""
        entries = []
        n = (len(self._native_ids) if self._native is not None
             else len(self._ids))
        for s in range(n):
            mid = self.id_of(s)
            if mid is not None:
                entries.append((s, mid, int(self.agg_mask[s]),
                                int(self.tail_sig[s])))
        free = [] if self._native is not None else list(self._free)
        return {"entries": entries, "free": free, "size": n}

    def load_entries(self, saved: dict) -> None:
        """Rebuild EXACT slot→id assignment from a checkpoint into this
        (fresh) map.  Python path: direct structure install, free list
        preserved — post-restore allocation order matches the
        uninterrupted process bit-for-bit.  Native path: ids insert in
        slot order with hole placeholders released afterwards; a
        resolver that does not assign sequentially fails loudly (the
        restore aborts typed rather than silently remapping slots)."""
        entries = sorted(saved["entries"])
        if self._native is not None:
            nxt = 0
            holes = []
            for slot, mid, mask, tail_sig in entries:
                while nxt < slot:
                    dummy = b"\x00ckpt-hole-%d" % nxt
                    s, _ = self._native.resolve([dummy], 0)
                    if int(s[0]) != nxt:
                        raise ValueError(
                            "native idmap did not allocate sequentially "
                            "during checkpoint restore")
                    holes.append(dummy)
                    nxt += 1
                s, _ = self._native.resolve([mid], mask)
                if int(s[0]) != slot:
                    raise ValueError(
                        f"native idmap restored {mid!r} at slot "
                        f"{int(s[0])}, checkpoint says {slot}")
                self._native_ids[slot] = mid
                self.agg_mask[slot] = np.uint64(mask)
                self.tail_sig[slot] = tail_sig
                nxt = slot + 1
            for dummy in holes:
                self._native.release(dummy, 0)
            return
        size = saved.get("size", (entries[-1][0] + 1 if entries else 0))
        self._ids = [None] * size
        self._slots = {}
        self.agg_mask[:] = 0
        self.tail_sig[:] = 0
        for slot, mid, mask, tail_sig in entries:
            self._ids[slot] = mid
            self._slots[(mid, mask)] = slot
            self.agg_mask[slot] = np.uint64(mask)
            self.tail_sig[slot] = tail_sig
        self._free = list(saved.get("free", ()))
        # A native-path checkpoint reports size == len(_native_ids)
        # (the preallocated capacity) with an EMPTY free list — the
        # native resolver keeps its own.  Restoring it here must
        # rediscover the holes or _allocate is permanently exhausted
        # for new series.  Python-path checkpoints carry free == holes
        # exactly, so this adds nothing and allocation order stays
        # bit-for-bit.
        known = set(self._free)
        known.update(slot for slot, _, _, _ in entries)
        self._free.extend(
            s for s in range(size - 1, -1, -1) if s not in known)

    def release(self, slot: int) -> None:
        if self._native is not None:
            mid = self._native_ids[slot] if slot < len(self._native_ids) else None
            if mid is None:
                return
            self._native.release(mid, int(self.agg_mask[slot]))
            self._native_ids[slot] = None
            self.agg_mask[slot] = 0
            self.tail_sig[slot] = 0
            return
        mid = self._ids[slot]
        if mid is None:
            return
        mask = int(self.agg_mask[slot])
        self._slots.pop((mid, mask), None)
        self._ids[slot] = None
        self.agg_mask[slot] = 0
        self.tail_sig[slot] = 0
        self._free.append(slot)


class MetricList:
    """All state for one (shard, storage policy) pair: three arenas plus
    window bookkeeping (reference list.go baseMetricList keyed by
    (resolution, flushOffset))."""

    def __init__(self, policy: StoragePolicy, opts: AggregatorOptions,
                 new_series_limiter=None):
        self.policy = policy
        self.opts = opts
        self.resolution = policy.resolution.window_nanos
        W, C = opts.num_windows, opts.capacity
        if new_series_limiter is None and opts.new_series_limit_per_sec > 0:
            from m3_tpu.storage.limits import NewSeriesLimiter

            new_series_limiter = NewSeriesLimiter(
                opts.new_series_limit_per_sec)
        self.new_series_limiter = new_series_limiter
        self.new_series_rejected = 0
        self.counters, self.gauges, self.timers = make_arenas(
            W, C, opts.timer_sample_capacity, opts.quantiles,
            timer_packed32=opts.timer_packed32, layout=opts.layout)
        self.maps = {
            MetricType.COUNTER: MetricMap(C, limiter=new_series_limiter),
            MetricType.GAUGE: MetricMap(C, limiter=new_series_limiter),
            MetricType.TIMER: MetricMap(C, limiter=new_series_limiter),
        }
        # Earliest window (aligned nanos) not yet consumed.  Windows in
        # [consumed_until, +W*resolution) are open; later ones rejected
        # (bufferFuture) and earlier dropped (bufferPast) — the
        # reference's too-early/too-late errors (entry.go).
        self.consumed_until: int | None = None
        self.drops = 0
        self.timed_rejects = {"too_early": 0, "too_far_future": 0}
        self.forward_errors = 0
        # Rollup pipeline TAILS: (metric type, slot) -> transformation
        # tuple, applied to that slot's window aggregates at consume
        # with per-(slot, aggregation type, op) previous-value state
        # (reference generic_elem.go:114 prevValues, :271-380 Consume).
        self._pipelines: Dict[tuple, tuple] = {}
        self._tf_state: Dict[tuple, tuple] = {}
        # tail ops tuple -> small stable signature for MetricMap's
        # per-slot conflict check (0 is reserved for "no tail").
        self._tail_sigs: Dict[tuple, int] = {}
        # Stage outputs awaiting delivery to their next-stage owner:
        # (ForwardSpec, value, window-end ts) tuples buffered at consume
        # and drained by the owning Aggregator/Downsampler AFTER the
        # consume pass (no re-entrant ingest mid-drain).
        self._forward_buffer: List[tuple] = []

    def _arena(self, mt: MetricType):
        return {
            MetricType.COUNTER: self.counters,
            MetricType.GAUGE: self.gauges,
            MetricType.TIMER: self.timers,
        }[mt]

    def add_batch(
        self,
        mt: MetricType,
        ids: Sequence[bytes],
        values: np.ndarray,
        times: np.ndarray,
        agg_id: AggregationID = AggregationID.DEFAULT,
        pipeline=None,
    ) -> None:
        """Resolve + ingest.  ``pipeline`` (rules.py RollupResult
        .pipeline, the ops after the rule's rollup op) attaches a
        transform tail to the batch's output slots.

        Loud-failure contract (round-3 VERDICT weak #4: tails were
        silently dropped, so `rollup(...).perSecond()` aggregated
        wrong): unsupported tail ops raise here, and MetricMap.resolve
        rejects a batch whose tail differs from what its slot already
        carries — including tail vs NO tail, either order — because the
        reference keys a separate element per full aggregation key
        (map.go:149) where this engine keys slots on (id, mask); two
        rules matching one output ID with different tails must be
        rewritten as two rollup IDs."""
        sig, key_ops = 0, ()
        if pipeline is not None and not pipeline.is_empty():
            key_ops = self._validate_tail(pipeline)
            if any(isinstance(op, ForwardSpec) for op in key_ops):
                mask = self.maps[mt]._mask_for(agg_id, mt)
                if bin(mask).count("1") != 1:
                    raise ValueError(
                        "a pipeline stage that forwards to a next rollup "
                        "must aggregate exactly ONE type (got mask "
                        f"{mask:#x}): multiple aggregate kinds would "
                        "conflate into one next-stage series")
            sig = self._tail_sigs.setdefault(key_ops,
                                             len(self._tail_sigs) + 1)
        slots = self.maps[mt].resolve(ids, agg_id, mt, tail_sig=sig)
        if sig:
            for s in np.unique(slots).tolist():
                if s >= 0:
                    self._pipelines[(mt, int(s))] = key_ops
        rej = slots < 0
        acc = None
        if rej.any():
            # Rate-limited series creations: drop those samples with a
            # typed counter (entry.go errWriteNewMetricRateLimitExceeded).
            self.new_series_rejected += int(rej.sum())
            acc = ~rej
            slots = slots[acc]
            values = np.asarray(values)[acc]
            times = np.asarray(times)[acc]
        self.add_batch_slots(mt, slots, values, times)
        return acc  # None = everything accepted

    @staticmethod
    def _validate_tail(pipeline) -> tuple:
        """Parse a pipeline tail into (transform types...,
        ForwardSpec?) — transforms up to the first APPLIED rollup op
        become this stage's consume-time transforms; the rollup op and
        everything after it become the forward target (validated when
        the next stage registers them)."""
        from m3_tpu.metrics.pipeline import (
            AppliedRollupOp, RollupOp, TransformationOp)

        tail = []
        ops = list(pipeline.ops)
        for i, op in enumerate(ops):
            if isinstance(op, TransformationOp):
                if op.type not in _SUPPORTED_TAIL:
                    raise ValueError(
                        f"unsupported pipeline transformation {op.type!r} "
                        "in rollup tail (see metrics/transformation.py)")
                if tail and tail[-1] == TransformationType.RESET:
                    # The forced zero is emitted raw — it never passes
                    # through later transforms, so RESET anywhere but
                    # the end of its stage would mis-emit.  (RESET
                    # directly before a rollup op is allowed: the extra
                    # datapoint simply never forwards, matching the
                    # reference's HasRollup branch.)
                    raise ValueError(
                        "RESET must be the last transformation of its "
                        "pipeline stage (its forced zero bypasses "
                        "subsequent transforms)")
                tail.append(op.type)
            elif isinstance(op, AppliedRollupOp):
                # Validate the WHOLE remaining chain now: a bad op deep
                # in a multi-stage tail must fail at registration (the
                # user-facing ingest call), never mid-consume where it
                # would wedge flushing for every metric.
                from m3_tpu.metrics.pipeline import Pipeline as _P

                MetricList._validate_tail(_P(tuple(ops[i + 1:])))
                tail.append(ForwardSpec(op.id, op.aggregation_id,
                                        tuple(ops[i + 1:])))
                break
            elif isinstance(op, RollupOp):
                raise ValueError(
                    "unapplied RollupOp in tail: rules must resolve "
                    "downstream rollups to AppliedRollupOp (rules.py "
                    "forward_match) before registration")
            else:
                raise ValueError(f"unsupported pipeline op {op!r} in tail")
        return tuple(tail)

    def _route_windows(self, times: np.ndarray):
        """Window-ring routing for a batch of timestamps.  Returns
        (windows int32 with the drop sentinel W for out-of-range,
        too_early mask, too_future mask)."""
        r = self.resolution
        W = self.opts.num_windows
        aligned = (times // r) * r
        if self.consumed_until is None:
            self.consumed_until = int(aligned.min())
        base = self.consumed_until
        offset = (aligned - base) // r
        too_early = offset < 0
        too_future = offset >= W
        in_range = ~(too_early | too_future)
        windows = np.where(in_range, (aligned // r) % W, W).astype(np.int32)
        return windows, too_early, too_future

    def add_batch_slots(
        self,
        mt: MetricType,
        slots: np.ndarray,
        values: np.ndarray,
        times: np.ndarray,
    ) -> None:
        """Pure device path: slots already resolved (the hot loop)."""
        if len(slots) == 0:  # e.g. a batch fully rejected by rate limits
            return
        windows, too_early, too_future = self._route_windows(times)
        self.drops += int(too_early.sum()) + int(too_future.sum())
        self._arena(mt).ingest(
            jnp.asarray(windows), jnp.asarray(slots), jnp.asarray(values), jnp.asarray(times)
        )

    def seed_windows(self, now_nanos: int) -> None:
        """Anchor an un-seeded window ring to the caller's clock: the
        ring becomes [now-(W-1)r, now+r) — (W-1) windows of bufferPast,
        one of bufferFuture, the reference's now±buffer validation for
        timed writes (entry.go addTimed).  No-op once seeded."""
        if self.consumed_until is None:
            r = self.resolution
            W = self.opts.num_windows
            self.consumed_until = (now_nanos // r) * r - (W - 1) * r

    def timed_check(self, times: np.ndarray):
        """Non-mutating window validation: (too_early, too_future)
        masks for a timed batch.  An un-seeded list accepts anything
        (ingest will seed from the batch)."""
        if self.consumed_until is None:
            z = np.zeros(len(times), bool)
            return z, z
        r = self.resolution
        W = self.opts.num_windows
        offset = ((times // r) * r - self.consumed_until) // r
        return offset < 0, offset >= W

    def add_timed_batch(
        self,
        mt: MetricType,
        ids: Sequence[bytes],
        values: np.ndarray,
        times: np.ndarray,
        agg_id: AggregationID = AggregationID.DEFAULT,
        now_nanos: int | None = None,
    ) -> np.ndarray:
        """Timed ingestion (reference aggregator.go:77 AddTimed →
        shard.AddTimed → entry.go addTimed): each sample lands in the
        window its OWN timestamp selects, and out-of-range samples are
        REJECTED back to the caller — errTooFarInThePast /
        errTooFarInTheFuture in the reference — instead of the untimed
        path's fire-and-forget drop counter.  Returns the accepted
        mask; per-reason counts accumulate in ``timed_rejects``.

        ``now_nanos`` anchors a FRESH list's window ring to the clock
        (see seed_windows) — without it the first batch's minimum
        timestamp seeds the ring, so one bogus ancient timestamp would
        anchor it in the past and reject everything after it as
        too-far-future.  Servers pass their wall clock."""
        if now_nanos is not None:
            self.seed_windows(now_nanos)
        values = np.asarray(values, np.float64)
        times = np.asarray(times, np.int64)
        # Validate windows BEFORE resolving: an out-of-window flood must
        # not allocate slots or consume new-series limiter budget — the
        # churn the limit exists to stop (reference entry.go addTimed
        # validates against now±buffer before writing).
        windows, too_early, too_future = self._route_windows(times)
        self.timed_rejects["too_early"] += int(too_early.sum())
        self.timed_rejects["too_far_future"] += int(too_future.sum())
        accepted = ~(too_early | too_future)
        sel = np.nonzero(accepted)[0]
        if sel.size == 0:
            return accepted
        slots = self.maps[mt].resolve([ids[i] for i in sel], agg_id, mt)
        rej = slots < 0
        if rej.any():
            # Rate-limited creations reject like window violations do.
            # Window-rejected samples never reached the limiter, so no
            # rejection is double-counted across the two counters.
            self.new_series_rejected += int(rej.sum())
            accepted[sel[rej]] = False
            sel = sel[~rej]
            slots = slots[~rej]
            if sel.size == 0:
                return accepted
        self._arena(mt).ingest(
            jnp.asarray(windows[sel]), jnp.asarray(slots),
            jnp.asarray(values[sel]), jnp.asarray(times[sel])
        )
        return accepted

    def open_windows(self, now_nanos: int) -> List[int]:
        """Closed windows that can actually hold data.

        Ingest only accepts timestamps in
        [consumed_until, consumed_until + W*resolution) — so after an
        idle gap only the first W windows past consumed_until need a
        device drain; the rest are provably empty and are skipped by
        advancing consumed_until directly (avoids one (C, lanes)
        device->host transfer per empty elapsed window).
        """
        if self.consumed_until is None:
            return []
        r = self.resolution
        out = []
        t = self.consumed_until
        while t + r <= now_nanos and len(out) < self.opts.num_windows:
            out.append(t)
            t += r
        return out

    def consume(self, target_nanos: int, flush_handler: FlushHandler | None = None,
                forward_sink=None):
        """Drain every closed window (reference generic_elem.go:271
        Consume: windows with start+resolution <= target).

        Forwarded stage outputs are delivered PER WINDOW, immediately
        after the window that produced them drains: a stage-1 aggregate
        of window t carries timestamp t+r, which is exactly the window
        the ring just opened — so when one consume pass drains several
        windows, each hop lands one window later instead of falling
        behind the advancing watermark and being dropped.
        ``forward_sink`` (the Aggregator's shard router) receives the
        entries; by default they re-ingest into this list — the
        downsampler's same-list multi-stage case."""
        results = []
        deliver = forward_sink if forward_sink is not None else self.add_forwarded
        # Loop until no closed window remains: per-window forward
        # delivery can put data into the window right past the ring
        # (the last drained window's outputs), so after a long idle gap
        # the ring must keep draining until the forward chain settles —
        # jumping the watermark immediately would strand those entries
        # in never-drained ring rows.
        while True:
            starts = self.open_windows(target_nanos)
            if not starts:
                break
            delivered = False
            for start in starts:
                w = (start // self.resolution) % self.opts.num_windows
                ts = start + self.resolution  # end-of-window timestamp
                for mt in (MetricType.COUNTER, MetricType.GAUGE,
                           MetricType.TIMER):
                    arena = self._arena(mt)
                    lanes, counts = arena.consume(w)
                    for flushed in self._emit(mt, arena, lanes, counts, ts):
                        results.append(flushed)
                        if flush_handler is not None:
                            flush_handler(self, flushed)
                    arena.reset_window(w)
                self.consumed_until = start + self.resolution
                if self._forward_buffer:
                    buf = self._forward_buffer
                    self._forward_buffer = []
                    delivered = True
                    deliver(buf)
            if not delivered:
                break
        if self.consumed_until is not None:
            r = self.resolution
            floor_target = (target_nanos // r) * r
            if floor_target > self.consumed_until:
                # Idle gap beyond the window ring: skip empty windows
                # (ingest only ever accepted [consumed_until, +W*r), all
                # drained above, and the settle loop handled forwards).
                self.consumed_until = floor_target
        return results

    def add_forwarded(self, entries: List[tuple]) -> None:
        """Ingest forwarded stage outputs (reference aggregator.go:395
        AddForwarded): each (ForwardSpec, value, ts) lands under the
        spec's output ID and aggregation with any remaining ops as this
        stage's tail.  Carried on the gauge arena — a forwarded partial
        aggregate is a plain float the next stage re-aggregates.

        Arrivals outside this list's open ring (a cross-shard hop whose
        destination is ahead of or behind the source this pass) clamp
        into the nearest open window rather than dropping — the role of
        the reference's maxAllowedForwardingDelay tolerance: bounded
        timing skew, never silent loss.  A tail-signature conflict
        (two rules forwarding DIFFERENT remaining tails to one output
        ID) drops that group with ``forward_errors`` counted: raising
        here would wedge the whole consume pass for unrelated
        metrics."""
        from m3_tpu.metrics.pipeline import Pipeline

        groups: Dict[tuple, List[tuple]] = {}
        r = self.resolution
        hi = (None if self.consumed_until is None else
              self.consumed_until + (self.opts.num_windows - 1) * r)
        for spec, v, ts in entries:
            if self.consumed_until is not None:
                ts = min(max(ts, self.consumed_until), hi)
            groups.setdefault((spec.aggregation_id, spec.tail), []).append(
                (spec.id, v, ts))
        for (agg_id, tail), items in groups.items():
            try:
                self.add_batch(
                    MetricType.GAUGE,
                    [mid for mid, _, _ in items],
                    np.asarray([v for _, v, _ in items], np.float64),
                    np.asarray([ts for _, _, ts in items], np.int64),
                    agg_id,
                    pipeline=Pipeline(tail) if tail else None,
                )
            except ValueError:
                self.forward_errors += len(items)

    def expire(self, now_nanos: int, ttl_nanos: int) -> int:
        """Release slots idle for longer than ttl (the reference GCs
        entries via lastAccess + entryTTL — map.go deleteExpired /
        entry.go ShouldExpire).  Reads the device last_at column, frees
        matching slots in every map, and clears all of each freed slot's
        arena state (last_at + every window-ring row + buffered samples),
        so a recycled slot cannot inherit the previous occupant's
        un-drained aggregates."""
        released = 0
        for mt in (MetricType.COUNTER, MetricType.GAUGE, MetricType.TIMER):
            arena = self._arena(mt)
            last_at = np.asarray(arena.state.last_at)
            stale = np.nonzero((last_at > 0) & (last_at < now_nanos - ttl_nanos))[0]
            if stale.size == 0:
                continue
            m = self.maps[mt]
            for s in stale:
                m.release(int(s))
            arena.clear_slots(stale.astype(np.int32))
            released += stale.size
            if self._pipelines or self._tf_state:
                # A recycled slot must not inherit the previous
                # occupant's transform tail or prev-value state.
                dead = set(stale.tolist())
                for k in [k for k in self._pipelines
                          if k[0] == mt and k[1] in dead]:
                    del self._pipelines[k]
                for k in [k for k in self._tf_state
                          if k[0] == mt and k[1] in dead]:
                    del self._tf_state[k]
        return released

    def _emit(self, mt, arena, lanes, counts, ts) -> List[FlushedMetric]:
        """Returns 0, 1, or 2 FlushedMetrics for one drained window:
        the window's aggregates, plus (when some slot's tail carries
        RESET) the forced-zero batch half a resolution later."""
        lanes = np.asarray(lanes)
        counts = np.asarray(counts)
        active = np.nonzero(counts > 0)[0]
        if active.size == 0:
            return []
        mask = self.maps[mt].agg_mask[active]
        out_slots: List[np.ndarray] = []
        out_types: List[np.ndarray] = []
        out_vals: List[np.ndarray] = []
        for t in AggregationType:
            if not t.is_valid():
                continue
            lane_i = arena.lane_for_type(t)
            if lane_i is None:
                continue
            want = (mask >> np.uint64(int(t))) & np.uint64(1)
            sel = np.nonzero(want.astype(bool))[0]
            if sel.size == 0:
                continue
            rows = active[sel]
            out_slots.append(rows.astype(np.int32))
            out_types.append(np.full(rows.size, int(t), np.int8))
            out_vals.append(lanes[rows, lane_i])
        if not out_slots:
            return []
        flushed = FlushedMetric(
            policy=self.policy,
            timestamp_nanos=ts,
            slots=np.concatenate(out_slots),
            types=np.concatenate(out_types),
            values=np.concatenate(out_vals),
            metric_type=mt,
        )
        if self._pipelines:
            return self._apply_tails(flushed)
        return [flushed]

    def _apply_tails(self, fm: FlushedMetric) -> List[FlushedMetric]:
        """Run each pipeline-carrying slot's transform tail over its
        window aggregates (reference generic_elem.go:271-380: Consume
        applies the parsed pipeline with prevValues state before
        flushing).  Rows whose binary transform has no usable previous
        value (first window, time going backwards, negative delta for
        monotonic transforms) are dropped from the flush — the
        reference emits nothing for empty datapoints.

        RESET rows additionally schedule a forced zero half a
        resolution after the window timestamp (unary_multi.go
        transformReset; generic_elem.go flushes the extra datapoint
        only on the local path — a forwarded row drops it, matching
        the reference's HasRollup branch)."""
        mt, ts = fm.metric_type, fm.timestamp_nanos
        piped = np.fromiter(
            (s for (m, s) in self._pipelines if m == mt), np.int64)
        if piped.size == 0:
            return [fm]
        hits = np.nonzero(np.isin(fm.slots, piped))[0]
        if hits.size == 0:
            return [fm]
        values = fm.values.copy()
        keep = np.ones(len(values), bool)
        reset_rows: List[int] = []
        state = self._tf_state
        for i in hits:
            slot, t_ = fm.slots[i], fm.types[i]
            tail = self._pipelines[(mt, int(slot))]
            v = float(values[i])
            want_reset = False
            for k, tt in enumerate(tail):
                skey = (mt, int(slot), int(t_), k)
                if isinstance(tt, ForwardSpec):
                    # Multi-stage pipeline: this stage's (transformed)
                    # window aggregate forwards to the next stage's
                    # owner instead of flushing locally (reference
                    # generic_elem Consume -> flushForwardedFn).  The
                    # extra RESET datapoint never forwards.
                    self._forward_buffer.append((tt, v, ts))
                    keep[i] = False
                    break
                if tt == TransformationType.RESET:
                    # Value passes through unchanged; the forced zero
                    # flushes as a second batch (see below).
                    want_reset = True
                elif tt == TransformationType.ABSOLUTE:
                    v = abs(v)
                elif tt == TransformationType.ADD:
                    run = state.get(skey, (0.0,))[0]
                    if not np.isnan(v):
                        run += v
                    state[skey] = (run,)
                    v = run
                else:  # PER_SECOND / INCREASE (binary, one step back)
                    # The first window has no previous value: INCREASE
                    # treats it as (NaN @ t=0) — NaN prev counts as 0,
                    # so the whole first aggregate emits (the repo's
                    # scalar oracle transformation.increase and the
                    # reference binary.go agree); PER_SECOND cannot
                    # rate against nothing and drops it.
                    prev = state.get(skey)
                    state[skey] = (v, ts)
                    if prev is None:
                        if tt == TransformationType.PER_SECOND:
                            keep[i] = False
                            break
                        prev = (np.nan, 0)
                    pv, pt = prev
                    if pt >= ts or np.isnan(v):
                        keep[i] = False
                        break
                    if tt == TransformationType.PER_SECOND:
                        if np.isnan(pv) or v - pv < 0:
                            keep[i] = False
                            break
                        v = (v - pv) * 1e9 / (ts - pt)
                    else:  # INCREASE: NaN prev treated as 0
                        pv = 0.0 if np.isnan(pv) else pv
                        if v - pv < 0:
                            keep[i] = False
                            break
                        v = v - pv
            values[i] = v
            if want_reset and keep[i]:
                # Dropped rows (forwarded / empty datapoint) emit no
                # extra zero — the reference's continue skips both.
                reset_rows.append(i)
        out: List[FlushedMetric] = []
        if not keep.all():
            if keep.any():
                out.append(FlushedMetric(
                    policy=fm.policy, timestamp_nanos=ts,
                    slots=fm.slots[keep], types=fm.types[keep],
                    values=values[keep], metric_type=mt,
                ))
        else:
            fm.values = values
            out.append(fm)
        if reset_rows:
            rows = np.asarray(reset_rows)
            out.append(FlushedMetric(
                policy=fm.policy,
                timestamp_nanos=ts + max(self.resolution // 2, 1),
                slots=fm.slots[rows].copy(),
                types=fm.types[rows].copy(),
                values=np.zeros(rows.size, np.float64),
                metric_type=mt,
            ))
        return out


@dataclasses.dataclass
class PassthroughBatch:
    """Pre-aggregated samples bypassing the arenas entirely (reference
    aggregator.go:86,422 AddPassthrough → passWriter.Write): already
    carrying their storage policy, they go straight to the output
    handler."""

    policy: StoragePolicy
    ids: list
    values: np.ndarray
    times: np.ndarray


class AggregatorShard:
    """One aggregator shard: a MetricList per storage policy
    (reference shard.go:171 AddUntimed + list registry)."""

    def __init__(self, shard_id: int, opts: AggregatorOptions,
                 new_series_limiter=None):
        self.shard_id = shard_id
        self.opts = opts
        self.lists = {
            sp: MetricList(sp, opts, new_series_limiter=new_series_limiter)
            for sp in opts.storage_policies
        }

    def add_batch(self, mt, ids, values, times, agg_id=AggregationID.DEFAULT):
        """The FIRST list's resolve charges the creation budget and
        decides which samples are series-rejected; follower lists
        ingest the accepted subset under a limiter bypass — one charge
        per creation across policies, and no policy can hold samples
        another rejected."""
        lists = list(self.lists.values())
        if not lists:
            return
        acc = lists[0].add_batch(mt, ids, values, times, agg_id)
        rest = lists[1:]
        if not rest:
            return
        if acc is not None:
            sel = np.nonzero(acc)[0]
            if sel.size == 0:
                return
            ids = [ids[i] for i in sel]
            values = np.asarray(values)[sel]
            times = np.asarray(times)[sel]
        lim = lists[0].new_series_limiter
        ctx = lim.bypass() if lim is not None else contextlib.nullcontext()
        with ctx:
            for ml in rest:
                ml.add_batch(mt, ids, values, times, agg_id)

    def add_timed_batch(self, mt, ids, values, times,
                        agg_id=AggregationID.DEFAULT,
                        now_nanos: int | None = None) -> np.ndarray:
        """All-or-nothing across storage policies: a sample out of range
        for ANY list is ingested into NONE (pre-checked without
        mutation), so the returned reject mask is trustworthy — a
        rejected sample never silently contributes to some policies'
        aggregates, and a caller retrying it cannot double-count."""
        lists = list(self.lists.values())
        if now_nanos is not None:
            for ml in lists:
                ml.seed_windows(now_nanos)
        accepted = np.ones(len(ids), bool)
        for ml in lists:
            early, future = ml.timed_check(times)
            accepted &= ~(early | future)
        pre_rejected = ~accepted  # rejected before any list's own add
        sel = np.nonzero(accepted)[0]
        if sel.size:
            ids_sel = [ids[i] for i in sel]
            # First list charges the creation budget and decides the
            # series rejections; followers ingest its accepted subset
            # under a bypass (one charge per creation; the reported
            # mask stays truthful for every policy).
            acc = lists[0].add_timed_batch(mt, ids_sel, values[sel],
                                           times[sel], agg_id)
            accepted[sel] &= acc
            if len(lists) > 1:
                sub = np.nonzero(acc)[0]
                lim = lists[0].new_series_limiter
                ctx = (lim.bypass() if lim is not None
                       else contextlib.nullcontext())
                if sub.size:
                    sel2 = sel[sub]
                    ids2 = [ids[i] for i in sel2]
                    with ctx:
                        for ml in lists[1:]:
                            ml.add_timed_batch(mt, ids2, values[sel2],
                                               times[sel2], agg_id)
        if pre_rejected.any():
            # Count each PRE-CHECK-rejected sample exactly ONCE, on the
            # first list that classifies it out-of-range — counters()
            # sums across lists, so per-list mirroring would report one
            # reject per agreeing policy.  Samples the first list
            # rejected in its own add (ring seeded from the batch when
            # now_nanos is None, or series-limited) were already
            # counted there and never reached the followers.
            rej_times = times[pre_rejected]
            remaining = np.ones(len(rej_times), bool)
            for ml in lists:
                early, future = ml.timed_check(rej_times)
                e = early & remaining
                f = future & remaining & ~e
                ml.timed_rejects["too_early"] += int(e.sum())
                ml.timed_rejects["too_far_future"] += int(f.sum())
                remaining &= ~(early | future)
                if not remaining.any():
                    break
        return accepted

    def consume(self, target_nanos: int, flush_handler=None,
                forward_sink=None):
        out = []
        for sp, ml in self.lists.items():
            sink = (None if forward_sink is None
                    else functools.partial(forward_sink, sp))
            out.extend(ml.consume(target_nanos, flush_handler, sink))
        return out


class Aggregator:
    """Top-level aggregator (reference aggregator.go:101): routes metrics
    to shards by murmur-style hash and drives consume across shards.

    Single-host form; the multi-device form shards the slot axis over a
    mesh (m3_tpu.parallel) so each device owns capacity/D slots.
    """

    def __init__(self, num_shards: int = 1, opts: AggregatorOptions | None = None,
                 passthrough_handler=None):
        self.opts = opts or AggregatorOptions()
        # ONE aggregator-wide creation budget shared by every shard's
        # maps (the reference rate-limits at the aggregator options
        # level, entry.go); None when unlimited.
        self.new_series_limiter = None
        if self.opts.new_series_limit_per_sec > 0:
            from m3_tpu.storage.limits import NewSeriesLimiter

            self.new_series_limiter = NewSeriesLimiter(
                self.opts.new_series_limit_per_sec)
        self.shards = [
            AggregatorShard(i, self.opts,
                            new_series_limiter=self.new_series_limiter)
            for i in range(num_shards)
        ]
        # Passthrough output (reference passWriter): pre-aggregated
        # samples skip the arenas and go straight here.
        self.passthrough_handler = passthrough_handler
        self.passthrough_samples = 0
        # rollup-drain latency histogram, attached by
        # instrument_aggregator (None = uninstrumented)
        self._hist_drain = None

    def shard_index(self, mid: bytes) -> int:
        # murmur3(id) % numShards, matching the reference router
        # (aggregator.go:505, sharding/shardset.go:148).
        return shard_for(mid, len(self.shards))

    def shard_for(self, mid: bytes) -> AggregatorShard:
        return self.shards[self.shard_index(mid)]

    def add_untimed_batch(self, mt, ids, values, times, agg_id=AggregationID.DEFAULT):
        if len(self.shards) == 1:
            self.shards[0].add_batch(mt, ids, values, times, agg_id)
            return
        by_shard: Dict[int, List[int]] = {}
        for i, mid in enumerate(ids):
            by_shard.setdefault(self.shard_index(mid), []).append(i)
        for sid, idxs in by_shard.items():
            sel = np.asarray(idxs)
            self.shards[sid].add_batch(
                mt, [ids[i] for i in idxs], values[sel], times[sel], agg_id
            )

    def add_timed_batch(self, mt, ids, values, times,
                        agg_id=AggregationID.DEFAULT,
                        now_nanos: int | None = None) -> np.ndarray:
        """Timed ingestion with per-sample accept/reject (reference
        aggregator.go:77 AddTimed; see MetricList.add_timed_batch)."""
        values = np.asarray(values, np.float64)
        times = np.asarray(times, np.int64)
        if len(self.shards) == 1:
            return self.shards[0].add_timed_batch(
                mt, ids, values, times, agg_id, now_nanos=now_nanos)
        accepted = np.ones(len(ids), bool)
        by_shard: Dict[int, List[int]] = {}
        for i, mid in enumerate(ids):
            by_shard.setdefault(self.shard_index(mid), []).append(i)
        for sid, idxs in by_shard.items():
            sel = np.asarray(idxs)
            acc = self.shards[sid].add_timed_batch(
                mt, [ids[i] for i in idxs], values[sel], times[sel], agg_id,
                now_nanos=now_nanos)
            accepted[sel] = acc
        return accepted

    def _route_forwards(self, policy: StoragePolicy,
                        entries: List[tuple]) -> None:
        """Per-window forward sink (consume context): same routing as
        add_forwarded_batch but non-strict — consume must not raise on
        a policy mismatch; mis-delivery is impossible for self-routed
        forwards (the policy came from our own list registry)."""
        self.add_forwarded_batch(policy, entries, strict=False)

    def add_forwarded_batch(self, policy: StoragePolicy,
                            entries: List[tuple],
                            strict: bool = True) -> None:
        """AddForwarded (aggregator.go:395): deliver stage outputs —
        from this process's consume pass or another aggregator over the
        wire — to the owning shard's list for ``policy``, routed by the
        NEXT stage's metric ID (forwarded_writer.go)."""
        by_shard: Dict[int, List[tuple]] = {}
        for spec, v, ts in entries:
            by_shard.setdefault(self.shard_index(spec.id), []).append(
                (spec, v, ts))
        for sidx, items in by_shard.items():
            ml = self.shards[sidx].lists.get(policy)
            if ml is None:
                if strict:
                    raise ValueError(
                        f"no metric list for storage policy {policy}")
                continue
            ml.add_forwarded(items)

    def add_passthrough_batch(self, ids, values, times,
                              policy: StoragePolicy) -> None:
        """Pre-aggregated metrics go straight to the output handler with
        their storage policy (reference aggregator.go:86,422
        AddPassthrough → passWriter.Write) — no arenas, no windows.
        Raises when no handler is configured: silently eating
        passthrough traffic would be data loss."""
        if self.passthrough_handler is None:
            raise RuntimeError(
                "no passthrough handler configured on this aggregator")
        batch = PassthroughBatch(
            policy=policy, ids=list(ids),
            values=np.asarray(values, np.float64),
            times=np.asarray(times, np.int64))
        self.passthrough_samples += len(batch.ids)
        self.passthrough_handler(batch)

    def consume(self, target_nanos: int, flush_handler=None):
        import time as _time

        t0 = _time.perf_counter()
        out = []
        for sh in self.shards:
            out.extend(sh.consume(target_nanos, flush_handler,
                                  forward_sink=self._route_forwards))
        if self._hist_drain is not None:
            self._hist_drain.record(_time.perf_counter() - t0)
        return out

    def counters(self) -> dict:
        """Operational-counter snapshot summed across every shard's
        lists (reference aggregator metrics scope, aggregator.go:101 /
        entry.go reject counters).  ``forward_errors`` is the
        forwarded-tail conflict / undeliverable count — silent-loss
        edges must be visible on /metrics and the admin status API, not
        only as in-process ints."""
        out = {
            "drops": 0,
            "forward_errors": 0,
            "timed_rejects_too_early": 0,
            "timed_rejects_too_far_future": 0,
            "new_series_rejected": 0,
            "passthrough_samples": self.passthrough_samples,
        }
        for sh in self.shards:
            for ml in sh.lists.values():
                out["drops"] += ml.drops
                out["forward_errors"] += ml.forward_errors
                out["timed_rejects_too_early"] += (
                    ml.timed_rejects["too_early"])
                out["timed_rejects_too_far_future"] += (
                    ml.timed_rejects["too_far_future"])
                out["new_series_rejected"] += ml.new_series_rejected
        return out


def instrument_aggregator(instrument, aggregator: "Aggregator"):
    """Mirror the aggregator's counters into gauges under
    ``<scope>.aggregator.*`` at every registry scrape (snapshot /
    render_prometheus), via the registry's collector hook — so the
    forwarded-tail conflict counter and friends land on /metrics
    without a polling thread.  Returns the collector fn; pass it to
    ``registry.unregister_collector`` at shutdown (the registry holds
    a strong reference to the aggregator through it)."""
    scope = instrument.scope("aggregator")
    # window-drain latency (hot path: every flush-manager tick) —
    # interned once here, recorded inside Aggregator.consume
    aggregator._hist_drain = scope.histogram("drain_seconds")

    def collect():
        for name, v in aggregator.counters().items():
            scope.gauge(name).update(v)

    scope.registry.register_collector(collect)
    return collect


