"""Leader/follower flush management with KV-persisted flush times.

Reference parity: `src/aggregator/aggregator/leader_flush_mgr.go:71-190`
(the elected leader drives window consumption and persists per-shard
flush times to KV after every flush round) and `follower_flush_mgr.go`
(followers watch the leader's persisted flush times and *shadow-consume*
their replica of the same input stream up to those times without
emitting).  Election is `election_mgr.go` → etcd leases, here
`cluster.kv.LeaderElection` with a TTL lease.

Semantics preserved from the reference:

* Exactly one instance emits per window (the lease holder).
* Flush times are persisted AFTER emission, so a leader crash between
  emit and persist re-emits that window under the new leader —
  at-least-once, identical to the reference (downstream storage writes
  are idempotent per (id, timestamp)).  The same holds for a stale
  ex-leader resuming a paused tick after its lease expired: it may
  re-emit a window the new leader already flushed (unavoidable without
  fencing tokens threaded to the downstream sink), but it can never
  roll the persisted watermark back — writes are max-merged under CAS.
* A restarted instance resumes at the persisted window
  (`leader_flush_mgr.go:78-80` reads flush times back), never re-opening
  windows the previous leader already drained.
* Followers stay drained to the leader's watermark, so promotion after
  lease expiry continues with no lost and no duplicated window (tested
  in tests/test_flush_mgr.py by killing the leader between ticks).
"""

from __future__ import annotations

import functools
import json
from typing import Callable, Dict, List, Tuple

from m3_tpu.aggregator.engine import Aggregator, FlushedMetric, MetricList
from m3_tpu.cluster.kv import KVStore, LeaderElection

FlushHandler = Callable[[MetricList, FlushedMetric], None]

DEFAULT_LEASE_NANOS = 30 * 10**9


class FlushManager:
    """Drives an Aggregator's consume loop under a leadership lease."""

    def __init__(
        self,
        aggregator: Aggregator,
        kv: KVStore,
        instance_id: str,
        scope: str = "agg",
        flush_handler: FlushHandler | None = None,
        lease_nanos: int = DEFAULT_LEASE_NANOS,
    ):
        self.aggregator = aggregator
        self.kv = kv
        self.instance_id = instance_id
        self.flush_handler = flush_handler
        self.election = LeaderElection(
            kv, f"flush/{scope}", instance_id, ttl_nanos=lease_nanos
        )
        self._times_key = f"_flushtimes/{scope}"

    # ---- flush-times persistence (leader_flush_mgr.go:78-80,184) ----

    def _read_times(self) -> Tuple[Dict[Tuple[int, str], int], int]:
        cur = self.kv.get(self._times_key)
        if cur is None:
            return {}, 0
        raw = json.loads(cur.data)
        return {
            (int(sid), pol): int(t)
            for sid, pols in raw.items()
            for pol, t in pols.items()
        }, cur.version

    def _write_times(self, times: Dict[Tuple[int, str], int]) -> None:
        """Advance the shared watermark, never roll it back.

        A stale ex-leader resuming a paused tick must not overwrite a new
        leader's progress: merge with max() against the current record
        and CAS on its version, retrying on conflict — so whichever
        instance writes last, the persisted watermark is monotone.
        """
        for _ in range(8):
            existing, version = self._read_times()
            merged = dict(existing)
            for k, t in times.items():
                if merged.get(k, 0) < t:
                    merged[k] = t
            if merged == existing:
                return
            raw: Dict[str, Dict[str, int]] = {}
            for (sid, pol), t in merged.items():
                raw.setdefault(str(sid), {})[pol] = t
            try:
                self.kv.check_and_set(
                    self._times_key, version, json.dumps(raw).encode()
                )
                return
            except ValueError:
                continue  # concurrent writer: re-read and re-merge
        raise RuntimeError(
            f"flush-times CAS on {self._times_key} lost 8 straight races; "
            "watermark not persisted (restart would re-emit flushed windows)"
        )

    def _collect_times(self) -> Dict[Tuple[int, str], int]:
        out: Dict[Tuple[int, str], int] = {}
        for sh in self.aggregator.shards:
            for sp, ml in sh.lists.items():
                if ml.consumed_until is not None:
                    out[(sh.shard_id, str(sp))] = ml.consumed_until
        return out

    # ---- lifecycle ----

    def restore(self) -> None:
        """On startup, resume every list at the persisted watermark so a
        restart neither re-emits drained windows nor drops the open one."""
        times, _ = self._read_times()
        for sh in self.aggregator.shards:
            for sp, ml in sh.lists.items():
                t = times.get((sh.shard_id, str(sp)))
                if t is not None and (
                    ml.consumed_until is None or ml.consumed_until < t
                ):
                    ml.consumed_until = t

    def tick(self, now_nanos: int) -> str:
        """One flush round; returns the role played ("leader"/"follower").

        Leader: drain every closed window, emit through the flush
        handler, then persist the new flush times.  Follower: shadow-
        consume (no emission) up to the leader's persisted times.
        """
        if self.election.campaign(now_nanos):
            results: List[FlushedMetric] = []

            def emit(ml: MetricList, fm: FlushedMetric) -> None:
                results.append(fm)
                if self.flush_handler is not None:
                    self.flush_handler(ml, fm)

            # Route through the aggregator's forward sink: multi-stage
            # rollup outputs must land on the NEXT stage's owning shard,
            # not re-ingest into their source shard's list.
            for sh in self.aggregator.shards:
                sh.consume(now_nanos, emit,
                           forward_sink=self.aggregator._route_forwards)
            self._write_times(self._collect_times())
            return "leader"

        # Follower: drain to the leader's watermark, discarding output
        # (our replica aggregated the same stream; the leader emitted it).
        # Forwards still shard-route so the replica's stage-2 state
        # matches the leader's placement.
        times, _ = self._read_times()
        for sh in self.aggregator.shards:
            for sp, ml in sh.lists.items():
                t = times.get((sh.shard_id, str(sp)))
                if t is not None:
                    ml.consume(
                        t, None,
                        functools.partial(
                            self.aggregator._route_forwards, sp))
        return "follower"

    def resign(self) -> None:
        self.election.resign()
