"""Packed arena: sort/segment ingest + adaptive-width counter state.

The scatter arenas (``arena.py``) pay one XLA scatter per statistic lane
— ~11 random-access passes plus a 3-key lex sort per ingest batch.  On
XLA-CPU a scatter has a ~40-60ns/element floor regardless of dtype, and
on TPU it measured ~1us/element (TPU_RESULTS_r05.json window #3).  This
module reformulates the whole hot path around ONE u64 key sort per
batch and otherwise touches memory only with the primitives XLA runs at
streaming speed (gather ~4.5ns/elt, cumsum ~6ns, dense ~4ns on the r07
box):

    key    = flat_idx << AB | arrival          (AB = batch-size bits)
    sorted -> permutation + per-slot segment boundaries
    sum/sum_sq/count  = cumulative-sum differences at the boundaries
    min/max/last      = one segmented associative scan
    state update      = DENSE merge over the (W*C,) arena — no scatter

Boundaries come from one monotone scatter-min (`indices_are_sorted`)
plus a reverse cummin — no searchsorted on the ingest path.  The only
remaining scatters are the timer sample append (one packed word) and
the bounded-K overflow-pool promotion below.

Counter state adopts the SALSA / Counter Pools layout
(arXiv:2102.12531, arXiv:2502.14699): narrow base lanes packed per
(window, slot) —

    base   u64: count:CB | sum:SB (biased)   (default 16/48)
    sq     i64: sum of squares               (full width: squares grow
                with value^2 and saturate any narrow lane in minutes —
                the round-8 bench caught a 24-bit sq lane doing so)
    minmax u32: o16(min) << 16 | o16(max)    (int16-exact)

— with a shared overflow pool of full-width i64 rows.  A slot whose
count or sum lane would saturate, or that sees a value outside the
int16 min/max range, PROMOTES: its exact running stats move to a pool
row and later batches add deltas there.  Promotion and spill are
branchless bounded-K scatters (``jnp.nonzero(size=K)``) under a
``lax.cond`` that costs nothing while no slot is promoted.  Per-slot
memory is 24B (base 8 + sq 8 + minmax 4 + pool index 4) vs the f64
arena's 40B — 1.67x, plus P*48B of pool (default P = C/16); narrower
CB/SB widths trade promotion rate for memory.  Packed counter stats
are EXACT: count/sum/sum_sq accumulate in (wrapping) i64 exactly like
the scatter path, min/max are int16-exact in the base and i64-exact
once promoted.

Gauge state keeps f64 sum/sum_sq/min/max/last (the parity contract
pins count/min/max/last bit-exact); the packed win for gauges is the
formulation: batch sums ride the segmented scan as tree-order f64 adds
— rounding stays at ~log2(N) ulps of each segment's OWN magnitude (a
cumsum-diff form was tried and rejected: its quantum scales with the
batch max, which blows the relative bound for tiny segments) and
+/-inf / NaN flow through with the scatter path's exact semantics —
replacing the 3-key lex sort + 8 scatters.

Timer state is one u64 word per buffered sample (slot<<32 |
orderable-f32(value)) — the packed32 drain representation extended to
ingest, so ingest is ONE scatter (append) and drain sorts the words
directly.  Moments are recovered at drain from the sorted buffer via
the same segmented scan (values carry f32 precision, within the
established packed32 1e-6 bound; counts are exact).

Everything here is jit-pure: the layout choice (M3_ARENA_LAYOUT) is
resolved on the host in arena.py and selects these ops at arena
construction — nothing reads the environment under a tracer.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from m3_tpu.aggregator.arena import (
    I64_MAX,
    I64_MIN,
    SCALAR_LANES,
    _guarded_consume,
    _guarded_ingest,
    _guarded_state_op,
    _ScalarLanesMixin,
    _TimerLanesMixin,
    _sanitize_slots,
    _stdev,
    decode_orderable_f32,
    orderable_f32,
    pad_slots,
    timer_append_plan,
)
from m3_tpu.x import devguard, membudget

# Default adaptive-width lane split for the counter base word
# (count:16 | sum:24 | sq:24) and the int16 min/max word.  Tests pass
# narrower widths to exercise promotion; widths are STATIC jit args.
# (count:16 | sum:48) in one u64 word; sum_sq keeps a dedicated i64
# column — squares grow with value^2 and would saturate any packed
# lane in minutes of real traffic (the round-8 bench caught exactly
# that with a 24-bit sq lane), and a full i64 sq column keeps packed
# counter moments BIT-exact vs the scatter path (mod-2^64 wrap
# included) instead of merely within 1e-6.
DEFAULT_WIDTHS = (16, 48)
# Bounded promotion fan-out per ingest batch: more than K promotions or
# pool-active slots in one batch sets the sticky `err` lane (the host
# wrapper raises at the next consume).  K scatters are ~micro-seconds.
PROMOTE_K = 4096
# int16-exact min/max range of the base minmax word.
_MM_LO = -(1 << 15)
_MM_HI = (1 << 15) - 1

_ERR_PROMOTE_K = 1  # more than K promotions/active pool slots in a batch
_ERR_POOL_FULL = 2  # overflow pool exhausted
# timer sample-buffer overflow: OWNED here so the err-bit namespace has
# one home, but only RAISED by the sharded step's lanes["err"] (the
# host PackedTimerArena grows its buffer and cannot overflow)
_ERR_TIMER_OVERFLOW = 4


# ---------------------------------------------------------------------------
# Shared sort/segment machinery
# ---------------------------------------------------------------------------


class _Segments(NamedTuple):
    """One sorted batch view: permutation + dense per-slot boundaries."""

    perm: jnp.ndarray   # i32 (N,) original position of sorted element
    sslot: jnp.ndarray  # i32 (N,) flat (window*C+slot) index, ascending
    head: jnp.ndarray   # bool (N,) first element of its segment
    start: jnp.ndarray  # i32 (WC,) first sorted position per dense slot
    end: jnp.ndarray    # i32 (WC,) one past last sorted position
    cnt: jnp.ndarray    # i64 (WC,) segment length (0 for empty slots)
    has: jnp.ndarray    # bool (WC,)
    ab: int             # arrival bits (static)


def _arrival_bits(n: int) -> int:
    return max(1, (max(n - 1, 1)).bit_length())


def packed_flat_index(windows, slots, num_windows: int, capacity: int):
    """Flat index for the packed ingest ops, with a slot-only GHOST
    region: [0, W*C) carries stats; [W*C, W*C+C) holds samples whose
    slot is valid but whose window dropped — they contribute only the
    per-slot ``last_at`` expiry time, mirroring the scatter arenas
    (whose last_at scatter-max is gated on the slot alone); W*C+C is
    the full drop sentinel."""
    valid_s = (slots >= 0) & (slots < capacity)
    valid_w = (windows >= 0) & (windows < num_windows)
    wc = num_windows * capacity
    base = windows.astype(jnp.int64) * capacity + slots
    return jnp.where(
        valid_w & valid_s, base,
        jnp.where(valid_s, wc + slots.astype(jnp.int64),
                  jnp.int64(wc + capacity)))


def _segment_view(idx: jnp.ndarray, n_flat: int) -> _Segments:
    """Sort a batch of flat indices into dense per-slot segments.

    ``idx`` values == n_flat are the drop sentinel: they sort to the
    tail and fall outside every dense slot's [start, end) range."""
    n = idx.shape[0]
    ab = _arrival_bits(n)
    if (n_flat + 1).bit_length() + ab > 63:
        raise ValueError(
            f"arena of {n_flat} flat slots with batches of {n} needs "
            f"{(n_flat + 1).bit_length() + ab} key bits > 63; shrink the "
            "batch or the arena")
    key = (idx.astype(jnp.uint64) << jnp.uint64(ab)) | jnp.arange(
        n, dtype=jnp.uint64)
    ks = jax.lax.sort(key)
    perm = (ks & jnp.uint64((1 << ab) - 1)).astype(jnp.int32)
    sslot = (ks >> jnp.uint64(ab)).astype(jnp.int32)
    head = jnp.concatenate(
        [jnp.ones(1, bool), sslot[1:] != sslot[:-1]])
    # Dense boundaries: one monotone scatter-min marks each slot's first
    # sorted position; a reverse cummin over the NEXT slots' starts
    # yields the ends (empty slots collapse to start > end -> cnt 0).
    bpos = jnp.full(n_flat + 1, n, jnp.int32).at[sslot].min(
        jnp.arange(n, dtype=jnp.int32), mode="drop",
        indices_are_sorted=True)
    start = bpos[:n_flat]
    end = jax.lax.cummin(bpos[1:], reverse=True)
    cnt = jnp.maximum(end - start, 0).astype(jnp.int64)
    return _Segments(perm, sslot, head, start, end, cnt, cnt > 0, ab)


def _seg_sum_i64(seg: _Segments, v_sorted: jnp.ndarray) -> jnp.ndarray:
    """Exact (mod-2^64-wrapping) per-slot sums via cumsum differences —
    identical arithmetic to the scatter path's i64 accumulate."""
    cs = jnp.concatenate([jnp.zeros(1, jnp.int64), jnp.cumsum(v_sorted)])
    return jnp.where(seg.has, cs[seg.end] - cs[seg.start], jnp.int64(0))


def _seg_flag_counts(seg: _Segments, flags: tuple) -> tuple:
    """Per-slot counts for up to three boolean lanes, packed into ONE
    i64 cumsum.  Each lane gets ``seg.ab + 1`` bits: a whole batch can
    land in ONE segment, so a count reaches n == 2^ab exactly at
    power-of-two batch sizes — ab bits alone would carry into the next
    lane.  Falls back to one cumsum per lane when the lanes don't fit
    63 bits."""
    lb = seg.ab + 1
    k = len(flags)
    if k * lb <= 63:
        word = flags[0].astype(jnp.int64)
        for i, f in enumerate(flags[1:], start=1):
            word = word + (f.astype(jnp.int64) << jnp.int64(i * lb))
        cs = jnp.concatenate([jnp.zeros(1, jnp.int64), jnp.cumsum(word)])
        d = jnp.where(seg.has, cs[seg.end] - cs[seg.start], jnp.int64(0))
        out = []
        for i in range(k):
            lane = (d >> jnp.int64(i * lb))
            if i < k - 1:
                lane = lane & jnp.int64((1 << lb) - 1)
            out.append(lane)
        return tuple(out)
    return tuple(_seg_sum_i64(seg, f.astype(jnp.int64)) for f in flags)


def _seg_scan(seg: _Segments, lanes: tuple, combine) -> tuple:
    """Segmented associative scan over the sorted batch: ``combine``
    merges two within-segment prefixes; segment heads reset the carry.
    Returns the RAW scanned lanes — gather per-slot reductions at each
    segment's end-1 with ``_at_ends`` (over whichever dense view the
    caller needs, so stats gathers stay on the [0, W*C) region while
    the time lane also covers the ghost region)."""
    def op(a, b):
        fa, va = a[0], a[1:]
        fb, vb = b[0], b[1:]
        merged = combine(va, vb)
        out = tuple(jnp.where(fb, nb, m) for nb, m in zip(vb, merged))
        return (fa | fb,) + out

    res = jax.lax.associative_scan(op, (seg.head,) + lanes)
    return res[1:]


def _at_ends(end: jnp.ndarray, lane: jnp.ndarray) -> jnp.ndarray:
    """Per-slot scan reduction: the scanned lane at each segment's
    last element (callers mask empty slots via their ``has``)."""
    lp = jnp.clip(end.astype(jnp.int64) - 1, 0, lane.shape[0] - 1)
    return lane[lp]


def _stats_view(seg: _Segments, wc: int) -> _Segments:
    """The [0, W*C) stats region of a ghost-extended segment view."""
    return seg._replace(start=seg.start[:wc], end=seg.end[:wc],
                        cnt=seg.cnt[:wc], has=seg.has[:wc])


# ---------------------------------------------------------------------------
# Packed counter arena (SALSA/Counter-Pools layout).  The orderable-f32
# word encoding is shared with the timer drain's packed32 form and lives
# in arena.py (one home; imported above).
# ---------------------------------------------------------------------------


class PackedCounterState(NamedTuple):
    base: jnp.ndarray      # u64 (W*C,) count | sum (biased) lanes
    sq: jnp.ndarray        # i64 (W*C,) sum of squares (wraps mod 2^64)
    minmax: jnp.ndarray    # u32 (W*C,) o16(min)<<16 | o16(max)
    pool_cnt: jnp.ndarray  # i64 (P,)
    pool_sum: jnp.ndarray  # i64 (P,)
    pool_sq: jnp.ndarray   # i64 (P,)
    pool_min: jnp.ndarray  # i64 (P,)
    pool_max: jnp.ndarray  # i64 (P,)
    pool_owner: jnp.ndarray  # i32 (P,) flat owner idx, -1 free
    pool_idx: jnp.ndarray  # i32 (W*C,) pool row, -1 unpromoted
    pool_n: jnp.ndarray    # i32 () live pool rows (derived from
    #                        pool_owner at every producer; carried for
    #                        cheap host observability — allocation
    #                        itself is the free-row scan in
    #                        _counter_merge, NOT a bump pointer)
    err: jnp.ndarray       # i32 () sticky error bits
    last_at: jnp.ndarray   # i64 (C,)


def _neutral_base(widths: tuple) -> int:
    cb, sb = widths
    return 1 << (sb - 1)  # cnt 0, sum at bias (python int: trace-safe)


_MM_NEUTRAL = np.uint32(0xFFFF0000)  # min lane 0xFFFF (+32767), max 0


def _unpack_base(base: jnp.ndarray, widths: tuple):
    cb, sb = widths
    cnt = (base >> jnp.uint64(sb)).astype(jnp.int64)
    s = (base & jnp.uint64((1 << sb) - 1)).astype(
        jnp.int64) - jnp.int64(1 << (sb - 1))
    return cnt, s


def _pack_base(cnt, s, widths: tuple) -> jnp.ndarray:
    cb, sb = widths
    return ((cnt.astype(jnp.uint64) << jnp.uint64(sb))
            | (s + jnp.int64(1 << (sb - 1))).astype(jnp.uint64))


def _unpack_minmax(mm: jnp.ndarray):
    mn = (mm >> jnp.uint32(16)).astype(jnp.int64) - jnp.int64(1 << 15)
    mx = (mm & jnp.uint32(0xFFFF)).astype(jnp.int64) - jnp.int64(1 << 15)
    return mn, mx


def _pack_minmax(mn, mx) -> jnp.ndarray:
    bias = jnp.int64(1 << 15)
    return (((mn + bias).astype(jnp.uint32) << jnp.uint32(16))
            | (mx + bias).astype(jnp.uint32))


def counter_init(num_windows: int, capacity: int,
                 pool_capacity: int | None = None,
                 widths: tuple = DEFAULT_WIDTHS) -> PackedCounterState:
    n = num_windows * capacity
    P = pool_capacity if pool_capacity is not None else max(64, n // 16)
    return PackedCounterState(
        base=jnp.full(n, _neutral_base(widths), jnp.uint64),
        sq=jnp.zeros(n, jnp.int64),
        minmax=jnp.full(n, _MM_NEUTRAL, jnp.uint32),
        pool_cnt=jnp.zeros(P, jnp.int64),
        pool_sum=jnp.zeros(P, jnp.int64),
        pool_sq=jnp.zeros(P, jnp.int64),
        pool_min=jnp.full(P, I64_MAX, jnp.int64),
        pool_max=jnp.full(P, I64_MIN, jnp.int64),
        pool_owner=jnp.full(P, -1, jnp.int32),
        pool_idx=jnp.full(n, -1, jnp.int32),
        pool_n=jnp.int32(0),
        err=jnp.int32(0),
        last_at=jnp.zeros(capacity, jnp.int64),
    )


def _merge_last_at(last_at, d_tmax, num_windows: int, capacity: int):
    """Fold per-flat-slot batch max-times (including the ghost region's
    window-dropped samples) into the per-slot expiry column."""
    return jnp.maximum(
        last_at,
        jnp.max(d_tmax.reshape(num_windows + 1, capacity), axis=0))


def _counter_sums(seg: _Segments, v: jnp.ndarray):
    """(d_sum, d_sq, wide flags) for a sorted counter value column."""
    d_sum = _seg_sum_i64(seg, v)
    d_sq = _seg_sum_i64(seg, v * v)
    wide = (v < jnp.int64(_MM_LO)) | (v > jnp.int64(_MM_HI))
    return d_sum, d_sq, wide


def _counter_batch_segments(sview: _Segments, seg: _Segments,
                            values: jnp.ndarray, times: jnp.ndarray):
    """Per-dense-slot batch aggregates for a counter-style i64 batch:
    stats over the (W*C,) region, max-time over the full ghost-extended
    domain (the last_at column)."""
    v = values[seg.perm]
    t = times[seg.perm]
    d_sum, d_sq, wide = _counter_sums(sview, v)
    (d_wide,) = _seg_flag_counts(sview, (wide,))
    s_min, s_max, s_t = _seg_scan(
        seg, (v, v, t),
        lambda a, b: (jnp.minimum(a[0], b[0]), jnp.maximum(a[1], b[1]),
                      jnp.maximum(a[2], b[2])))
    d_min = jnp.where(sview.has, _at_ends(sview.end, s_min), I64_MAX)
    d_max = jnp.where(sview.has, _at_ends(sview.end, s_max), I64_MIN)
    d_tmax = jnp.where(seg.has, _at_ends(seg.end, s_t), I64_MIN)
    return (sview.cnt, d_sum, d_sq, d_min, d_max, d_wide), d_tmax


def _counter_merge(state: PackedCounterState, segs, last_at,
                   num_windows: int, capacity: int, widths: tuple,
                   promote_k: int):
    """Dense merge of batch aggregates into the packed counter state,
    with bounded-K overflow-pool promotion."""
    cb, sb = widths
    d_cnt, d_sum, d_sq, d_min, d_max, d_wide = segs
    wc = num_windows * capacity
    K = min(promote_k, wc)
    P = state.pool_cnt.shape[0]

    b_cnt, b_sum = _unpack_base(state.base, widths)
    b_min, b_max = _unpack_minmax(state.minmax)
    # a slot with no base samples holds the int16 NEUTRAL sentinels
    # (32767/-32768) — mask them to the true identities before merging,
    # or a virgin slot promoting on an all-wide first batch would seed
    # its pool row with the sentinel as an "observed" min/max
    b_min = jnp.where(b_cnt > 0, b_min, I64_MAX)
    b_max = jnp.where(b_cnt > 0, b_max, I64_MIN)
    n_cnt = b_cnt + d_cnt
    n_sum = b_sum + d_sum
    n_sq = state.sq + d_sq  # full-width column: never a promote trigger
    n_min = jnp.minimum(b_min, d_min)
    n_max = jnp.maximum(b_max, d_max)

    promoted = state.pool_idx >= 0
    lane_over = ((n_cnt >= jnp.int64(1 << cb))
                 | (n_sum >= jnp.int64(1 << (sb - 1)))
                 | (n_sum < jnp.int64(-(1 << (sb - 1))))
                 | (d_wide > 0))
    seg_has = d_cnt > 0
    to_pool = seg_has & ~promoted & lane_over
    active = seg_has & promoted

    def with_pool(op):
        (pool_cnt, pool_sum, pool_sq, pool_min, pool_max, pool_owner,
         pool_idx, pool_n, err) = op
        num_new = to_pool.sum().astype(jnp.int32)
        kn = jnp.nonzero(to_pool, size=K, fill_value=wc)[0]
        valid = jnp.arange(K, dtype=jnp.int32) < num_new
        # Allocate from FREE rows (owner < 0): the scan over P reuses
        # rows released by clear_slots, so slot churn cannot
        # permanently exhaust the pool the way a bump pointer did.  A
        # candidate with no free row left keeps pool_idx == -1 (its
        # base lanes clip — flagged by err, but never aliased onto
        # another slot's pool row).
        free = jnp.nonzero(pool_owner < 0, size=K,
                           fill_value=P)[0].astype(jnp.int32)
        room = free < P
        take = valid & room
        pids = jnp.where(take, free, jnp.int32(P))
        pool_idx = pool_idx.at[kn].set(
            jnp.where(take, pids, jnp.int32(-1)), mode="drop")
        pool_owner = pool_owner.at[pids].set(kn.astype(jnp.int32),
                                             mode="drop")
        kc = jnp.clip(kn, 0, wc - 1)
        pool_cnt = pool_cnt.at[pids].set(n_cnt[kc], mode="drop")
        pool_sum = pool_sum.at[pids].set(n_sum[kc], mode="drop")
        pool_sq = pool_sq.at[pids].set(n_sq[kc], mode="drop")
        pool_min = pool_min.at[pids].set(n_min[kc], mode="drop")
        pool_max = pool_max.at[pids].set(n_max[kc], mode="drop")
        # already-promoted slots with batch data: add deltas to rows
        num_act = active.sum().astype(jnp.int32)
        ka = jnp.nonzero(active, size=K, fill_value=wc)[0]
        kac = jnp.clip(ka, 0, wc - 1)
        pid_a = jnp.where(ka < wc, pool_idx[kac], jnp.int32(P))
        pool_cnt = pool_cnt.at[pid_a].add(d_cnt[kac], mode="drop")
        pool_sum = pool_sum.at[pid_a].add(d_sum[kac], mode="drop")
        pool_sq = pool_sq.at[pid_a].add(d_sq[kac], mode="drop")
        pool_min = pool_min.at[pid_a].min(d_min[kac], mode="drop")
        pool_max = pool_max.at[pid_a].max(d_max[kac], mode="drop")
        err = err | jnp.where(num_new > K, _ERR_PROMOTE_K, 0)
        err = err | jnp.where(num_act > K, _ERR_PROMOTE_K, 0)
        err = err | jnp.where((valid & ~room).any(), _ERR_POOL_FULL, 0)
        pool_n = (pool_owner >= 0).sum().astype(jnp.int32)
        return (pool_cnt, pool_sum, pool_sq, pool_min, pool_max,
                pool_owner, pool_idx, pool_n, err.astype(jnp.int32))

    pool_ops = (state.pool_cnt, state.pool_sum, state.pool_sq,
                state.pool_min, state.pool_max, state.pool_owner,
                state.pool_idx, state.pool_n, state.err)
    (pool_cnt, pool_sum, pool_sq, pool_min, pool_max, pool_owner,
     pool_idx, pool_n, err) = jax.lax.cond(
        to_pool.any() | active.any(), with_pool, lambda op: op, pool_ops)

    in_pool = pool_idx >= 0
    # pooled slots keep a neutral base word; the rest repack
    base = jnp.where(
        in_pool, jnp.uint64(_neutral_base(widths)),
        _pack_base(jnp.clip(n_cnt, 0, (1 << cb) - 1),
                   jnp.clip(n_sum, -(1 << (sb - 1)), (1 << (sb - 1)) - 1),
                   widths))
    minmax = jnp.where(
        in_pool, jnp.uint32(_MM_NEUTRAL),
        _pack_minmax(jnp.clip(n_min, _MM_LO, _MM_HI),
                     jnp.clip(n_max, _MM_LO, _MM_HI)))

    return PackedCounterState(
        base=base, sq=jnp.where(in_pool, jnp.int64(0), n_sq),
        minmax=minmax,
        pool_cnt=pool_cnt, pool_sum=pool_sum, pool_sq=pool_sq,
        pool_min=pool_min, pool_max=pool_max, pool_owner=pool_owner,
        pool_idx=pool_idx, pool_n=pool_n, err=err, last_at=last_at)


@functools.partial(
    jax.jit, donate_argnums=0,
    static_argnames=("num_windows", "capacity", "widths", "promote_k"))
def counter_ingest(
    state: PackedCounterState,
    idx: jnp.ndarray,     # i64 (N,) flat window*C+slot; == W*C drops
    values: jnp.ndarray,  # i64 (N,)
    times: jnp.ndarray,   # i64 (N,)
    num_windows: int,
    capacity: int,
    widths: tuple = DEFAULT_WIDTHS,
    promote_k: int = PROMOTE_K,
) -> PackedCounterState:
    wc = num_windows * capacity
    seg = _segment_view(idx, wc + capacity)
    d, d_tmax = _counter_batch_segments(_stats_view(seg, wc), seg,
                                        values, times)
    last_at = _merge_last_at(state.last_at, d_tmax, num_windows, capacity)
    return _counter_merge(state, d, last_at, num_windows, capacity,
                          widths, promote_k)


def _counter_lanes(state: PackedCounterState, widths: tuple):
    """Dense (W*C,) full-precision stat lanes merging base and pool."""
    b_cnt, b_sum = _unpack_base(state.base, widths)
    b_min, b_max = _unpack_minmax(state.minmax)
    in_pool = state.pool_idx >= 0
    P = state.pool_cnt.shape[0]
    pidx = jnp.clip(state.pool_idx, 0, P - 1)
    cnt = jnp.where(in_pool, state.pool_cnt[pidx], b_cnt)
    s = jnp.where(in_pool, state.pool_sum[pidx], b_sum)
    sq = jnp.where(in_pool, state.pool_sq[pidx], state.sq)
    mn = jnp.where(in_pool, state.pool_min[pidx],
                   jnp.where(b_cnt > 0, b_min, I64_MAX))
    mx = jnp.where(in_pool, state.pool_max[pidx],
                   jnp.where(b_cnt > 0, b_max, I64_MIN))
    return cnt, s, sq, mn, mx


@functools.partial(jax.jit, static_argnames=("capacity", "widths"))
def counter_consume(state: PackedCounterState, window: jnp.ndarray,
                    capacity: int, widths: tuple = DEFAULT_WIDTHS):
    cnt_a, s_a, sq_a, mn_a, mx_a = _counter_lanes(state, widths)
    off = window * capacity
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, off, capacity)
    cnt = sl(cnt_a)
    s = sl(s_a).astype(jnp.float64)
    ssq = sl(sq_a).astype(jnp.float64)
    cntf = cnt.astype(jnp.float64)
    mean = jnp.where(cnt == 0, 0.0, s / jnp.where(cnt == 0, 1, cnt))
    lanes = jnp.stack(
        [
            jnp.full(capacity, jnp.nan, jnp.float64),  # LAST
            jnp.where(cnt == 0, 0.0, sl(mn_a).astype(jnp.float64)),
            jnp.where(cnt == 0, 0.0, sl(mx_a).astype(jnp.float64)),
            mean,
            cntf,
            s,
            ssq,
            _stdev(cntf, ssq, s),
        ],
        axis=1,
    )
    return lanes, cnt


@functools.partial(
    jax.jit, donate_argnums=0,
    static_argnames=("num_windows", "capacity", "widths"))
def counter_reset_window(state: PackedCounterState, window: jnp.ndarray,
                         num_windows: int, capacity: int,
                         widths: tuple = DEFAULT_WIDTHS
                         ) -> PackedCounterState:
    off = window * capacity
    upd = lambda a, v: jax.lax.dynamic_update_slice_in_dim(
        a, jnp.full(capacity, v, a.dtype), off, 0)
    # pool rows owned by this window reset densely over P (no scatter)
    own_w = jnp.where(state.pool_owner >= 0,
                      state.pool_owner // capacity, -1)
    hit = own_w == window.astype(jnp.int32)
    return state._replace(
        base=upd(state.base, _neutral_base(widths)),
        sq=upd(state.sq, 0),
        minmax=upd(state.minmax, _MM_NEUTRAL),
        pool_cnt=jnp.where(hit, jnp.int64(0), state.pool_cnt),
        pool_sum=jnp.where(hit, jnp.int64(0), state.pool_sum),
        pool_sq=jnp.where(hit, jnp.int64(0), state.pool_sq),
        pool_min=jnp.where(hit, I64_MAX, state.pool_min),
        pool_max=jnp.where(hit, I64_MIN, state.pool_max),
    )


@functools.partial(
    jax.jit, donate_argnums=0,
    static_argnames=("num_windows", "capacity", "widths"))
def counter_clear_slots(state: PackedCounterState, slots: jnp.ndarray,
                        num_windows: int, capacity: int,
                        widths: tuple = DEFAULT_WIDTHS
                        ) -> PackedCounterState:
    idx = (jnp.arange(num_windows, dtype=jnp.int64)[:, None] * capacity
           + slots[None, :]).ravel()
    idx = jnp.where(
        (slots[None, :] >= capacity).repeat(num_windows, 0).ravel(),
        num_windows * capacity, idx)
    # pool rows whose owner slot is cleared are RELEASED (owner -1)
    # via a sorted membership probe — the free-list allocator in
    # _counter_merge reuses them, so recycling slots can't leak the
    # pool dry (slots is small and host-sorted by pad_slots' caller;
    # sort again defensively)
    sorted_slots = jnp.sort(slots.astype(jnp.int32))
    own_slot = jnp.where(state.pool_owner >= 0,
                         state.pool_owner % capacity, -1)
    pos = jnp.clip(jnp.searchsorted(sorted_slots, own_slot), 0,
                   sorted_slots.shape[0] - 1)
    hit = (sorted_slots[pos] == own_slot) & (state.pool_owner >= 0)
    pool_owner = jnp.where(hit, jnp.int32(-1), state.pool_owner)
    return state._replace(
        base=state.base.at[idx].set(_neutral_base(widths), mode="drop"),
        sq=state.sq.at[idx].set(0, mode="drop"),
        minmax=state.minmax.at[idx].set(_MM_NEUTRAL, mode="drop"),
        pool_cnt=jnp.where(hit, jnp.int64(0), state.pool_cnt),
        pool_sum=jnp.where(hit, jnp.int64(0), state.pool_sum),
        pool_sq=jnp.where(hit, jnp.int64(0), state.pool_sq),
        pool_min=jnp.where(hit, I64_MAX, state.pool_min),
        pool_max=jnp.where(hit, I64_MIN, state.pool_max),
        pool_owner=pool_owner,
        pool_idx=state.pool_idx.at[idx].set(-1, mode="drop"),
        pool_n=(pool_owner >= 0).sum().astype(jnp.int32),
        last_at=state.last_at.at[slots].set(0, mode="drop"),
    )


# ---------------------------------------------------------------------------
# Packed gauge arena (sort-formulation ingest; f64 lanes stay bit-exact
# for count/min/max/last, fixed-point batch sums for sum/sum_sq)
# ---------------------------------------------------------------------------


class PackedGaugeState(NamedTuple):
    sum: jnp.ndarray        # f64 (W*C,)
    sum_sq: jnp.ndarray     # f64
    count: jnp.ndarray      # i64
    min: jnp.ndarray        # f64, identity +inf
    max: jnp.ndarray        # f64, identity -inf
    last_bits: jnp.ndarray  # i64 (W*C,) f64 bit pattern of `last`
    last_time: jnp.ndarray  # i64
    last_at: jnp.ndarray    # i64 (C,)


def gauge_init(num_windows: int, capacity: int) -> PackedGaugeState:
    n = num_windows * capacity
    return PackedGaugeState(
        sum=jnp.zeros(n, jnp.float64),
        sum_sq=jnp.zeros(n, jnp.float64),
        count=jnp.zeros(n, jnp.int64),
        min=jnp.full(n, jnp.inf, jnp.float64),
        max=jnp.full(n, -jnp.inf, jnp.float64),
        last_bits=jnp.zeros(n, jnp.int64),
        last_time=jnp.zeros(n, jnp.int64),
        last_at=jnp.zeros(capacity, jnp.int64),
    )


def _gauge_scan_lanes(v: jnp.ndarray, t: jnp.ndarray):
    """Scan input lanes for a gauge value column: (sum, sum_sq, min,
    max, tmax, last-bits).  Sum lanes exclude NaN (count still carries
    it) but pass +/-inf through — tree-order f64 addition reproduces
    the scatter path's inf/NaN semantics natively and keeps the
    within-segment rounding at ~log2(N) ulps of the segment's own
    magnitude (no cross-segment prefix cancellation)."""
    nan = jnp.isnan(v)
    safe = jnp.where(nan, 0.0, v)
    return (safe, safe * safe, jnp.where(nan, jnp.inf, v),
            jnp.where(nan, -jnp.inf, v), t, v.view(jnp.int64))


def _gauge_scan_combine(a, b):
    """(sum, sum_sq, min, max, tmax, last-bits) segmented combine; last
    is the value of the strictly-greatest time (sorted ties = first
    arrival wins)."""
    return (
        a[0] + b[0],
        a[1] + b[1],
        jnp.minimum(a[2], b[2]),
        jnp.maximum(a[3], b[3]),
        jnp.maximum(a[4], b[4]),
        jnp.where(b[4] > a[4], b[5], a[5]),
    )


def _gauge_gather(sview: _Segments, seg: _Segments, scanned: tuple):
    """Per-slot gauge aggregates from the raw scanned lanes."""
    s_sum, s_sq, s_min, s_max, s_t, s_lastb = scanned
    d_sum = jnp.where(sview.has, _at_ends(sview.end, s_sum), 0.0)
    d_sq = jnp.where(sview.has, _at_ends(sview.end, s_sq), 0.0)
    d_min = jnp.where(sview.has, _at_ends(sview.end, s_min), jnp.inf)
    d_max = jnp.where(sview.has, _at_ends(sview.end, s_max), -jnp.inf)
    d_t = jnp.where(sview.has, _at_ends(sview.end, s_t), I64_MIN)
    d_lastb = _at_ends(sview.end, s_lastb)
    d_tmax = jnp.where(seg.has, _at_ends(seg.end, s_t), I64_MIN)
    return (sview.cnt, d_sum, d_sq, d_min, d_max, d_t, d_lastb), d_tmax


def _gauge_batch_segments(sview: _Segments, seg: _Segments,
                          values: jnp.ndarray, times: jnp.ndarray):
    v = values[seg.perm]
    t = times[seg.perm]
    scanned = _seg_scan(seg, _gauge_scan_lanes(v, t),
                        _gauge_scan_combine)
    return _gauge_gather(sview, seg, scanned)


def _gauge_merge(state: PackedGaugeState, segs, last_at,
                 num_windows: int, capacity: int) -> PackedGaugeState:
    d_cnt, d_sum, d_sq, d_min, d_max, d_t, d_lastb = segs
    has = d_cnt > 0
    replace = has & (d_t > state.last_time)
    return PackedGaugeState(
        sum=jnp.where(has, state.sum + d_sum, state.sum),
        sum_sq=jnp.where(has, state.sum_sq + d_sq, state.sum_sq),
        count=state.count + d_cnt,
        min=jnp.minimum(state.min, d_min),
        max=jnp.maximum(state.max, d_max),
        last_bits=jnp.where(replace, d_lastb, state.last_bits),
        last_time=jnp.where(replace, d_t, state.last_time),
        last_at=last_at,
    )


@functools.partial(
    jax.jit, donate_argnums=0,
    static_argnames=("num_windows", "capacity"))
def gauge_ingest(
    state: PackedGaugeState,
    idx: jnp.ndarray,     # i64 (N,) flat; == W*C drops
    values: jnp.ndarray,  # f64 (N,)
    times: jnp.ndarray,   # i64 (N,)
    num_windows: int,
    capacity: int,
) -> PackedGaugeState:
    wc = num_windows * capacity
    seg = _segment_view(idx, wc + capacity)
    d, d_tmax = _gauge_batch_segments(_stats_view(seg, wc), seg,
                                      values, times)
    last_at = _merge_last_at(state.last_at, d_tmax, num_windows, capacity)
    return _gauge_merge(state, d, last_at, num_windows, capacity)


@functools.partial(jax.jit, static_argnames=("capacity",))
def gauge_consume(state: PackedGaugeState, window: jnp.ndarray,
                  capacity: int):
    off = window * capacity
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, off, capacity)
    s, ssq, cnt = sl(state.sum), sl(state.sum_sq), sl(state.count)
    cntf = cnt.astype(jnp.float64)
    mx, mn = sl(state.max), sl(state.min)
    mean = jnp.where(cnt == 0, 0.0, s / jnp.where(cnt == 0, 1, cnt))
    lanes = jnp.stack(
        [
            sl(state.last_bits).view(jnp.float64),
            jnp.where(jnp.isinf(mn), jnp.nan, mn),
            jnp.where(jnp.isinf(mx), jnp.nan, mx),
            mean,
            cntf,
            s,
            ssq,
            _stdev(cntf, ssq, s),
        ],
        axis=1,
    )
    return lanes, cnt


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("capacity",))
def gauge_reset_window(state: PackedGaugeState, window: jnp.ndarray,
                       capacity: int) -> PackedGaugeState:
    off = window * capacity
    upd = lambda a, v: jax.lax.dynamic_update_slice_in_dim(
        a, jnp.full(capacity, v, a.dtype), off, 0)
    return state._replace(
        sum=upd(state.sum, 0.0),
        sum_sq=upd(state.sum_sq, 0.0),
        count=upd(state.count, 0),
        min=upd(state.min, jnp.inf),
        max=upd(state.max, -jnp.inf),
        last_bits=upd(state.last_bits, 0),
        last_time=upd(state.last_time, 0),
    )


@functools.partial(
    jax.jit, donate_argnums=0,
    static_argnames=("num_windows", "capacity"))
def gauge_clear_slots(state: PackedGaugeState, slots: jnp.ndarray,
                      num_windows: int, capacity: int) -> PackedGaugeState:
    idx = (jnp.arange(num_windows, dtype=jnp.int64)[:, None] * capacity
           + slots[None, :]).ravel()
    idx = jnp.where(
        (slots[None, :] >= capacity).repeat(num_windows, 0).ravel(),
        num_windows * capacity, idx)
    return state._replace(
        sum=state.sum.at[idx].set(0.0, mode="drop"),
        sum_sq=state.sum_sq.at[idx].set(0.0, mode="drop"),
        count=state.count.at[idx].set(0, mode="drop"),
        min=state.min.at[idx].set(jnp.inf, mode="drop"),
        max=state.max.at[idx].set(-jnp.inf, mode="drop"),
        last_bits=state.last_bits.at[idx].set(0, mode="drop"),
        last_time=state.last_time.at[idx].set(0, mode="drop"),
        last_at=state.last_at.at[slots].set(0, mode="drop"),
    )


# ---------------------------------------------------------------------------
# Fused counter+gauge rollup ingest (one sort serves both arenas — the
# sharded step / bench shape, where one routed batch feeds every type)
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit, donate_argnums=(0, 1),
    static_argnames=("num_windows", "capacity", "widths", "promote_k"))
def rollup_ingest(
    cstate: PackedCounterState,
    gstate: PackedGaugeState,
    idx: jnp.ndarray,      # i64 (N,) flat; == W*C drops
    cvalues: jnp.ndarray,  # i64 (N,)
    gvalues: jnp.ndarray,  # f64 (N,)
    times: jnp.ndarray,    # i64 (N,)
    num_windows: int,
    capacity: int,
    widths: tuple = DEFAULT_WIDTHS,
    promote_k: int = PROMOTE_K,
):
    wc = num_windows * capacity
    seg = _segment_view(idx, wc + capacity)
    sview = _stats_view(seg, wc)
    cv = cvalues[seg.perm]
    gv = gvalues[seg.perm]
    t = times[seg.perm]
    c_sum, c_sq, wide = _counter_sums(sview, cv)
    (d_wide,) = _seg_flag_counts(sview, (wide,))

    # ONE scan serves both arenas: counter min/max lanes prepended to
    # the gauge lane set (which shares the time column for last/last_at)
    def combine(a, b):
        return (jnp.minimum(a[0], b[0]), jnp.maximum(a[1], b[1])) \
            + _gauge_scan_combine(a[2:], b[2:])

    scanned = _seg_scan(seg, (cv, cv) + _gauge_scan_lanes(gv, t),
                        combine)
    c_min = jnp.where(sview.has, _at_ends(sview.end, scanned[0]),
                      I64_MAX)
    c_max = jnp.where(sview.has, _at_ends(sview.end, scanned[1]),
                      I64_MIN)
    gd, d_tmax = _gauge_gather(sview, seg, scanned[2:])

    c_last = _merge_last_at(cstate.last_at, d_tmax, num_windows, capacity)
    g_last = _merge_last_at(gstate.last_at, d_tmax, num_windows, capacity)
    cd = (sview.cnt, c_sum, c_sq, c_min, c_max, d_wide)
    return (_counter_merge(cstate, cd, c_last, num_windows, capacity,
                           widths, promote_k),
            _gauge_merge(gstate, gd, g_last, num_windows, capacity))


# ---------------------------------------------------------------------------
# Packed timer arena: u64 sample words, moments recovered at drain
# ---------------------------------------------------------------------------


class PackedTimerState(NamedTuple):
    sample: jnp.ndarray    # u64 (W, S) slot<<32 | orderable_f32(value)
    sample_n: jnp.ndarray  # i64 (W,) write offsets (> S = overflow)
    last_at: jnp.ndarray   # i64 (C,)


def _timer_empty_word(capacity: int) -> int:
    """The empty-sentinel sample word: slot == capacity sorts past every
    real slot (python int: safe under the tracer)."""
    return capacity << 32


def timer_init(num_windows: int, capacity: int,
               sample_capacity: int) -> PackedTimerState:
    empty = _timer_empty_word(capacity)
    return PackedTimerState(
        sample=jnp.full((num_windows, sample_capacity), empty, jnp.uint64),
        sample_n=jnp.zeros(num_windows, jnp.int64),
        last_at=jnp.zeros(capacity, jnp.int64),
    )


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("capacity",))
def timer_ingest(
    state: PackedTimerState,
    windows: jnp.ndarray,  # i32 (N,) ring index; OOB drops
    slots: jnp.ndarray,    # i32 (N,)
    values: jnp.ndarray,   # f64 (N,)
    times: jnp.ndarray,    # i64 (N,)
    capacity: int,
) -> PackedTimerState:
    """Append a batch as packed words — ONE scatter.  Moments are
    recovered at drain from the sorted buffer, so the only other work
    is the shared append plan (arena.timer_append_plan) and the
    last_at expiry column."""
    num_w, scap = state.sample.shape
    _drop, flat, per_w_counts = timer_append_plan(
        windows, slots, state.sample_n, capacity, scap)
    word = (slots.astype(jnp.uint64) << jnp.uint64(32)) | orderable_f32(
        values)
    slot_safe = _sanitize_slots(slots, capacity)
    return PackedTimerState(
        sample=state.sample.ravel().at[flat].set(
            word, mode="drop").reshape(num_w, scap),
        sample_n=state.sample_n + per_w_counts,
        last_at=state.last_at.at[slot_safe].max(times, mode="drop"),
    )


@functools.partial(jax.jit, static_argnames=("capacity", "quantiles"))
def timer_consume(
    state: PackedTimerState,
    window: jnp.ndarray,
    capacity: int,
    quantiles: tuple,
):
    """Drain one window: sort the packed words (slot asc, value asc in
    f32 order), then counts from boundaries, sum/sum_sq from an exact
    fixed-point cumsum of the decoded values (f32 value precision — the
    packed32 1e-6 envelope), min/max/quantiles from rank positions."""
    num_w, scap = state.sample.shape
    words = jax.lax.dynamic_index_in_dim(state.sample, window,
                                         keepdims=False)
    keys = jax.lax.sort(words)
    s_slot = (keys >> jnp.uint64(32)).astype(jnp.int32)
    s_val = decode_orderable_f32(keys & jnp.uint64(0xFFFFFFFF))

    qs = jnp.arange(capacity, dtype=jnp.int32)
    seg_start = jnp.searchsorted(s_slot, qs)
    seg_end = jnp.searchsorted(s_slot, qs, side="right")
    seg_n = (seg_end - seg_start).astype(jnp.int64)
    empty = seg_n == 0

    # Moments from a segmented scan over the sorted words: tree-order
    # f64 adds keep rounding at ~log2(S) ulps of each segment's own
    # magnitude, and real non-finite samples flow through with the f64
    # semantics (inf sums, NaN poisons).  Empty-sentinel words decode
    # to NaN and are masked out.
    valid = s_slot < capacity
    v = jnp.where(valid, s_val, 0.0)
    head = jnp.concatenate(
        [jnp.ones(1, bool), s_slot[1:] != s_slot[:-1]])

    def op(a, b):
        fa, sa, qa = a
        fb, sb, qb = b
        return (fa | fb, jnp.where(fb, sb, sa + sb),
                jnp.where(fb, qb, qa + qb))

    _, s_sums, s_sqs = jax.lax.associative_scan(
        op, (head, v, v * v))
    lp = jnp.clip(seg_end.astype(jnp.int64) - 1, 0, scap - 1)
    s = jnp.where(empty, 0.0, s_sums[lp])
    ssq = jnp.where(empty, 0.0, s_sqs[lp])
    cntf = seg_n.astype(jnp.float64)
    mean = jnp.where(empty, 0.0, s / jnp.where(empty, 1.0, cntf))

    mn = jnp.where(empty, 0.0, s_val[jnp.clip(seg_start, 0, scap - 1)])
    mx = jnp.where(empty, 0.0, s_val[jnp.clip(seg_end - 1, 0, scap - 1)])

    qlanes = []
    for q in quantiles:
        ranks = jnp.ceil(q * cntf).astype(jnp.int64) - 1
        ranks = jnp.clip(ranks, 0, jnp.maximum(seg_n - 1, 0))
        qv = s_val[jnp.clip(seg_start + ranks, 0, scap - 1)]
        qlanes.append(jnp.where(empty, 0.0, qv))

    lanes = jnp.stack(
        [
            jnp.full(capacity, jnp.nan, jnp.float64),  # LAST
            mn,
            mx,
            mean,
            cntf,
            s,
            ssq,
            _stdev(cntf, ssq, s),
            *qlanes,
        ],
        axis=1,
    )
    return lanes, seg_n


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("capacity",))
def timer_reset_window(state: PackedTimerState, window: jnp.ndarray,
                       capacity: int) -> PackedTimerState:
    num_w, scap = state.sample.shape
    empty = _timer_empty_word(capacity)
    return PackedTimerState(
        sample=jax.lax.dynamic_update_slice(
            state.sample,
            jnp.full((1, scap), empty, jnp.uint64),
            (window.astype(jnp.int32), jnp.int32(0)),
        ),
        sample_n=state.sample_n.at[window].set(0),
        last_at=state.last_at,
    )


@functools.partial(
    jax.jit, donate_argnums=0,
    static_argnames=("num_windows", "capacity"))
def timer_clear_slots(state: PackedTimerState, slots: jnp.ndarray,
                      num_windows: int, capacity: int) -> PackedTimerState:
    """Retarget cleared slots' buffered words to the empty sentinel so a
    recycled slot's quantiles can't include the previous occupant."""
    empty = jnp.uint64(_timer_empty_word(capacity))
    sorted_slots = jnp.sort(slots.astype(jnp.int32))
    flat = state.sample.ravel()
    wslot = (flat >> jnp.uint64(32)).astype(jnp.int32)
    pos = jnp.clip(jnp.searchsorted(sorted_slots, wslot), 0,
                   sorted_slots.shape[0] - 1)
    hit = sorted_slots[pos] == wslot
    return PackedTimerState(
        sample=jnp.where(hit, empty, flat).reshape(state.sample.shape),
        sample_n=state.sample_n,
        last_at=state.last_at.at[slots].set(0, mode="drop"),
    )


# ---------------------------------------------------------------------------
# Host wrappers (drop-in for arena.CounterArena / GaugeArena / TimerArena)
# ---------------------------------------------------------------------------


class PackedCounterArena(_ScalarLanesMixin):
    """Packed counter slots: adaptive-width base + overflow pool."""

    def __init__(self, num_windows: int, capacity: int,
                 pool_capacity: int | None = None,
                 widths: tuple = DEFAULT_WIDTHS,
                 promote_k: int = PROMOTE_K):
        self.num_windows = num_windows
        self.capacity = capacity
        self.widths = tuple(widths)
        self.promote_k = promote_k
        self._mem = membudget.reserve(
            "aggregator.counter",
            membudget.counter_arena_bytes("packed", num_windows, capacity,
                                          pool_capacity),
            owner=self)
        self.state = counter_init(num_windows, capacity, pool_capacity,
                                  self.widths)

    def _check_err(self):
        err = int(self.state.err)
        if err:
            what = []
            if err & _ERR_PROMOTE_K:
                what.append(f"more than promote_k={self.promote_k} pool "
                            "promotions/updates in one batch")
            if err & _ERR_POOL_FULL:
                what.append("overflow pool exhausted")
            # Raise ONCE, then clear: the flag marks stats since the
            # last check as unreliable; the window ring's drain+reset
            # cycle washes the clipped rows out within W drains, so a
            # transient burst must not wedge every later flush forever.
            # A recurring condition re-sets the flag and raises again.
            self.state = self.state._replace(err=jnp.int32(0))
            # DeviceStateError (a RuntimeError): resident arena state
            # is unreliable — typed so the device guard's classifier
            # and the engine's degrade paths see it as the state
            # poisoning it is, not a generic crash.
            raise devguard.DeviceStateError(
                "arena.consume",
                "packed counter arena overflow-pool error: "
                + "; ".join(what)
                + " — grow pool_capacity/promote_k or use the f64 layout"
                " (M3_ARENA_LAYOUT=f64); stats since the previous "
                "consume are unreliable (flag cleared: the window ring "
                "washes the damage out over the next drains)")

    def ingest(self, windows, slots, values, times):
        idx = packed_flat_index(jnp.asarray(windows), jnp.asarray(slots),
                                self.num_windows, self.capacity)
        # the packed formulation is already the jnp path — the guard's
        # fallback re-runs it with the faultpoints skipped (impl unused)
        self.state = _guarded_ingest(lambda impl: counter_ingest(
            self.state, idx, jnp.asarray(values).astype(jnp.int64),
            jnp.asarray(times), self.num_windows, self.capacity,
            self.widths, self.promote_k))

    def consume(self, window: int):
        self._check_err()
        return _guarded_consume(lambda: counter_consume(
            self.state, jnp.int32(window), self.capacity, self.widths))

    def reset_window(self, window: int):
        self.state = _guarded_state_op(lambda: counter_reset_window(
            self.state, jnp.int32(window), self.num_windows,
            self.capacity, self.widths))

    def clear_slots(self, slots):
        self.state = _guarded_state_op(lambda: counter_clear_slots(
            self.state,
            jnp.asarray(pad_slots(np.asarray(slots), self.capacity)),
            self.num_windows, self.capacity, self.widths))


class PackedGaugeArena(_ScalarLanesMixin):
    def __init__(self, num_windows: int, capacity: int):
        self.num_windows = num_windows
        self.capacity = capacity
        self._mem = membudget.reserve(
            "aggregator.gauge",
            membudget.gauge_arena_bytes("packed", num_windows, capacity),
            owner=self)
        self.state = gauge_init(num_windows, capacity)

    def ingest(self, windows, slots, values, times):
        idx = packed_flat_index(jnp.asarray(windows), jnp.asarray(slots),
                                self.num_windows, self.capacity)
        self.state = _guarded_ingest(lambda impl: gauge_ingest(
            self.state, idx, jnp.asarray(values).astype(jnp.float64),
            jnp.asarray(times), self.num_windows, self.capacity))

    def consume(self, window: int):
        return _guarded_consume(lambda: gauge_consume(
            self.state, jnp.int32(window), self.capacity))

    def reset_window(self, window: int):
        self.state = _guarded_state_op(lambda: gauge_reset_window(self.state, jnp.int32(window),
                                        self.capacity))

    def clear_slots(self, slots):
        self.state = _guarded_state_op(lambda: gauge_clear_slots(
            self.state,
            jnp.asarray(pad_slots(np.asarray(slots), self.capacity)),
            self.num_windows, self.capacity))


class PackedTimerArena(_TimerLanesMixin):
    DEFAULT_QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, num_windows: int, capacity: int,
                 sample_capacity: int,
                 quantiles: tuple = DEFAULT_QUANTILES):
        self.num_windows = num_windows
        self.capacity = capacity
        self.sample_capacity = sample_capacity
        self.quantiles = tuple(quantiles)
        self._mem = membudget.reserve(
            "aggregator.timer",
            membudget.timer_arena_bytes("packed", num_windows, capacity,
                                        sample_capacity),
            owner=self)
        self.state = timer_init(num_windows, capacity, sample_capacity)
        self._sample_n_host = np.zeros(num_windows, np.int64)

    def ingest(self, windows, slots, values, times):
        windows_np = np.asarray(windows)
        slots_np = np.asarray(slots)
        in_range = ((windows_np >= 0) & (windows_np < self.num_windows)
                    & (slots_np >= 0) & (slots_np < self.capacity))
        per_w = np.bincount(windows_np[in_range],
                            minlength=self.num_windows)
        # Commit-after-success (the ShardBuffer.write pattern): a
        # _grow budget reject or device failure must leave the shadow
        # mirroring state.sample_n, or every later batch re-rejects.
        new_n = self._sample_n_host + per_w
        needed = int(new_n.max())
        if needed > self.sample_capacity:
            self._grow(needed)
        self.state = _guarded_ingest(lambda impl: timer_ingest(
            self.state, jnp.asarray(windows_np.astype(np.int32)),
            jnp.asarray(slots_np.astype(np.int32)),
            jnp.asarray(values).astype(jnp.float64),
            jnp.asarray(times), self.capacity))
        self._sample_n_host = new_n

    def _grow(self, needed: int) -> None:
        new_cap = self.sample_capacity
        while new_cap < needed:
            new_cap *= 2
        self._mem.resize(membudget.timer_arena_bytes(
            "packed", self.num_windows, self.capacity, new_cap))
        pad = new_cap - self.sample_capacity
        empty = np.uint64(_timer_empty_word(self.capacity))
        self.state = PackedTimerState(
            sample=jnp.pad(self.state.sample, ((0, 0), (0, pad)),
                           constant_values=empty),
            sample_n=self.state.sample_n,
            last_at=self.state.last_at,
        )
        self.sample_capacity = new_cap

    def consume(self, window: int):
        return _guarded_consume(lambda: timer_consume(
            self.state, jnp.int32(window), self.capacity, self.quantiles))

    def reset_window(self, window: int):
        self.state = _guarded_state_op(lambda: timer_reset_window(self.state, jnp.int32(window),
                                        self.capacity))
        self._sample_n_host[window] = 0

    def clear_slots(self, slots):
        self.state = _guarded_state_op(lambda: timer_clear_slots(
            self.state,
            jnp.asarray(pad_slots(np.asarray(slots), self.capacity)),
            self.num_windows, self.capacity))
