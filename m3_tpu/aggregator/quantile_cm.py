"""Host-side Cormode–Muthukrishnan biased-quantile stream, faithful to the
reference (``src/aggregator/aggregation/quantile/cm/stream.go``).

The device path (arena.TimerArena) computes **exact** window quantiles via
sort — always within the CM eps bound — so this implementation exists as
(a) the parity oracle for tests comparing device quantiles against
reference-algorithm outputs, and (b) the fallback for host-only deploys.

Algorithm parity points:
* two buffers (bufLess/bufMore) around an insertion cursor, swapped on
  cursor reset (stream.go:96-116,428-432);
* insert walks the sample list forward, inserting each pending value v
  before the first sample >= v with (numRanks=1, delta=rank spread)
  (stream.go:280-338);
* compress walks backward merging samples whose combined rank span stays
  under the biased threshold (stream.go:342-401);
* quantile computation scans for the first sample whose maxRank exceeds
  rank+threshold/2 and returns the previous sample (stream.go:231-277).

Defaults mirror cm/options.go: eps=1e-3, insertAndCompressEvery=1024.
"""

from __future__ import annotations

import heapq
import math
from typing import List, Sequence

_MIN_SAMPLES_TO_COMPRESS = 3
DEFAULT_EPS = 1e-3
DEFAULT_INSERT_AND_COMPRESS_EVERY = 1024


class _Sample:
    __slots__ = ("value", "num_ranks", "delta", "prev", "next")

    def __init__(self, value: float = 0.0, num_ranks: int = 0, delta: int = 0):
        self.value = value
        self.num_ranks = num_ranks
        self.delta = delta
        self.prev: _Sample | None = None
        self.next: _Sample | None = None


class _SampleList:
    """Doubly-linked sample list (reference cm/list.go)."""

    __slots__ = ("head", "tail", "length")

    def __init__(self):
        self.head: _Sample | None = None
        self.tail: _Sample | None = None
        self.length = 0

    def push_back(self, s: _Sample) -> None:
        s.prev, s.next = self.tail, None
        if self.tail is not None:
            self.tail.next = s
        else:
            self.head = s
        self.tail = s
        self.length += 1

    def insert_before(self, s: _Sample, at: _Sample) -> None:
        prev = at.prev
        s.prev, s.next = prev, at
        at.prev = s
        if prev is not None:
            prev.next = s
        else:
            self.head = s
        self.length += 1

    def remove(self, s: _Sample) -> None:
        if s.prev is not None:
            s.prev.next = s.next
        else:
            self.head = s.next
        if s.next is not None:
            s.next.prev = s.prev
        else:
            self.tail = s.prev
        s.prev = s.next = None
        self.length -= 1


class Stream:
    """CM biased-quantile stream (reference cm/stream.go:41-59)."""

    def __init__(
        self,
        quantiles: Sequence[float],
        eps: float = DEFAULT_EPS,
        insert_and_compress_every: int = DEFAULT_INSERT_AND_COMPRESS_EVERY,
    ):
        self.quantiles = list(quantiles)
        self.eps = eps
        self.insert_and_compress_every = insert_and_compress_every
        self.samples = _SampleList()
        self.buf_less: List[float] = []  # min-heaps, as in cm/heap.go
        self.buf_more: List[float] = []
        self.insert_cursor: _Sample | None = None
        self.compress_cursor: _Sample | None = None
        self.compress_min_rank = 0
        self.num_values = 0
        self.insert_and_compress_counter = 0
        self.computed_quantiles = [math.nan] * len(self.quantiles)
        self.flushed = False

    # -- ingestion (stream.go:77-116) ------------------------------------

    def add(self, value: float) -> None:
        self.add_batch([value])

    def add_batch(self, values: Sequence[float]) -> None:
        self.flushed = False
        if not values:
            return
        i = 0
        if self.samples.length == 0:
            s = _Sample(values[0], 1, 0)
            self.samples.push_back(s)
            self.insert_cursor = self.samples.head
            self.num_values += 1
            i = 1

        insert_point_value = self.insert_cursor.value
        counter = self.insert_and_compress_counter
        for value in values[i:]:
            if value < insert_point_value:
                heapq.heappush(self.buf_less, value)
            else:
                heapq.heappush(self.buf_more, value)
            if counter == self.insert_and_compress_every:
                self._insert()
                self._compress()
                counter = 0
            counter += 1
        self.insert_and_compress_counter = counter

    # -- flush / query (stream.go:123-171) -------------------------------

    def flush(self) -> None:
        if self.flushed:
            return
        while self.buf_less or self.buf_more:
            if not self.buf_more:
                self._reset_insert_cursor()
            self._insert()
            self._compress()
        self._calc_quantiles()
        self.flushed = True

    def min(self) -> float:
        return self.quantile(0.0)

    def max(self) -> float:
        return self.quantile(1.0)

    def quantile(self, q: float) -> float:
        if q < 0.0 or q > 1.0:
            return math.nan
        if self.samples.length == 0:
            return 0.0
        if q == 0.0:
            return self.samples.head.value
        if q == 1.0:
            return self.samples.tail.value
        for i, qt in enumerate(self.quantiles):
            if qt >= q:
                return self.computed_quantiles[i]
        return math.nan

    # -- internals --------------------------------------------------------

    def _calc_quantiles(self) -> None:
        """stream.go:231-277."""
        if not self.quantiles or self.num_values == 0:
            return
        if self.num_values <= _MIN_SAMPLES_TO_COMPRESS:
            buf = []
            curr = self.samples.head
            while curr is not None:
                buf.append(curr.value)
                curr = curr.next
            n = len(buf)
            for i, q in enumerate(self.quantiles):
                idx = min(int(q * n), n - 1)
                self.computed_quantiles[i] = buf[idx]
            return

        thresholds = []
        for q in self.quantiles:
            rank = math.ceil(q * self.num_values)
            thresholds.append((rank, math.ceil(self._threshold(rank) / 2.0)))

        min_rank = 0
        max_rank = 0
        idx = 0
        curr = self.samples.head
        prev = self.samples.head
        while curr is not None and idx < len(self.computed_quantiles):
            max_rank = min_rank + curr.num_ranks + curr.delta
            rank, threshold = thresholds[idx]
            if max_rank > rank + threshold or min_rank > rank:
                self.computed_quantiles[idx] = prev.value
                idx += 1
            min_rank += curr.num_ranks
            prev = curr
            curr = curr.next

        for i in range(idx, len(thresholds)):
            rank, threshold = thresholds[i]
            if max_rank >= rank + threshold or min_rank > rank:
                self.computed_quantiles[i] = prev.value

    def _insert(self) -> None:
        """stream.go:280-338."""
        comp_value = (
            self.compress_cursor.value if self.compress_cursor is not None else math.nan
        )
        # Reference sorts bufMore descending and consumes from the end
        # (ascending); an ascending sort consumed front-to-back matches.
        vals = sorted(self.buf_more)
        pos = 0
        n = len(vals)

        while self.insert_cursor is not None and pos < n:
            curr = self.insert_cursor
            insert_point_value = curr.value
            while pos < n and vals[pos] <= insert_point_value:
                val = vals[pos]
                pos += 1
                s = _Sample(val, 1, curr.num_ranks + curr.delta - 1)
                self.samples.insert_before(s, curr)
                if comp_value >= val:  # NaN compare false, as in Go
                    self.compress_min_rank += 1
                self.num_values += 1
            self.insert_cursor = self.insert_cursor.next

        if self.insert_cursor is None and pos < n:
            back = self.samples.tail
            while pos < n and vals[pos] >= back.value:
                val = vals[pos]
                pos += 1
                s = _Sample(val, 1, 0)
                self.samples.push_back(s)
                back = self.samples.tail
                self.num_values += 1

        self.buf_more = []
        self._reset_insert_cursor()

    def _compress(self) -> None:
        """stream.go:342-397."""
        if self.samples.length < _MIN_SAMPLES_TO_COMPRESS:
            return
        if self.compress_cursor is None:
            self.compress_cursor = self.samples.tail.prev
            self.compress_min_rank = (
                self.num_values - 1 - self.compress_cursor.num_ranks
            )
            self.compress_cursor = self.compress_cursor.prev

        num_vals = self.num_values
        eps2 = 2.0 * self.eps
        while self.compress_cursor is not None and self.compress_cursor is not self.samples.head:
            curr = self.compress_cursor
            nxt = curr.next
            prev = curr.prev
            max_rank = self.compress_min_rank + curr.num_ranks + curr.delta

            threshold = None
            for q in self.quantiles:
                if max_rank >= int(q * num_vals):
                    quantile_min = int(eps2 * max_rank / q)
                else:
                    quantile_min = int(eps2 * (num_vals - max_rank) / (1.0 - q))
                if threshold is None or quantile_min < threshold:
                    threshold = quantile_min

            self.compress_min_rank -= curr.num_ranks
            test_val = curr.num_ranks + nxt.num_ranks + nxt.delta
            if threshold is not None and test_val <= threshold:
                if self.insert_cursor is curr:
                    self.insert_cursor = nxt
                nxt.num_ranks += curr.num_ranks
                self.samples.remove(curr)
            self.compress_cursor = prev

        if self.compress_cursor is self.samples.head:
            self.compress_cursor = None

    def _threshold(self, rank: int) -> int:
        """stream.go:403-423."""
        min_val = None
        eps2 = 2.0 * self.eps
        for q in self.quantiles:
            if rank >= int(q * self.num_values):
                quantile_min = int(eps2 * rank / q)
            else:
                quantile_min = int(eps2 * (self.num_values - rank) / (1.0 - q))
            if min_val is None or quantile_min < min_val:
                min_val = quantile_min
        return min_val if min_val is not None else 0

    def _reset_insert_cursor(self) -> None:
        self.buf_less, self.buf_more = self.buf_more, self.buf_less
        self.insert_cursor = self.samples.head
