"""Device-side aggregation arenas: per-(window, slot) statistic tensors.

This is the TPU re-design of the reference's per-metric aggregation
objects (``src/aggregator/aggregation/counter.go:31-70``, ``gauge.go:31-99``,
``timer.go:31-100``) and the window-keyed element values
(``src/aggregator/aggregator/generic_elem.go:181-196`` AddUnion window
alignment).  Instead of one heap object per (metric, window), each metric
type owns flat statistic tensors of shape ``(W * C,)`` — a ring of W
resolution windows by C metric slots — and an ingest batch is a handful of
scatter reductions:

    sum/count/sumsq  ->  .at[idx].add
    min/max          ->  .at[idx].min / .at[idx].max
    last (by time)   ->  lexicographic sort (slot, time, -arrival) +
                         conditional scatter of per-slot winners

Timer quantiles are **exact**: samples append into a per-window device
buffer; flush lex-sorts (slot, value) pairs and reads ranks
``ceil(q*n)`` per segment — stronger than the reference's
Cormode-Muthukrishnan eps-approximate stream (quantile/cm/stream.go), and
TPU-shaped (one big radix sort instead of pointer chasing).  A
bit-faithful host CM stream lives in ``quantile_cm.py`` for parity tests.

All 22 aggregation outputs (src/metrics/aggregation/type.go:34-55) are
computed as lanes of a (C, L) matrix at window drain; the caller masks
lanes by each slot's compressed AggregationID.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from m3_tpu.metrics.aggregation import AggregationType
from m3_tpu.x import devguard, membudget

I64_MIN = np.iinfo(np.int64).min
I64_MAX = np.iinfo(np.int64).max

# Fixed output-lane order for non-quantile statistics.  Quantile lanes are
# appended after these, in the order of the arena's `quantiles` tuple.
SCALAR_LANES = (
    AggregationType.LAST,
    AggregationType.MIN,
    AggregationType.MAX,
    AggregationType.MEAN,
    AggregationType.COUNT,
    AggregationType.SUM,
    AggregationType.SUM_SQ,
    AggregationType.STDEV,
)


def raw(jitted):
    """The traceable python function behind a jitted arena op, for
    composing arena ops inside larger jit/shard_map programs."""
    return getattr(jitted, "__wrapped__", jitted)


# ---------------------------------------------------------------------------
# Ingest implementation selection, M3_ARENA_INGEST=scatter|pallas
# or set_ingest_impl():
#   scatter — XLA scatter ops (default; fastest on XLA-CPU).
#   pallas  — binned segment reduction kernel (parallel/pallas_ingest.py):
#             built for TPU, where scatter measured ~1us/element at C=1M
#             (TPU_RESULTS_r05.json window #3); also wins on CPU when
#             slot collisions serialize the scatter AND the flat arena
#             (W*C) is moderate.
# (A third sort/scan/gather impl — parallel/sorted_ingest.py — was
# deleted in round 6: BENCH_r05 measured it at 0.45-0.50x of scatter on
# CPU and it was never validated faster on real TPU hardware.  Its
# generic segmented-scan helpers live on in parallel/segmented.py.)
# The bench's rollup/timer stages time the candidates side by side.
# The choice binds at TRACE time, so set_ingest_impl clears the arena
# jit caches — jits composed elsewhere via raw() keep whatever impl
# they traced with.
# ---------------------------------------------------------------------------

INGEST_IMPLS = ("scatter", "pallas", "auto")
_INGEST_IMPLS = INGEST_IMPLS  # back-compat alias
_INGEST_IMPL = (os.environ.get("M3_ARENA_INGEST", "").strip().lower()
                or "scatter")
if _INGEST_IMPL not in _INGEST_IMPLS:
    raise ValueError(
        f"M3_ARENA_INGEST={_INGEST_IMPL!r}: must be one of {_INGEST_IMPLS} "
        "(a typo silently running scatter would invalidate the very "
        "measurement the flag exists to apply)")


def ingest_impl() -> str:
    """The CONFIGURED impl (may be 'auto'); see resolved_ingest_impl."""
    return _INGEST_IMPL


def resolved_ingest_impl() -> str:
    """'auto' resolves per backend: scatter where XLA's scatter is fast
    (CPU), the Pallas kernel where scatter measured ~1us/element (TPU —
    TPU_RESULTS_r05.json window #3).  Resolution happens at trace
    time, so a backend can't change under an already-compiled arena."""
    if _INGEST_IMPL != "auto":
        return _INGEST_IMPL
    import jax

    return "pallas" if jax.default_backend() == "tpu" else "scatter"


# Jitted programs that COMPOSE raw(ingest) ops and must be re-traced
# when the impl flips (e.g. parallel/sharded_agg's sharded programs).
# Modules register theirs via register_ingest_consumer at import time.
_INGEST_CONSUMERS: list = []


def register_ingest_consumer(jitted) -> None:
    _INGEST_CONSUMERS.append(jitted)


def set_ingest_impl(impl: str) -> None:
    global _INGEST_IMPL
    if impl not in _INGEST_IMPLS:
        raise ValueError(f"unknown ingest impl {impl!r}")
    _INGEST_IMPL = impl
    for f in (counter_ingest, gauge_ingest, timer_ingest,
              *_INGEST_CONSUMERS):
        try:
            f.clear_cache()
        except AttributeError:  # raw function or older jax
            pass


# ---------------------------------------------------------------------------
# Arena layout selection, M3_ARENA_LAYOUT=packed|f64|auto (default auto)
# or set_arena_layout():
#   packed — the sort/segment formulation + adaptive-width counter state
#            (aggregator/packed.py): one u64 key sort per ingest batch,
#            dense merges, no hot-path scatter.  Counter stats exact,
#            gauge sum/sum_sq within 1e-6 of the f64 path (segmented
#            tree adds), timer value lanes at f32 (packed32) precision.
#   f64    — the original scatter arenas in THIS module: the parity
#            oracle, bit-exact reference semantics throughout.
#   auto   — packed (faster on both measured backends: CPU avoids the
#            ~60ns/elt scatter floor, TPU its ~1us/elt scatter).
# Resolution happens on the HOST at arena construction (tracewatch
# contract: nothing reads the environment under a tracer) — engine
# arenas bind their layout at __init__, the sharded program takes it as
# a static argument.
# ---------------------------------------------------------------------------

LAYOUTS = ("packed", "f64", "auto")
_LAYOUT = (os.environ.get("M3_ARENA_LAYOUT", "").strip().lower()
           or "auto")
if _LAYOUT not in LAYOUTS:
    raise ValueError(
        f"M3_ARENA_LAYOUT={_LAYOUT!r}: must be one of {LAYOUTS} "
        "(a typo silently running the default would invalidate the very "
        "comparison the flag exists to make)")


def arena_layout() -> str:
    """The CONFIGURED layout (may be 'auto'); see resolved_arena_layout."""
    return _LAYOUT


def resolved_arena_layout() -> str:
    """'auto' resolves to 'packed' on every backend: the sort/segment
    formulation wins on CPU (no scatter floor) and by construction on
    TPU (scatter measured ~1us/element there).  'f64' remains the
    explicit parity-oracle escape hatch."""
    return "packed" if _LAYOUT == "auto" else _LAYOUT


def set_arena_layout(layout: str) -> None:
    """Host-side layout override (bench/tests).  Arenas bind layout at
    construction, so this affects arenas built AFTER the call."""
    global _LAYOUT
    if layout not in LAYOUTS:
        raise ValueError(f"unknown arena layout {layout!r}")
    _LAYOUT = layout


def resolve_layout_arg(layout: str | None) -> str:
    """Resolve a per-call/per-engine layout argument to a CONCRETE
    layout: None/"" follow the configured seam, an explicit "auto"
    resolves to packed, and anything else must be a known layout — a
    typo silently selecting some default would invalidate the very
    comparison the seam exists to make (the env guard's rationale,
    applied to the programmatic path too)."""
    if not layout:
        return resolved_arena_layout()
    if layout == "auto":
        return "packed"
    if layout not in LAYOUTS:
        raise ValueError(
            f"unknown arena layout {layout!r}: must be one of {LAYOUTS}")
    return layout


def make_arenas(num_windows: int, capacity: int, sample_capacity: int,
                quantiles: tuple, timer_packed32: bool = False,
                layout: str | None = None):
    """(counter, gauge, timer) arenas for a layout (None = resolved
    seam) — the one construction seam engine.py and tests share."""
    layout = resolve_layout_arg(layout)
    if layout == "packed":
        from m3_tpu.aggregator import packed

        return (packed.PackedCounterArena(num_windows, capacity),
                packed.PackedGaugeArena(num_windows, capacity),
                packed.PackedTimerArena(num_windows, capacity,
                                        sample_capacity, quantiles))
    return (CounterArena(num_windows, capacity),
            GaugeArena(num_windows, capacity),
            TimerArena(num_windows, capacity, sample_capacity,
                       quantiles, packed32=timer_packed32))


def _seg3(sum_col, sq_col, cnt_col, idx, values, impl: str | None = None):
    """The sum / sum² / count accumulation every arena shares, routed
    through the configured implementation.  ``idx`` >= len(sum_col)
    drops (the sentinel contract) on both paths.  The pallas path
    computes all three lanes in ONE batch sweep
    (pallas_segment_moments: the hit mask is shared).  ``impl`` pins
    the choice explicitly (the arena wrappers thread it as a STATIC
    jit argument so the device guard's fallback — pallas → scatter —
    needs no cache clearing and never retraces); None keeps the
    trace-time resolved seam for raw() composition (sharded_agg)."""
    if (impl or resolved_ingest_impl()) == "pallas":
        from m3_tpu.parallel import pallas_ingest as pi

        n_out = sum_col.shape[0]
        s, c, sq = pi.segment_moments_chunked(
            idx.astype(jnp.int32), values, n_out)
        return (sum_col + s, sq_col + sq,
                cnt_col + c.astype(cnt_col.dtype))
    return (sum_col.at[idx].add(values, mode="drop"),
            sq_col.at[idx].add(values * values, mode="drop"),
            cnt_col.at[idx].add(1, mode="drop"))


def pad_slots(slots: np.ndarray, capacity: int) -> np.ndarray:
    """Pad a slot array to the next power of two with the drop sentinel
    (slot == capacity scatters out of range under mode='drop'), bounding
    the number of distinct shapes the *_clear_slots jits see."""
    n = max(1, len(slots))
    padded = 1 << (n - 1).bit_length()
    out = np.full(padded, capacity, np.int32)
    out[: len(slots)] = slots
    return out


def flat_window_index(windows, slots, num_windows: int, capacity: int):
    """Flatten (window ring index, slot) to the arena's (W*C,) index;
    out-of-ring windows AND out-of-range slots map to the drop sentinel
    W*C.  Without the slot check, a valid window with slot >= C would
    compute w*C + slot inside window w+1's region — the exact aliasing
    timer_ingest was fixed for; sentineling here keeps every ingest
    impl parity on ANY input (including pad_slots sentinels and
    negative slots)."""
    oob = ((windows < 0) | (windows >= num_windows)
           | (slots < 0) | (slots >= capacity))
    return jnp.where(
        oob, num_windows * capacity, windows * capacity + slots
    ).astype(jnp.int64)


def _sanitize_slots(slots, capacity: int):
    """Slots for the last_at scatter: a NEGATIVE slot would numpy-wrap
    under mode='drop' (a lowering artifact — it would bump slot C+s's
    expiry), so map it to the drop sentinel C; slots >= C already fall
    out of the (C,) column's range and drop.  Keeps the scatter paths
    on the package-wide contract (invalid indices DROP — also pinned
    by xla_segment_ingest and the pallas kernel)."""
    return jnp.where(slots < 0, capacity, slots)


def orderable_f32(v: jnp.ndarray) -> jnp.ndarray:
    """f64 -> u64 holding order-preserving f32 bits in the low 32
    (IEEE-754 total order as unsigned; negatives flip entirely,
    positives flip the sign bit).  One home for the packed32 bit trick
    — the timer drain here and the packed arena's sample words
    (aggregator/packed.py) must never diverge."""
    b = v.astype(jnp.float32).view(jnp.uint32).astype(jnp.uint64)
    return jnp.where(
        b >= jnp.uint64(0x80000000),
        jnp.uint64(0xFFFFFFFF) - b,
        b | jnp.uint64(0x80000000),
    )


def decode_orderable_f32(bits: jnp.ndarray) -> jnp.ndarray:
    """Inverse of orderable_f32 -> f64 (carries f32 precision)."""
    b = jnp.where(
        bits >= jnp.uint64(0x80000000),
        bits & jnp.uint64(0x7FFFFFFF),
        jnp.uint64(0xFFFFFFFF) - bits,
    )
    return b.astype(jnp.uint32).view(jnp.float32).astype(jnp.float64)


def _stdev(count, sum_sq, sum_):
    """Sample stdev from moments (reference aggregation/common.go:29-36).

    ``count*sum_sq - sum^2`` suffers catastrophic cancellation when the
    mean dwarfs the spread (mean ~1e9, stdev ~1 leaves no mantissa bits
    for the variance): the true difference can round to a small
    NEGATIVE number.  Clamp at 0 — the earlier ``abs()`` fabricated a
    spurious stdev out of the cancellation noise instead."""
    div = count * (count - 1)
    num = jnp.maximum(count * sum_sq - sum_ * sum_, 0.0)
    return jnp.where(div <= 0, 0.0, jnp.sqrt(num / jnp.where(div == 0, 1, div)))


# ---------------------------------------------------------------------------
# Counter arena (int64 values; reference aggregation/counter.go).
# ---------------------------------------------------------------------------


class CounterState(NamedTuple):
    sum: jnp.ndarray  # i64 (W*C,)
    sum_sq: jnp.ndarray  # i64
    count: jnp.ndarray  # i64
    max: jnp.ndarray  # i64, identity I64_MIN
    min: jnp.ndarray  # i64, identity I64_MAX
    last_at: jnp.ndarray  # i64 (C,) — per-slot last write time, for expiry


def counter_init(num_windows: int, capacity: int) -> CounterState:
    n = num_windows * capacity
    return CounterState(
        sum=jnp.zeros(n, jnp.int64),
        sum_sq=jnp.zeros(n, jnp.int64),
        count=jnp.zeros(n, jnp.int64),
        max=jnp.full(n, I64_MIN, jnp.int64),
        min=jnp.full(n, I64_MAX, jnp.int64),
        last_at=jnp.zeros(capacity, jnp.int64),
    )


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("impl",))
def counter_ingest(
    state: CounterState,
    idx: jnp.ndarray,  # i32 (N,) flattened window*C + slot; >= W*C to drop
    slots: jnp.ndarray,  # i32 (N,)
    values: jnp.ndarray,  # i64 (N,)
    times: jnp.ndarray,  # i64 (N,)
    impl: str | None = None,  # static ingest impl (None = resolved seam)
) -> CounterState:
    """Counter.Update for a batch (reference counter.go:53-76)."""
    s, sq, c = _seg3(state.sum, state.sum_sq, state.count, idx, values,
                     impl)
    slot_safe = _sanitize_slots(slots, state.last_at.shape[0])
    return CounterState(
        sum=s,
        sum_sq=sq,
        count=c,
        max=state.max.at[idx].max(values, mode="drop"),
        min=state.min.at[idx].min(values, mode="drop"),
        last_at=state.last_at.at[slot_safe].max(times, mode="drop"),
    )


@functools.partial(jax.jit, static_argnames=("capacity",))
def counter_consume(state: CounterState, window: jnp.ndarray, capacity: int):
    """Drain one window row -> (C, L) lane matrix (reference counter.go
    accessors Sum/SumSq/Count/Max/Min/Mean/Stdev; Last is invalid for
    counters and emitted as NaN)."""
    off = window * capacity
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, off, capacity)
    s = sl(state.sum).astype(jnp.float64)
    ssq = sl(state.sum_sq).astype(jnp.float64)
    cnt = sl(state.count)
    cntf = cnt.astype(jnp.float64)
    mean = jnp.where(cnt == 0, 0.0, s / jnp.where(cnt == 0, 1, cnt))
    lanes = jnp.stack(
        [
            jnp.full(capacity, jnp.nan, jnp.float64),  # LAST
            jnp.where(cnt == 0, 0.0, sl(state.min).astype(jnp.float64)),
            jnp.where(cnt == 0, 0.0, sl(state.max).astype(jnp.float64)),
            mean,
            cntf,
            s,
            ssq,
            _stdev(cntf, ssq, s),
        ],
        axis=1,
    )
    return lanes, cnt


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("capacity",))
def counter_reset_window(state: CounterState, window: jnp.ndarray, capacity: int) -> CounterState:
    off = window * capacity
    upd = lambda a, v: jax.lax.dynamic_update_slice_in_dim(
        a, jnp.full(capacity, v, a.dtype), off, 0
    )
    return CounterState(
        sum=upd(state.sum, 0),
        sum_sq=upd(state.sum_sq, 0),
        count=upd(state.count, 0),
        max=upd(state.max, I64_MIN),
        min=upd(state.min, I64_MAX),
        last_at=state.last_at,
    )


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("num_windows", "capacity"))
def counter_clear_slots(
    state: CounterState, slots: jnp.ndarray, num_windows: int, capacity: int
) -> CounterState:
    """Zero a set of slots across every window ring row (slot free; the
    reference deletes the whole Entry object — map.go deleteExpired — so
    a recycled slot must not inherit un-drained window stats)."""
    idx = (
        jnp.arange(num_windows, dtype=jnp.int64)[:, None] * capacity + slots[None, :]
    ).ravel()
    # Padded sentinel slots (== capacity) must not alias slot 0 of the
    # next window row: route them to the global OOB drop index.
    idx = jnp.where(
        (slots[None, :] >= capacity).repeat(num_windows, 0).ravel(),
        num_windows * capacity,
        idx,
    )
    return CounterState(
        sum=state.sum.at[idx].set(0, mode="drop"),
        sum_sq=state.sum_sq.at[idx].set(0, mode="drop"),
        count=state.count.at[idx].set(0, mode="drop"),
        max=state.max.at[idx].set(I64_MIN, mode="drop"),
        min=state.min.at[idx].set(I64_MAX, mode="drop"),
        last_at=state.last_at.at[slots].set(0, mode="drop"),
    )


# ---------------------------------------------------------------------------
# Gauge arena (float64 values; reference aggregation/gauge.go).
# ---------------------------------------------------------------------------


class GaugeState(NamedTuple):
    last: jnp.ndarray  # f64 (W*C,)
    last_time: jnp.ndarray  # i64 (W*C,) — timestamp backing `last`
    sum: jnp.ndarray  # f64
    sum_sq: jnp.ndarray  # f64
    count: jnp.ndarray  # i64
    max: jnp.ndarray  # f64, identity -inf (NaN surfaced when count==0)
    min: jnp.ndarray  # f64, identity +inf
    last_at: jnp.ndarray  # i64 (C,)


def gauge_init(num_windows: int, capacity: int) -> GaugeState:
    n = num_windows * capacity
    return GaugeState(
        last=jnp.zeros(n, jnp.float64),
        last_time=jnp.zeros(n, jnp.int64),
        sum=jnp.zeros(n, jnp.float64),
        sum_sq=jnp.zeros(n, jnp.float64),
        count=jnp.zeros(n, jnp.int64),
        max=jnp.full(n, -jnp.inf, jnp.float64),
        min=jnp.full(n, jnp.inf, jnp.float64),
        last_at=jnp.zeros(capacity, jnp.int64),
    )


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("impl",))
def gauge_ingest(
    state: GaugeState,
    idx: jnp.ndarray,  # i32 (N,) flattened; >= W*C to drop
    slots: jnp.ndarray,  # i32 (N,)
    values: jnp.ndarray,  # f64 (N,)
    times: jnp.ndarray,  # i64 (N,)
    impl: str | None = None,  # static ingest impl (None = resolved seam)
) -> GaugeState:
    """Gauge.Update for a batch (reference gauge.go:53-104).

    Semantics mirrored: `last` tracks the value with the greatest
    timestamp, first arrival winning ties (gauge.go:82-91 only updates
    when strictly after); count includes NaN values but sum/min/max
    ignore them (gauge.go:57-63,95-103).
    """
    n = values.shape[0]
    nan = jnp.isnan(values)
    safe = jnp.where(nan, 0.0, values)

    # Per-slot winner for `last`: sort by (idx asc, time asc, arrival
    # desc); the final element of each idx-segment is (max time, min
    # arrival).  Conditional scatter beats the stored (time, arrival)
    # only when strictly newer.
    arrival_desc = jnp.arange(n - 1, -1, -1, dtype=jnp.int32)
    s_idx, _s_time, _s_arr, s_val, s_times = jax.lax.sort(
        (idx, times, arrival_desc, values, times), num_keys=3
    )
    is_winner = jnp.concatenate([s_idx[1:] != s_idx[:-1], jnp.ones(1, bool)])
    old_time = state.last_time[jnp.clip(s_idx, 0, state.last_time.shape[0] - 1)]
    take = is_winner & (s_times > old_time)
    widx = jnp.where(take, s_idx, state.last.shape[0])  # OOB -> dropped

    g_s, g_sq, g_c = _seg3(state.sum, state.sum_sq, state.count, idx, safe,
                           impl)
    slot_safe = _sanitize_slots(slots, state.last_at.shape[0])
    return GaugeState(
        last=state.last.at[widx].set(s_val, mode="drop"),
        last_time=state.last_time.at[widx].set(s_times, mode="drop"),
        sum=g_s,
        sum_sq=g_sq,
        count=g_c,
        max=state.max.at[idx].max(jnp.where(nan, -jnp.inf, values), mode="drop"),
        min=state.min.at[idx].min(jnp.where(nan, jnp.inf, values), mode="drop"),
        last_at=state.last_at.at[slot_safe].max(times, mode="drop"),
    )


@functools.partial(jax.jit, static_argnames=("capacity",))
def gauge_consume(state: GaugeState, window: jnp.ndarray, capacity: int):
    off = window * capacity
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, off, capacity)
    s, ssq, cnt = sl(state.sum), sl(state.sum_sq), sl(state.count)
    cntf = cnt.astype(jnp.float64)
    mx, mn = sl(state.max), sl(state.min)
    mean = jnp.where(cnt == 0, 0.0, s / jnp.where(cnt == 0, 1, cnt))
    lanes = jnp.stack(
        [
            sl(state.last),
            jnp.where(jnp.isinf(mn), jnp.nan, mn),  # NaN until a value seen
            jnp.where(jnp.isinf(mx), jnp.nan, mx),
            mean,
            cntf,
            s,
            ssq,
            _stdev(cntf, ssq, s),
        ],
        axis=1,
    )
    return lanes, cnt


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("capacity",))
def gauge_reset_window(state: GaugeState, window: jnp.ndarray, capacity: int) -> GaugeState:
    off = window * capacity
    upd = lambda a, v: jax.lax.dynamic_update_slice_in_dim(
        a, jnp.full(capacity, v, a.dtype), off, 0
    )
    return GaugeState(
        last=upd(state.last, 0.0),
        last_time=upd(state.last_time, 0),
        sum=upd(state.sum, 0.0),
        sum_sq=upd(state.sum_sq, 0.0),
        count=upd(state.count, 0),
        max=upd(state.max, -jnp.inf),
        min=upd(state.min, jnp.inf),
        last_at=state.last_at,
    )


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("num_windows", "capacity"))
def gauge_clear_slots(
    state: GaugeState, slots: jnp.ndarray, num_windows: int, capacity: int
) -> GaugeState:
    idx = (
        jnp.arange(num_windows, dtype=jnp.int64)[:, None] * capacity + slots[None, :]
    ).ravel()
    # Padded sentinel slots (== capacity) must not alias slot 0 of the
    # next window row: route them to the global OOB drop index.
    idx = jnp.where(
        (slots[None, :] >= capacity).repeat(num_windows, 0).ravel(),
        num_windows * capacity,
        idx,
    )
    return GaugeState(
        last=state.last.at[idx].set(0.0, mode="drop"),
        last_time=state.last_time.at[idx].set(0, mode="drop"),
        sum=state.sum.at[idx].set(0.0, mode="drop"),
        sum_sq=state.sum_sq.at[idx].set(0.0, mode="drop"),
        count=state.count.at[idx].set(0, mode="drop"),
        max=state.max.at[idx].set(-jnp.inf, mode="drop"),
        min=state.min.at[idx].set(jnp.inf, mode="drop"),
        last_at=state.last_at.at[slots].set(0, mode="drop"),
    )


# ---------------------------------------------------------------------------
# Timer arena (float64 values + exact quantiles; reference
# aggregation/timer.go + quantile/cm/stream.go).
# ---------------------------------------------------------------------------


class TimerState(NamedTuple):
    sum: jnp.ndarray  # f64 (W*C,)
    sum_sq: jnp.ndarray  # f64
    count: jnp.ndarray  # i64
    sample_slot: jnp.ndarray  # i32 (W, S) — slot per buffered sample
    sample_val: jnp.ndarray  # f64 (W, S)
    sample_n: jnp.ndarray  # i64 (W,) — write offsets (may exceed S: overflow)
    last_at: jnp.ndarray  # i64 (C,)


def timer_append_plan(windows, slots, sample_n, capacity: int, scap: int):
    """Destination plan for appending a timer batch into per-window
    sample buffers: (drop mask, flat destination offsets with the drop
    sentinel num_w*scap, per-window appended counts).

    Buffer order is irrelevant (consume sorts the whole window at
    drain), so ranks come from one exclusive cumsum per window over the
    membership mask — W is small and static, and this avoids carrying
    the value column through a device sort.  ONE home for the plan: the
    f64 and packed timer ingests (aggregator/packed.py) share it, so
    overflow accounting can never diverge between the layouts."""
    num_w = sample_n.shape[0]
    oob = (windows < 0) | (windows >= num_w)
    drop = oob | (slots < 0) | (slots >= capacity)
    order_key = jnp.where(drop, num_w, windows)
    onehot = order_key[None, :] == jnp.arange(
        num_w, dtype=order_key.dtype)[:, None]
    ranks_all = jnp.cumsum(onehot.astype(jnp.int64), axis=1) - 1  # (W, N)
    w_clip = jnp.clip(order_key, 0, num_w - 1)
    rank = jnp.take_along_axis(ranks_all, w_clip[None, :], axis=0)[0]
    dst = sample_n[w_clip] + rank
    flat = jnp.where(
        ~drop & (dst < scap), w_clip.astype(jnp.int64) * scap + dst,
        num_w * scap)
    per_w_counts = onehot.sum(axis=1, dtype=sample_n.dtype)
    return drop, flat, per_w_counts


def timer_init(num_windows: int, capacity: int, sample_capacity: int) -> TimerState:
    n = num_windows * capacity
    return TimerState(
        sum=jnp.zeros(n, jnp.float64),
        sum_sq=jnp.zeros(n, jnp.float64),
        count=jnp.zeros(n, jnp.int64),
        sample_slot=jnp.full((num_windows, sample_capacity), capacity, jnp.int32),
        sample_val=jnp.zeros((num_windows, sample_capacity), jnp.float64),
        sample_n=jnp.zeros(num_windows, jnp.int64),
        last_at=jnp.zeros(capacity, jnp.int64),
    )


@functools.partial(jax.jit, donate_argnums=0,
                   static_argnames=("capacity", "impl"))
def timer_ingest(
    state: TimerState,
    windows: jnp.ndarray,  # i32 (N,) window ring index per sample; >= W drops
    slots: jnp.ndarray,  # i32 (N,)
    values: jnp.ndarray,  # f64 (N,)
    times: jnp.ndarray,  # i64 (N,)
    capacity: int,
    impl: str | None = None,  # static ingest impl (None = resolved seam)
) -> TimerState:
    """Timer.AddBatch for a batch of (slot, value) samples
    (reference timer.go:55-76): moments scatter-add plus sample append.

    Samples append into each window's buffer at offsets
    ``sample_n[w] + rank-within-batch``; indices beyond S drop (the
    moment stats stay exact; quantiles degrade — counted by the caller
    via sample_n overflow).
    """
    num_w, scap = state.sample_slot.shape
    # Out-of-range SLOTS must drop too: w*C + slot with slot >= C would
    # otherwise land in window w+1's region (fuzz-caught).  The
    # combined mask also gates the sample APPEND — a dropped sample
    # must not consume quantile-buffer capacity or inflate sample_n's
    # overflow accounting (timer_append_plan owns both contracts).
    drop, flat, per_w_counts = timer_append_plan(
        windows, slots, state.sample_n, capacity, scap)
    idx = jnp.where(drop, num_w * capacity,
                    windows * capacity + slots)

    t_s, t_sq, t_c = _seg3(state.sum, state.sum_sq, state.count, idx, values,
                           impl)
    slot_safe = _sanitize_slots(slots, capacity)
    return TimerState(
        sum=t_s,
        sum_sq=t_sq,
        count=t_c,
        sample_slot=state.sample_slot.ravel()
        .at[flat]
        .set(slots, mode="drop")
        .reshape(num_w, scap),
        sample_val=state.sample_val.ravel()
        .at[flat]
        .set(values, mode="drop")
        .reshape(num_w, scap),
        sample_n=state.sample_n + per_w_counts,
        last_at=state.last_at.at[slot_safe].max(times, mode="drop"),
    )


@functools.partial(jax.jit,
                   static_argnames=("capacity", "quantiles", "packed32"))
def timer_consume(
    state: TimerState,
    window: jnp.ndarray,
    capacity: int,
    quantiles: tuple,
    packed32: bool = False,
):
    """Drain one timer window -> (C, L + Q) lanes.

    Exact quantiles via lex-sort of (slot, value) and per-segment rank
    reads at ``ceil(q*n)`` (the reference CM stream targets the same rank
    within eps error — quantile/cm/stream.go:239-247).

    ``packed32`` replaces the two-key (i32 slot, f64 value) lex-sort —
    the drain's dominant cost, and software-emulated f64 compares on
    TPU — with ONE i64 key per sample: ``slot << 32 | orderable(f32)``
    (sign-flip trick keeps float order in unsigned bit order).
    Quantile reads decode the f32 back, so quantile/min/max lanes carry
    f32 precision (~1e-7 relative) — four orders tighter than the
    reference CM stream's default 1e-3 eps, but no longer bit-equal to
    the f64 sort.  The bound holds on f32's FINITE NORMAL range only:
    |v| above ~3.4e38 saturates to ±inf and |v| below ~1.2e-38 flushes
    toward 0 in these lanes — timer values are durations, so real
    deployments sit comfortably inside; pick the exact drain if yours
    do not.  Moments (sum/sum_sq/count/mean/stdev) are computed from
    the f64 accumulators either way and stay exact."""
    num_w, scap = state.sample_slot.shape
    off = window * capacity
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, off, capacity)
    s, ssq, cnt = sl(state.sum), sl(state.sum_sq), sl(state.count)
    cntf = cnt.astype(jnp.float64)
    mean = jnp.where(cnt == 0, 0.0, s / jnp.where(cnt == 0, 1, cnt))

    slots_w = jax.lax.dynamic_index_in_dim(state.sample_slot, window, keepdims=False)
    vals_w = jax.lax.dynamic_index_in_dim(state.sample_val, window, keepdims=False)
    if packed32:
        keys = jax.lax.sort(
            (slots_w.astype(jnp.uint64) << jnp.uint64(32))
            | orderable_f32(vals_w))
        s_slot = (keys >> jnp.uint64(32)).astype(jnp.int32)
        s_val = decode_orderable_f32(keys & jnp.uint64(0xFFFFFFFF))
    else:
        s_slot, s_val = jax.lax.sort((slots_w, vals_w), num_keys=2)

    seg_start = jnp.searchsorted(s_slot, jnp.arange(capacity, dtype=jnp.int32))
    seg_end = jnp.searchsorted(
        s_slot, jnp.arange(capacity, dtype=jnp.int32), side="right"
    )
    seg_n = (seg_end - seg_start).astype(jnp.float64)

    mn = s_val[jnp.clip(seg_start, 0, scap - 1)]
    mx = s_val[jnp.clip(seg_end - 1, 0, scap - 1)]
    empty = seg_n == 0
    mn = jnp.where(empty, 0.0, mn)
    mx = jnp.where(empty, 0.0, mx)

    qlanes = []
    for q in quantiles:
        ranks = jnp.ceil(q * seg_n).astype(jnp.int64) - 1
        ranks = jnp.clip(ranks, 0, jnp.maximum(seg_n.astype(jnp.int64) - 1, 0))
        qv = s_val[jnp.clip(seg_start + ranks, 0, scap - 1)]
        qlanes.append(jnp.where(empty, 0.0, qv))

    lanes = jnp.stack(
        [
            jnp.full(capacity, jnp.nan, jnp.float64),  # LAST (invalid for timers)
            mn,
            mx,
            mean,
            cntf,
            s,
            ssq,
            _stdev(cntf, ssq, s),
            *qlanes,
        ],
        axis=1,
    )
    return lanes, cnt


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("capacity",))
def timer_reset_window(state: TimerState, window: jnp.ndarray, capacity: int) -> TimerState:
    num_w, scap = state.sample_slot.shape
    off = window * capacity
    upd = lambda a, v: jax.lax.dynamic_update_slice_in_dim(
        a, jnp.full(capacity, v, a.dtype), off, 0
    )
    return TimerState(
        sum=upd(state.sum, 0.0),
        sum_sq=upd(state.sum_sq, 0.0),
        count=upd(state.count, 0),
        sample_slot=jax.lax.dynamic_update_slice(
            state.sample_slot,
            jnp.full((1, scap), capacity, jnp.int32),
            (window.astype(jnp.int32), jnp.int32(0)),
        ),
        sample_val=state.sample_val,
        sample_n=state.sample_n.at[window].set(0),
        last_at=state.last_at,
    )


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("num_windows", "capacity"))
def timer_clear_slots(
    state: TimerState, slots: jnp.ndarray, num_windows: int, capacity: int
) -> TimerState:
    """Clear freed timer slots: zero the moment rows and retarget their
    buffered samples to the drop sentinel so a recycled slot's quantiles
    don't include the previous occupant's samples."""
    idx = (
        jnp.arange(num_windows, dtype=jnp.int64)[:, None] * capacity + slots[None, :]
    ).ravel()
    # Padded sentinel slots (== capacity) must not alias slot 0 of the
    # next window row: route them to the global OOB drop index.
    idx = jnp.where(
        (slots[None, :] >= capacity).repeat(num_windows, 0).ravel(),
        num_windows * capacity,
        idx,
    )
    sorted_slots = jnp.sort(slots.astype(jnp.int32))
    flat = state.sample_slot.ravel()
    pos = jnp.clip(
        jnp.searchsorted(sorted_slots, flat), 0, sorted_slots.shape[0] - 1
    )
    hit = sorted_slots[pos] == flat
    new_sample_slot = jnp.where(hit, jnp.int32(capacity), flat).reshape(
        state.sample_slot.shape
    )
    return TimerState(
        sum=state.sum.at[idx].set(0.0, mode="drop"),
        sum_sq=state.sum_sq.at[idx].set(0.0, mode="drop"),
        count=state.count.at[idx].set(0, mode="drop"),
        sample_slot=new_sample_slot,
        sample_val=state.sample_val,
        sample_n=state.sample_n,
        last_at=state.last_at.at[slots].set(0, mode="drop"),
    )


# ---------------------------------------------------------------------------
# Thin stateful wrappers used by the engine.
# ---------------------------------------------------------------------------


class _ScalarLanesMixin:
    @property
    def lane_types(self):
        return SCALAR_LANES

    def lane_for_type(self, t: AggregationType) -> int | None:
        return SCALAR_LANES.index(t) if t in SCALAR_LANES else None


class _TimerLanesMixin:
    """Quantile-extended lane mapping shared by the f64 and packed
    timer arenas (requires a ``quantiles`` tuple attribute)."""

    @property
    def lane_types(self):
        """Primary type per lane; quantile-aliased types (e.g. MEDIAN ==
        P50) resolve through lane_for_type."""
        qtypes = []
        for q in self.quantiles:
            primary = next(
                (
                    t
                    for t in AggregationType
                    if t is not AggregationType.MEDIAN and t.quantile() == q
                ),
                AggregationType.UNKNOWN,
            )
            qtypes.append(primary)
        return SCALAR_LANES + tuple(qtypes)

    def lane_for_type(self, t: AggregationType) -> int | None:
        if t in SCALAR_LANES:
            return SCALAR_LANES.index(t)
        q = t.quantile()
        if q is not None and q in self.quantiles:
            return len(SCALAR_LANES) + self.quantiles.index(q)
        return None


def _guarded_ingest(call):
    """Run one arena ingest behind the device guard.  The fallback
    re-issues the call with the scatter (jnp) ingest impl as a STATIC
    argument — on TPU that steps down from the Pallas kernel with no
    cache clearing and no retrace of the primary; on CPU primary and
    fallback coincide and the re-run simply skips the device
    faultpoints (the injected-fault contract).  A failure that
    persists through the fallback raises typed to the engine."""
    return devguard.run_guarded(
        "arena.ingest", lambda: call(resolved_ingest_impl()),
        lambda: call("scatter"))


def _guarded_consume(call):
    """Arena window drains re-probe/fall back like ingests; the
    fallback is the same jnp program with the faultpoints skipped (the
    consume path has no lower impl to step down to — its lanes are
    already the jnp formulation)."""
    def primary():
        out = call()
        devguard.transfer_point("arena.consume")
        return out

    return devguard.run_guarded("arena.consume", primary, call)


def _guarded_state_op(call):
    """Window resets and slot clears ride the consume cycle's stage
    breaker (they follow a drain / an expiry sweep); like consume, the
    fallback is the same program with the faultpoints skipped."""
    return devguard.run_guarded("arena.consume", call, call)


class CounterArena(_ScalarLanesMixin):
    """Counter slots over a W-window ring (reference counter.go semantics)."""

    def __init__(self, num_windows: int, capacity: int):
        self.num_windows = num_windows
        self.capacity = capacity
        self._mem = membudget.reserve(
            "aggregator.counter",
            membudget.counter_arena_bytes("f64", num_windows, capacity),
            owner=self)
        self.state = counter_init(num_windows, capacity)

    def ingest(self, windows, slots, values, times):
        idx = flat_window_index(windows, slots, self.num_windows, self.capacity)
        self.state = _guarded_ingest(lambda impl: counter_ingest(
            self.state, idx, slots, values.astype(jnp.int64), times,
            impl=impl))

    def consume(self, window: int):
        return _guarded_consume(lambda: counter_consume(
            self.state, jnp.int32(window), self.capacity))

    def reset_window(self, window: int):
        self.state = _guarded_state_op(lambda: counter_reset_window(self.state, jnp.int32(window), self.capacity))

    def clear_slots(self, slots):
        self.state = _guarded_state_op(lambda: counter_clear_slots(
            self.state,
            jnp.asarray(pad_slots(np.asarray(slots), self.capacity)),
            self.num_windows,
            self.capacity,
        ))


class GaugeArena(_ScalarLanesMixin):
    def __init__(self, num_windows: int, capacity: int):
        self.num_windows = num_windows
        self.capacity = capacity
        self._mem = membudget.reserve(
            "aggregator.gauge",
            membudget.gauge_arena_bytes("f64", num_windows, capacity),
            owner=self)
        self.state = gauge_init(num_windows, capacity)

    def ingest(self, windows, slots, values, times):
        idx = flat_window_index(windows, slots, self.num_windows, self.capacity)
        self.state = _guarded_ingest(lambda impl: gauge_ingest(
            self.state, idx, slots, values.astype(jnp.float64), times,
            impl=impl))

    def consume(self, window: int):
        return _guarded_consume(lambda: gauge_consume(
            self.state, jnp.int32(window), self.capacity))

    def reset_window(self, window: int):
        self.state = _guarded_state_op(lambda: gauge_reset_window(self.state, jnp.int32(window), self.capacity))

    def clear_slots(self, slots):
        self.state = _guarded_state_op(lambda: gauge_clear_slots(
            self.state,
            jnp.asarray(pad_slots(np.asarray(slots), self.capacity)),
            self.num_windows,
            self.capacity,
        ))


class TimerArena(_TimerLanesMixin):
    DEFAULT_QUANTILES = (0.5, 0.95, 0.99)

    def __init__(
        self,
        num_windows: int,
        capacity: int,
        sample_capacity: int,
        quantiles: tuple = DEFAULT_QUANTILES,
        packed32: bool = False,
    ):
        self.num_windows = num_windows
        self.capacity = capacity
        self.sample_capacity = sample_capacity
        self.quantiles = tuple(quantiles)
        self.packed32 = packed32
        self._mem = membudget.reserve(
            "aggregator.timer",
            membudget.timer_arena_bytes("f64", num_windows, capacity,
                                        sample_capacity),
            owner=self)
        self.state = timer_init(num_windows, capacity, sample_capacity)
        # Host shadow of state.sample_n: avoids a device sync per ingest
        # batch just to run the overflow check.
        self._sample_n_host = np.zeros(num_windows, np.int64)

    def ingest(self, windows, slots, values, times):
        """Append a batch; grows the per-window sample buffer first if the
        batch would overflow it (the reference CM stream never drops
        samples — stream.go AddBatch — so neither do we; growth is
        geometric to amortize the re-jit)."""
        windows_np = np.asarray(windows)
        slots_np = np.asarray(slots)
        # Mirror the device-side drop mask exactly: samples dropped for
        # an out-of-range slot never reach the buffer, so they must not
        # count toward growth/overflow either.
        in_range = ((windows_np >= 0) & (windows_np < self.num_windows)
                    & (slots_np >= 0) & (slots_np < self.capacity))
        per_w = np.bincount(
            windows_np[in_range], minlength=self.num_windows
        )
        # Commit-after-success (the ShardBuffer.write pattern): a
        # _grow budget reject or device failure must leave the shadow
        # mirroring state.sample_n, or every later batch re-rejects.
        new_n = self._sample_n_host + per_w
        needed = int(new_n.max())
        if needed > self.sample_capacity:
            self._grow(needed)
        self.state = _guarded_ingest(lambda impl: timer_ingest(
            self.state,
            jnp.asarray(windows_np.astype(np.int32)),
            slots,
            values.astype(jnp.float64),
            times,
            self.capacity,
            impl=impl,
        ))
        self._sample_n_host = new_n

    def _grow(self, needed: int) -> None:
        new_cap = self.sample_capacity
        while new_cap < needed:
            new_cap *= 2
        # Admission before the pad allocates: an over-budget grow
        # raises typed (the reference CM stream's never-drop contract
        # yields to the budget — the caller sees the reject, the
        # existing samples stay intact).
        self._mem.resize(membudget.timer_arena_bytes(
            "f64", self.num_windows, self.capacity, new_cap))
        pad = new_cap - self.sample_capacity
        self.state = TimerState(
            sum=self.state.sum,
            sum_sq=self.state.sum_sq,
            count=self.state.count,
            sample_slot=jnp.pad(
                self.state.sample_slot,
                ((0, 0), (0, pad)),
                constant_values=self.capacity,
            ),
            sample_val=jnp.pad(self.state.sample_val, ((0, 0), (0, pad))),
            sample_n=self.state.sample_n,
            last_at=self.state.last_at,
        )
        self.sample_capacity = new_cap

    def consume(self, window: int):
        return _guarded_consume(lambda: timer_consume(
            self.state, jnp.int32(window), self.capacity, self.quantiles,
            self.packed32,
        ))

    def reset_window(self, window: int):
        self.state = _guarded_state_op(lambda: timer_reset_window(self.state, jnp.int32(window), self.capacity))
        self._sample_n_host[window] = 0

    def clear_slots(self, slots):
        self.state = _guarded_state_op(lambda: timer_clear_slots(
            self.state,
            jnp.asarray(pad_slots(np.asarray(slots), self.capacity)),
            self.num_windows,
            self.capacity,
        ))
