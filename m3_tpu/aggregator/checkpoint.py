"""Bit-exact aggregation-arena checkpoint/restore.

The PR 8 packed arena made aggregator state *checkpointable*: every
lane is a fixed-width device tensor (SALSA/Counter-Pools discipline —
arXiv:2102.12531, arXiv:2502.14699), so "the aggregator's state" is a
finite list of named arrays plus host bookkeeping, not a heap of
per-metric objects.  This module cashes that in before ROADMAP item 1
makes device residency mandatory: open aggregation windows survive a
SIGKILL instead of silently losing up to a full resolution window of
acked samples.

Serialization contract:

* **Arrays are raw bytes** — every arena lane (packed AND f64 layouts)
  is dumped device→host and written verbatim, each with its own
  adler32 through the persist layer's digest helper.  Restore is
  therefore BIT-exact by construction: save → SIGKILL → restore →
  consume equals uninterrupted consume for all bit-exact lanes (the
  checkpoint parity tests pin sha256 over the drained lanes; gauge
  sums stay inside the documented 1e-6 packed envelope only when
  comparing *across* layouts, never across a checkpoint).
* **Host bookkeeping is pickled** — slot maps (exact slot→id
  assignment, free lists), window watermarks (``consumed_until``),
  pipeline tails + transform state, reject counters, the
  downsampler's series-tag registry.  The pickle rides inside the same
  checksummed envelope.
* **Corruption is typed** — a bad magic/schema raises
  :class:`~m3_tpu.persist.corruption.FormatCorruption`, a digest
  mismatch :class:`~m3_tpu.persist.corruption.ChecksumMismatch`
  (persist's detect → quarantine → keep-serving discipline: the
  restoring node moves the rotten file aside and boots fresh rather
  than crash-looping).
* **Writes are atomic** — temp file + rename, checkpoint-last: a
  SIGKILL mid-save leaves the previous checkpoint intact.

Drivers: :class:`AggregatorCheckpointer` is saved by the mediator every
``coordinator.checkpoint_every`` ticks and by ``Assembly.drain``
(SIGTERM), and restored by ``run_node`` before the node starts serving.
"""

from __future__ import annotations

import os
import pickle
import struct
import tempfile
from pathlib import Path
from typing import Dict, List, Tuple

import numpy as np

from m3_tpu.persist.capacity import capacity_guard, inject
from m3_tpu.persist.corruption import ChecksumMismatch, FormatCorruption
from m3_tpu.persist.digest import digest

MAGIC = b"M3AGGCKPT"
SCHEMA = 1

__all__ = ["AggregatorCheckpointer", "save_lists", "load_lists",
           "restore_lists", "list_state", "restore_list_state"]


# ---------------------------------------------------------------------------
# MetricList <-> (meta, arrays)
# ---------------------------------------------------------------------------


def list_state(ml) -> Tuple[dict, List[Tuple[str, np.ndarray]]]:
    """One MetricList as (host meta, named device lanes).  Lane names
    are ``<arena>.<field>`` over the state NamedTuple's fields — the
    format follows the STATE, so a layout's field-set change
    (packed vs f64) needs no format change."""
    arrays: List[Tuple[str, np.ndarray]] = []
    arena_meta: Dict[str, dict] = {}
    for aname, arena in (("counter", ml.counters), ("gauge", ml.gauges),
                         ("timer", ml.timers)):
        st = arena.state
        arena_meta[aname] = {
            "state_cls": type(st).__name__,
            "fields": list(st._fields),
            "sample_capacity": getattr(arena, "sample_capacity", None),
            "sample_n_host": getattr(arena, "_sample_n_host", None),
        }
        for f in st._fields:
            arrays.append((f"{aname}.{f}", np.asarray(getattr(st, f))))
    maps = {}
    for mt, m in ml.maps.items():
        maps[int(mt)] = m.to_entries()
    meta = {
        "policy": str(ml.policy),
        "layout": type(ml.counters).__name__,  # Packed* vs plain
        "opts": {
            "capacity": ml.opts.capacity,
            "num_windows": ml.opts.num_windows,
            "timer_sample_capacity": ml.timers.sample_capacity,
            "quantiles": tuple(ml.opts.quantiles),
            "timer_packed32": ml.opts.timer_packed32,
            "layout": ("packed" if type(ml.counters).__name__.startswith(
                "Packed") else "f64"),
        },
        "consumed_until": ml.consumed_until,
        "drops": ml.drops,
        "timed_rejects": dict(ml.timed_rejects),
        "new_series_rejected": ml.new_series_rejected,
        "forward_errors": ml.forward_errors,
        "maps": maps,
        "pipelines": dict(ml._pipelines),
        "tf_state": dict(ml._tf_state),
        "tail_sigs": dict(ml._tail_sigs),
        "forward_buffer": list(ml._forward_buffer),
        "arenas": arena_meta,
    }
    return meta, arrays


def restore_list_state(ml, meta: dict, arrays: Dict[str, np.ndarray]) -> None:
    """Install a saved state into a freshly constructed MetricList of
    the SAME geometry (the loader builds it from the checkpoint's own
    opts).  Array dtypes/shapes are validated against the live state —
    a geometry mismatch is format corruption, not a crash deep in
    XLA."""
    import jax.numpy as jnp

    for aname, arena in (("counter", ml.counters), ("gauge", ml.gauges),
                         ("timer", ml.timers)):
        st = arena.state
        am = meta["arenas"][aname]
        if list(st._fields) != am["fields"]:
            raise FormatCorruption(
                f"checkpoint arena {aname!r} fields {am['fields']} do not "
                f"match this build's {list(st._fields)}",
                component="aggregator.checkpoint")
        vals = {}
        for f in st._fields:
            live = np.asarray(getattr(st, f))
            saved = arrays[f"{aname}.{f}"]
            if saved.shape != live.shape or saved.dtype != live.dtype:
                raise FormatCorruption(
                    f"checkpoint lane {aname}.{f}: {saved.dtype}"
                    f"{saved.shape} vs live {live.dtype}{live.shape}",
                    component="aggregator.checkpoint")
            vals[f] = jnp.asarray(saved)
        arena.state = type(st)(**vals)
        if am.get("sample_n_host") is not None:
            arena._sample_n_host = np.asarray(am["sample_n_host"]).copy()
    from m3_tpu.metrics.types import MetricType

    for mt_val, entries in meta["maps"].items():
        ml.maps[MetricType(mt_val)].load_entries(entries)
    ml.consumed_until = meta["consumed_until"]
    ml.drops = meta["drops"]
    ml.timed_rejects = dict(meta["timed_rejects"])
    ml.new_series_rejected = meta["new_series_rejected"]
    ml.forward_errors = meta["forward_errors"]
    ml._pipelines = dict(meta["pipelines"])
    ml._tf_state = dict(meta["tf_state"])
    ml._tail_sigs = dict(meta["tail_sigs"])
    ml._forward_buffer = list(meta["forward_buffer"])


# ---------------------------------------------------------------------------
# File envelope: MAGIC | u8 schema | u64 header_len | pickle(header)
#                | raw array blob   (array digests live in the header;
#                the header's own digest rides a trailing u32)
# ---------------------------------------------------------------------------


def save_lists(lists: dict, path, extra_meta: dict | None = None) -> int:
    """Write ``{StoragePolicy: MetricList}`` (+ optional extra host
    meta, e.g. the downsampler's series tags) to ``path`` atomically.
    Returns bytes written."""
    entries = []
    blobs: List[bytes] = []
    offset = 0
    for sp, ml in lists.items():
        meta, arrays = list_state(ml)
        arr_meta = []
        for name, a in arrays:
            a = np.asarray(a)
            # NOTE: ascontiguousarray would promote 0-d lanes (pool_n,
            # err) to (1,); record the true shape, serialize the bytes
            raw = np.ascontiguousarray(a).tobytes()
            arr_meta.append({
                "name": name, "dtype": str(a.dtype), "shape": a.shape,
                "offset": offset, "nbytes": len(raw),
                "digest": digest(raw),
            })
            blobs.append(raw)
            offset += len(raw)
        meta["arrays"] = arr_meta
        entries.append(meta)
    header = {
        "schema": SCHEMA,
        "lists": entries,
        "extra": extra_meta or {},
    }
    hbytes = pickle.dumps(header, protocol=4)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                               prefix=path.name + ".tmp")
    try:
        # capacity_guard also unlinks tmp on ENOSPC; the outer
        # BaseException handler keeps covering every OTHER failure
        # (serialization bugs, KeyboardInterrupt mid-save).
        with capacity_guard(path=path, component="checkpoint", op="write",
                            cleanup=(tmp,)):
            inject("checkpoint.write")
            with os.fdopen(fd, "wb") as f:
                f.write(MAGIC)
                f.write(struct.pack("<BQ", SCHEMA, len(hbytes)))
                f.write(struct.pack("<I", digest(hbytes)))
                f.write(hbytes)
                for raw in blobs:
                    f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return len(MAGIC) + 13 + len(hbytes) + offset


def load_lists(path):
    """Parse + verify a checkpoint → (header dict, arrays-by-list).
    Typed failures: FormatCorruption (magic/schema/truncation),
    ChecksumMismatch (header or lane digest)."""
    path = Path(path)
    data = path.read_bytes()
    if len(data) < len(MAGIC) + 13 or not data.startswith(MAGIC):
        raise FormatCorruption("aggregator checkpoint: bad magic/truncated",
                               path=str(path),
                               component="aggregator.checkpoint")
    off = len(MAGIC)
    schema, hlen = struct.unpack_from("<BQ", data, off)
    off += 9
    (hdig,) = struct.unpack_from("<I", data, off)
    off += 4
    if schema != SCHEMA:
        raise FormatCorruption(
            f"aggregator checkpoint schema {schema} != {SCHEMA}",
            path=str(path), component="aggregator.checkpoint")
    hbytes = data[off:off + hlen]
    if len(hbytes) != hlen:
        raise FormatCorruption("aggregator checkpoint: truncated header",
                               path=str(path),
                               component="aggregator.checkpoint")
    if digest(hbytes) != hdig:
        raise ChecksumMismatch(
            "aggregator checkpoint header digest mismatch",
            path=str(path), component="aggregator.checkpoint",
            check="adler32")
    header = pickle.loads(hbytes)
    blob = data[off + hlen:]
    per_list: List[Dict[str, np.ndarray]] = []
    for meta in header["lists"]:
        arrays: Dict[str, np.ndarray] = {}
        for am in meta["arrays"]:
            raw = blob[am["offset"]:am["offset"] + am["nbytes"]]
            if len(raw) != am["nbytes"]:
                raise FormatCorruption(
                    f"aggregator checkpoint: truncated lane {am['name']}",
                    path=str(path), component="aggregator.checkpoint")
            if digest(raw) != am["digest"]:
                raise ChecksumMismatch(
                    f"aggregator checkpoint lane {am['name']} digest "
                    "mismatch", path=str(path),
                    component="aggregator.checkpoint", check="adler32")
            arrays[am["name"]] = np.frombuffer(
                raw, dtype=np.dtype(am["dtype"])).reshape(am["shape"])
        per_list.append(arrays)
    return header, per_list


def restore_lists(path, make_list):
    """Load a checkpoint and rebuild every MetricList through
    ``make_list(policy_str, opts_dict)`` (the caller owns list
    construction so engine/downsampler geometry knobs stay theirs).
    Returns (``{policy_str: MetricList}``, extra meta)."""
    header, per_list = load_lists(path)
    out = {}
    for meta, arrays in zip(header["lists"], per_list):
        ml = make_list(meta["policy"], meta["opts"])
        restore_list_state(ml, meta, arrays)
        out[meta["policy"]] = ml
    return out, header.get("extra", {})


# ---------------------------------------------------------------------------
# Driver: mediator-tick + drain checkpointing of a Downsampler
# ---------------------------------------------------------------------------


class AggregatorCheckpointer:
    """Owns one checkpoint file for a coordinator Downsampler.

    ``save()`` snapshots every (policy, MetricList) under the
    downsampler's lock (a torn snapshot racing the ingest path would
    not be bit-exact); ``restore()`` rebuilds them on boot, moving a
    corrupt file aside (``<path>.corrupt``) and starting fresh — the
    persist quarantine discipline, never a crash loop."""

    def __init__(self, downsampler, path, instrument=None):
        self.downsampler = downsampler
        self.path = Path(path)
        self.saves = 0
        self.save_errors = 0
        self.restores = 0
        self.corrupt = 0
        self._scope = (instrument.scope("aggregator.checkpoint")
                       if instrument is not None else None)

    def save(self) -> dict:
        try:
            nbytes = self.downsampler.checkpoint_to(self.path)
        except Exception:  # noqa: BLE001 — a failed save must not kill
            # the mediator loop; counted + logged by the caller's tick
            self.save_errors += 1
            if self._scope is not None:
                self._scope.counter("save_errors").inc()
            raise
        self.saves += 1
        if self._scope is not None:
            self._scope.counter("saves").inc()
            self._scope.gauge("bytes").update(nbytes)
        return {"bytes": nbytes, "path": str(self.path)}

    def restore(self) -> bool:
        if not self.path.exists():
            return False
        from m3_tpu.persist.corruption import CorruptionError

        try:
            self.downsampler.restore_from(self.path)
        except CorruptionError:
            self.corrupt += 1
            if self._scope is not None:
                self._scope.counter("corrupt").inc()
            # quarantine-in-place: keep the bytes for forensics, never
            # crash-loop the node on them
            try:
                with capacity_guard(path=self.path, component="checkpoint",
                                    op="sideline"):
                    os.replace(self.path, str(self.path) + ".corrupt")
            except OSError:
                pass
            return False
        self.restores += 1
        if self._scope is not None:
            self._scope.counter("restores").inc()
        return True

    def status(self) -> dict:
        return {
            "path": str(self.path),
            "saves": self.saves,
            "save_errors": self.save_errors,
            "restores": self.restores,
            "corrupt": self.corrupt,
        }
