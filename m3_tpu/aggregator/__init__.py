"""TPU-native streaming metrics aggregator.

Re-design of the reference's ``src/aggregator``: the sharded in-memory
rollup engine (per-metric Counter/Timer/Gauge elements keyed by
(id, aggregation key), windowed by storage-policy resolution, drained by a
leader-driven flush loop) becomes **array programming over a fixed-capacity
slot arena**:

* host side owns the string metric IDs and a slot allocator
  (``engine.MetricMap``), mirroring the reference's find-or-create Entry
  path (aggregator/map.go:149, entry.go:264);
* device side holds per-(window, slot) statistic tensors and ingests
  batches with scatter reductions (``arena.py``), mirroring
  GenericElem.AddUnion -> Counter/Gauge.Update / Timer.AddBatch;
* flush (``GenericElem.Consume`` generic_elem.go:271) becomes one
  vectorized drain of a closed window ring row: all 22 aggregation
  outputs computed as lanes, masked by each slot's compressed
  aggregation-type ID.
"""

from m3_tpu.aggregator.arena import (
    CounterArena,
    GaugeArena,
    TimerArena,
)
