"""Series-axis sharded M3TSZ decode: all local devices, one call.

The two-phase decode (encoding/m3tsz_jax.py) is embarrassingly
parallel across the series axis — the sequential scan is per-series —
but XLA-CPU runs each (S,) element op single-threaded (the per-op
arrays sit below its intra-op parallelization threshold), so a
single-device decode uses ONE core no matter how many the host has.
The native C++ yardstick bench.py compares against threads across
cores; this helper makes the comparison fair by sharding the series
axis over every local device with the repo's shard_map seam
(parallel/mesh.py) — on a 2-core CPU host with 2 virtual devices it
measured 1.74x (13.4M vs 7.7M dps, round 6), and on a TPU pod slice
the same call spreads series across chips (ROADMAP item 3's decode
axis).

Bit-identity: each shard runs the IDENTICAL per-series program, so
outputs equal the single-device decode exactly (pinned by
tests/test_pallas_decode.py).  Series counts that don't divide the
device count are zero-padded; padded rows decode as errors and are
sliced off before returning.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from m3_tpu.encoding import m3tsz_jax as codec
from m3_tpu.parallel.mesh import shard_map_compat


def _raw(fn):
    return getattr(fn, "__wrapped__", fn)


@functools.lru_cache(maxsize=8)
def _sharded_fn(n_dev: int, max_points: int, default_unit: int,
                chains: str, scan_major: bool, extract: str):
    # dtype=object: a Mesh axis of Device objects, not numeric lanes
    mesh = Mesh(np.array(jax.devices()[:n_dev], dtype=object), ("s",))
    # The raw (unjitted) decode impl: chains/extract arrive as statics
    # resolved by OUR caller on the host, and the value-control table
    # rides as a replicated ARGUMENT (P() spec) — the same
    # constant-bloat/retrace-risk contract the codec's own wrapper
    # upholds (a module-global reference here would bake ~1MB of table
    # into this jit's HLO too).
    inner = functools.partial(
        _raw(codec._decode_batch_device), max_points=max_points,
        default_unit=default_unit, chains=chains, scan_major=scan_major,
        extract=extract)
    out_sp = P(None, "s") if scan_major else P("s", None)
    return jax.jit(shard_map_compat(
        inner, mesh,
        in_specs=(P("s"), P("s"), P()),
        out_specs=(out_sp, out_sp, out_sp, P("s"), P("s"), P("s"))))


def decode_batch_device_sharded(words, nbits, max_points: int,
                                default_unit: int = 1,
                                chains: str = "auto",
                                scan_major: bool = False,
                                devices: int | None = None):
    """decode_batch_device over all (or ``devices``) local devices,
    series-sharded.  Same contract and bit-identical outputs; falls
    back to the single-device jit when only one device exists."""
    n_dev = devices or jax.device_count()
    S = words.shape[0]
    n_dev = min(n_dev, max(S, 1))
    if n_dev <= 1:
        return codec.decode_batch_device(
            words, nbits, max_points, default_unit=default_unit,
            chains=chains, scan_major=scan_major)
    if chains == "auto":
        chains = codec.resolved_chains()
    pad = (-S) % n_dev
    if pad:
        words = jnp.pad(words, ((0, pad), (0, 0)))
        nbits = jnp.pad(nbits, (0, pad))
    def _run(ch: str):
        return _sharded_fn(n_dev, max_points, default_unit, ch, scan_major,
                           codec._resolved_extract(ch))(
            words, nbits, codec.value_ctrl_table())

    # same guard + static-seam fallback as the codec's own wrapper
    # (m3tsz_jax.decode_batch_device)
    from m3_tpu.x import devguard

    out = devguard.run_guarded(
        "decode", lambda: _run(chains),
        lambda: _run(codec.fallback_chains(chains)))
    if pad:
        sl = ((slice(None), slice(None, S)) if scan_major
              else (slice(None, S), slice(None)))
        out = (out[0][sl], out[1][sl], out[2][sl],
               out[3][:S], out[4][:S], out[5][:S])
    return out
