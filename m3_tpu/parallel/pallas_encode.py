"""Pallas TPU kernel for the M3TSZ phase-2 word PLACEMENT.

The two-phase encode (encoding/m3tsz_jax.py, round 9) mirrors the
round-6 decode split: a cheap sequential scan resolves the format into
per-datapoint ``(value, bit offset, width)`` lanes, and phase 2
assembles the output stream words from the lane fragments.  Placement
is a SCATTER by construction — every fragment lands at its word index
— and TPU scatters measured ~1us/element (TPU_RESULTS_r05.json), so
this kernel inverts it into the same masked-sum shape as the decode
gather kernel (parallel/pallas_decode.py): walk a 2-D grid over
(series, word tiles x fragment tiles), compare each fragment's word
key against the tile's word lane ids, and accumulate the hits into
revisited (1, WT) output blocks.  Fragments at distinct bit ranges
never overlap, so the u32 partial sums are exact ORs.

All-uint32 on purpose (no 64-bit integer ops inside Mosaic): the
caller splits each u64 fragment into big-endian u32 halves — half
``h`` of the fragment at u64 word ``k`` targets u32 word ``2k + h`` —
and recombines the (S, 2W) u32 output into u64 stream words outside
the kernel, exactly how the decode kernel funnels outside Mosaic.

``place_words`` is the jnp/Pallas seam used by ``M3_ENCODE_PLACE=
pallas`` (interpret mode anywhere without a real TPU backend — the
clean-fallback contract tier-1 pins); ``place_words_jnp`` is the
scatter-add reference the parity tests compare against.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas ships with jax, but guard anyway: this module is optional
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    HAVE_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    HAVE_PALLAS = False

U32 = jnp.uint32
U64 = jnp.uint64
I32 = jnp.int32

FT = 512   # fragment lanes per grid step
WT = 512   # output u32 words per grid row: one (1, WT) revisited block;
           # the (FT, WT) hit mask is the kernel's VMEM high-water mark


def _place_kernel(keys_ref, vals_ref, out_ref):
    """One (s, w, f) grid step: accumulate fragment-tile f's
    contribution to series s's word tile w.  Fragments go down the
    sublane axis, word lanes across — the same mask orientation as the
    decode gather kernel, with gather/scatter roles reversed."""
    w = pl.program_id(1)
    f = pl.program_id(2)
    base = w * WT
    lane_ids = base + jax.lax.broadcasted_iota(I32, (1, WT), 1)  # (1, WT)
    keys = keys_ref[0, :][:, None]                               # (FT, 1)
    vals = vals_ref[0, :][:, None]                               # (FT, 1)
    hit = keys == lane_ids                                       # (FT, WT)
    part = jnp.sum(jnp.where(hit, vals, jnp.zeros((), U32)), axis=0,
                   dtype=U32)[None, :]                           # (1, WT)

    @pl.when(f == 0)
    def _init():
        out_ref[:, :] = part

    @pl.when(f > 0)
    def _accumulate():
        out_ref[:, :] = out_ref[:, :] + part


@functools.partial(jax.jit, static_argnames=("w32", "interpret"))
def _place_pallas(vals32, keys32, w32: int, interpret: bool):
    """(S, F) u32 fragments + u32-word keys -> (S, w32) u32 sums."""
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable in this jax build")
    S, F = vals32.shape
    Fpad = ((F + FT - 1) // FT) * FT
    Wpad = ((w32 + WT - 1) // WT) * WT
    # Padding fragments carry an impossible word key (>= Wpad) so they
    # match no word lane; real keys beyond w32 are dropped the same way
    # (the caller's fallback flag owns stream-overflow reporting).
    kp = jnp.full((S, Fpad), Wpad, I32).at[:, :F].set(
        jnp.minimum(keys32, jnp.asarray(Wpad, I32)))
    vp = jnp.zeros((S, Fpad), U32).at[:, :F].set(vals32)
    grid = (S, Wpad // WT, Fpad // FT)
    spec_w = pl.BlockSpec((1, WT), lambda s, w, f: (s, w))
    out = pl.pallas_call(
        _place_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, FT), lambda s, w, f: (s, f)),
            pl.BlockSpec((1, FT), lambda s, w, f: (s, f)),
        ],
        out_specs=spec_w,
        out_shape=jax.ShapeDtypeStruct((S, Wpad), U32),
        interpret=interpret,
    )(kp, vp)
    return out[:, :w32]


def auto_interpret() -> bool:
    """Compiled Mosaic needs a TPU; anywhere else the kernel runs in
    interpret mode (plain jnp semantics, slow — test-only)."""
    return jax.default_backend() != "tpu"


def _split32(frags, keys):
    """u64 fragments -> interleaved big-endian u32 halves + u32 keys."""
    S, F = frags.shape
    vals32 = jnp.stack(
        [(frags >> jnp.asarray(32, U64)).astype(U32),
         (frags & jnp.asarray(0xFFFFFFFF, U64)).astype(U32)],
        axis=2).reshape(S, 2 * F)
    keys32 = jnp.stack(
        [keys * jnp.asarray(2, I32),
         keys * jnp.asarray(2, I32) + jnp.asarray(1, I32)],
        axis=2).reshape(S, 2 * F)
    return vals32, keys32


def place_words(frags, keys, out_words: int,
                interpret: bool | None = None):
    """Assemble (S, out_words) u64 stream-word contributions from u64
    ``frags`` at u64-word indices ``keys`` (both (S, F)).  Fragments
    with keys outside [0, out_words) are dropped (the encoder's
    fallback flag reports stream overflow); fragment bit ranges must
    be disjoint (the M3TSZ lane contract), making the u32 sums exact.
    """
    if interpret is None:
        interpret = auto_interpret()
    vals32, keys32 = _split32(frags, keys)
    out32 = _place_pallas(vals32, keys32, 2 * out_words,
                          interpret=interpret)
    return ((out32[:, 0::2].astype(U64) << jnp.asarray(32, U64))
            | out32[:, 1::2].astype(U64))


def place_words_jnp(frags, keys, out_words: int):
    """Scatter-add reference semantics for :func:`place_words` — the
    parity oracle (tests/test_encode_fuzz.py pins kernel == this)."""
    S, F = frags.shape
    sidx = jnp.broadcast_to(jnp.arange(S, dtype=I32)[:, None], (S, F))
    ok = (keys >= 0) & (keys < out_words)
    out = jnp.zeros((S, out_words), U64)
    return out.at[sidx, jnp.clip(keys, 0, out_words - 1)].add(
        jnp.where(ok, frags, jnp.zeros((), U64)))
