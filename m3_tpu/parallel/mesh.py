"""Device-mesh topology for the framework's distribution axes.

The reference distributes work along two axes: **virtual shards** (4096-way
murmur3 hash of the series ID, `src/dbnode/sharding/shardset.go:148-163`)
mapped to instances by a placement (`src/cluster/placement/algo/sharded.go`),
and **replicas** (RF=3 fan-out with quorum consistency,
`src/dbnode/topology/consistency_level.go:36-46`).  The TPU-native design
maps both onto one `jax.sharding.Mesh`:

* ``shard`` axis — series-shard data parallelism.  Device arrays carry a
  leading logical-shard axis laid out over this mesh axis; a series lives on
  exactly one shard (slot allocation is per-shard, host-side).  Intra-shard
  traffic that the reference sends over TChannel becomes ICI collectives.
* ``replica`` axis — redundancy.  State is replicated across this axis;
  cross-replica checksum comparison (the repair path,
  `src/dbnode/storage/repair.go:115-246`) is a cheap `ppermute`/compare
  on device instead of a metadata RPC sweep.

Multi-host scaling keeps the same program: the mesh simply spans hosts, XLA
routes `psum`/`all_gather` over ICI within a slice and DCN across slices —
replacing the reference's NCCL/MPI-analogous TChannel+protobuf data plane
(SURVEY.md §5.8).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"
REPLICA_AXIS = "replica"


def enable_cpu_core_devices(n: int | None = None) -> None:
    """One virtual CPU device per core (default: os.cpu_count()), so
    series-sharded programs (parallel/sharded_decode.py) can use every
    core — XLA-CPU runs their small per-op arrays single-threaded, and
    the bench's native C++ yardstick threads across cores.

    Must run BEFORE the backend initializes (first jnp/jit/devices()
    touch); afterwards both knobs are inert.  Sets BOTH: the XLA_FLAGS
    env var is what jax 0.4.x honors (read at backend init), while
    jax_num_cpu_devices covers newer builds that ignore the flag.  The
    one caller that cannot use this helper is tests/conftest.py, which
    must set the env before jax is imported at all (the axon
    sitecustomize imports jax at interpreter startup).
    """
    import os

    n = n or max(1, os.cpu_count() or 1)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # pre-jax_num_cpu_devices era
        pass


def shard_map_compat(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions, replica-check disabled.

    Newer jax exposes ``jax.shard_map(..., check_vma=...)``; 0.4.x has
    only ``jax.experimental.shard_map.shard_map(..., check_rep=...)``.
    Every shard_map program in the tree goes through this one seam so
    the suite runs on both."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:  # pre-check_vma keyword era
            pass
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


@dataclasses.dataclass(frozen=True)
class MeshTopology:
    """A (shard × replica) device mesh plus its canonical shardings."""

    mesh: Mesh

    @property
    def num_shards(self) -> int:
        return self.mesh.shape[SHARD_AXIS]

    @property
    def num_replicas(self) -> int:
        return self.mesh.shape[REPLICA_AXIS]

    def sharded(self, *trailing: None) -> NamedSharding:
        """Sharding for arrays with a leading logical-shard axis."""
        return NamedSharding(self.mesh, P(SHARD_AXIS, *trailing))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def make_mesh(
    num_shards: int | None = None,
    num_replicas: int = 1,
    devices=None,
) -> MeshTopology:
    """Build the (shard, replica) mesh over the available devices.

    Defaults to all devices on the shard axis, RF=1.  The reference's RF=3
    corresponds to ``num_replicas=3`` (each replica group holds a full copy
    of every shard, as an M3 placement does).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if num_shards is None:
        if n % num_replicas != 0:
            raise ValueError(f"{n} devices not divisible by RF={num_replicas}")
        num_shards = n // num_replicas
    if num_shards * num_replicas != n:
        raise ValueError(
            f"mesh {num_shards}x{num_replicas} != {n} devices"
        )
    arr = np.asarray(devices).reshape(num_shards, num_replicas)
    return MeshTopology(Mesh(arr, (SHARD_AXIS, REPLICA_AXIS)))
