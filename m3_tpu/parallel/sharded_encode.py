"""Series-axis sharded M3TSZ encode: all local devices, one call.

The two-phase encode (encoding/m3tsz_jax.py, round 9) is embarrassingly
parallel across the series axis — phase 1's sequential scan is
per-series and phase 2's prefix sum runs along time — but XLA-CPU runs
each (S,) element op single-threaded (the per-op arrays sit below its
intra-op parallelization threshold), so a single-device encode uses ONE
core no matter how many the host has.  The native C++ yardstick
(bench.py) threads across cores; this helper makes the comparison fair
by sharding the series axis over every local device with the repo's
shard_map seam (parallel/mesh.py) — the exact mirror of
sharded_decode.py, and on a TPU pod slice the same call spreads series
across chips (ROADMAP item 3's ingest axis).

Bit-identity: each shard runs the IDENTICAL per-series program, so
outputs equal the single-device encode exactly (pinned by
tests/test_encode_fuzz.py).  Series counts that don't divide the device
count are zero-padded; padded rows emit nothing (their valid masks are
all-False) and are sliced off before returning.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from m3_tpu.encoding import m3tsz_jax as codec


def _raw(fn):
    return getattr(fn, "__wrapped__", fn)


@functools.lru_cache(maxsize=8)
def _sharded_fn(n_dev: int, unit: int, out_words: int, place: str,
                has_prefix: bool):
    # dtype=object: a Mesh axis of Device objects, not numeric lanes
    mesh = Mesh(np.array(jax.devices()[:n_dev], dtype=object), ("s",))
    # The raw (unjitted) encode impl: unit/out_words/place arrive as
    # statics resolved by OUR caller on the host (the same retrace-risk
    # contract the codec's own wrapper upholds).
    if has_prefix:
        def inner(ts, vb, st, va, pb):
            return _raw(codec._encode_batch_device)(
                ts, vb, st, va, unit=unit, out_words=out_words,
                prefix_bits=pb, place=place)
        in_specs = (P("s"), P("s"), P("s"), P("s"), P("s"))
    else:
        def inner(ts, vb, st, va):
            return _raw(codec._encode_batch_device)(
                ts, vb, st, va, unit=unit, out_words=out_words,
                prefix_bits=None, place=place)
        in_specs = (P("s"), P("s"), P("s"), P("s"))
    from m3_tpu.parallel.mesh import shard_map_compat

    out_specs = {"words": P("s"), "total_bits": P("s"), "fallback": P("s")}
    return jax.jit(shard_map_compat(inner, mesh, in_specs=in_specs,
                                    out_specs=out_specs))


def encode_batch_device_sharded(timestamps, value_bits, start, valid,
                                unit: int = 1, out_words: int = 0,
                                prefix_bits=None, place: str = "auto",
                                devices: int | None = None):
    """encode_batch_device over all (or ``devices``) local devices,
    series-sharded.  Same contract and bit-identical outputs; falls
    back to the single-device jit when only one device exists."""
    n_dev = devices or jax.device_count()
    S, T = timestamps.shape
    n_dev = min(n_dev, max(S, 1))
    if n_dev <= 1:
        return codec.encode_batch_device(
            timestamps, value_bits, start, valid, unit=unit,
            out_words=out_words, prefix_bits=prefix_bits, place=place)
    if place == "auto":
        place = codec.resolved_place()
    if out_words == 0:
        out_words = (T * 16) // 64 + 4  # the codec's own default, pinned
    pad = (-S) % n_dev
    if pad:
        timestamps = jnp.pad(timestamps, ((0, pad), (0, 0)))
        value_bits = jnp.pad(value_bits, ((0, pad), (0, 0)))
        start = jnp.pad(start, (0, pad))
        valid = jnp.pad(valid, ((0, pad), (0, 0)))
        if prefix_bits is not None:
            prefix_bits = jnp.pad(prefix_bits, (0, pad))
    args = (timestamps, value_bits, start, valid)
    if prefix_bits is not None:
        args = args + (prefix_bits,)

    def _run(p: str):
        return _sharded_fn(n_dev, unit, out_words, p,
                           prefix_bits is not None)(*args)

    # same guard + static-seam fallback as the codec's own wrapper
    # (m3tsz_jax.encode_batch_device) — the sharded dispatch is a
    # distinct stage entry point, so it gets its own guarded call
    from m3_tpu.x import devguard

    out = devguard.run_guarded(
        "encode", lambda: _run(place),
        lambda: _run(codec.fallback_place(place)))
    if pad:
        out = {k: v[:S] for k, v in out.items()}
    return out
