"""Sharded storage/query path over the device mesh.

The reference scatters a range query across shard-owning hosts and
merges replica streams on the coordinator
(`src/query/storage/fanout/storage.go:110`, dbnode `FetchTagged` per
shard owner, `encoding/multi_reader_iterator.go`).  The TPU-native
equivalent keeps the (shard × series × time) layout resident on a
`jax.sharding.Mesh` and runs the whole storage→query pipeline as one
SPMD program under ``shard_map``:

  1. **Sharded batched decode** — each device decodes only its own
     shard's packed M3TSZ streams (the window-carry scan from
     ``encoding/m3tsz_jax.py``), zero cross-device traffic.
  2. **Temporal stencil** — `rate()` with Prometheus extrapolation over
     the decoded (series × step) matrix, still local
     (`query/temporal.py`, reference `functions/temporal/rate.go`).
  3. **Cross-shard reduction** — per-shard partial `sum by (le)` bucket
     matrices combine with a single ``psum`` over the shard axis (XLA
     lowers it to a tree/ring all-reduce riding ICI), then
     `histogram_quantile` runs replicated on the reduced (bucket × step)
     matrix (`query/device_fns.py`, reference
     `functions/linear/histogram_quantile.go`).

This is the fan-out/merge query of SURVEY §2.7 with the network hop
replaced by a collective: the query
``histogram_quantile(q, sum by (le) (rate(bucket[R])))`` evaluated
end-to-end from compressed bytes to quantiles without leaving the mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from m3_tpu.encoding import m3tsz_jax as codec
from m3_tpu.parallel.mesh import SHARD_AXIS, MeshTopology, shard_map_compat
from m3_tpu.query import device_fns
from m3_tpu.query import temporal

_I64_MAX = np.iinfo(np.int64).max


def _raw(fn):
    return getattr(fn, "__wrapped__", fn)


def decode_to_step_series(words, nbits, max_points: int, ctrl_tbl,
                          chains: str = "fused", extract: str = "jnp"):
    """Device decode of packed streams -> padded (ts, float64 values)
    ready for the temporal stencils: invalid slots carry ts = i64 max
    (excluded by the window searchsorted) and NaN values.

    Query math runs in the backend's native f64 (emulated on TPU):
    range-function output is not part of the bit-exactness contract the
    codec upholds — only the decoded payload integers are, and those
    stay exact.  ``ctrl_tbl`` is the codec's value-control table
    threaded as an argument (``codec.value_ctrl_table()``) and
    ``chains``/``extract`` are host-resolved statics — the
    constant-bloat/retrace-risk contract.
    """
    ts, payload, meta, err, prec, _ann = _raw(codec._decode_batch_device)(
        words, nbits, ctrl_tbl, max_points, chains=chains, extract=extract
    )
    valid = (meta & 16) != 0
    isf = (meta & 8) != 0
    mult = (meta & 7).astype(jnp.int64)
    fvals = jax.lax.bitcast_convert_type(payload, jnp.float64)
    ivals = payload.astype(jnp.int64).astype(jnp.float64) / (
        10.0 ** mult.astype(jnp.float64)
    )
    vals = jnp.where(isf, fvals, ivals)
    ts_p = jnp.where(valid, ts, _I64_MAX)
    vals_p = jnp.where(valid, vals, jnp.nan)
    return ts_p, vals_p, err | prec


def sharded_decode_rate_hq(
    topo: MeshTopology,
    words: jnp.ndarray,        # u64 (D, S, W) packed streams, shard-sharded
    nbits: jnp.ndarray,        # i64 (D, S)
    bucket_ids: jnp.ndarray,   # i32 (D, S) le-bucket index per series
    step_times: jnp.ndarray,   # i64 (T,) replicated
    ubs: jnp.ndarray,          # f64 (B,) ascending upper bounds, +Inf last
    range_nanos: int,
    q: float,
    max_points: int,
    num_buckets: int,
):
    """histogram_quantile(q, sum by (le) (rate(bucket[range]))) over the
    mesh.  Returns (rates (D, S, T) shard-sharded, hq (T,) replicated,
    errs (D, S)).  Host wrapper: resolves the codec's chains/extract
    seams and fetches the value-control table as a replicated argument
    (constant-bloat/retrace-risk contract), then dispatches to the
    jitted SPMD program."""
    chains = codec.resolved_chains()
    return _sharded_decode_rate_hq(
        topo, words, nbits, bucket_ids, step_times, ubs,
        codec.value_ctrl_table(), range_nanos=range_nanos, q=q,
        max_points=max_points, num_buckets=num_buckets, chains=chains,
        extract=codec._resolved_extract(chains))


@functools.partial(
    jax.jit,
    static_argnames=("topo", "max_points", "num_buckets", "q", "range_nanos",
                     "chains", "extract"),
)
def _sharded_decode_rate_hq(
    topo: MeshTopology,
    words: jnp.ndarray,
    nbits: jnp.ndarray,
    bucket_ids: jnp.ndarray,
    step_times: jnp.ndarray,
    ubs: jnp.ndarray,
    ctrl_tbl: jnp.ndarray,     # u32 (2^18,) codec value-control table
    range_nanos: int,
    q: float,
    max_points: int,
    num_buckets: int,
    chains: str,
    extract: str,
):
    mesh = topo.mesh

    def local(words, nbits, bucket_ids, step_times, ubs, ctrl_tbl):
        w, nb, bid = words[0], nbits[0], bucket_ids[0]
        ts_p, vals_p, errs = decode_to_step_series(
            w, nb, max_points, ctrl_tbl, chains=chains, extract=extract)
        rates = _raw(temporal.rate_family)(
            ts_p, vals_p, step_times, range_nanos, "rate"
        )  # (S, T)
        # Partial sum-by-bucket, then one all-reduce over the shard axis.
        # Bucket counts are small and static, so the by-bucket sum is an
        # unrolled masked reduction — exact f64 adds, no scatter (TPU
        # scatter measured ~1us/element, TPU_RESULTS_r05.json window #3).
        r0 = jnp.nan_to_num(rates)
        bidc = jnp.clip(bid, 0, num_buckets - 1)
        if num_buckets <= 64:
            part = jnp.stack([
                jnp.sum(jnp.where((bidc == b)[:, None], r0, 0.0), axis=0)
                for b in range(num_buckets)
            ])
        else:  # degenerate many-bucket case: keep the scatter form
            part = jnp.zeros((num_buckets, step_times.shape[0]),
                             dtype=jnp.float64)
            part = part.at[bidc].add(r0)
        total = jax.lax.psum(part, SHARD_AXIS)
        hq = device_fns._histogram_quantile_kernel(
            total,
            jnp.arange(num_buckets, dtype=jnp.int32)[None, :],
            jnp.asarray([num_buckets], jnp.int32),
            ubs[None, :],
            q,
        )[0]
        return rates[None], hq, errs[None]

    return shard_map_compat(
        local,
        mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(), P(),
                  P()),
        out_specs=(P(SHARD_AXIS), P(), P(SHARD_AXIS)),
    )(words, nbits, bucket_ids, step_times, ubs, ctrl_tbl)


def single_device_reference(words, nbits, bucket_ids, step_times, ubs,
                            range_nanos, q, max_points, num_buckets):
    """The same pipeline on one device over the flattened series axis —
    the equality oracle for the sharded path."""
    D, S = nbits.shape
    flat_w = words.reshape(D * S, -1)
    flat_nb = nbits.reshape(D * S)
    flat_bid = np.asarray(bucket_ids).reshape(D * S)
    chains = codec.resolved_chains()
    ts_p, vals_p, errs = decode_to_step_series(
        jnp.asarray(flat_w), jnp.asarray(flat_nb), max_points,
        codec.value_ctrl_table(), chains=chains,
        extract=codec._resolved_extract(chains)
    )
    rates = temporal.rate_family(ts_p, vals_p, jnp.asarray(step_times),
                                 range_nanos, "rate")
    total = np.zeros((num_buckets, len(step_times)), dtype=np.float64)
    r = np.nan_to_num(np.asarray(rates))
    np.add.at(total, np.clip(flat_bid, 0, num_buckets - 1), r)
    hq = device_fns._histogram_quantile_kernel(
        jnp.asarray(total),
        jnp.arange(num_buckets, dtype=jnp.int32)[None, :],
        jnp.asarray([num_buckets], jnp.int32),
        jnp.asarray(ubs)[None, :],
        q,
    )[0]
    return np.asarray(rates).reshape(D, S, -1), np.asarray(hq), np.asarray(errs).reshape(D, S)
