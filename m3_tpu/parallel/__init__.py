from m3_tpu.parallel.mesh import MeshTopology, make_mesh
from m3_tpu.parallel.sharded_agg import (
    ShardedAggregatorState,
    sharded_init,
    sharded_ingest_consume,
)

__all__ = [
    "MeshTopology",
    "make_mesh",
    "ShardedAggregatorState",
    "sharded_init",
    "sharded_ingest_consume",
]
