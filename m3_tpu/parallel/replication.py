"""Replica-axis collectives: divergence detection + quorum on device.

The reference detects replica divergence with a metadata RPC sweep
(per-block checksums fetched from every replica and compared host-side,
`src/dbnode/storage/repair.go:115-246`) and accumulates write quorum in
the client session (`src/dbnode/client/session.go:1213-1400`).  On a
(shard × replica) mesh both become one-collective programs:

* **checksum compare** — each replica fingerprints its local shard state
  (every array of the pytree, bit-cast and mix-reduced), then a ring
  `ppermute` along the replica axis hands each replica its neighbor's
  fingerprint; equality around the full ring means all replicas agree.
  Cost: one scalar per shard over ICI, vs a metadata RPC per block.
* **quorum** — per-replica ack bits psum'd over the replica axis and
  compared against the consistency level's requirement, giving each
  shard's quorum verdict without leaving the device.

Tested on the virtual 8-device CPU mesh (tests/test_replication.py);
the same program spans real ICI/DCN meshes unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from m3_tpu.parallel.mesh import (
    REPLICA_AXIS, SHARD_AXIS, MeshTopology, shard_map_compat,
)
from m3_tpu.x import fault

_MIX = jnp.uint64(0x9E3779B97F4A7C15)  # Fibonacci hashing constant


def _fingerprint_leaf(a: jnp.ndarray) -> jnp.ndarray:
    """Order-sensitive 64-bit mix-reduce of one array's raw bits."""
    if a.dtype == jnp.bool_:
        a = a.astype(jnp.uint8)
    same_size = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}
    bits = jax.lax.bitcast_convert_type(
        a, same_size[a.dtype.itemsize]
    ).astype(jnp.uint64)
    flat = bits.reshape(-1)
    pos = jnp.arange(flat.shape[0], dtype=jnp.uint64)
    # position-dependent mixing so permuted state doesn't collide
    mixed = (flat ^ (pos * _MIX)) * _MIX
    return jnp.sum(mixed)


def fingerprint_tree(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    fp = jnp.uint64(0)
    for i, leaf in enumerate(leaves):
        fp = fp * _MIX + _fingerprint_leaf(leaf) + jnp.uint64(i + 1)
    return fp


def replica_divergence(topo: MeshTopology, state) -> jnp.ndarray:
    """Host entry: the ``replication.collective`` faultpoint sits at
    the host→device boundary (delay = a stalled collective round,
    error = an aborted one; a fault here can never corrupt device
    state because the program has not launched yet)."""
    fault.fire("replication.collective")
    return _replica_divergence(topo, state)


@functools.partial(jax.jit, static_argnames=("topo",))
def _replica_divergence(topo: MeshTopology, state) -> jnp.ndarray:
    """(num_shards, num_replicas) bool: True where a replica's state
    fingerprint differs from its ring-neighbor's.

    All-False ⇔ every replica of every shard is bit-identical.  A single
    corrupt replica flips exactly two entries (its own and its
    predecessor's edge), which localizes the bad replica pair; host code
    then repairs via peers (storage/repair.py) or state re-broadcast.

    ``state``: pytree of arrays with leading (num_shards, num_replicas)
    axes, sharded over both mesh axes — each device holds its own
    replica's copy of its shard's state (replicas each maintain their
    copy independently, so they *can* diverge; this detects it).
    """
    mesh = topo.mesh
    R = topo.num_replicas

    def local(state):
        fp = fingerprint_tree(jax.tree.map(lambda a: a[0, 0], state))
        perm = [(i, (i + 1) % R) for i in range(R)]
        neighbor = jax.lax.ppermute(fp, REPLICA_AXIS, perm)
        return (fp != neighbor)[None, None]

    spec = jax.tree.map(lambda _: P(SHARD_AXIS, REPLICA_AXIS), state)
    return shard_map_compat(
        local,
        mesh,
        in_specs=(spec,),
        out_specs=P(SHARD_AXIS, REPLICA_AXIS),
    )(state)


def quorum_ack(topo: MeshTopology, acks: jnp.ndarray, required: int):
    """Host entry for the quorum collective; same faultpoint contract
    as :func:`replica_divergence`."""
    fault.fire("replication.collective")
    return _quorum_ack(topo, acks, required)


@functools.partial(jax.jit, static_argnames=("topo", "required"))
def _quorum_ack(topo: MeshTopology, acks: jnp.ndarray, required: int):
    """Device-side consistency accumulation (session.go:1213-1400).

    ``acks``: (num_shards, num_replicas) bool/int — per-replica success
    bits for one replicated write round, sharded over the mesh.
    Returns ((num_shards,) bool quorum-met, (num_shards,) int32 counts),
    computed with a psum over the replica axis.
    """
    mesh = topo.mesh

    def local(a):
        got = jax.lax.psum(a.astype(jnp.int32), REPLICA_AXIS)
        return (got >= required), got

    ok, got = shard_map_compat(
        local,
        mesh,
        in_specs=(P(SHARD_AXIS, REPLICA_AXIS),),
        out_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None)),
    )(acks)
    return ok[:, 0], got[:, 0]
