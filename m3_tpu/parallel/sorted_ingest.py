"""Sort + segmented-scan + gather-merge ingest for the aggregation arenas.

Round-5 live-TPU measurements (TPU_RESULTS_r05.json window #3) showed
XLA scatter is the arena bottleneck on the flagship hardware: the C=1M
rollup ingests at ~1.07M samples/s uncontended, and even the timer's
COLLISION-FREE append scatters run ~1.4M samples/s — TPU scatter costs
~1us/element regardless of collisions.  The reference hot loop this
replaces is a hash-map walk with per-entry locks
(src/aggregator/aggregator/generic_elem.go:181-196, aggregation/
counter.go:53-76, gauge.go:53-104); the TPU-shaped answer is to use the
ops the hardware is actually fast at — sort, scan, gather:

1. ONE lexicographic sort per batch, slot-major composite key
   ``k = slot*(W+1) + window`` (the sentinel window W keeps
   window-dropped samples inside their slot's block, so per-slot
   last-write times still see them, exactly like the scatter path).
2. A head-flag segmented ``associative_scan`` computes every
   per-(window, slot) statistic — sum / sum-of-squares / count / min /
   max — in a single pass; a second single-lane scan reduces per-slot
   last-write times.
3. ``searchsorted`` GATHERS each arena cell's segment total (the last
   occurrence of its key carries the inclusive-scan segment result).
   No scatter anywhere: the merge is dense, deterministic elementwise
   work over the (W*C,) columns the ingest was going to rewrite anyway.

Semantics are pinned equal to the scatter path (tests/
test_sorted_ingest.py): OOB drops, NaN handling (counted, not summed),
gauge last-value winner rules (max time, first arrival on ties, only
strictly-newer beats the stored winner), and per-slot expiry times.
Float sums may differ from scatter order by normal reassociation
rounding; integer lanes are bit-equal.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def head_flag_scan(is_start, adds=(), mins=(), maxs=()):
    """Inclusive segmented reduction via one associative scan.

    ``is_start`` (N,) bool marks segment heads of the already-sorted
    batch.  Each array in ``adds``/``mins``/``maxs`` — shape (N,) or
    (N, ...) with any trailing lane dims — is reduced with +/min/max
    within segments; position i of a result holds the reduction of its
    segment's prefix up to i, so the LAST position of a segment holds
    the full segment total.  Returns (adds, mins, maxs) tuples in the
    caller's order.
    """
    n_adds, n_mins = len(adds), len(mins)

    def comb(a, b):
        fa, fb = a[0], b[0]
        out = [fa | fb]
        j = 1

        def sel(flag, yes, no):
            # broadcast the (k,) head flag across any trailing lane dims
            return jnp.where(
                flag.reshape(flag.shape + (1,) * (yes.ndim - 1)), yes, no)

        for _ in range(n_adds):
            out.append(sel(fb, b[j], a[j] + b[j]))
            j += 1
        for _ in range(n_mins):
            out.append(sel(fb, b[j], jnp.minimum(a[j], b[j])))
            j += 1
        for _ in range(len(maxs)):
            out.append(sel(fb, b[j], jnp.maximum(a[j], b[j])))
            j += 1
        return tuple(out)

    res = jax.lax.associative_scan(
        comb, (is_start,) + tuple(adds) + tuple(mins) + tuple(maxs))
    return (res[1:1 + n_adds], res[1 + n_adds:1 + n_adds + n_mins],
            res[1 + n_adds + n_mins:])


def last_occurrence(sorted_keys, queries):
    """(position, found) of the last occurrence of each query in
    ``sorted_keys`` — the gather side of the merge.  Positions are
    clamped valid so callers can gather unconditionally and mask with
    ``found``."""
    n = sorted_keys.shape[0]
    pos = jnp.searchsorted(sorted_keys, queries, side="right") - 1
    pos_c = jnp.clip(pos, 0, max(n - 1, 0))
    found = (pos >= 0) & (sorted_keys[pos_c] == queries)
    return pos_c, found


def composite_key(idx, slots, num_windows: int, capacity: int):
    """Slot-major sort key ``slot*(W+1) + window``.

    Valid samples (0 <= idx < W*C) keep their window; dropped samples
    (negative or sentinel idx) map to the sentinel window W so they
    stay inside their own slot's block — visible to per-slot reductions
    (scatter's last_at semantics), invisible to per-(window, slot)
    queries (nothing queries window W).  Out-of-range slots (negative
    or >= C) map to the sentinel slot C, which nothing queries either.
    (The raw scatter path would WRAP a negative slot numpy-style even
    under mode='drop' — a lowering artifact, not a contract; the
    package-wide sentinel contract, already pinned by
    xla_segment_ingest and the pallas kernel, is that invalid indices
    DROP, and the sorted impl follows it.)
    """
    window = jnp.where((idx < 0) | (idx >= num_windows * capacity),
                       num_windows, idx // capacity)
    slot_c = jnp.where((slots < 0) | (slots > capacity),
                       capacity, slots).astype(jnp.int64)
    return slot_c * (num_windows + 1) + window


def arena_queries(num_windows: int, capacity: int):
    """Composite keys for every (window, slot) arena cell, in flat
    ``window*C + slot`` order (the arenas' column layout)."""
    o = jnp.arange(num_windows * capacity, dtype=jnp.int64)
    w, c = o // capacity, o % capacity
    return c * (num_windows + 1) + w


def slot_tail_queries(num_windows: int, capacity: int):
    """For per-slot reductions: the largest possible key in each slot's
    block (window sentinel W), so last_occurrence(side=right) lands on
    the block's final element even when only dropped samples exist."""
    c = jnp.arange(capacity, dtype=jnp.int64)
    return c * (num_windows + 1) + num_windows


def slot_block_end(sorted_keys, num_windows: int, capacity: int):
    """(position, nonempty) of the final element of each slot's block
    in the slot-major sorted batch."""
    tail_q = slot_tail_queries(num_windows, capacity)
    n = sorted_keys.shape[0]
    pos = jnp.searchsorted(sorted_keys, tail_q, side="right") - 1
    pos_c = jnp.clip(pos, 0, max(n - 1, 0))
    # The block is non-empty iff the element at pos belongs to this slot.
    blk = sorted_keys[pos_c] // (num_windows + 1)
    nonempty = (pos >= 0) & (blk == jnp.arange(capacity, dtype=jnp.int64))
    return pos_c, nonempty


def merged_slot_last_at(last_at, s_k, s_tim, num_windows: int,
                        capacity: int):
    """The per-slot last-write-time merge both arenas share: segmented
    max of sorted times over slot blocks (window-dropped samples
    included, matching the scatter path's unconditional last_at bump),
    gathered at each block's end and maxed into the existing column."""
    i64_min = jnp.iinfo(jnp.int64).min
    slot_start = jnp.concatenate(
        [jnp.ones(1, bool),
         (s_k[1:] // (num_windows + 1)) != (s_k[:-1] // (num_windows + 1))])
    _, _, (stmax,) = head_flag_scan(slot_start, maxs=(s_tim,))
    spos, sfound = slot_block_end(s_k, num_windows, capacity)
    return jnp.maximum(last_at, jnp.where(sfound, stmax[spos], i64_min))
