"""Pallas TPU kernel for the aggregator's segmented ingest reduction.

SURVEY §7 phase 1 prescribes hand-written Pallas where XLA's cost model
fails; for this framework's hot ops the measured decisions are:

* **M3TSZ decode** — NOT Pallas.  The codec's per-lane dynamic bit
  cursors need per-lane gathers, which Mosaic lowers to the same
  O(S×W) masked reductions XLA does; the production formulation
  (encoding/m3tsz_jax.py) already avoids them with a carried register
  window, its HBM ceiling sits ~10× above the BASELINE target, and the
  host tail is covered by the threaded native codec (34M dp/s/core).
* **Rollup ingest** — the one op where XLA's lowering is known-risky:
  `at[idx].add` with colliding indices serializes on TPU.  The arena
  path uses XLA scatter (validated, exact); THIS module provides the
  hand-scheduled alternative — a sort-free, two-pass binned segment
  reduction shaped for the VPU — for hardware/XLA versions where the
  scatter dominates the north-star bench.

The kernel: ingest N (slot, value) pairs into C accumulator slots.
2-D grid over (slot tiles, batch slabs); each step loads one SLAB of
the batch into VMEM (BlockSpec does the slicing — the first live-TPU
run proved Mosaic rejects `lax.dynamic_slice` on VMEM values, so the
slab walk lives in the grid, not in a fori_loop) and accumulates
`value * (slot == lane_slot)` partial sums into its tile's output
block, which Pallas keeps revisiting across the inner slab dimension.
No scatter, no atomics, deterministic, and the slab copies pipeline
against compute.  Cost is O(N × C / tile) vector work: wins over
serialized scatter when the collision rate is high and C is moderate
(the downsampler's rollup arenas), loses for huge sparse C — callers
choose per shape.

Correctness is pinned against the XLA scatter path in
tests/test_pallas_ingest.py (interpret mode on CPU — semantics only).
THIS 2-D formulation has not yet compiled on a live chip: the round-5
relay died before the rewrite could be measured (TPU_RESULTS_r05.json
note_window3 — the recorded Mosaic failure is the OLD 1-D form's).
The bench's pallas stage re-validates sum/count equality on-chip
before timing, and the arena default remains XLA scatter until that
stage records a verdict for this form.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas ships with jax, but guard anyway: this module is optional
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    HAVE_PALLAS = False

TILE = 1024   # slots per grid step: 8 sublanes x 128 lanes of f32 work
SLAB = 512    # batch points per grid step: the (TILE, SLAB) hit mask
              # (2MB f32 / 4MB f64) is the kernel's VMEM high-water mark
MAX_BATCH = 1 << 18  # bounds npad so index arithmetic stays i32-safe;
                     # callers chunk bigger batches (the arenas already
                     # ingest in bounded device batches)


def _ingest_kernel(slots_ref, values_ref, out_sum_ref, out_cnt_ref,
                   *out_sq_ref):
    """One (i, j) grid step: accumulate batch slab j into slot tile i.
    slots/values arrive as (1, SLAB) VMEM blocks (BlockSpec slices the
    batch — Mosaic has no dynamic_slice, so the slab walk IS the inner
    grid dimension); outputs are (1, TILE) blocks of the (C/TILE, TILE)
    accumulators, revisited across j with explicit first-step
    initialization.  Everything is 2-D with the reduction over
    SUBLANES: the hit mask is (SLAB, TILE) — slab points down the
    sublane axis, slot lanes across — so the partial sums land
    lane-shaped, exactly the layout of the (1, TILE) output block.
    When invoked with a third output ref (the moments form), the SAME
    hit mask also accumulates the sum of squares — one batch sweep
    serves all three lanes (the arena hot path would otherwise pay the
    O(N x C/TILE) sweep twice)."""
    with_sq = bool(out_sq_ref)
    base = pl.program_id(0) * TILE
    j = pl.program_id(1)
    lane_slots = base + jax.lax.broadcasted_iota(jnp.int32, (1, TILE), 1)
    sl = slots_ref[0, :]
    va = values_ref[0, :]
    hit = sl[:, None] == lane_slots                    # (SLAB, TILE)
    # select, not multiply-by-mask: `mask * NaN` would poison every
    # slot in the tile, where the scatter oracle poisons only the hit
    # slot (the arenas pre-mask NaNs, but the kernel's contract is
    # exact equivalence with xla_segment_ingest on ANY input)
    zero = jnp.zeros((), va.dtype)
    hv = jnp.where(hit, va[:, None], zero)
    p_sum = jnp.sum(hv, axis=0, keepdims=True)         # (1, TILE)
    # counts accumulate in int32 regardless of value dtype: a
    # low-precision value dtype (bf16) would saturate its counts
    # (dtype pinned — x64 mode would promote the sum to int64)
    p_cnt = jnp.sum(hit, axis=0, keepdims=True, dtype=jnp.int32)
    # hv*hv is the already-masked value squared — NaN-safe for free
    p_sq = jnp.sum(hv * hv, axis=0, keepdims=True) if with_sq else None

    @pl.when(j == 0)
    def _init():
        out_sum_ref[:, :] = p_sum
        out_cnt_ref[:, :] = p_cnt
        if with_sq:
            out_sq_ref[0][:, :] = p_sq

    @pl.when(j > 0)
    def _accumulate():
        out_sum_ref[:, :] = out_sum_ref[:, :] + p_sum
        out_cnt_ref[:, :] = out_cnt_ref[:, :] + p_cnt
        if with_sq:
            out_sq_ref[0][:, :] = out_sq_ref[0][:, :] + p_sq


@functools.partial(jax.jit,
                   static_argnames=("capacity", "interpret", "with_sq"))
def _segment_call(slots, values, capacity: int, interpret: bool,
                  with_sq: bool):
    """Shared padding + pallas_call for the 2- and 3-output forms."""
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable in this jax build")
    C = capacity
    Cpad = ((C + TILE - 1) // TILE) * TILE
    n = values.shape[0]
    if n > MAX_BATCH:
        raise ValueError(
            f"batch of {n} exceeds MAX_BATCH={MAX_BATCH}: chunk the "
            "batch (segment_ingest_chunked / segment_moments_chunked)")
    npad = max(SLAB, ((n + SLAB - 1) // SLAB) * SLAB)  # >=1 slab (empty ok)
    # pad with an impossible slot: contributes to no tile
    slots_p = jnp.full(npad, Cpad, jnp.int32).at[:n].set(
        jnp.where((slots < 0) | (slots >= C), Cpad, slots).astype(jnp.int32))
    values_p = jnp.zeros(npad, values.dtype).at[:n].set(values)
    nslabs = npad // SLAB
    ntiles = Cpad // TILE
    # Everything 2-D: Mosaic's layout assignment wants (sublane, lane)
    # shapes (the 1-D form died in tiling on the first live-TPU run).
    slots_2d = slots_p.reshape(nslabs, SLAB)
    values_2d = values_p.reshape(nslabs, SLAB)

    # (slot tiles, batch slabs): j is the innermost (sequential)
    # dimension, so each tile's output block stays resident while the
    # whole batch streams past it slab by slab.
    grid = (ntiles, nslabs)
    out_specs = [
        pl.BlockSpec((1, TILE), lambda i, j: (i, 0)),
        pl.BlockSpec((1, TILE), lambda i, j: (i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((ntiles, TILE), values.dtype),
        jax.ShapeDtypeStruct((ntiles, TILE), jnp.int32),
    ]
    if with_sq:
        out_specs.append(pl.BlockSpec((1, TILE), lambda i, j: (i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((ntiles, TILE), values.dtype))
    outs = pl.pallas_call(
        _ingest_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, SLAB), lambda i, j: (j, 0)),
            pl.BlockSpec((1, SLAB), lambda i, j: (j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(slots_2d, values_2d)
    return tuple(o.reshape(-1)[:C] for o in outs)


def pallas_segment_ingest(slots: jnp.ndarray, values: jnp.ndarray,
                          capacity: int, interpret: bool = False):
    """Sum + count ``values`` grouped by ``slots`` into (capacity,)
    accumulators with a Pallas grid over slot tiles.

    ``slots`` out of [0, capacity) are dropped (the arena drop-sentinel
    contract).  The batch is padded to whole slabs with an
    out-of-range slot so the kernel needs no tail masking.
    """
    return _segment_call(slots, values, capacity, interpret, False)


def pallas_segment_moments(slots: jnp.ndarray, values: jnp.ndarray,
                           capacity: int, interpret: bool = False):
    """(sum, count, sum of squares) in ONE batch sweep — the arena hot
    path's shape (sum/sum²/count lanes share the hit mask)."""
    s, c, sq = _segment_call(slots, values, capacity, interpret, True)
    return s, c, sq


def _minmax_kernel(slots_ref, values_ref, out_min_ref, out_max_ref):
    """Min/max sibling of ``_ingest_kernel``: same (slot tile, batch
    slab) grid and hit mask, min/max accumulate instead of sum.  Serves
    the packed arena's min/max stage on TPU as the binned alternative
    to its segmented associative scan (aggregator/packed.py) — same
    two-pass structure as the moments form, so the flip decision can be
    measured per backend with the existing bench machinery."""
    base = pl.program_id(0) * TILE
    j = pl.program_id(1)
    lane_slots = base + jax.lax.broadcasted_iota(jnp.int32, (1, TILE), 1)
    sl = slots_ref[0, :]
    va = values_ref[0, :]
    hit = sl[:, None] == lane_slots                    # (SLAB, TILE)
    if jnp.issubdtype(va.dtype, jnp.floating):
        lo = jnp.array(-jnp.inf, va.dtype)
        hi = jnp.array(jnp.inf, va.dtype)
    else:
        info = jnp.iinfo(va.dtype)
        lo = jnp.array(info.min, va.dtype)
        hi = jnp.array(info.max, va.dtype)
    p_min = jnp.min(jnp.where(hit, va[:, None], hi), axis=0,
                    keepdims=True)
    p_max = jnp.max(jnp.where(hit, va[:, None], lo), axis=0,
                    keepdims=True)

    @pl.when(j == 0)
    def _init():
        out_min_ref[:, :] = p_min
        out_max_ref[:, :] = p_max

    @pl.when(j > 0)
    def _accumulate():
        out_min_ref[:, :] = jnp.minimum(out_min_ref[:, :], p_min)
        out_max_ref[:, :] = jnp.maximum(out_max_ref[:, :], p_max)


@functools.partial(jax.jit, static_argnames=("capacity", "interpret"))
def pallas_segment_minmax(slots, values, capacity: int,
                          interpret: bool = False):
    """Per-slot (min, max) with the binned Pallas grid.  Empty slots
    return the identities (+inf/-inf or integer extremes) — callers
    mask by their own counts, exactly the arena contract.  Slots out
    of [0, capacity) drop."""
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable in this jax build")
    C = capacity
    Cpad = ((C + TILE - 1) // TILE) * TILE
    n = values.shape[0]
    if n > MAX_BATCH:
        raise ValueError(
            f"batch of {n} exceeds MAX_BATCH={MAX_BATCH}: chunk the "
            "batch (segment_minmax_chunked)")
    npad = max(SLAB, ((n + SLAB - 1) // SLAB) * SLAB)
    slots_p = jnp.full(npad, Cpad, jnp.int32).at[:n].set(
        jnp.where((slots < 0) | (slots >= C), Cpad, slots).astype(jnp.int32))
    # pad values are never selected: pad slots point at no tile
    values_p = jnp.zeros(npad, values.dtype).at[:n].set(values)
    nslabs = npad // SLAB
    ntiles = Cpad // TILE
    grid = (ntiles, nslabs)
    outs = pl.pallas_call(
        _minmax_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, SLAB), lambda i, j: (j, 0)),
            pl.BlockSpec((1, SLAB), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, TILE), lambda i, j: (i, 0)),
            pl.BlockSpec((1, TILE), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((ntiles, TILE), values.dtype),
            jax.ShapeDtypeStruct((ntiles, TILE), values.dtype),
        ],
        interpret=interpret,
    )(slots_p.reshape(nslabs, SLAB), values_p.reshape(nslabs, SLAB))
    return tuple(o.reshape(-1)[:C] for o in outs)


def segment_minmax_chunked(slots, values, capacity: int,
                           interpret: bool | None = None):
    """`pallas_segment_minmax` over arbitrarily large batches."""
    if interpret is None:
        interpret = auto_interpret()
    n = values.shape[0]
    mn = mx = None
    for lo in range(0, max(n, 1), MAX_BATCH):
        m1, x1 = pallas_segment_minmax(
            slots[lo:lo + MAX_BATCH], values[lo:lo + MAX_BATCH],
            capacity, interpret=interpret)
        mn = m1 if mn is None else jnp.minimum(mn, m1)
        mx = x1 if mx is None else jnp.maximum(mx, x1)
    return mn, mx


def auto_interpret() -> bool:
    """Pallas runs compiled (Mosaic) only on a real TPU backend;
    everywhere else the kernel executes in interpret mode — identical
    semantics (it is plain jnp), orders of magnitude slower, which is
    why the arenas only flip to pallas by explicit config."""
    import jax

    return jax.default_backend() != "tpu"


def segment_ingest_chunked(slots, values, capacity: int,
                           interpret: bool | None = None):
    """`pallas_segment_ingest` over arbitrarily large batches: static
    MAX_BATCH chunks accumulated on device.  Shapes are static under
    jit, so the chunk loop unrolls at trace time."""
    if interpret is None:
        interpret = auto_interpret()
    n = values.shape[0]
    s = c = None
    for lo in range(0, max(n, 1), MAX_BATCH):
        s1, c1 = pallas_segment_ingest(
            slots[lo:lo + MAX_BATCH], values[lo:lo + MAX_BATCH],
            capacity, interpret=interpret)
        s = s1 if s is None else s + s1
        c = c1 if c is None else c + c1
    return s, c


def segment_moments_chunked(slots, values, capacity: int,
                            interpret: bool | None = None):
    """`pallas_segment_moments` over arbitrarily large batches."""
    if interpret is None:
        interpret = auto_interpret()
    n = values.shape[0]
    s = c = sq = None
    for lo in range(0, max(n, 1), MAX_BATCH):
        s1, c1, q1 = pallas_segment_moments(
            slots[lo:lo + MAX_BATCH], values[lo:lo + MAX_BATCH],
            capacity, interpret=interpret)
        s = s1 if s is None else s + s1
        c = c1 if c is None else c + c1
        sq = q1 if sq is None else sq + q1
    return s, c, sq


def xla_segment_ingest(slots, values, capacity: int):
    """The validated default: XLA scatter-add (what the arenas use)."""
    idx = jnp.where((slots < 0) | (slots >= capacity), capacity,
                    slots).astype(jnp.int32)
    s = jnp.zeros(capacity + 1, values.dtype).at[idx].add(
        values, mode="drop")[:capacity]
    c = jnp.zeros(capacity + 1, jnp.int32).at[idx].add(
        1, mode="drop")[:capacity]
    return s, c
