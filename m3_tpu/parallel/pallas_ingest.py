"""Pallas TPU kernel for the aggregator's segmented ingest reduction.

SURVEY §7 phase 1 prescribes hand-written Pallas where XLA's cost model
fails; for this framework's hot ops the measured decisions are:

* **M3TSZ decode** — NOT Pallas.  The codec's per-lane dynamic bit
  cursors need per-lane gathers, which Mosaic lowers to the same
  O(S×W) masked reductions XLA does; the production formulation
  (encoding/m3tsz_jax.py) already avoids them with a carried register
  window, its HBM ceiling sits ~10× above the BASELINE target, and the
  host tail is covered by the threaded native codec (34M dp/s/core).
* **Rollup ingest** — the one op where XLA's lowering is known-risky:
  `at[idx].add` with colliding indices serializes on TPU.  The arena
  path uses XLA scatter (validated, exact); THIS module provides the
  hand-scheduled alternative — a sort-free, two-pass binned segment
  reduction shaped for the VPU — for hardware/XLA versions where the
  scatter dominates the north-star bench.

The kernel: ingest N (slot, value) pairs into C accumulator slots.
Grid over slot tiles of 128×8; each grid step streams the whole batch
through VMEM and accumulates `value * (slot == lane_slot)` partial sums
with an 8×128-shaped reduction — no scatter, no atomics, deterministic.
Cost is O(N × C / tile) vector work: wins over serialized scatter when
the collision rate is high and C is moderate (the downsampler's rollup
arenas), loses for huge sparse C — callers choose per shape.

Correctness is pinned against the XLA scatter path in
tests/test_pallas_ingest.py (interpret mode on CPU — semantics only;
Mosaic lowering needs real-TPU validation, which is why the arena
default remains XLA scatter until the bench can measure both).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas ships with jax, but guard anyway: this module is optional
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    HAVE_PALLAS = False

TILE = 1024   # slots per grid step: 8 sublanes x 128 lanes of f32 work
SLAB = 512    # batch points per inner step: (TILE, SLAB) must fit VMEM
MAX_BATCH = 1 << 18  # both (npad,) inputs are VMEM-resident per grid step:
                     # ~4MB at f64 — callers chunk bigger batches (the
                     # arenas already ingest in bounded device batches)


def _ingest_kernel(slots_ref, values_ref, out_sum_ref, out_cnt_ref,
                   *out_sq_ref):
    """One grid step: accumulate the WHOLE batch into this step's
    1024-slot tile.  slots/values are (N,) in VMEM (same block every
    step); outputs are (TILE,) blocks of the (C,) accumulators.  When
    invoked with a third output ref (the moments form), the SAME hit
    mask also accumulates the sum of squares — one batch sweep serves
    all three lanes (the arena hot path would otherwise pay the
    O(N x C/TILE) sweep twice)."""
    with_sq = bool(out_sq_ref)
    step = pl.program_id(0)
    base = step * TILE
    slots = slots_ref[:]
    values = values_ref[:]
    n = slots.shape[0]
    # A (TILE, n) one-hot membership matrix would blow VMEM, so the
    # batch reduces in SLAB-point steps: each inner step materializes
    # only a (TILE, SLAB) mask (4MB at f64) and accumulates into the
    # tile's running sums.
    nslabs = (n + SLAB - 1) // SLAB
    lane_slots = base + jax.lax.broadcasted_iota(jnp.int32, (TILE, 1), 0)

    def slab_body(k, acc):
        s_sum, s_cnt, s_sq = acc
        lo = k * SLAB
        sl = jax.lax.dynamic_slice(slots, (lo,), (SLAB,))
        va = jax.lax.dynamic_slice(values, (lo,), (SLAB,))
        hitf = (sl[None, :] == lane_slots).astype(values.dtype)  # (TILE, SLAB)
        hv = hitf * va[None, :]
        s_sum = s_sum + jnp.sum(hv, axis=1)
        if with_sq:
            s_sq = s_sq + jnp.sum(hv * va[None, :], axis=1)
        # counts accumulate in int32 regardless of value dtype: a
        # low-precision value dtype (bf16) would saturate its counts
        # (dtype pinned — x64 mode would promote the sum to int64)
        s_cnt = s_cnt + jnp.sum(sl[None, :] == lane_slots, axis=1,
                                dtype=jnp.int32)
        return s_sum, s_cnt, s_sq

    zero_v = jnp.zeros((TILE,), values.dtype)
    zero_c = jnp.zeros((TILE,), jnp.int32)
    total, cnt, sq = jax.lax.fori_loop(
        0, nslabs, slab_body, (zero_v, zero_c, zero_v))
    out_sum_ref[:] = total
    out_cnt_ref[:] = cnt
    if with_sq:
        out_sq_ref[0][:] = sq


@functools.partial(jax.jit,
                   static_argnames=("capacity", "interpret", "with_sq"))
def _segment_call(slots, values, capacity: int, interpret: bool,
                  with_sq: bool):
    """Shared padding + pallas_call for the 2- and 3-output forms."""
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable in this jax build")
    C = capacity
    Cpad = ((C + TILE - 1) // TILE) * TILE
    n = values.shape[0]
    if n > MAX_BATCH:
        raise ValueError(
            f"batch of {n} exceeds MAX_BATCH={MAX_BATCH}: both input "
            "arrays are VMEM-resident per grid step — chunk the batch")
    npad = max(SLAB, ((n + SLAB - 1) // SLAB) * SLAB)  # >=1 slab (empty ok)
    # pad with an impossible slot: contributes to no tile
    slots_p = jnp.full(npad, Cpad, jnp.int32).at[:n].set(
        jnp.where((slots < 0) | (slots >= C), Cpad, slots).astype(jnp.int32))
    values_p = jnp.zeros(npad, values.dtype).at[:n].set(values)

    grid = Cpad // TILE
    out_specs = [
        pl.BlockSpec((TILE,), lambda i: (i,)),
        pl.BlockSpec((TILE,), lambda i: (i,)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((Cpad,), values.dtype),
        jax.ShapeDtypeStruct((Cpad,), jnp.int32),
    ]
    if with_sq:
        out_specs.append(pl.BlockSpec((TILE,), lambda i: (i,)))
        out_shape.append(jax.ShapeDtypeStruct((Cpad,), values.dtype))
    outs = pl.pallas_call(
        _ingest_kernel,
        grid=(grid,),
        in_specs=[
            # every grid step streams the whole batch
            pl.BlockSpec((npad,), lambda i: (0,)),
            pl.BlockSpec((npad,), lambda i: (0,)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(slots_p, values_p)
    return tuple(o[:C] for o in outs)


def pallas_segment_ingest(slots: jnp.ndarray, values: jnp.ndarray,
                          capacity: int, interpret: bool = False):
    """Sum + count ``values`` grouped by ``slots`` into (capacity,)
    accumulators with a Pallas grid over slot tiles.

    ``slots`` out of [0, capacity) are dropped (the arena drop-sentinel
    contract).  The batch is padded to whole slabs with an
    out-of-range slot so the kernel needs no tail masking.
    """
    return _segment_call(slots, values, capacity, interpret, False)


def pallas_segment_moments(slots: jnp.ndarray, values: jnp.ndarray,
                           capacity: int, interpret: bool = False):
    """(sum, count, sum of squares) in ONE batch sweep — the arena hot
    path's shape (sum/sum²/count lanes share the hit mask)."""
    s, c, sq = _segment_call(slots, values, capacity, interpret, True)
    return s, c, sq


def auto_interpret() -> bool:
    """Pallas runs compiled (Mosaic) only on a real TPU backend;
    everywhere else the kernel executes in interpret mode — identical
    semantics (it is plain jnp), orders of magnitude slower, which is
    why the arenas only flip to pallas by explicit config."""
    import jax

    return jax.default_backend() != "tpu"


def segment_ingest_chunked(slots, values, capacity: int,
                           interpret: bool | None = None):
    """`pallas_segment_ingest` over arbitrarily large batches: static
    MAX_BATCH chunks accumulated on device.  Shapes are static under
    jit, so the chunk loop unrolls at trace time."""
    if interpret is None:
        interpret = auto_interpret()
    n = values.shape[0]
    s = c = None
    for lo in range(0, max(n, 1), MAX_BATCH):
        s1, c1 = pallas_segment_ingest(
            slots[lo:lo + MAX_BATCH], values[lo:lo + MAX_BATCH],
            capacity, interpret=interpret)
        s = s1 if s is None else s + s1
        c = c1 if c is None else c + c1
    return s, c


def segment_moments_chunked(slots, values, capacity: int,
                            interpret: bool | None = None):
    """`pallas_segment_moments` over arbitrarily large batches."""
    if interpret is None:
        interpret = auto_interpret()
    n = values.shape[0]
    s = c = sq = None
    for lo in range(0, max(n, 1), MAX_BATCH):
        s1, c1, q1 = pallas_segment_moments(
            slots[lo:lo + MAX_BATCH], values[lo:lo + MAX_BATCH],
            capacity, interpret=interpret)
        s = s1 if s is None else s + s1
        c = c1 if c is None else c + c1
        sq = q1 if sq is None else sq + q1
    return s, c, sq


def xla_segment_ingest(slots, values, capacity: int):
    """The validated default: XLA scatter-add (what the arenas use)."""
    idx = jnp.where((slots < 0) | (slots >= capacity), capacity,
                    slots).astype(jnp.int32)
    s = jnp.zeros(capacity + 1, values.dtype).at[idx].add(
        values, mode="drop")[:capacity]
    c = jnp.zeros(capacity + 1, jnp.int32).at[idx].add(
        1, mode="drop")[:capacity]
    return s, c
