"""Multi-device aggregator: the full ingest→rollup step over a mesh.

This is the distribution layer the reference builds from sharded
placements + TChannel fan-out (`src/aggregator/aggregator/aggregator.go:505`
shardFor, `src/aggregator/sharding`) and multi-stage forwarded rollups
(`src/aggregator/aggregator/forwarded_writer.go`), re-designed as one SPMD
program:

* every logical shard's arenas live as a leading axis of the state arrays,
  laid out over the mesh's ``shard`` axis;
* ingest batches arrive pre-routed per shard (host shard router =
  murmur3 % num_shards, as `sharding/shardset.go:148`) and each device
  scatters only its own block — zero cross-device traffic on the hot path,
  exactly the property the reference's shard ownership gives it;
* window drain computes per-shard lanes locally, then the cross-shard
  rollup stage (the reference forwards partial aggregates between
  aggregator instances over TCP) is a single ``psum`` over the shard axis
  riding ICI.

State is replicated over the ``replica`` axis (the RF axis of an M3
placement); because the program is deterministic SPMD, replicas stay
bit-identical without the reference's leader/follower flush protocol
(`aggregator/aggregator/follower_flush_mgr.go`) — the election only picks
who *emits*.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from m3_tpu.aggregator import arena as _arena
from m3_tpu.aggregator import packed as _packed
from m3_tpu.parallel.mesh import (
    REPLICA_AXIS, SHARD_AXIS, MeshTopology, shard_map_compat,
)


_raw = _arena.raw


class ShardedAggregatorState(NamedTuple):
    # f64 layout: arena.CounterState/GaugeState/TimerState; packed
    # layout: packed.Packed*State.  All arrays carry a leading
    # (num_shards,) axis over the mesh's shard axis.
    counters: NamedTuple
    gauges: NamedTuple
    timers: NamedTuple


def sharded_init(
    topo: MeshTopology,
    num_windows: int,
    capacity: int,
    sample_capacity: int,
    layout: str | None = None,
) -> ShardedAggregatorState:
    """Per-shard arenas, placed: shard axis over the mesh's shard axis,
    replicated over the replica axis.  ``layout`` follows the
    M3_ARENA_LAYOUT seam (None = resolved; "auto" -> packed; unknown
    strings raise — see arena.resolve_layout_arg)."""
    D = topo.num_shards
    layout = _arena.resolve_layout_arg(layout)

    def rep(state):
        return jax.tree.map(
            lambda a: jax.device_put(
                jnp.broadcast_to(a[None], (D,) + a.shape), topo.sharded()
            ),
            state,
        )

    if layout == "packed":
        return ShardedAggregatorState(
            counters=rep(_packed.counter_init(num_windows, capacity)),
            gauges=rep(_packed.gauge_init(num_windows, capacity)),
            timers=rep(_packed.timer_init(num_windows, capacity,
                                          sample_capacity)),
        )
    return ShardedAggregatorState(
        counters=rep(_arena.counter_init(num_windows, capacity)),
        gauges=rep(_arena.gauge_init(num_windows, capacity)),
        timers=rep(_arena.timer_init(num_windows, capacity, sample_capacity)),
    )


class ShardedBatch(NamedTuple):
    """One pre-routed ingest batch: leading axis = logical shard."""

    windows: jnp.ndarray  # i32 (D, N) ring index per sample; OOB drops
    slots: jnp.ndarray  # i32 (D, N)
    counter_values: jnp.ndarray  # i64 (D, N)
    gauge_values: jnp.ndarray  # f64 (D, N)
    timer_values: jnp.ndarray  # f64 (D, N)
    times: jnp.ndarray  # i64 (D, N)


def sharded_ingest_consume(
    topo: MeshTopology,
    state: ShardedAggregatorState,
    batch: ShardedBatch,
    window: jnp.ndarray,
    num_windows: int,
    capacity: int,
    quantiles: tuple = (0.5, 0.95, 0.99),
    timer_packed32: bool = False,
    layout: str | None = None,
):
    """Host wrapper: resolves the arena-layout seam (None = the
    M3_ARENA_LAYOUT resolution, matching sharded_init's default;
    "auto" -> packed, unknown strings raise) and rides it into the
    jitted step as a STATIC argument — a layout flip via
    set_arena_layout retraces instead of silently running the old
    trace (the jaxlint retrace-risk / trace-frozen-config contract)."""
    layout = _arena.resolve_layout_arg(layout)
    return _sharded_ingest_consume(topo, state, batch, window,
                                   num_windows, capacity, quantiles,
                                   timer_packed32, layout)


@functools.partial(
    jax.jit,
    static_argnames=("topo", "num_windows", "capacity", "quantiles",
                     "timer_packed32", "layout"),
    donate_argnums=(1,),
)
def _sharded_ingest_consume(
    topo: MeshTopology,
    state: ShardedAggregatorState,
    batch: ShardedBatch,
    window: jnp.ndarray,  # i32 scalar: ring index to drain after ingest
    num_windows: int,
    capacity: int,
    quantiles: tuple,
    timer_packed32: bool,
    layout: str,
):
    """The framework's "training step": ingest a routed batch into every
    shard's arenas, drain one window (then reset its ring row, as the
    single-device engine pairs consume with reset_window), and produce
    both the per-shard lane matrices and the cross-shard global rollup.

    Returns (new_state, lanes) where lanes is a dict:
      counter/gauge/timer -> ((D, C, L) lanes, (D, C) counts), sharded
      rollup              -> (C, 4) global [sum, count, min, max] across
                            shards (the forwarded-pipeline stage, via
                            psum/pmin/pmax); min/max are NaN for slots
                            with no gauge samples on any shard
    """
    mesh = topo.mesh

    def local_step(state, batch, window):
        # Each device sees a (1, ...) block: its own shard.
        sq = lambda tree: jax.tree.map(lambda a: a[0], tree)
        st = ShardedAggregatorState(*map(sq, state))
        b = ShardedBatch(*(a[0] for a in batch))

        if layout == "packed":
            # One fused sort serves the counter+gauge arenas; the timer
            # appends packed words (see aggregator/packed.py).
            pidx = _packed.packed_flat_index(
                b.windows, b.slots, num_windows, capacity)
            counters, gauges = _raw(_packed.rollup_ingest)(
                st.counters, st.gauges, pidx, b.counter_values,
                b.gauge_values, b.times, num_windows, capacity)
            timers = _raw(_packed.timer_ingest)(
                st.timers, b.windows, b.slots, b.timer_values, b.times,
                capacity)
            # The packed states can only degrade LOUDLY: the engine
            # path raises from the host wrapper, so the sharded step
            # must surface the same conditions — the counter overflow-
            # pool err bits, plus timer sample-buffer overflow (the
            # fixed-capacity sharded buffer silently loses MOMENTS as
            # well as quantiles past sample_capacity, unlike the f64
            # arenas whose scatter moments survive buffer overflow).
            scap = st.timers.sample.shape[1]
            shard_err = (counters.err
                         | jnp.where((timers.sample_n > scap).any(),
                                     jnp.int32(_packed._ERR_TIMER_OVERFLOW),
                                     jnp.int32(0)))
            c_lanes, c_cnt = _raw(_packed.counter_consume)(
                counters, window, capacity)
            g_lanes, g_cnt = _raw(_packed.gauge_consume)(
                gauges, window, capacity)
            t_lanes, t_cnt = _raw(_packed.timer_consume)(
                timers, window, capacity, quantiles)
            counters = _raw(_packed.counter_reset_window)(
                counters, window, num_windows, capacity)
            gauges = _raw(_packed.gauge_reset_window)(
                gauges, window, capacity)
            timers = _raw(_packed.timer_reset_window)(
                timers, window, capacity)
        else:
            idx = _arena.flat_window_index(
                b.windows, b.slots, num_windows, capacity)

            counters = _raw(_arena.counter_ingest)(
                st.counters, idx, b.slots, b.counter_values, b.times
            )
            gauges = _raw(_arena.gauge_ingest)(
                st.gauges, idx, b.slots, b.gauge_values, b.times
            )
            timers = _raw(_arena.timer_ingest)(
                st.timers, b.windows, b.slots, b.timer_values, b.times,
                capacity
            )

            c_lanes, c_cnt = _raw(_arena.counter_consume)(
                counters, window, capacity)
            g_lanes, g_cnt = _raw(_arena.gauge_consume)(
                gauges, window, capacity)
            t_lanes, t_cnt = _raw(_arena.timer_consume)(
                timers, window, capacity, quantiles, timer_packed32
            )

            # The drained window's ring row resets for reuse (engine.py
            # consume() pairs every drain with reset_window).
            counters = _raw(_arena.counter_reset_window)(
                counters, window, capacity)
            gauges = _raw(_arena.gauge_reset_window)(
                gauges, window, capacity)
            timers = _raw(_arena.timer_reset_window)(
                timers, window, capacity)
            shard_err = jnp.int32(0)  # f64 arenas have no degraded mode

        # Cross-shard rollup stage: the multi-stage pipeline's second hop.
        # Sum/count roll up by psum; min/max by pmin/pmax over real values,
        # with the all-shards-empty NaN sentinel restored afterwards.
        g_sum = jax.lax.psum(
            jnp.nan_to_num(g_lanes[:, 5]) + c_lanes[:, 5], SHARD_AXIS
        )
        g_count = jax.lax.psum(c_lanes[:, 4] + g_lanes[:, 4], SHARD_AXIS)
        g_min = jax.lax.pmin(
            jnp.where(jnp.isnan(g_lanes[:, 1]), jnp.inf, g_lanes[:, 1]), SHARD_AXIS
        )
        g_max = jax.lax.pmax(
            jnp.where(jnp.isnan(g_lanes[:, 2]), -jnp.inf, g_lanes[:, 2]), SHARD_AXIS
        )
        g_min = jnp.where(jnp.isposinf(g_min), jnp.nan, g_min)
        g_max = jnp.where(jnp.isneginf(g_max), jnp.nan, g_max)
        rollup = jnp.stack([g_sum, g_count, g_min, g_max], axis=1)

        new_state = ShardedAggregatorState(counters, gauges, timers)
        ex = lambda tree: jax.tree.map(lambda a: a[None], tree)
        lanes = {
            "counter": (c_lanes[None], c_cnt[None]),
            "gauge": (g_lanes[None], g_cnt[None]),
            "timer": (t_lanes[None], t_cnt[None]),
            "rollup": rollup,
            # per-shard degraded-state flags: nonzero means the packed
            # layout's stats are unreliable (overflow-pool truncation /
            # timer sample overflow) — callers MUST check, the raise
            # that guards the engine path cannot fire inside shard_map
            "err": shard_err[None],
        }
        return ShardedAggregatorState(*map(ex, new_state)), lanes

    shard_spec = jax.tree.map(lambda _: P(SHARD_AXIS), state)
    batch_spec = ShardedBatch(*(P(SHARD_AXIS) for _ in batch))
    out_lane_spec = {
        "counter": (P(SHARD_AXIS), P(SHARD_AXIS)),
        "gauge": (P(SHARD_AXIS), P(SHARD_AXIS)),
        "timer": (P(SHARD_AXIS), P(SHARD_AXIS)),
        "rollup": P(),
        "err": P(SHARD_AXIS),
    }
    return shard_map_compat(
        local_step,
        mesh,
        in_specs=(shard_spec, batch_spec, P()),
        out_specs=(shard_spec, out_lane_spec),
    )(state, batch, window)


# The sharded program composes raw(ingest) ops, whose scatter-vs-pallas
# choice binds at trace time — register so arena.set_ingest_impl can
# invalidate this cache too.
_arena.register_ingest_consumer(_sharded_ingest_consume)
