"""Pallas TPU kernel for the M3TSZ phase-2 branchless field gather.

The two-phase decode (encoding/m3tsz_jax.py, round 6) splits the codec
into a cheap sequential bit-boundary scan (phase 1: control bits only,
emitting per-datapoint ``(bit_offset, field_width)`` lanes) and a fully
parallel field-extraction pass (phase 2) that pulls timestamp-DoD and
value payloads out of the packed stream words.  Phase 2's only
non-elementwise op is the GATHER: every (series, datapoint) lane needs
the 3 consecutive int32-packed words covering its bit offset.  On
XLA-CPU a ``take_along_axis`` is cheap; on TPU per-lane dynamic gathers
lower to masked reductions whose cost model XLA gets wrong for this
shape — the exact failure pallas_ingest.py exists for.  THIS module is
the hand-scheduled alternative, mirroring that file's seam:

* ``extract_fields``    — the public entry: (S, P) offsets/widths over
  (S, W32) uint32 words -> (S, P) uint64 field values.  Routes to the
  Pallas kernel or the jnp fallback via ``M3_DECODE_EXTRACT``
  (``pallas`` | ``jnp`` | ``auto``; auto = pallas only on a real TPU
  backend, everywhere else jnp — identical semantics, so CPU-only
  hosts fall back cleanly, which tier-1 pins in
  tests/test_pallas_decode.py).
* The kernel walks a 2-D grid over (series, word tiles) — all-uint32,
  Mosaic-shaped like the proven ingest kernel: the hit masks are 2-D
  (points down sublanes, word lanes across), the three gathered words
  accumulate into revisited (1, P) output blocks, and the 64-bit
  funnel shift happens OUTSIDE the kernel as plain elementwise XLA
  (no 64-bit integer ops inside Mosaic).

The word representation is int32-packed on purpose (ISSUE 6 / the
packed32 timer-drain precedent, BENCH_r05: fixed-width 32-bit lanes
are the decode-friendly layout DeXOR-class codecs standardize on):
u32 word ``k`` holds stream bits ``[32k, 32k+32)`` MSB-first, i.e. the
big-endian halves of the encoder's u64 words in order.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

try:  # pallas ships with jax, but guard anyway: this module is optional
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    HAVE_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    HAVE_PALLAS = False

U32 = jnp.uint32
U64 = jnp.uint64
I32 = jnp.int32

PT = 512   # datapoint lanes per grid row: one (1, PT) output block
WT = 512   # stream words per grid step: the (PT, WT) hit mask is the
           # kernel's VMEM high-water mark (3 x 1MB u32 compares)


def _shr64(v, s):
    """u64 >> s with s possibly >= 64 (yields 0)."""
    s = jnp.asarray(s, U64)
    return jnp.where(s >= jnp.asarray(64, U64), jnp.asarray(0, U64),
                     v >> jnp.minimum(s, jnp.asarray(63, U64)))


def _funnel64(w0, w1, w2, offs, widths):
    """The shared bit funnel: 3 consecutive u32 words -> the ``widths``-
    bit field starting at bit ``offs & 31`` of w0, right-aligned in u64.
    Pure elementwise; identical math on both impls so the Pallas path is
    bit-equal to the jnp path by construction."""
    r = (offs & jnp.asarray(31, I32)).astype(U64)
    big = (w0.astype(U64) << jnp.asarray(32, U64)) | w1.astype(U64)
    tail = jnp.where(
        r > jnp.asarray(0, U64),
        _shr64(w2.astype(U64), jnp.asarray(32, U64) - r),
        jnp.asarray(0, U64))
    funnel = ((big << r) | tail)
    return _shr64(funnel, jnp.asarray(64, U64)
                  - jnp.minimum(widths.astype(U64), jnp.asarray(64, U64)))


def _gather3_jnp(words32, offs):
    """(w0, w1, w2) at word index offs>>5 via take_along_axis — the
    XLA-CPU-fast path.  Indices clip into the caller's >=2-word zero
    pad, so out-of-range offsets read zeros, never OOB."""
    W32 = words32.shape[1]
    w = jnp.clip(offs >> jnp.asarray(5, I32), 0, max(W32 - 3, 0))
    return tuple(
        jnp.take_along_axis(words32, w + jnp.asarray(k, I32), axis=1)
        for k in range(3))


def _gather_kernel(offs_ref, words_ref, w0_ref, w1_ref, w2_ref):
    """One (s, j) grid step: accumulate word-tile j's contribution to
    series s's three gathered-word lanes.  Each datapoint's word index
    lands in exactly one tile, so accumulation across j is exact; the
    (PT, WT) hit masks put points down the sublane axis and word lanes
    across — partial sums land lane-shaped like the (1, PT) outputs."""
    j = pl.program_id(2)
    base = j * WT
    lane_ids = base + jax.lax.broadcasted_iota(I32, (1, WT), 1)
    widx = (offs_ref[0, :] >> jnp.asarray(5, I32))[:, None]   # (PT, 1)
    row = words_ref[0, :][None, :]                            # (1, WT)
    zero = jnp.zeros((), U32)
    outs = (w0_ref, w1_ref, w2_ref)
    parts = []
    for k in range(3):
        hit = (widx + jnp.asarray(k, I32)) == lane_ids        # (PT, WT)
        parts.append(jnp.sum(jnp.where(hit, row, zero), axis=1,
                             dtype=U32)[None, :])             # (1, PT)

    @pl.when(j == 0)
    def _init():
        for ref, p in zip(outs, parts):
            ref[:, :] = p

    @pl.when(j > 0)
    def _accumulate():
        for ref, p in zip(outs, parts):
            ref[:, :] = ref[:, :] + p


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gather3_pallas(words32, offs, interpret: bool):
    """The Pallas gather: same (w0, w1, w2) contract as _gather3_jnp."""
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable in this jax build")
    S, W32 = words32.shape
    P = offs.shape[1]
    Wpad = ((W32 + WT - 1) // WT) * WT
    Ppad = ((P + PT - 1) // PT) * PT
    wp = jnp.zeros((S, Wpad), U32).at[:, :W32].set(words32)
    # Clip like the jnp path so both impls read the same padded zeros
    # for out-of-range offsets (bit-parity is the contract).
    oc = jnp.clip(offs >> jnp.asarray(5, I32), 0, max(W32 - 3, 0))
    # Padding lanes carry an impossible word index (>= Wpad) so they
    # match no word lane and gather 0.
    op = jnp.full((S, Ppad), Wpad << 5, I32).at[:, :P].set(
        oc << jnp.asarray(5, I32))
    grid = (S, Ppad // PT, Wpad // WT)
    out_shape = [jax.ShapeDtypeStruct((S, Ppad), U32)] * 3
    spec_pt = pl.BlockSpec((1, PT), lambda s, p, j: (s, p))
    outs = pl.pallas_call(
        _gather_kernel,
        grid=grid,
        in_specs=[
            spec_pt,
            pl.BlockSpec((1, WT), lambda s, p, j: (s, j)),
        ],
        out_specs=[spec_pt] * 3,
        out_shape=out_shape,
        interpret=interpret,
    )(op, wp)
    return tuple(o[:, :P] for o in outs)


_IMPLS = ("pallas", "jnp", "auto")


def configured_impl() -> str:
    impl = os.environ.get("M3_DECODE_EXTRACT", "auto").strip() or "auto"
    if impl not in _IMPLS:
        raise ValueError(
            f"M3_DECODE_EXTRACT={impl!r}: expected one of {_IMPLS}")
    return impl


def resolved_impl() -> str:
    """'pallas' only where Mosaic actually compiles (a real TPU
    backend); every other host resolves to the identical-semantics jnp
    path — the clean-fallback contract tier-1 guards."""
    impl = configured_impl()
    if impl != "auto":
        return impl
    if not HAVE_PALLAS:
        return "jnp"
    return "pallas" if jax.default_backend() == "tpu" else "jnp"


def auto_interpret() -> bool:
    """Compiled Mosaic needs a TPU; anywhere else the kernel runs in
    interpret mode (plain jnp semantics, slow — test-only)."""
    return jax.default_backend() != "tpu"


def extract_fields64_t(words_t, offs_t, widths_t):
    """Scan-major u64 variant of :func:`extract_fields_t` for the jnp
    path: ``words_t`` is the (W, S) uint64 stream-word array TRANSPOSED
    so the series axis is minor.  A 64-bit read at any bit offset spans
    at most 2 consecutive u64 words, so this needs one fewer gather per
    lane than the u32 path AND skips the int32 repack of the whole
    stream array — on XLA-CPU the repack (transpose + stack + reshape
    of (2W, S)) cost more than the gathers themselves (round-6
    measurement).  The Pallas kernel keeps the u32 contract (no 64-bit
    integer ops inside Mosaic); bit-parity between the two paths is
    pinned by tests/test_pallas_decode.py."""
    W = words_t.shape[0]
    w = jnp.clip(offs_t >> jnp.asarray(6, I32), 0, max(W - 2, 0))
    wa = jnp.take_along_axis(words_t, w, axis=0, mode="promise_in_bounds")
    wb = jnp.take_along_axis(words_t, w + jnp.asarray(1, I32), axis=0,
                             mode="promise_in_bounds")
    r = (offs_t & jnp.asarray(63, I32)).astype(U64)
    big = (wa << r) | jnp.where(
        r > jnp.asarray(0, U64),
        wb >> (jnp.asarray(64, U64) - jnp.maximum(r, jnp.asarray(1, U64))),
        jnp.asarray(0, U64))
    return _shr64(big, jnp.asarray(64, U64)
                  - jnp.minimum(widths_t.astype(U64), jnp.asarray(64, U64)))


def extract_fields_t(words32_t, offs_t, widths_t, impl: str | None = None,
                     interpret: bool | None = None):
    """Scan-major variant of :func:`extract_fields`: ``words32_t`` is
    (W32, S) — the int32-packed stream words TRANSPOSED so the series
    axis is minor — and ``offs_t``/``widths_t`` are (F, S), the layout
    ``lax.scan`` stacks lane tables in.  Returns (F, S) uint64.

    On the jnp path this gathers along axis 0 directly (no transposes
    of the F-sized arrays — on XLA-CPU the three transposes the
    row-major entry point would need cost more than the gather itself);
    the Pallas kernel keeps its proven row-major grid, so that impl
    transposes at the boundary where transposes are cheap (TPU).
    """
    if impl is None:
        impl = resolved_impl()
    if impl == "pallas":
        out = extract_fields(words32_t.T, offs_t.T, widths_t.T,
                             impl=impl, interpret=interpret)
        return out.T
    W32 = words32_t.shape[0]
    w = jnp.clip(offs_t >> jnp.asarray(5, I32), 0, max(W32 - 3, 0))
    w0, w1, w2 = (
        jnp.take_along_axis(words32_t, w + jnp.asarray(k, I32), axis=0,
                            mode="promise_in_bounds")
        for k in range(3))
    return _funnel64(w0, w1, w2, offs_t, widths_t)


def extract_fields(words32, offs, widths, impl: str | None = None,
                   interpret: bool | None = None):
    """Extract ``widths[s, p]``-bit fields at bit offsets ``offs[s, p]``
    from int32-packed stream words ``words32`` (S, W32).

    Words are MSB-first u32 lanes (bits [32k, 32k+32) in word k — the
    big-endian halves of the codec's u64 words).  Width 0 yields 0;
    offsets past the stream read the caller's zero padding (callers
    pad >= 2 words).  Returns (S, P) uint64, right-aligned fields.
    """
    if impl is None:
        impl = resolved_impl()
    if impl == "pallas":
        if interpret is None:
            interpret = auto_interpret()
        w0, w1, w2 = _gather3_pallas(words32, offs, interpret=interpret)
    else:
        w0, w1, w2 = _gather3_jnp(words32, offs)
    return _funnel64(w0, w1, w2, offs, widths)
