"""Generic segmented-reduction primitives over sorted batches.

Sort + head-flag segmented ``associative_scan`` + ``searchsorted``
gather is the scatter-free reduction idiom on accelerators: reduce
within segments of an already-sorted batch in one pass, then gather
each query key's segment total from the last occurrence of the key.
query/functions.py builds its grouped PromQL aggregations on these.

(The aggregation arenas used to carry a third ingest implementation on
this idiom — parallel/sorted_ingest.py, built for TPU where scatter
measured ~1us/element.  BENCH_r05 measured it at 0.45-0.50x of the
scatter path on CPU and it was never validated faster on real TPU
hardware, so round 6 deleted it; the TPU answer to slow scatters is
the hand-scheduled Pallas kernel, parallel/pallas_ingest.py.  These
two helpers are what survived: they are generic and still earn their
keep under the query engine.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def head_flag_scan(is_start, adds=(), mins=(), maxs=()):
    """Inclusive segmented reduction via one associative scan.

    ``is_start`` (N,) bool marks segment heads of the already-sorted
    batch.  Each array in ``adds``/``mins``/``maxs`` — shape (N,) or
    (N, ...) with any trailing lane dims — is reduced with +/min/max
    within segments; position i of a result holds the reduction of its
    segment's prefix up to i, so the LAST position of a segment holds
    the full segment total.  Returns (adds, mins, maxs) tuples in the
    caller's order.
    """
    n_adds, n_mins = len(adds), len(mins)

    def comb(a, b):
        fa, fb = a[0], b[0]
        out = [fa | fb]
        j = 1

        def sel(flag, yes, no):
            # broadcast the (k,) head flag across any trailing lane dims
            return jnp.where(
                flag.reshape(flag.shape + (1,) * (yes.ndim - 1)), yes, no)

        for _ in range(n_adds):
            out.append(sel(fb, b[j], a[j] + b[j]))
            j += 1
        for _ in range(n_mins):
            out.append(sel(fb, b[j], jnp.minimum(a[j], b[j])))
            j += 1
        for _ in range(len(maxs)):
            out.append(sel(fb, b[j], jnp.maximum(a[j], b[j])))
            j += 1
        return tuple(out)

    res = jax.lax.associative_scan(
        comb, (is_start,) + tuple(adds) + tuple(mins) + tuple(maxs))
    return (res[1:1 + n_adds], res[1 + n_adds:1 + n_adds + n_mins],
            res[1 + n_adds + n_mins:])


def last_occurrence(sorted_keys, queries):
    """(position, found) of the last occurrence of each query in
    ``sorted_keys`` — the gather side of the merge.  Positions are
    clamped valid so callers can gather unconditionally and mask with
    ``found``."""
    n = sorted_keys.shape[0]
    pos = jnp.searchsorted(sorted_keys, queries, side="right") - 1
    pos_c = jnp.clip(pos, 0, max(n - 1, 0))
    found = (pos >= 0) & (sorted_keys[pos_c] == queries)
    return pos_c, found
