"""dtest: drive real node processes through destructive scenarios.

Equivalent of the reference's m3em agent + dtest harness
(`src/m3em/agent` — gRPC process lifecycle: setup/start/stop/heartbeat;
`src/cmd/tools/dtest` — node add/remove/seed scenarios driving it).
The gRPC agent collapses to direct subprocess management on one host —
the scenarios (kill -9 mid-write, restart, verify recovery) are the
point, not the transport.

`NodeProcess` owns one `m3_tpu.server.node_main` subprocess: spawn,
wait-healthy (polls the /health endpoint through the node.json status
file), graceful stop (SIGTERM → commitlog flush), hard kill (SIGKILL —
the crash case bootstrap must recover from), restart.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path


class NodeProcess:
    def __init__(self, config_path: str, root: str, env: dict | None = None):
        self.config_path = str(config_path)
        self.root = Path(root)
        self.env = dict(os.environ, **(env or {}))
        self.proc: subprocess.Popen | None = None
        self.port: int | None = None

    @property
    def status_path(self) -> Path:
        return self.root / "node.json"

    # -- lifecycle (m3em operator Setup/Start/Stop/Teardown) --------------

    @property
    def log_path(self) -> Path:
        return self.root / "node.log"

    def start(self, timeout_s: float = 120.0) -> None:
        if self.proc is not None and self.proc.poll() is None:
            raise RuntimeError("node already running")
        self.status_path.unlink(missing_ok=True)
        # stderr goes to a FILE, never a pipe: a node logging >64KB
        # would block on a full pipe buffer mid-request otherwise
        log_f = open(self.log_path, "ab")
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "m3_tpu.server.node_main",
                 self.config_path],
                env=self.env,
                stdout=subprocess.DEVNULL,
                stderr=log_f,
            )
        finally:
            log_f.close()  # the child holds its own descriptor
        self.wait_healthy(timeout_s)

    def _log_tail(self, nbytes: int = 2000) -> str:
        if self.log_path.exists():
            return self.log_path.read_bytes()[-nbytes:].decode(
                errors="replace")
        return "<no log file>"

    def wait_healthy(self, timeout_s: float) -> None:
        """Heartbeat-until-ready (m3em agent heartbeats).

        On timeout, the raised error CARRIES the diagnosis: the tail of
        the node's log file and the last /health payload (or the error
        fetching it).  A wedged node used to fail with a bare
        TimeoutError while the actual reason sat in an unprinted file
        under tmp — a soak/CI run must surface it in the failure
        itself."""
        deadline = time.monotonic() + timeout_s
        last_health: object = "<never reached /health>"
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"node died during startup (rc={self.proc.returncode}): "
                    f"{self._log_tail()}"
                )
            if self.status_path.exists():
                try:
                    status = json.loads(self.status_path.read_text())
                except json.JSONDecodeError:
                    time.sleep(0.05)
                    continue
                self.port = status["port"]
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{self.port}/health", timeout=2
                    ) as r:
                        if r.status == 200:
                            return
                except urllib.error.HTTPError as e:
                    # non-200: the BODY is the diagnosis (urlopen raises
                    # HTTPError rather than returning the response)
                    body = (e.read() or b"")[:2000].decode(errors="replace")
                    last_health = f"<health {e.code}: {body}>"
                except OSError as e:
                    last_health = f"<health fetch failed: {e}>"
            time.sleep(0.1)
        raise TimeoutError(
            f"node did not become healthy within {timeout_s:.0f}s; "
            f"last /health: {last_health!r}; log tail:\n{self._log_tail()}")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def stop(self, timeout_s: float = 30.0) -> int:
        """Graceful: SIGTERM → clean close (commitlog fsync)."""
        if not self.alive():
            return self.proc.returncode if self.proc else -1
        self.proc.send_signal(signal.SIGTERM)
        self.proc.wait(timeout=timeout_s)
        return self.proc.returncode

    def kill(self) -> None:
        """The crash scenario: SIGKILL, no cleanup, no flush."""
        if self.alive():
            self.proc.kill()
            self.proc.wait(timeout=30)

    def restart(self, timeout_s: float = 120.0) -> None:
        self.kill()  # no-op when already dead
        self.start(timeout_s)

    # -- client helpers ----------------------------------------------------

    def write_json(self, samples: list) -> int:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/api/v1/json/write",
            data=json.dumps(samples).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.load(r)["written"]

    def query_range(self, query: str, start_s: int, end_s: int,
                    step: str = "10s") -> list:
        url = (f"http://127.0.0.1:{self.port}/api/v1/query_range?"
               f"query={urllib.request.quote(query)}&start={start_s}"
               f"&end={end_s}&step={step}")
        with urllib.request.urlopen(url, timeout=60) as r:
            out = json.load(r)
        if out.get("status") != "success":
            raise RuntimeError(out)
        return out["data"]["result"]


# -- cross-process observability collection ---------------------------------
#
# The read side of round 10's tracing/histogram substrate: pull every
# process's span ring / metric scrape over HTTP and join them, so a
# scenario can assert on ONE stitched trace or ONE fleet-merged p99
# instead of per-process fragments.


def collect_traces(ports, local_spans=None, timeout_s: float = 30.0):
    """Fetch every node's span ring (``/api/v1/debug/traces``) and join
    with any in-test spans (``Span.to_dict`` rows, e.g. from the
    driving process's own Tracer) → {trace_id: [span dicts]}, each
    trace parent-before-child.  ``ports`` are HTTP (or admin) ports on
    127.0.0.1."""
    from m3_tpu.instrument.tracing import join_traces

    spans = list(local_spans or [])
    for port in ports:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/debug/traces",
                timeout=timeout_s) as r:
            spans.extend(json.load(r)["data"])
    return join_traces(spans)


def scrape_fleet(ports, timeout_s: float = 10.0):
    """Strict-parse every node's /metrics, TOLERATING dead nodes:
    ``{port: [Sample] | None}`` — None marks an unreachable node (the
    soak scrapes mid-SIGKILL, so this is a normal outcome, not an
    error).  A scrape that ARRIVES but fails the strict parser still
    raises: a live node emitting malformed exposition is a bug, not a
    fault window."""
    from m3_tpu.instrument import exposition

    out = {}
    for port in ports:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=timeout_s) as r:
                text = r.read().decode()
        except OSError:
            out[port] = None
            continue
        out[port] = exposition.parse_text(text)
    return out


def merged_histogram(ports, base: str, timeout_s: float = 30.0):
    """Scrape every node's /metrics, strict-parse, and vector-add one
    histogram's bucket lanes across the fleet.  Returns the merged
    {le: cumulative count} map — feed it to
    ``exposition.merged_quantile(merged, q)`` for fleet p50/p99.
    Exact because every Histogram shares instrument.HISTOGRAM_BOUNDS."""
    from m3_tpu.instrument import exposition

    scrapes = []
    for port in ports:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=timeout_s) as r:
            scrapes.append(exposition.parse_text(r.read().decode()))
    return exposition.merge_histograms(scrapes, base)
