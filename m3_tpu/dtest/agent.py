"""m3em-style process agent: remote node lifecycle over HTTP.

Equivalent of `src/m3em/agent` (gRPC operator: Setup/Start/Stop/
Teardown + heartbeats, proto `m3em/generated/proto/m3em/operator.proto`)
— the piece that lets the dtest harness drive node processes on OTHER
hosts instead of only its own.  gRPC collapses to a small JSON/HTTP
surface (the framework's admin-plane convention):

    POST /setup      {"name", "config_yaml"}   write config under the
                                               agent's workdir
    POST /start      {"name"}                  spawn node_main, wait
                                               healthy
    POST /stop       {"name"}                  SIGTERM (graceful)
    POST /kill       {"name"}                  SIGKILL (crash scenario)
    POST /teardown   {"name"}                  kill + delete workdir
    GET  /status                               heartbeat: every node's
                                               {alive, pid, ports}
    GET  /logs?name=n&tail=N                   last N bytes of node log

The agent reuses the local ``NodeProcess`` harness for the actual
lifecycle, so scenarios behave identically whether driven in-process
(tests) or through an agent (multi-host dtests).  ``AgentClient``
mirrors the server surface 1:1.
"""

from __future__ import annotations

import json
import shutil
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from m3_tpu.dtest.harness import NodeProcess


class Agent:
    """Owns the node processes on one host."""

    def __init__(self, workdir: str):
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.nodes: dict[str, NodeProcess] = {}
        self._mu = threading.Lock()

    # -- operator verbs (m3em operator.proto Setup/Start/Stop/Teardown) --

    @staticmethod
    def _check_name(name: str) -> str:
        """Node names become filesystem paths under the workdir (and
        teardown rmtree's them); anything that could escape is rejected
        — the agent serves remote drivers over HTTP."""
        if (not name or len(name) > 64
                or not all(c.isalnum() or c in "-_." for c in name)
                or name in (".", "..")):
            raise ValueError(f"invalid node name {name!r}")
        return name

    def setup(self, name: str, config_yaml: str) -> dict:
        name = self._check_name(name)
        with self._mu:
            if name in self.nodes and self.nodes[name].alive():
                raise ValueError(f"node {name!r} is running; stop it first")
            root = self.workdir / name / "data"
            root.mkdir(parents=True, exist_ok=True)
            cfg = self.workdir / name / "node.yaml"
            cfg.write_text(config_yaml)
            self.nodes[name] = NodeProcess(str(cfg), str(root))
            return {"name": name, "root": str(root)}

    def _node(self, name: str) -> NodeProcess:
        with self._mu:
            node = self.nodes.get(name)
        if node is None:
            raise KeyError(f"unknown node {name!r}; setup first")
        return node

    def start(self, name: str, timeout_s: float = 120.0) -> dict:
        node = self._node(name)
        node.start(timeout_s)
        return self.status()["nodes"][name]

    def stop(self, name: str) -> dict:
        rc = self._node(name).stop()
        return {"name": name, "rc": rc}

    def kill(self, name: str) -> dict:
        self._node(name).kill()
        return {"name": name, "killed": True}

    def teardown(self, name: str) -> dict:
        name = self._check_name(name)
        with self._mu:
            node = self.nodes.pop(name, None)
        if node is not None:
            node.kill()
        shutil.rmtree(self.workdir / name, ignore_errors=True)
        return {"name": name, "torn_down": True}

    def status(self) -> dict:
        """The heartbeat payload (m3em agent heartbeats carry process
        liveness the same way)."""
        with self._mu:
            snapshot = list(self.nodes.items())
        out = {}
        for name, node in snapshot:
            st = {"alive": node.alive(), "port": node.port}
            if node.status_path.exists():
                try:
                    st.update(json.loads(node.status_path.read_text()))
                except json.JSONDecodeError:
                    pass
            out[name] = st
        return {"nodes": out}

    def logs(self, name: str, tail: int = 4096) -> bytes:
        node = self._node(name)
        if not node.log_path.exists():
            return b""
        data = node.log_path.read_bytes()
        return data[-tail:]

    def close(self) -> None:
        with self._mu:
            nodes = list(self.nodes.values())
        for node in nodes:
            node.kill()


class _AgentHandler(BaseHTTPRequestHandler):
    agent: Agent = None

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, obj) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        u = urllib.parse.urlparse(self.path)
        try:
            if u.path == "/status":
                return self._json(200, self.agent.status())
            if u.path == "/logs":
                q = urllib.parse.parse_qs(u.query)
                data = self.agent.logs(q["name"][0],
                                       int(q.get("tail", ["4096"])[0]))
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            return self._json(404, {"error": f"unknown path {u.path}"})
        except (KeyError, ValueError) as e:
            return self._json(400, {"error": str(e)})

    def do_POST(self):
        path = self.path.rstrip("/")
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n)) if n else {}
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            if path == "/setup":
                return self._json(200, self.agent.setup(
                    body["name"], body["config_yaml"]))
            if path == "/start":
                return self._json(200, self.agent.start(
                    body["name"], float(body.get("timeout_s", 120.0))))
            if path == "/stop":
                return self._json(200, self.agent.stop(body["name"]))
            if path == "/kill":
                return self._json(200, self.agent.kill(body["name"]))
            if path == "/teardown":
                return self._json(200, self.agent.teardown(body["name"]))
            return self._json(404, {"error": f"unknown path {path}"})
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            return self._json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — never drop the socket
            return self._json(500, {"error": f"{type(e).__name__}: {e}"})


def serve_agent_background(workdir: str, host: str = "127.0.0.1",
                           port: int = 0):
    agent = Agent(workdir)
    handler = type("_Bound", (_AgentHandler,), {"agent": agent})
    srv = ThreadingHTTPServer((host, port), handler)
    srv.agent = agent
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class AgentClient:
    """Driver-side handle to one agent (dtest's view of m3em)."""

    def __init__(self, address: tuple[str, int], timeout_s: float = 150.0):
        self.base = f"http://{address[0]}:{address[1]}"
        self.timeout_s = timeout_s

    def _post(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            return json.load(r)

    def setup(self, name: str, config_yaml: str) -> dict:
        return self._post("/setup", {"name": name, "config_yaml": config_yaml})

    def start(self, name: str) -> dict:
        return self._post("/start", {"name": name})

    def stop(self, name: str) -> dict:
        return self._post("/stop", {"name": name})

    def kill(self, name: str) -> dict:
        return self._post("/kill", {"name": name})

    def teardown(self, name: str) -> dict:
        return self._post("/teardown", {"name": name})

    def status(self) -> dict:
        with urllib.request.urlopen(self.base + "/status",
                                    timeout=self.timeout_s) as r:
            return json.load(r)

    def logs(self, name: str, tail: int = 4096) -> bytes:
        with urllib.request.urlopen(
            f"{self.base}/logs?name={urllib.parse.quote(name)}&tail={tail}",
            timeout=self.timeout_s,
        ) as r:
            return r.read()
