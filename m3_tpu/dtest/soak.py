"""Million-series soak: the resilience substrate under production
intensity, measured.

PRs 1-5 built deadlines, admission, breakers, migration, quarantine/
scrub and faultpoints; PR 10 built the measurement substrate (fleet-
mergeable histograms, strict /metrics parsing).  This module is the
proving ground that turns both into NUMBERS: a dtest-tier load harness
that stands up a real multi-process cluster, drives sustained ingest of
a configurable series space (>=1M active series at full scale) plus
concurrent PromQL + Graphite query traffic, while a deterministic
chaos scheduler (x/chaos) injects a scripted timeline of peer death,
disk corruption, wire faults and a rolling node replace — and commits a
BENCH-style ``SOAK_rNN.json`` artifact:

* fleet-merged p50/p99 ingest + query latency PER PHASE (healthy /
  each fault window / recovered), from strict-parsed /metrics scraped
  at phase boundaries (restart-aware counter deltas, partial-scrape
  flagged) plus the driver's own observations;
* shed/backoff/error rates and breaker/migration/quarantine counter
  deltas per phase;
* a **zero-acked-sample-loss verdict**: every write the session ACKED
  at Majority is re-read at Majority after recovery and compared value-
  for-value; sha256 digests over the sorted ledger and the sorted
  recovered projection make the verdict independently checkable.

Durability accounting is exact by construction: the workload generator
is a pure function of ``(series index, sweep, seed)``, so the ledger
stores acked BATCH DESCRIPTORS (sweep, slice, timestamp), not samples —
a million-series ledger is a few hundred tuples, and verification
regenerates the expected samples bit-for-bit.  Extra samples found in
the store but not in the ledger are possible and EXPECTED (a Majority-
failed write may still have landed on one replica; at-least-once
retries re-send) — they are counted (``unacked_extras``) but are not
loss.

``cli soak`` runs it; ``cli soak --smoke`` is the tier-1 shape
(2 nodes, ~20K series, one wire-fault window, under a minute);
``cli soak --check BASELINE`` re-runs the baseline's config and exits
nonzero on SLO/loss regression — the before/after gate ROADMAP item
1's device-resident pipeline rebuild is judged with.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import shutil
import socket
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path
from typing import Dict, List

import numpy as np

from m3_tpu.x.chaos import ChaosEvent, ChaosScheduler

NS = "default"
SCHEMA = 1


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SoakConfig:
    nodes: int = 3            # initial cluster size (rf = min(3, nodes))
    series: int = 1_000_000   # bulk series space (active series >= this)
    batch: int = 10_000       # samples per ingest batch
    sweeps: int = 1           # minimum full passes over the series space
    max_sweeps: int = 12      # hard cap (chaos overrun guard)
    num_shards: int = 8
    # Per-shard active-series cap in the node config: the first 1M-run
    # hit the storage default (2^17/shard = 524K/node) as a wall of
    # rejected creations — nodes must be SIZED for the cardinality they
    # serve.  8 shards x 2^18 = 2M headroom over the 1M space + churn.
    slot_capacity: int = 1 << 18
    churn: float = 0.02       # fraction of series re-keyed per sweep
    seed: int = 10
    query_corpus: int = 200   # tagged series per engine (promql+graphite)
    query_interval_s: float = 2.0
    hist_series: int = 2000   # historical corpus (flushes to filesets —
    hist_points: int = 3      # the corruption/migration substrate)
    block_size: str = "6h"    # bulk blocks: long enough that a warm seal
    buffer_past: str = "30m"  # mid-run is unlikely (a 2h block sealing
    #                           1M series through the encoder would stall
    #                           every node for minutes on a small box)
    verify_batch: int = 20_000
    smoke: bool = False
    # phase durations (seconds); replace waits on cutover, recovered
    # lasts until the sweep target is met
    t_healthy: float = 60.0
    t_wire: float = 45.0
    t_kill: float = 60.0
    t_corrupt: float = 45.0
    wire_spec: str = "rpc.server=delay:ms=25:p=0.5;rpc.server=drop:p=0.1"
    # device-fault window (x/devguard seam): every guarded device
    # dispatch on the target node fails typed for t_device seconds —
    # the ingest buffer degrades to its host staging path, the stage
    # breaker trips, and the zero-acked-loss verdict must still hold
    # (ISSUE 13's acceptance dtest, riding the soak's own ledger).
    # 0 disables the window.
    t_device: float = 30.0
    device_spec: str = "device.dispatch=error"
    replace: bool = True
    # Self-monitoring (round 14): every node scrapes itself — and, in
    # fleet mode, its peers — into the _m3_selfmon namespace through
    # the real write path on the mediator tick, so the soak's SLO
    # record is retro-queryable PromQL history instead of harness-side
    # scrape diffs.  selfmon_windows are the burn-rate rule windows,
    # soak-scaled (a 14.4x-over-1h page rule would never fire inside a
    # minutes-long run).
    selfmon: bool = True
    selfmon_budget: int = 4000
    selfmon_long: str = "120s"
    selfmon_short: str = "30s"
    # extra SLO rule dicts appended to every node's selfmon config
    # (the acceptance dtest injects a wire-error burn rule here)
    selfmon_extra_rules: list = dataclasses.field(default_factory=list)
    # Self-healing (round 18): the x/controller control plane rides
    # every node's mediator tick whenever selfmon is on.  Its trigger
    # is a DEDICATED error-ratio rule ("ingest-errors": the share of
    # rpc write frames dropped at the wire), appended next to the
    # recorded latency SLOs — an error ratio is exactly 0.0 on a
    # healthy run, so the smoke pin (controller enabled, ZERO actions)
    # can never flake on a slow box's latency blips, while the
    # recorded 0.25s-lane SLO stays the honest latency record.  The
    # 0.90 objective (budget 0.1, factor 1.0) fires at >10% dropped
    # frames: the smoke wire window (drop p=0.05) stays below it, the
    # selfheal sustained window (drop p=0.4) blows through it.
    controller: bool = True
    controller_fire_ticks: int = 3
    controller_clear_ticks: int = 3
    controller_hold_ticks: int = 2
    controller_min_interval: str = "3s"
    # selfheal phase: a ``sustained`` chaos event (arm + hold +
    # auto-disarm as ONE timeline entry) hard enough to trip the
    # controller; off by default so the pinned phase-label lists of
    # the full and smoke shapes stay exactly as committed.
    selfheal: bool = False
    t_selfheal: float = 45.0
    selfheal_spec: str = "rpc.server=drop:p=0.4"
    # Disk-pressure resilience (round 20): with disk_capacity set, each
    # node runs its x/diskbudget ledger in capacity-quota mode (the
    # nodes share one real filesystem, so statvfs would watermark them
    # all at once) and the timeline gains a disk_pressure window:
    # ballast-fill node 1's root to disk_spec (a target FREE ratio),
    # hold t_disk seconds, auto-release.  The reserve is deliberately
    # small — soak quotas are hundreds of MB, and the 64M production
    # default would put CRITICAL at absurdly high free ratios.
    # disk_rule optionally binds the controller's emergency_cleanup
    # pulse to the disk-pressure SLO rule ("" = record-only).
    disk_capacity: str = ""   # per-node byte quota; "" = ledger off
    disk_reserve: str = "4M"
    disk_low: float = 0.25
    disk_crit: float = 0.10
    t_disk: float = 0.0       # disk-pressure window seconds; 0 = off
    disk_spec: str = "0.18"   # ballast target free ratio (LOW, not crit)
    disk_rule: str = ""       # controller disk binding rule name

    @classmethod
    def smoke_config(cls, **kw) -> "SoakConfig":
        """The tier-1 shape: 2 nodes, ~20K series, one wire-fault
        window, no kill/corrupt/replace — generator, chaos scheduler,
        ledger verify and artifact schema exercised end to end in well
        under a minute of load."""
        base = dict(
            nodes=2, series=20_000, batch=2_000, sweeps=2, num_shards=2,
            slot_capacity=1 << 16, churn=0.05, query_corpus=40,
            query_interval_s=1.0,
            hist_series=200, hist_points=2, verify_batch=5_000, smoke=True,
            t_healthy=6.0, t_wire=10.0, t_kill=0.0, t_corrupt=0.0,
            t_device=8.0,
            wire_spec="rpc.server=delay:ms=10:p=0.4;rpc.server=drop:p=0.05",
            replace=False,
            # short disk-pressure window: ballast to free=0.18 — LOW
            # (eager cleanup, ledger visible) but above CRITICAL, so
            # nothing sheds and the quiet-controller pin still holds
            disk_capacity="192M", t_disk=6.0,
        )
        base.update(kw)
        return cls(**base)

    @property
    def rf(self) -> int:
        return min(3, self.nodes)


def build_timeline(cfg: SoakConfig) -> List[ChaosEvent]:
    """The scripted chaos: phase marks bucket the SLOs, fault events
    ride between them.  Offsets are fixed by config — same config +
    seed = same chaos (the determinism contract TESTING.md documents).

    Full shape:  healthy → wire_faults (delay+drop at the rpc server
    boundary of node 1) → sigkill (node nodes-1 killed cold, restarted
    mid-window: WAL replay + peers bootstrap under load) → corrupt
    (byte-flipped flushed fileset on node 1 → scrub → quarantine → peer
    repair) → replace (rolling replace of node nodes-1 by the spare
    through the migration path) → recovered."""
    ev: List[ChaosEvent] = []
    t = 0.0
    ev.append(ChaosEvent(t, "phase", arg="healthy"))
    t += cfg.t_healthy
    ev.append(ChaosEvent(t, "phase", arg="wire_faults"))
    ev.append(ChaosEvent(t + 1, "wire_fault", node=1 % cfg.nodes,
                         arg=cfg.wire_spec))
    t += cfg.t_wire
    ev.append(ChaosEvent(t - 1, "clear_faults", node=1 % cfg.nodes))
    if cfg.t_device > 0:
        # Device-boundary faults on node 0 (always a write target of
        # the replicated session): guarded stages fail typed, the
        # buffer append degrades to host staging, breakers trip —
        # acked samples must all verify after the window clears.
        ev.append(ChaosEvent(t, "phase", arg="device_faults"))
        ev.append(ChaosEvent(t + 1, "device_fault", node=0,
                             arg=cfg.device_spec))
        t += cfg.t_device
        ev.append(ChaosEvent(t - 1, "clear_faults", node=0))
    if cfg.t_disk > 0 and cfg.disk_capacity:
        # Disk-pressure window: one windowed event ballast-fills node
        # 1's root to the target free ratio and auto-releases 2s before
        # the phase ends, so 'recovered' (or the next window) starts
        # with the ledger relaxing back.
        ev.append(ChaosEvent(t, "phase", arg="disk_pressure"))
        ev.append(ChaosEvent(t + 1, "disk_pressure", node=1 % cfg.nodes,
                             arg=cfg.disk_spec,
                             hold_s=max(1.0, cfg.t_disk - 3)))
        t += cfg.t_disk
    victim = cfg.nodes - 1
    if cfg.t_kill > 0:
        ev.append(ChaosEvent(t, "phase", arg="sigkill"))
        ev.append(ChaosEvent(t + 1, "kill", node=victim))
        ev.append(ChaosEvent(t + max(2.0, cfg.t_kill * 0.4), "restart",
                             node=victim))
        t += cfg.t_kill
    if cfg.t_corrupt > 0:
        ev.append(ChaosEvent(t, "phase", arg="corrupt"))
        ev.append(ChaosEvent(t + 1, "corrupt", node=1 % cfg.nodes))
        t += cfg.t_corrupt
    if cfg.replace:
        ev.append(ChaosEvent(t, "phase", arg="replace"))
        ev.append(ChaosEvent(t + 1, "replace", node=victim))
        t += 2  # replace blocks until cutover; recovered marks after it
    if cfg.selfheal and cfg.t_selfheal > 0:
        # One ``sustained`` entry: arm the heavy drop spec on node 1,
        # hold long enough for the controller to shed, auto-disarm 2s
        # before the phase ends so the recovered window starts clean.
        ev.append(ChaosEvent(t, "phase", arg="selfheal"))
        ev.append(ChaosEvent(t + 1, "sustained", node=1 % cfg.nodes,
                             arg=cfg.selfheal_spec,
                             hold_s=max(1.0, cfg.t_selfheal - 3)))
        t += cfg.t_selfheal
    ev.append(ChaosEvent(t, "phase", arg="recovered"))
    return ev


# ---------------------------------------------------------------------------
# workload generator (columnar, pure function of (index, sweep, seed))
# ---------------------------------------------------------------------------

_MIX = 2654435761  # Knuth multiplicative hash


class WorkloadGen:
    """Deterministic columnar sample generator.

    Three value families striped across the series space by index:
    gauge noise (hash-mixed), monotonic counters (sweep-scaled), and
    spiky (quiet baseline with periodic 1e6 spikes).  A seeded ``churn``
    subset re-keys every sweep (``.g<sweep>`` suffix) — sustained NEW
    series creation, the pressure the new-series limiter and index
    exist to absorb.  Everything is a pure function of
    ``(index, sweep, seed)`` so the soak ledger can store slice
    descriptors and regenerate expected samples exactly at verify
    time."""

    def __init__(self, series: int, churn: float = 0.02, seed: int = 0):
        self.series = int(series)
        self.churn = float(churn)
        self.seed = int(seed)

    def _churned(self, idx: np.ndarray) -> np.ndarray:
        return ((idx * _MIX + self.seed * 1013904223) % 100_000
                < self.churn * 100_000)

    def ids(self, sweep: int, lo: int, hi: int) -> List[bytes]:
        idx = np.arange(lo, hi)
        gens = np.where(self._churned(idx), sweep, 0)
        return [b"soak.%08d.g%03d" % (i, g)
                for i, g in zip(idx.tolist(), gens.tolist())]

    def values(self, sweep: int, lo: int, hi: int) -> np.ndarray:
        idx = np.arange(lo, hi, dtype=np.int64)
        fam = idx % 3
        gauge = ((idx * _MIX + (sweep + self.seed) * 40503)
                 & 0xFFFFF).astype(np.float64) / 1048.576
        counter = (sweep + 1.0) * ((idx % 97) + 1.0)
        spiky = np.where((idx + sweep) % 50 == 0, 1e6, 1.0)
        return np.where(fam == 0, gauge, np.where(fam == 1, counter, spiky))

class Ledger:
    """Acked-write ledger: batch DESCRIPTORS, not samples.

    ``bulk`` rows are ``(sweep, lo, hi, ts)`` — regenerated through the
    same WorkloadGen at verify; ``explicit`` rows are ``(sid, ts, val)``
    for the small corpora (historical seed, query corpus).  ``expected``
    expands the whole thing into {sid: {ts: val}} (last write wins on
    the impossible same-(sid,ts) collision, matching storage)."""

    def __init__(self, gen: WorkloadGen):
        self.gen = gen
        self.bulk: List[tuple] = []
        self.explicit: List[tuple] = []
        self._lock = threading.Lock()

    def ack_bulk(self, sweep: int, lo: int, hi: int, ts: int) -> None:
        with self._lock:
            self.bulk.append((sweep, lo, hi, ts))

    def ack_explicit(self, rows) -> None:
        with self._lock:
            self.explicit.extend(rows)

    @property
    def acked_samples(self) -> int:
        with self._lock:
            return (sum(hi - lo for _, lo, hi, _ in self.bulk)
                    + len(self.explicit))

    def expected(self) -> Dict[bytes, Dict[int, float]]:
        with self._lock:
            bulk = list(self.bulk)
            explicit = list(self.explicit)
        out: Dict[bytes, Dict[int, float]] = {}
        for sweep, lo, hi, ts in bulk:
            ids = self.gen.ids(sweep, lo, hi)
            vals = self.gen.values(sweep, lo, hi)
            for sid, v in zip(ids, vals.tolist()):
                out.setdefault(sid, {})[ts] = v
        for sid, ts, v in explicit:
            out.setdefault(sid, {})[int(ts)] = float(v)
        return out


def _digest(stream) -> str:
    """sha256 over canonical sample lines (sorted upstream)."""
    h = hashlib.sha256()
    for sid, ts, val in stream:
        h.update(sid)
        h.update(b"\t%d\t%r\n" % (ts, val))
    return h.hexdigest()


# ---------------------------------------------------------------------------
# phase tracking (driver observations + /metrics boundary scrapes)
# ---------------------------------------------------------------------------


class _Phase:
    __slots__ = ("name", "t_start", "t_end", "ingest_lat", "query_lat",
                 "acked_batches", "acked_samples", "failed_batches",
                 "query_ok", "query_shed", "query_err", "scrape_before",
                 "scrape_after")

    def __init__(self, name: str, t_start: float, scrape_before):
        self.name = name
        self.t_start = t_start
        self.t_end: float | None = None
        self.ingest_lat: List[float] = []
        self.query_lat: List[float] = []
        self.acked_batches = 0
        self.acked_samples = 0
        self.failed_batches = 0
        self.query_ok = 0
        self.query_shed = 0
        self.query_err = 0
        self.scrape_before = scrape_before
        self.scrape_after = None


# counter deltas reported per phase: (artifact key, /metrics name)
_PHASE_COUNTERS = (
    ("db_writes", "m3tpu_db_writes"),
    ("shard_not_owned", "m3tpu_db_shard_not_owned"),
    ("new_series_rejected", "m3tpu_db_new_series_rejected"),
    ("corruption_detected", "m3tpu_db_corruption_detected"),
    ("corruption_quarantined", "m3tpu_db_corruption_quarantined"),
    ("scrub_repairs", "m3tpu_scrub_repairs_completed"),
    ("migration_blocks_streamed", "m3tpu_topology_blocks_streamed"),
    ("query_shed_total", "m3tpu_query_shed_total"),
    ("query_deadline_exceeded", "m3tpu_query_deadline_exceeded_total"),
)


class PhaseTracker:
    """Phase-bucketed SLO accounting.  ``transition(label)`` scrapes the
    whole fleet ONCE (tolerating dead nodes) and uses that scrape as
    both the closing boundary of the old phase and the opening boundary
    of the new one, so per-phase /metrics deltas tile the run exactly."""

    def __init__(self, scrape_fn):
        self._scrape = scrape_fn
        self._lock = threading.Lock()
        self.phases: List[_Phase] = []
        self._t0 = time.monotonic()

    @property
    def current(self) -> _Phase | None:
        with self._lock:
            return self.phases[-1] if self.phases else None

    def transition(self, label: str) -> None:
        now = time.monotonic() - self._t0
        scrape = self._scrape()
        with self._lock:
            if self.phases:
                self.phases[-1].t_end = now
                self.phases[-1].scrape_after = scrape
            self.phases.append(_Phase(label, now, scrape))

    def finish(self) -> None:
        self.transition("__end__")
        with self._lock:
            self.phases.pop()  # the sentinel carried the closing scrape

    def record_ingest(self, latency_s: float, n: int) -> None:
        with self._lock:
            if self.phases:
                p = self.phases[-1]
                p.ingest_lat.append(latency_s)
                p.acked_batches += 1
                p.acked_samples += n

    def record_ingest_failure(self) -> None:
        with self._lock:
            if self.phases:
                self.phases[-1].failed_batches += 1

    def record_query(self, latency_s: float, outcome: str) -> None:
        with self._lock:
            if self.phases:
                p = self.phases[-1]
                if outcome == "ok":
                    p.query_lat.append(latency_s)
                    p.query_ok += 1
                elif outcome == "shed":
                    p.query_shed += 1
                else:
                    p.query_err += 1

    # -- artifact rendering -------------------------------------------------

    def render(self) -> List[dict]:
        from m3_tpu.instrument import exposition

        out = []
        for p in self.phases:
            dur = (p.t_end or (time.monotonic() - self._t0)) - p.t_start

            def _lat(vals):
                if not vals:
                    return {"n": 0, "driver_p50_ms": None,
                            "driver_p99_ms": None}
                a = np.asarray(vals)
                return {"n": len(vals),
                        "driver_p50_ms": round(float(np.quantile(a, 0.5))
                                               * 1e3, 3),
                        "driver_p99_ms": round(float(np.quantile(a, 0.99))
                                               * 1e3, 3)}

            rec = {
                "name": p.name,
                "start_s": round(p.t_start, 1),
                "duration_s": round(dur, 1),
                "ingest": dict(
                    _lat(p.ingest_lat),
                    acked_batches=p.acked_batches,
                    acked_samples=p.acked_samples,
                    failed_batches=p.failed_batches,
                    samples_per_s=round(p.acked_samples / dur, 1)
                    if dur > 0 else None,
                ),
                "query": dict(
                    _lat(p.query_lat),
                    ok=p.query_ok, shed=p.query_shed, errors=p.query_err,
                ),
            }
            if p.scrape_after is not None:
                rec["fleet_ingest"] = exposition.fleet_summary(
                    p.scrape_after, "m3tpu_db_write_batch_seconds",
                    before=p.scrape_before)
                rec["fleet_query"] = exposition.fleet_summary(
                    p.scrape_after, "m3tpu_query_seconds",
                    before=p.scrape_before)
                deltas = {}
                for key, metric in _PHASE_COUNTERS:
                    total = 0.0
                    for node, after in p.scrape_after.items():
                        if after is None:
                            continue
                        a = exposition.counter_value(after, metric)
                        b = exposition.counter_value(
                            (p.scrape_before or {}).get(node), metric)
                        # restart-aware: a counter below its previous
                        # value means the process restarted — the new
                        # process's absolute value IS the delta
                        total += a if a < b else a - b
                    deltas[key] = total
                rec["counters"] = deltas
            out.append(rec)
        return out


# ---------------------------------------------------------------------------
# the cluster (real node processes) + chaos ops adapter
# ---------------------------------------------------------------------------


def _free_ports(n: int) -> list:
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            socks.append(s)  # registered before bind: no leak on raise
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


class SoakCluster:
    """N+1 real node processes (the +1 is the replace spare) over a
    shared remote KV, placement installed through the admin API, all
    chaos verbs implemented against live public surfaces: SIGKILL +
    restart via the process harness, wire faults via
    ``POST /api/v1/debug/faults``, corruption via on-disk byte flips +
    admin scrub, replace via the placement admin verb + the PR 4
    migration path.  Also the ChaosScheduler's ops adapter."""

    def __init__(self, cfg: SoakConfig, workdir: Path, tracker: PhaseTracker
                 | None = None):
        self.cfg = cfg
        self.workdir = Path(workdir)
        self.tracker = tracker
        self.kv_srv = None
        self.kv = None
        self.session = None
        self.nodes: List = []
        self.rpc_ports: List[int] = []
        self.total = cfg.nodes + (1 if cfg.replace else 0)
        self.log: List[str] = []
        self._log_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def note(self, msg: str) -> None:
        with self._log_lock:
            self.log.append(f"{time.strftime('%H:%M:%S')} {msg}")

    def _selfmon_config(self, k: int) -> dict:
        """Node k's selfmon section (JSON is valid YAML): fleet mode —
        every node scrapes every OTHER node's /metrics under its
        instance tag — with the soak-scaled burn windows (the 1h/6h
        SRE defaults would never fire inside a minutes-long run)."""
        from m3_tpu.query.slo import latency_ratio

        win = [{"long": self.cfg.selfmon_long,
                "short": self.cfg.selfmon_short, "factor": 2.0}]
        rules = [
            {"name": "ingest-latency", "objective": 0.999,
             "ratio": latency_ratio("m3tpu_db_write_batch_seconds", "0.25"),
             "windows": win},
            {"name": "query-latency", "objective": 0.99,
             "ratio": latency_ratio("m3tpu_query_seconds", "1.0"),
             "windows": win},
        ] + list(self.cfg.selfmon_extra_rules)
        if self.cfg.controller:
            # The controller's dedicated trigger (see SoakConfig): the
            # dropped-frame share of rpc write traffic, scoped to THIS
            # node's instance — self-healing is a node-local decision
            # on the node's OWN burn, and the fleet-wide sum would
            # dilute one node's drops under every peer's (selfmon-
            # inflated) completion rate.  Zero on a healthy run by
            # construction; fires past 10% drops (the drop share can
            # never exceed the armed drop probability, so the smoke
            # window's p=0.05 is quiet by margin, not by luck).
            # fault_drop_triggers is the x/fault mirror every node
            # exposes; both sides are frame-rate, same unit.
            inst = f'{{instance="i{k}"}}'
            # FIRST in the rule list: the whole pass runs under one
            # deadline budget and rules past it degrade to "error"
            # (burn unknown) — the control plane's sensor must never
            # be the one starved behind the heavy latency-histogram
            # rules on a loaded box (unknown means HOLD forever).
            rules.insert(0, {
                "name": "ingest-errors", "objective": 0.90,
                "ratio": (f"sum(rate(fault_drop_triggers{inst}"
                          "[{window}])) / "
                          "clamp_min(sum(rate("
                          f"m3tpu_db_write_batch_seconds_count{inst}"
                          "[{window}])) + "
                          f"sum(rate(fault_drop_triggers{inst}"
                          "[{window}])), 0.1)"),
                "windows": [{"long": "30s", "short": "10s",
                             "factor": 1.0}],
            })
            if self.cfg.selfheal:
                # Satellite of round 20 (ROADMAP item-7 follow-on):
                # the selfheal profile binds the device lane too.  The
                # ratio is the fallback share of guarded device calls
                # — exactly 0.0 on a healthy run (same no-flake
                # property as ingest-errors), driven hard by the
                # device_fault sustained window.
                rules.insert(1, {
                    "name": "device-errors", "objective": 0.90,
                    "ratio": ("sum(rate(device_fallback_total"
                              f"{inst}" "[{window}])) / "
                              "clamp_min(sum(rate(device_guard_calls"
                              f"{inst}" "[{window}])), 0.1)"),
                    "windows": [{"long": "30s", "short": "10s",
                                 "factor": 1.0}],
                })
        if self.cfg.disk_capacity:
            # Round 20: disk headroom as an SLO.  disk_free_ratio is a
            # LEVEL (a gauge), not an event rate, so the burn ratio is
            # "how far below the LOW watermark did this window get",
            # normalized over the LOW→CRITICAL span: 0.0 at/above LOW,
            # 1.0 at/below CRITICAL.  max_over_time makes a brief dip
            # count for the whole window — exactly what paging on disk
            # pressure should do.  Node-local, like ingest-errors.
            inst = f'{{instance="i{k}"}}'
            span = max(0.01, self.cfg.disk_low - self.cfg.disk_crit)
            rules.insert(0, {
                "name": "disk-pressure", "objective": 0.75,
                "ratio": (f"clamp_max(clamp_min({self.cfg.disk_low} - "
                          f"max_over_time(disk_free_ratio{inst}"
                          "[{window}])" f", 0) / {span}, 1)"),
                "windows": [{"long": "30s", "short": "10s",
                             "factor": 1.0}],
            })
        return {
            "enabled": True, "every": 1,
            "budget": self.cfg.selfmon_budget,
            "instance": f"i{k}",
            "peers": [f"i{i}=127.0.0.1:{p}"
                      for i, p in enumerate(self.fixed_http_ports)
                      if i != k],
            # 3 rules x 2 windows x 2 ratio queries over a fleet-
            # scraped namespace on a shared box: the 2s default budget
            # systematically starves the tail of the rule list
            "slo_deadline": "6s",
            "default_rules": False, "rules": rules,
        }

    def _controller_config(self) -> dict:
        """Every node's round-18 control plane: the ingest binding
        rides the dedicated error-ratio trigger; the latency SLOs stay
        record-only (bound to nothing) so a slow box's latency blips
        can never move an actuator mid-run."""
        cfg = self.cfg
        return {
            "enabled": True, "every": 1,
            "ingest_rule": "ingest-errors", "query_rule": "",
            # selfheal profile binds the device and node lanes too
            # (round 20 satellite): device burn → the devguard/
            # checkpoint/membudget actuators; node burn → rebalance,
            # with disk pressure as its realistic driver.  Default
            # profile leaves both record-only, so the smoke quiet-
            # controller pin can never flake.
            "device_rule": "device-errors" if cfg.selfheal else "",
            "node_rule": ("disk-pressure"
                          if cfg.selfheal and cfg.disk_capacity else ""),
            "disk_rule": cfg.disk_rule,
            "fire_ticks": cfg.controller_fire_ticks,
            "clear_ticks": cfg.controller_clear_ticks,
            "hold_ticks": cfg.controller_hold_ticks,
            "min_action_interval": cfg.controller_min_interval,
        }

    def start(self) -> None:
        from m3_tpu.client.session import ConsistencyLevel, ReplicatedSession
        from m3_tpu.cluster.kv_remote import (
            RemoteKVStore, serve_kv_background,
        )
        from m3_tpu.dtest.harness import NodeProcess
        from m3_tpu.server.rpc import RemoteDatabase

        (self.workdir / "kv").mkdir(parents=True, exist_ok=True)
        self.kv_srv = serve_kv_background(root=str(self.workdir / "kv"))
        # HTTP ports are pre-allocated (not ephemeral) since round 14:
        # the selfmon fleet mode needs every node's /metrics endpoint
        # in every OTHER node's static config.  ONE _free_ports call
        # for both sets — two calls could hand the second set a port
        # the kernel just released from the first (bind-failure flake).
        ports = _free_ports(2 * self.total)
        self.rpc_ports = ports[:self.total]
        self.fixed_http_ports = ports[self.total:]
        for k in range(self.total):
            root = self.workdir / f"n{k}" / "data"
            cfgp = self.workdir / f"n{k}" / "node.yaml"
            peers = [f"127.0.0.1:{p}" for i, p in enumerate(self.rpc_ports)
                     if i != k]
            selfmon_yaml = ""
            if self.cfg.selfmon:
                selfmon_yaml = "selfmon: " + json.dumps(
                    self._selfmon_config(k)) + "\n"
                if self.cfg.controller:  # requires selfmon (validated)
                    selfmon_yaml += "controller: " + json.dumps(
                        self._controller_config()) + "\n"
            if self.cfg.disk_capacity:
                # capacity-quota mode: all nodes share one real
                # filesystem, so statvfs would watermark them together
                selfmon_yaml += "disk: " + json.dumps({
                    "enabled": True,
                    "capacity": self.cfg.disk_capacity,
                    "reserve": self.cfg.disk_reserve,
                    "low_ratio": self.cfg.disk_low,
                    "critical_ratio": self.cfg.disk_crit,
                }) + "\n"
            cfgp.parent.mkdir(parents=True, exist_ok=True)
            cfgp.write_text(f"""
db:
  root: {root}
  instance_id: i{k}
  kv_endpoint: 127.0.0.1:{self.kv_srv.port}
  rpc_listen_port: {self.rpc_ports[k]}
  peers: [{", ".join(repr(p) for p in peers)}]
  bootstrap_peers: true
  namespaces:
    default:
      num_shards: {self.cfg.num_shards}
      slot_capacity: {self.cfg.slot_capacity}
      block_size: {self.cfg.block_size}
      buffer_past: {self.cfg.buffer_past}
coordinator: {{listen_port: {self.fixed_http_ports[k]}, admin_listen_port: 0}}
mediator:
  enabled: true
  tick_interval: {"1s" if self.cfg.smoke else "2s"}
  snapshot_every: 1000000
  cleanup_every: 30
  scrub_volumes: 0
  migrate_blocks: 4
  migrate_grace_ticks: 2
{selfmon_yaml}""")
            root.mkdir(parents=True, exist_ok=True)
            self.nodes.append(NodeProcess(
                str(cfgp), str(root), env={"M3_DRAIN_TIMEOUT_S": "60"}))
        for k in range(self.cfg.nodes):  # the spare stays down for now
            self.nodes[k].start(timeout_s=300)
        self.note(f"{self.cfg.nodes} nodes up (+{self.total - self.cfg.nodes} "
                  "spare config)")
        self._admin(0, "POST", "/api/v1/services/m3db/placement/init", {
            "instances": [
                {"id": f"i{k}", "isolation_group": f"g{k}",
                 "endpoint": f"127.0.0.1:{self.rpc_ports[k]}"}
                for k in range(self.cfg.nodes)
            ],
            "num_shards": self.cfg.num_shards, "rf": self.cfg.rf,
        })

        def resolve(inst):
            h, _, p = inst.endpoint.rpartition(":")
            return RemoteDatabase((h, int(p)))

        self.kv = RemoteKVStore(("127.0.0.1", self.kv_srv.port),
                                watch_poll_s=0.25)
        self.session = ReplicatedSession.dynamic(
            self.kv, resolve,
            write_level=ConsistencyLevel.MAJORITY,
            read_level=ConsistencyLevel.MAJORITY,
        )

    def close(self) -> None:
        if self.session is not None:
            self.session.close()
        if self.kv is not None:
            self.kv.close()
        for nd in self.nodes:
            nd.kill()
        if self.kv_srv is not None:
            self.kv_srv.shutdown()
            self.kv_srv.server_close()

    # -- node access -------------------------------------------------------

    def _status(self, k: int) -> dict:
        return json.loads(
            (self.workdir / f"n{k}" / "data" / "node.json").read_text())

    def http_port(self, k: int) -> int | None:
        try:
            return self._status(k)["port"]
        except (OSError, ValueError, KeyError):
            return None

    def _admin(self, k: int, method: str, path: str, body=None) -> dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self._status(k)['admin_port']}{path}",
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.load(r)

    def node_post(self, k: int, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self._status(k)['port']}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.load(r)

    def alive_nodes(self) -> List[int]:
        return [k for k in range(self.total)
                if k < len(self.nodes) and self.nodes[k].alive()]

    def promql(self, k: int, query: str, namespace: str | None = None,
               time_s: int | None = None, timeout_s: float = 60.0) -> list:
        """Instant PromQL query against node k's HTTP API; with
        ``namespace`` the query runs over that namespace's storage
        (how ``_m3_selfmon`` history is read from outside)."""
        url = (f"http://127.0.0.1:{self.http_port(k)}/api/v1/query?"
               f"query={urllib.request.quote(query)}"
               f"&time={time_s if time_s is not None else int(time.time())}")
        if namespace:
            url += f"&namespace={namespace}"
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            out = json.load(r)
        if out.get("status") != "success":
            raise RuntimeError(out)
        return out["data"]["result"]

    def scrape_all(self) -> dict:
        """{node index: parsed /metrics | None} — the PhaseTracker's
        boundary scrape.  Dead/mid-restart nodes scrape as None (the
        partial-merge path exposition.fleet_summary flags)."""
        from m3_tpu.dtest.harness import scrape_fleet

        started = [k for k in range(self.total)
                   if (self.workdir / f"n{k}" / "data" / "node.json").exists()
                   or self.nodes[k].alive()]
        ports = {k: self.http_port(k) for k in started}
        by_port = scrape_fleet([p for p in ports.values() if p], timeout_s=10)
        return {k: (by_port.get(p) if p is not None else None)
                for k, p in ports.items()}

    # -- chaos ops (ChaosScheduler adapter) --------------------------------

    def phase(self, label: str) -> None:
        self.note(f"phase -> {label}")
        if self.tracker is not None:
            self.tracker.transition(label)

    def kill(self, k: int) -> None:
        self.note(f"SIGKILL node {k}")
        self.nodes[k].kill()

    def restart(self, k: int) -> None:
        self.note(f"restart node {k}")
        self.nodes[k].restart(timeout_s=600)

    def arm_faults(self, k: int, spec: str) -> None:
        self.note(f"arm faults on node {k}: {spec}")
        self.node_post(k, "/api/v1/debug/faults",
                       {"disarm": True, "arm": spec})

    def clear_faults(self, k: int) -> None:
        self.note(f"clear faults on node {k}")
        self.node_post(k, "/api/v1/debug/faults", {"disarm": True})

    def corrupt(self, k: int, seed: int) -> None:
        import random

        root = self.workdir / f"n{k}" / "data"
        victims = sorted(p for p in root.glob(
            "data/default/*/fileset-*-data.db") if p.stat().st_size > 0)
        if not victims:
            raise RuntimeError(f"corrupt: no flushed filesets on node {k}")
        rng = random.Random(f"soak-corrupt:{seed}")
        victim = victims[rng.randrange(len(victims))]
        raw = bytearray(victim.read_bytes())
        raw[rng.randrange(len(raw))] ^= 0xFF
        victim.write_bytes(bytes(raw))
        self.note(f"corrupted {victim.relative_to(root)} on node {k}")
        # force detection + peer repair NOW (the mediator's budgeted
        # sweep would find it eventually; the soak wants the window
        # deterministic)
        out = self._admin(k, "POST", "/api/v1/database/scrub",
                          {"repair": True})
        self.note(f"scrub on node {k}: {out.get('scrub')}")

    def disk_fill(self, k: int, target: float) -> None:
        """Ballast-fill node k's storage root so its capacity-quota
        ledger sees ``target`` free ratio.  The ballast is a SPARSE
        file (truncate, no real bytes): the ledger walks ``st_size``,
        so the node experiences genuine watermark pressure while the
        shared host filesystem spends nothing — which is also why the
        soak runs quota mode instead of statvfs."""
        from m3_tpu.x.membudget import parse_bytes

        root = self.workdir / f"n{k}" / "data"
        ballast = root / "ballast.fill"
        capacity = parse_bytes(self.cfg.disk_capacity)
        used = 0
        for p in root.rglob("*"):
            try:
                if p != ballast and p.is_file():
                    used += p.lstat().st_size
            except OSError:
                continue
        size = max(0, int(capacity * (1.0 - target)) - used)
        with open(ballast, "wb") as f:
            f.truncate(size)
        self.note(f"disk ballast on node {k}: {size} bytes "
                  f"(target free ratio {target})")

    def disk_release(self, k: int) -> None:
        ballast = self.workdir / f"n{k}" / "data" / "ballast.fill"
        ballast.unlink(missing_ok=True)
        self.note(f"disk ballast released on node {k}")

    def replace(self, k: int, timeout_s: float = 600.0) -> None:
        from m3_tpu.cluster.placement import PlacementService

        spare = self.total - 1
        if not self.nodes[spare].alive():
            self.note(f"starting spare node {spare}")
            self.nodes[spare].start(timeout_s=600)
        self.note(f"rolling replace: i{k} -> i{spare}")
        self._admin(0, "POST", "/api/v1/services/m3db/placement/replace", {
            "leaving_id": f"i{k}",
            "instance": {"id": f"i{spare}", "isolation_group": f"g{spare}",
                         "endpoint": f"127.0.0.1:{self.rpc_ports[spare]}"},
        })
        ps = PlacementService(self.kv)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            p = ps.get()
            newcomer = p.instances.get(f"i{spare}")
            if (newcomer is not None and newcomer.shards
                    and all(a.state.value == "A"
                            for a in newcomer.shards.values())
                    and not p.instances[f"i{k}"].shards):
                self.note(f"cutover complete: i{spare} AVAILABLE, "
                          f"i{k} drained")
                # Wait for the donor's GRACE DROP before SIGTERM: the
                # drop resets its (possibly million-series) buffers, so
                # the drain's final snapshot is cheap.  Stopping at
                # cutover would snapshot the full warm window — minutes
                # of encode on a big soak, blowing the stop timeout.
                root = self.workdir / f"n{k}" / "data"
                drop_deadline = time.monotonic() + 120
                while time.monotonic() < drop_deadline:
                    if not list(root.glob("data/default/*/fileset-*")):
                        break
                    time.sleep(1.0)
                rc = self.nodes[k].stop(timeout_s=300)
                self.note(f"donor node {k} drained (rc={rc})")
                return
            time.sleep(1.0)
        raise TimeoutError(f"replace i{k}->i{spare}: cutover incomplete "
                           f"after {timeout_s:.0f}s")


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def _ingest_loop(cluster: SoakCluster, gen: WorkloadGen, ledger: Ledger,
                 tracker: PhaseTracker, scheduler: ChaosScheduler,
                 cfg: SoakConfig, stop: threading.Event,
                 first_ack: threading.Event) -> int:
    """Sustained bulk ingest: slice the series space into batches, write
    at Majority through the replicated session, ledger ONLY what was
    acked.  Runs at least cfg.sweeps full passes, then keeps the load
    on until the chaos timeline has finished.  ``first_ack`` fires
    after the first acknowledged batch — the run starts its chaos clock
    there, so the one-time node-side JAX compiles (tens of seconds on a
    cold write path) land in the setup phase, not inside 'healthy'."""
    sess = cluster.session
    sweep = 0
    while not stop.is_set():
        for lo in range(0, cfg.series, cfg.batch):
            if stop.is_set():
                break
            hi = min(lo + cfg.batch, cfg.series)
            ids = gen.ids(sweep, lo, hi)
            vals = gen.values(sweep, lo, hi)
            ts = time.time_ns()
            tsa = np.full(hi - lo, ts, np.int64)
            t0 = time.perf_counter()
            try:
                rejected = sess.write_batch(NS, ids, tsa, vals, now_nanos=ts)
            except Exception:  # noqa: BLE001 — unacked: no durability claim
                tracker.record_ingest_failure()
                stop.wait(0.2)
                continue
            if rejected:
                # partially-accepted batch (new-series cap/limiter): a
                # rejected sample was NOT stored, so nothing in this
                # batch enters the durability ledger — counted as a
                # failed batch, the per-phase counters carry the
                # node-side rejection totals
                tracker.record_ingest_failure()
                continue
            tracker.record_ingest(time.perf_counter() - t0, hi - lo)
            ledger.ack_bulk(sweep, lo, hi, ts)
            first_ack.set()
        sweep += 1
        if sweep >= cfg.sweeps and scheduler.done:
            break
        if sweep >= cfg.max_sweeps:
            cluster.note(f"ingest: max_sweeps={cfg.max_sweeps} reached with "
                         "chaos still running")
            break
    return sweep


def _query_loop(cluster: SoakCluster, ledger: Ledger, tracker: PhaseTracker,
                cfg: SoakConfig, stop: threading.Event) -> None:
    """Concurrent query traffic: every interval, write a fresh point to
    the tagged query corpora (PromQL labels + Graphite path docs) and
    fire one PromQL range query and one Graphite render at a rotating
    live node.  503/504/429 count as shed (the overload substrate doing
    its job), everything else non-200 as an error."""
    from m3_tpu.index.doc import Document, Field

    sess = cluster.session
    rnd = 0
    C = cfg.query_corpus
    while not stop.wait(cfg.query_interval_s):
        ts = time.time_ns()
        # corpus points: deterministic value = rnd + i/1000
        docs = []
        rows = []
        vals = np.arange(C, dtype=np.float64) / 1000.0 + rnd
        for i in range(C):
            pid = b"soakq;%04d" % i
            docs.append(Document(pid, (
                Field(b"__name__", b"soakq"),
                Field(b"family", b"f%d" % (i % 3)),
                Field(b"idx", b"%04d" % i),
            )))
            rows.append((pid, ts, vals[i]))
            gid = b"soak.q.s%04d" % i
            docs.append(Document(gid, (
                Field(b"__g0__", b"soak"),
                Field(b"__g1__", b"q"),
                Field(b"__g2__", b"s%04d" % i),
            )))
            rows.append((gid, ts, vals[i]))
        ts2 = np.full(len(docs), ts, np.int64)
        try:
            if sess.write_tagged_batch(NS, docs, ts2, np.repeat(vals, 2),
                                       now_nanos=ts) == 0:
                ledger.ack_explicit(rows)
            else:
                tracker.record_ingest_failure()
        except Exception:  # noqa: BLE001
            tracker.record_ingest_failure()
        alive = cluster.alive_nodes()
        if not alive:
            rnd += 1
            continue
        port = cluster.http_port(alive[rnd % len(alive)])
        if port is None:
            rnd += 1
            continue
        now_s = ts // 10**9
        if rnd % 2 == 0:
            url = (f"http://127.0.0.1:{port}/api/v1/query_range?"
                   f"query=sum(soakq)%20by%20(family)&start={now_s - 300}"
                   f"&end={now_s}&step=30s&timeout=10s")
        else:
            url = (f"http://127.0.0.1:{port}/render?target=soak.q.*"
                   f"&from=-5min&until=now&timeout=10s")
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(url, timeout=15) as r:
                r.read()
            tracker.record_query(time.perf_counter() - t0, "ok")
        except urllib.error.HTTPError as e:
            tracker.record_query(time.perf_counter() - t0,
                                 "shed" if e.code in (429, 503, 504)
                                 else "err")
        except OSError:
            tracker.record_query(time.perf_counter() - t0, "err")
        rnd += 1


def _write_historical(cluster: SoakCluster, ledger: Ledger,
                      cfg: SoakConfig) -> None:
    """Seed a small corpus two blocks in the past so the mediator
    flushes real filesets early — the substrate the corruption window
    (quarantine → peer repair) and the rolling replace (block
    streaming) act on."""
    from m3_tpu.core.config import parse_duration

    bsz = parse_duration(cfg.block_size)
    t_hist = (time.time_ns() // bsz - 2) * bsz
    ids = [b"soakhist.%05d" % i for i in range(cfg.hist_series)]
    for p in range(cfg.hist_points):
        ts = t_hist + (p + 1) * 10**9
        vals = np.arange(cfg.hist_series, dtype=np.float64) + p * 1000.0
        tsa = np.full(cfg.hist_series, ts, np.int64)
        if cluster.session.write_batch(NS, ids, tsa, vals, now_nanos=ts):
            raise RuntimeError("historical corpus writes were rejected "
                               "(undersized slot capacity?)")
        ledger.ack_explicit(
            [(sid, ts, float(v)) for sid, v in zip(ids, vals.tolist())])
    # wait for every initial node to flush the historical block
    def flushed(k):
        return list((cluster.workdir / f"n{k}" / "data").glob(
            "data/default/*/fileset-*-data.db"))

    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        if all(flushed(k) for k in range(cfg.nodes)):
            cluster.note("historical corpus flushed on every node")
            return
        time.sleep(1.0)
    raise TimeoutError("historical corpus did not flush to filesets")


def _verify(cluster: SoakCluster, ledger: Ledger, cfg: SoakConfig) -> dict:
    """The zero-acked-sample-loss verdict: regenerate every acked
    sample from the ledger, re-read ALL of them at Majority through the
    batched fetch, compare value-for-value.  Digests are computed over
    the same sorted iteration for both sides, so
    ``ledger_sha256 == recovered_sha256`` exactly when nothing acked
    was lost or altered."""
    t0 = time.perf_counter()
    expected = ledger.expected()
    sids = sorted(expected)
    t_min = min((min(pts) for pts in expected.values()), default=0)
    t_max = max((max(pts) for pts in expected.values()), default=0)
    h_ledger = hashlib.sha256()
    h_got = hashlib.sha256()
    missing = mismatched = present = extras = 0
    missing_examples: List[str] = []
    for lo in range(0, len(sids), cfg.verify_batch):
        chunk = sids[lo:lo + cfg.verify_batch]
        got_lists = cluster.session.fetch_batch(
            NS, chunk, t_min, t_max + 1)
        for sid, got in zip(chunk, got_lists):
            want = expected[sid]
            got_map = dict(got)
            extras += sum(1 for t in got_map if t not in want)
            for ts in sorted(want):
                val = want[ts]
                h_ledger.update(sid)
                h_ledger.update(b"\t%d\t%r\n" % (ts, val))
                gv = got_map.get(ts)
                if gv is None:
                    missing += 1
                    if len(missing_examples) < 10:
                        missing_examples.append(f"{sid!r}@{ts}")
                    continue
                if gv != val:
                    mismatched += 1
                    if len(missing_examples) < 10:
                        missing_examples.append(
                            f"{sid!r}@{ts}: {gv!r} != {val!r}")
                    continue
                present += 1
                h_got.update(sid)
                h_got.update(b"\t%d\t%r\n" % (ts, gv))
    return {
        "acked_samples": present + missing + mismatched,
        "active_series": len(sids),
        "verified_present": present,
        "missing": missing,
        "mismatched": mismatched,
        "unacked_extras": extras,
        "missing_examples": missing_examples,
        "ledger_sha256": h_ledger.hexdigest(),
        "recovered_sha256": h_got.hexdigest(),
        "zero_acked_loss": missing == 0 and mismatched == 0,
        "verify_seconds": round(time.perf_counter() - t0, 1),
    }


def selfmon_report(cluster: SoakCluster, window_s: int) -> dict:
    """The round-14 SLO record: instead of harness-side scrape diffs,
    the run's fleet SLOs are PromQL queries over the ``_m3_selfmon``
    HISTORY a live node stored through its own write path — the same
    queries an operator would issue mid-incident, issued here against
    ONE node whose fleet scrape covered its peers.  Returns the
    queries, their answers, the per-(rule, instance) max burn verdicts
    over the run, and the queried node's /health ``slo`` section."""
    alive = cluster.alive_nodes()
    if not alive:
        return {"error": "no live node to query"}
    k = alive[0]
    w = f"{max(60, window_s)}s"
    out: dict = {"queried_node": k, "window": w, "queries": {}}

    def one_value(query: str):
        rows = cluster.promql(k, query, namespace="_m3_selfmon")
        if not rows:
            return None
        v = float(rows[0]["value"][1])
        return None if v != v else round(v, 6)

    for key, q in (
        ("fleet_ingest_p99_s",
         f"histogram_quantile(0.99, sum(rate("
         f"m3tpu_db_write_batch_seconds_bucket[{w}])) by (le))"),
        ("fleet_ingest_p50_s",
         f"histogram_quantile(0.5, sum(rate("
         f"m3tpu_db_write_batch_seconds_bucket[{w}])) by (le))"),
        ("fleet_query_p99_s",
         f"histogram_quantile(0.99, sum(rate("
         f"m3tpu_query_seconds_bucket[{w}])) by (le))"),
        ("fleet_write_batches_per_s",
         f"sum(rate(m3tpu_db_write_batch_seconds_count[{w}]))"),
    ):
        try:
            out["queries"][key] = one_value(q)
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            out["queries"][key] = f"error: {type(e).__name__}: {e}"
    verdicts = []
    rows = cluster.promql(k, f"max_over_time(m3tpu_slo_burn[{w}])",
                          namespace="_m3_selfmon")
    for r in rows:
        verdicts.append({
            "rule": r["metric"].get("rule"),
            "instance": r["metric"].get("instance"),
            "max_burn": round(float(r["value"][1]), 4),
        })
    out["verdicts"] = sorted(
        verdicts, key=lambda v: (v["rule"] or "", v["instance"] or ""))
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{cluster.http_port(k)}/health",
                timeout=30) as r:
            out["health_slo"] = json.load(r).get("slo")
    except OSError:
        out["health_slo"] = None
    return out


def controller_report(cluster: SoakCluster, window_s: int) -> dict:
    """The round-18 self-healing record: every controller decision was
    emitted as a ``controller_action`` gauge sample and self-scraped
    into ``_m3_selfmon``, so the run's full act→hold→relax sequence is
    retro-queryable PromQL history FROM A PEER — the same question an
    operator asks post-incident ("what did the control plane do, and
    did it relax back?").  Also snapshots every live node's ``/health``
    ``controller`` section (actions_total, per-actuator at_baseline)."""
    alive = cluster.alive_nodes()
    if not alive:
        return {"error": "no live node to query"}
    k = alive[0]
    w = f"{max(60, window_s)}s"
    out: dict = {"queried_node": k, "window": w, "history": [],
                 "actions_total": 0, "nodes": {}}
    rows = cluster.promql(k, f"max_over_time(m3tpu_controller_action[{w}])",
                          namespace="_m3_selfmon")
    for r in rows:
        out["history"].append({
            "instance": r["metric"].get("instance"),
            "rule": r["metric"].get("rule"),
            "actuator": r["metric"].get("actuator"),
            "action": r["metric"].get("action"),
            "last_level": round(float(r["value"][1]), 6),
        })
    out["history"].sort(key=lambda h: (h["instance"] or "",
                                       h["actuator"] or "",
                                       h["action"] or ""))
    for n in alive:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{cluster.http_port(n)}/health",
                    timeout=30) as r:
                ctl = json.load(r).get("controller")
        except OSError:
            ctl = None
        if ctl:
            out["nodes"][f"i{n}"] = {
                "actions_total": ctl.get("actions_total", 0),
                "held_unknown": ctl.get("held_unknown", 0),
                "rate_limited": ctl.get("rate_limited", 0),
                "at_baseline": {
                    name: a.get("at_baseline")
                    for name, a in ctl.get("actuators", {}).items()},
            }
            out["actions_total"] += int(ctl.get("actions_total", 0))
    return out


# ---------------------------------------------------------------------------
# the run + the regression gate
# ---------------------------------------------------------------------------


def run_soak(cfg: SoakConfig, workdir: str | None = None,
             keep_workdir: bool = False, log=print) -> dict:
    """Stand up the cluster, drive load + chaos, verify, render the
    artifact.  Returns the artifact dict (committed as SOAK_rNN.json at
    full scale; schema identical at smoke scale)."""
    import tempfile

    from m3_tpu.x import retry as xretry

    wd = Path(workdir) if workdir else Path(tempfile.mkdtemp(prefix="soak-"))
    started_unix = int(time.time())
    t_run0 = time.monotonic()
    tracker: PhaseTracker | None = None
    cluster = None
    retry_before = dict(xretry.counters())
    try:
        tracker = PhaseTracker(lambda: cluster.scrape_all())
        cluster = SoakCluster(cfg, wd, tracker)
        log(f"soak: workdir {wd}; starting {cfg.nodes} nodes "
            f"(+{1 if cfg.replace else 0} spare)...")
        cluster.start()
        gen = WorkloadGen(cfg.series, cfg.churn, cfg.seed)
        ledger = Ledger(gen)
        tracker.transition("setup")
        log("soak: writing historical corpus (fileset substrate)...")
        _write_historical(cluster, ledger, cfg)

        timeline = build_timeline(cfg)
        scheduler = ChaosScheduler(timeline, cluster, seed=cfg.seed)
        stop = threading.Event()
        first_ack = threading.Event()
        sweeps_box: List[int] = []
        qthread = threading.Thread(
            target=_query_loop,
            args=(cluster, ledger, tracker, cfg, stop), daemon=True)
        ithread = threading.Thread(
            target=lambda: sweeps_box.append(_ingest_loop(
                cluster, gen, ledger, tracker, scheduler, cfg, stop,
                first_ack)),
            daemon=True)
        log(f"soak: load on — {cfg.series} series x {cfg.sweeps}+ sweeps, "
            f"chaos timeline of {len(timeline)} events")
        ithread.start()
        qthread.start()
        # chaos clock starts at the first ACKED batch: the cold write
        # path's one-time compiles belong to setup, not to 'healthy'
        if not first_ack.wait(600):
            raise TimeoutError("no batch acked within 600s of load start")
        scheduler.start()
        ithread.join()
        scheduler.stop()
        stop.set()
        qthread.join(30)
        tracker.finish()
        sweeps_done = sweeps_box[0] if sweeps_box else 0

        log(f"soak: load off after {sweeps_done} sweeps, "
            f"{ledger.acked_samples} acked samples; verifying at "
            "Majority...")
        # recovery precondition: every placement member answering
        for k in cluster.alive_nodes():
            cluster.nodes[k].wait_healthy(120)
        verdict = _verify(cluster, ledger, cfg)
        log(f"soak: verdict zero_acked_loss={verdict['zero_acked_loss']} "
            f"({verdict['verified_present']} present, "
            f"{verdict['missing']} missing, "
            f"{verdict['mismatched']} mismatched, "
            f"{verdict['unacked_extras']} unacked extras)")

        # Round 14: the run's SLO record comes from PromQL over the
        # fleet's self-stored _m3_selfmon history, not harness scrape
        # diffs — at least one burn verdict must be retro-queryable or
        # the self-monitoring contract is broken (verdict gated).
        selfmon_rec = None
        if cfg.selfmon:
            try:
                selfmon_rec = selfmon_report(
                    cluster, int(time.monotonic() - t_run0) + 60)
            except Exception as e:  # noqa: BLE001 — the artifact must
                # record the failure; the verdict flag below trips
                selfmon_rec = {"error": f"{type(e).__name__}: {e}"}
            verdict["slo_recorded"] = bool(selfmon_rec.get("verdicts"))
            log(f"soak: selfmon verdicts={len(selfmon_rec.get('verdicts', []))} "
                f"fleet ingest p99="
                f"{selfmon_rec.get('queries', {}).get('fleet_ingest_p99_s')}s")

        # Round 18: the controller's decision record.  A selfheal run
        # must show actions AND every actuator back at baseline; any
        # other run must show ZERO actions (the enabled-but-quiet
        # invariant the smoke tier pins).
        controller_rec = None
        if cfg.selfmon and cfg.controller:
            try:
                controller_rec = controller_report(
                    cluster, int(time.monotonic() - t_run0) + 60)
            except Exception as e:  # noqa: BLE001 — recorded, not fatal
                controller_rec = {"error": f"{type(e).__name__}: {e}"}
            acted = int(controller_rec.get("actions_total", 0) or 0)
            baseline_ok = all(
                all(n.get("at_baseline", {}).values())
                for n in controller_rec.get("nodes", {}).values())
            verdict["controller_quiet"] = (acted == 0)
            verdict["controller_relaxed"] = baseline_ok
            if cfg.selfheal:
                verdict["controller_acted"] = acted > 0
            log(f"soak: controller actions={acted} "
                f"relaxed_to_baseline={baseline_ok}")

        retry_after = xretry.counters()
        artifact = {
            "kind": "SOAK",
            "schema": SCHEMA,
            "started_unix": started_unix,
            "wall_s": round(time.monotonic() - t_run0, 1),
            "config": dataclasses.asdict(cfg),
            "sweeps_completed": sweeps_done,
            "phases": tracker.render(),
            "chaos": scheduler.log,
            "driver": {
                "retry_counters": {
                    k: v - retry_before.get(k, 0)
                    for k, v in retry_after.items()
                    if v - retry_before.get(k, 0)
                },
                "read_breakers": cluster.session.breaker_states(),
                "routing_misses": cluster.session.routing_misses,
            },
            "cluster_log": cluster.log,
            "verdict": verdict,
        }
        if selfmon_rec is not None:
            artifact["selfmon"] = selfmon_rec
        if controller_rec is not None:
            artifact["controller"] = controller_rec
        return artifact
    finally:
        if cluster is not None:
            cluster.close()
        if not keep_workdir:
            shutil.rmtree(wd, ignore_errors=True)


def check_artifact(new: dict, baseline: dict,
                   tolerance: float = 2.0) -> List[str]:
    """The regression gate: nonempty return = FAIL.

    * the new run's zero-acked-loss verdict must PASS — loss is never
      within tolerance;
    * for every phase present in both artifacts, the new p99s (driver-
      observed and fleet-merged, ingest and query) must stay within
      ``tolerance`` x the baseline's — a ratio, not an absolute, so the
      gate is meaningful across box speeds.  The ``setup`` phase is
      EXCLUDED: it exists precisely to quarantine one-time jit compiles
      and cold-path warmup (see run_soak), and its p99 swings many x
      between identical runs — a gate that false-fails on compile noise
      gates nothing (the loss verdict still covers setup's writes);
    * schema/kind must match (a gate comparing different artifact
      shapes proves nothing).
    """
    errs: List[str] = []
    if new.get("kind") != baseline.get("kind"):
        errs.append(f"artifact kind {new.get('kind')!r} != baseline "
                    f"{baseline.get('kind')!r}")
        return errs
    if new.get("schema") != baseline.get("schema"):
        # a schema bump may rename the very fields compared below, and
        # every .get() miss would silently skip its comparison — the
        # gate must fail loudly instead of passing vacuously
        errs.append(f"artifact schema {new.get('schema')!r} != baseline "
                    f"{baseline.get('schema')!r}")
        return errs
    if not new.get("verdict", {}).get("zero_acked_loss"):
        v = new.get("verdict", {})
        errs.append(
            f"acked-sample loss: {v.get('missing')} missing, "
            f"{v.get('mismatched')} mismatched of {v.get('acked_samples')}")
    if new.get("verdict", {}).get("slo_recorded") is False:
        # selfmon was on but the run left no queryable burn verdict in
        # _m3_selfmon — the self-monitoring contract itself regressed
        errs.append("selfmon: no SLO verdict queryable from _m3_selfmon")
    base_phases = {p["name"]: p for p in baseline.get("phases", ())}
    for p in new.get("phases", ()):  # noqa: B007
        if p["name"] == "setup":
            continue
        b = base_phases.get(p["name"])
        if b is None:
            continue
        for side in ("ingest", "query"):
            nv = (p.get(side) or {}).get("driver_p99_ms")
            bv = (b.get(side) or {}).get("driver_p99_ms")
            if nv is not None and bv:
                if nv > bv * tolerance:
                    errs.append(
                        f"phase {p['name']}: {side} driver p99 "
                        f"{nv:.1f}ms > {tolerance}x baseline {bv:.1f}ms")
            fq = (p.get(f"fleet_{side}") or {}).get("quantiles", {})
            bq = (b.get(f"fleet_{side}") or {}).get("quantiles", {})
            nf, bf = fq.get("p99"), bq.get("p99")
            if nf is not None and bf:
                if nf > bf * tolerance:
                    errs.append(
                        f"phase {p['name']}: fleet {side} p99 "
                        f"{nf * 1e3:.1f}ms > {tolerance}x baseline "
                        f"{bf * 1e3:.1f}ms")
    return errs


def config_from_artifact(artifact: dict, **overrides) -> SoakConfig:
    """Rebuild the run config a committed artifact was produced with
    (the --check contract: the gate re-runs the BASELINE's shape, so
    the comparison is like-for-like)."""
    fields = {f.name for f in dataclasses.fields(SoakConfig)}
    raw = {k: v for k, v in artifact.get("config", {}).items()
           if k in fields}
    raw.update(overrides)
    return SoakConfig(**raw)
