"""Destructive-test harness (reference `src/m3em` + `src/cmd/tools/dtest`)."""
