"""Typed corruption errors for every persisted artifact.

The reference treats on-disk corruption as an *expected, recoverable*
event: filesets are checksum-verified on read and a failed verify is
handled (skip + repair from peers), never a process abort
(`src/dbnode/persist/fs/read.go` digest verification,
`src/dbnode/storage/repair.go`).  Before this module every verify site
in ``persist/`` raised a bare ``ValueError``, indistinguishable from an
argument error — callers could not tell "this volume is bit-rotted,
quarantine it and fall back" from "you passed garbage".

:class:`CorruptionError` subclasses ``ValueError`` ON PURPOSE: every
existing ``except ValueError`` site keeps working, and the RPC server's
application-error mapping (``server/rpc.py`` → ``RPC_ERR`` frame →
``RemoteError`` on the client) is unchanged — a remote replica serving
a corrupt block still surfaces as a ``RemoteError`` the repair sweep
demotes.  What changes is that *local* handlers can now catch exactly
the corruption class and route it to quarantine
(``persist/quarantine.py``) instead of letting it abort a bootstrap or
fail a query.

The m3lint ``corruption-typed`` rule makes this permanent: a
digest/checksum/magic verify under ``m3_tpu/persist/`` raising a bare
``ValueError`` is a gate failure.
"""

from __future__ import annotations

__all__ = ["CorruptionError", "ChecksumMismatch", "FormatCorruption"]


class CorruptionError(ValueError):
    """A persisted artifact failed an integrity check.

    ``path`` is the offending file (when known), ``component`` the
    artifact family (``fileset`` / ``snapshot.meta`` / ``commitlog`` /
    ``bloom``), and ``check`` the specific verification that failed
    (``checkpoint``, ``digest:data``, ``segment-checksum``,
    ``info-magic``, ...) — enough for a quarantine reason file to say
    *why* a volume was pulled without re-running the verify.
    """

    def __init__(self, message: str, *, path=None, component: str | None = None,
                 check: str | None = None):
        super().__init__(message)
        self.path = str(path) if path is not None else None
        self.component = component
        self.check = check

    def describe(self) -> dict:
        """JSON-ready detail for quarantine reason files / logs."""
        return {
            "error_type": type(self).__name__,
            "error": str(self),
            "path": self.path,
            "component": self.component,
            "check": self.check,
        }


class ChecksumMismatch(CorruptionError):
    """Stored digest/checksum does not match the bytes on disk (bit
    rot, torn write past the checkpoint, or an injected corrupt
    fault)."""


class FormatCorruption(CorruptionError):
    """The artifact's framing is invalid: wrong magic, unsupported
    version, or a truncated/torn structure."""
