"""Commit log: the write-ahead log for crash recovery.

Equivalent of the reference's async batched WAL
(`src/dbnode/persist/fs/commitlog/commit_log.go:716 Write / :733
WriteBatch`, chunked writer with size+checksum headers `writer.go:43-52`,
fsync policy, reader/iterator for bootstrap `iterator.go`).  Differences
by design: entries are struct-framed binary (not msgpack — SURVEY.md §7
"what deliberately does NOT carry over"), and batching is explicit (the
ingest path is already batched arrays, so the WAL appends whole batches,
not per-sample enqueues).

Chunk layout:  [payload_len u32][payload_adler u32][header_adler u32]
               [payload]
Entry layout within a payload: repeated
  [ns_len u8][ns][id_len u16][id][timestamp i64][value f64][unit u8]
  [annot_len u16][annot]

A torn final chunk (crash mid-write) fails its checksum and is dropped by
the reader, truncating recovery to the last complete chunk — the same
guarantee the reference's chunked writer provides.
"""

from __future__ import annotations

import os
import struct
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Iterator

import numpy as np

from m3_tpu.persist.capacity import capacity_guard, inject
from m3_tpu.persist.corruption import ChecksumMismatch, FormatCorruption
from m3_tpu.persist.digest import digest
from m3_tpu.x import fault

_CHUNK_HDR = struct.Struct("<III")


@dataclass(frozen=True)
class CommitLogEntry:
    series_id: bytes
    timestamp: int
    value: float
    unit: int = 0
    annotation: bytes = b""
    namespace: bytes = b"default"


class FsyncPolicy:
    NEVER = "never"
    EVERY_WRITE = "every_write"
    INTERVAL = "interval"


class CommitLogWriter:
    """Appends batches as checksummed chunks; rotate() starts a new file
    (the reference rotates on block boundaries for cleanup —
    commit_log.go NotifyOpts/rotation)."""

    def __init__(self, root, fsync: str = FsyncPolicy.INTERVAL,
                 fsync_interval_s: float = 1.0, rotate_bytes: int = 0,
                 fsync_histogram=None):
        self.dir = Path(root) / "commitlogs"
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fsync = fsync
        self.fsync_interval_s = fsync_interval_s
        # Size-based rotation bound (0 = only rotate on demand, the
        # pre-existing behavior).  Without it a node whose snapshot
        # cadence is long appends to ONE segment forever, and cleanup
        # can never reclaim WAL space — the growth bound that makes
        # commitlog bytes reclaimable under disk pressure.
        self.rotate_bytes = rotate_bytes
        # Optional instrument.Histogram: fsync wall time.  A stalling
        # disk shows up here long before ENOSPC does, and the histogram
        # is windowed so an SLO rule over it reflects *current* device
        # behavior.
        self._fsync_hist = fsync_histogram
        self._last_fsync = 0.0
        self._active_bytes = 0
        self._f = None
        self._seq = self._next_seq()
        self.rotate()

    def _next_seq(self) -> int:
        seqs = [int(p.stem.split("-")[1]) for p in self.dir.glob("commitlog-*.db")]
        return max(seqs, default=-1) + 1

    @property
    def seq(self) -> int:
        """Sequence number of the ACTIVE log file."""
        return self._seq

    @property
    def path(self) -> Path:
        return self.dir / f"commitlog-{self._seq}.db"

    def rotate(self) -> Path | None:
        """Close the active log and open the next one; returns the path
        of the ROTATED-OUT file (None on first open)."""
        old = None
        if self._f:
            old = self.path
            # through the commitlog.flush faultpoint (m3lint
            # fault-coverage): a rotation fsync is as injectable a
            # boundary as a write fsync
            self._flush_fsync()
            self._f.close()
            self._seq += 1
        with capacity_guard(path=self.path, component="commitlog", op="open"):
            self._f = open(self.path, "ab")
        self._active_bytes = self.path.stat().st_size
        return old

    def write_batch(self, ids: list[bytes], timestamps: np.ndarray,
                    values: np.ndarray, unit: int = 0,
                    annotations: list[bytes] | None = None,
                    namespace: bytes = b"default") -> None:
        parts = []
        for i, sid in enumerate(ids):
            ann = annotations[i] if annotations else b""
            parts.append(struct.pack("<B", len(namespace)))
            parts.append(namespace)
            parts.append(struct.pack("<H", len(sid)))
            parts.append(sid)
            parts.append(struct.pack("<qdB", int(timestamps[i]), float(values[i]), unit))
            parts.append(struct.pack("<H", len(ann)))
            parts.append(ann)
        payload = b"".join(parts)
        pd = digest(payload)
        hdr_body = struct.pack("<II", len(payload), pd)
        chunk = hdr_body + struct.pack("<I", digest(hdr_body)) + payload
        with capacity_guard(path=self.path, component="commitlog", op="write"):
            inject("commitlog.write")
            self._f.write(chunk)
        self._active_bytes += len(chunk)
        if self.rotate_bytes and self._active_bytes >= self.rotate_bytes:
            # Rotate AFTER the append so the chunk that crossed the
            # bound is fsynced by rotate()'s flush — the new segment
            # starts empty and the old one is immediately eligible for
            # reclaim once its entries are flushed to filesets.
            self.rotate()
        elif self.fsync == FsyncPolicy.EVERY_WRITE:
            self._flush_fsync()
        elif self.fsync == FsyncPolicy.INTERVAL:
            now = time.monotonic()
            if now - self._last_fsync >= self.fsync_interval_s:
                self._flush_fsync()
                self._last_fsync = now

    def _flush_fsync(self) -> None:
        """Disk-boundary faultpoint ``commitlog.flush``: delay models a
        slow device, error a failing one, and drop SKIPS the fsync —
        the durability hole a later SIGKILL turns into a torn tail the
        reader's checksum contract must absorb."""
        if fault.fire("commitlog.flush") == "drop":
            return
        t0 = time.monotonic()
        with capacity_guard(path=self.path, component="commitlog", op="fsync"):
            self._f.flush()
            os.fsync(self._f.fileno())
        if self._fsync_hist is not None:
            self._fsync_hist.record(time.monotonic() - t0)

    def close(self) -> None:
        if self._f:
            self._flush_fsync()
            self._f.close()
            self._f = None


def read_commitlog(path, strict: bool = False) -> Iterator[CommitLogEntry]:
    """Yields entries from one log file; stops (without raising) at the
    first torn/corrupt chunk — the crash-recovery contract.

    Streams CHUNK BY CHUNK: replay memory is bounded by the largest
    chunk (one ingest batch), not the log size — the reference's WAL
    reader is an iterator over the chunked writer's frames for the same
    reason (`persist/fs/commitlog/reader.go`).  The truncation contract
    is bit-for-bit the old whole-file reader's: a chunk is yielded only
    when its header digest AND payload digest verify, and the first
    failure ends iteration.

    ``strict=True`` (integrity tooling, never recovery) raises a typed
    :class:`CorruptionError` at the failure instead of truncating, so a
    scrub can distinguish "clean end" from "torn tail".
    """
    path = Path(path)
    with open(path, "rb") as f:
        while True:
            hdr = f.read(_CHUNK_HDR.size)
            if len(hdr) < _CHUNK_HDR.size:
                if hdr and strict:
                    raise FormatCorruption(
                        "torn chunk header", path=path,
                        component="commitlog", check="chunk-header-torn")
                return
            plen, pdig, hdig = _CHUNK_HDR.unpack(hdr)
            if digest(hdr[:8]) != hdig:
                if strict:
                    raise ChecksumMismatch(
                        "chunk header checksum mismatch", path=path,
                        component="commitlog", check="chunk-header")
                return
            payload = f.read(plen)
            if len(payload) < plen or digest(payload) != pdig:
                if strict:
                    raise ChecksumMismatch(
                        "chunk payload checksum mismatch", path=path,
                        component="commitlog", check="chunk-payload")
                return
            epos = 0
            while epos < plen:
                (nslen,) = struct.unpack_from("<B", payload, epos)
                epos += 1
                ns = payload[epos : epos + nslen]
                epos += nslen
                (idlen,) = struct.unpack_from("<H", payload, epos)
                epos += 2
                sid = payload[epos : epos + idlen]
                epos += idlen
                ts, val, unit = struct.unpack_from("<qdB", payload, epos)
                epos += 17
                (alen,) = struct.unpack_from("<H", payload, epos)
                epos += 2
                ann = payload[epos : epos + alen]
                epos += alen
                yield CommitLogEntry(sid, ts, val, unit, ann, ns)


def commitlog_seq(path) -> int:
    """Sequence number encoded in a commitlog filename."""
    return int(Path(path).stem.split("-")[1])


def list_commitlogs(root) -> list[Path]:
    d = Path(root) / "commitlogs"
    if not d.exists():
        return []
    return sorted(d.glob("commitlog-*.db"), key=commitlog_seq)
