"""Typed disk-capacity errors for every persisted artifact.

The reference runs ``storage/cleanup.go`` and commitlog retention
precisely because a dbnode that fills its disk dies mid-flush
(`src/dbnode/storage/cleanup.go`, `src/dbnode/persist/fs/write.go`
error paths).  Before this module an ENOSPC anywhere in ``persist/``
surfaced as a raw ``OSError`` that killed whatever flush, snapshot,
WAL append, or checkpoint hit it — indistinguishable from a permission
error, invisible to the shed/reclaim machinery, and prone to leaving a
half-written ``*.tmp`` file beside the real artifact.

:class:`DiskCapacityError` subclasses ``OSError`` ON PURPOSE: every
existing ``except OSError`` site keeps working, and the RPC server's
application-error mapping (``server/rpc.py`` → ``RPC_ERR`` frame →
``RemoteError`` on the client) is unchanged — a replica out of disk
still surfaces as a per-replica failure the consistency level absorbs.
What changes is that *local* handlers can now catch exactly the
capacity class and route it to the disk-pressure machinery
(``x/diskbudget.py``) instead of letting it abort a tick.

Use :func:`capacity_guard` around a write/fsync/rename site: it
classifies ENOSPC/EDQUOT into the typed error, unlinks the atomic-write
temp file so the error path never litters, and bumps a per-component
counter mirrored onto /metrics.  :func:`sweep_temp_files` removes any
survivors (hard kill between write and classify) at bootstrap.

The m3lint ``enospc-typed`` rule makes this permanent: a write/fsync/
rename site under ``m3_tpu/persist/`` (or the aggregator checkpoint)
outside a ``capacity_guard`` block is a gate failure.
"""

from __future__ import annotations

import contextlib
import errno
import os
import threading
from pathlib import Path

from m3_tpu.x import fault

__all__ = [
    "CAPACITY_ERRNOS",
    "DiskCapacityError",
    "capacity_guard",
    "counters",
    "inject",
    "reset",
    "sweep_temp_files",
]

# The two "disk is full" errnos: no space on the filesystem, and the
# (user or project) quota exceeded.  Everything else an OSError can
# carry (EACCES, EIO, ...) is NOT a capacity problem and must keep its
# original type — shedding ingest will not fix a dying disk.
CAPACITY_ERRNOS = (errno.ENOSPC, errno.EDQUOT)

_lock = threading.Lock()
_by_component: dict[str, int] = {}


class DiskCapacityError(OSError):
    """A write to persistent storage failed because the disk is full.

    ``path`` is the file being written (when known), ``component`` the
    artifact family (``fileset`` / ``snapshot`` / ``commitlog`` /
    ``checkpoint`` / ``quarantine``), and ``op`` the operation that hit
    the wall (``write`` / ``fsync`` / ``rename``) — enough for a log
    line or /health entry to say *what* ran out of room without a
    stack trace.
    """

    def __init__(self, message: str, *, path=None, component: str | None = None,
                 op: str | None = None, err: int = errno.ENOSPC):
        super().__init__(err, message)
        self.path = str(path) if path is not None else None
        self.component = component
        self.op = op

    def describe(self) -> dict:
        """JSON-ready detail for logs / the /health disk section."""
        return {
            "error_type": type(self).__name__,
            "error": str(self),
            "errno": self.errno,
            "path": self.path,
            "component": self.component,
            "op": self.op,
        }


@contextlib.contextmanager
def capacity_guard(path=None, component: str | None = None,
                   op: str | None = None, cleanup=()):
    """Classify ENOSPC/EDQUOT from the wrapped write site.

    On a capacity errno: unlink every path in ``cleanup`` (the atomic-
    write temp files — best effort, so the error path never litters),
    bump the per-component counter, and re-raise as
    :class:`DiskCapacityError` chained to the original.  Any other
    ``OSError`` (and an already-typed capacity error from a nested
    guard) passes through untouched.
    """
    try:
        yield
    except DiskCapacityError:
        raise
    except OSError as e:
        if e.errno not in CAPACITY_ERRNOS:
            raise
        for p in cleanup:
            try:
                os.unlink(p)
            except OSError:
                pass
        with _lock:
            key = component or "unknown"
            _by_component[key] = _by_component.get(key, 0) + 1
        where = f" ({path})" if path is not None else ""
        raise DiskCapacityError(
            f"disk capacity exhausted during {component or 'write'}"
            f" {op or 'write'}{where}: {e.strerror or e}",
            path=path, component=component, op=op, err=e.errno,
        ) from e


def inject(point: str) -> None:
    """Blessed faultpoint → ENOSPC bridge for the torn-write matrix.

    ``fault.fire`` raises :class:`~m3_tpu.x.fault.FaultInjected` (a
    ``ConnectionError``) in error mode; persistence call sites need a
    *capacity* fault instead, flowing through the same ``except
    OSError`` classification as a real full disk.  Call this inside a
    ``capacity_guard`` block, before the real write.
    """
    try:
        fault.fire(point)
    except fault.FaultInjected:
        raise OSError(  # noqa: TRY003 — classified by the enclosing guard
            errno.ENOSPC, f"injected by faultpoint {point}: no space left"
        ) from None


def counters() -> dict:
    """Flat counter dict for /metrics mirroring: ``<component>.enospc``."""
    with _lock:
        return {f"{k}.enospc": v for k, v in sorted(_by_component.items())}


def reset() -> None:
    """Test hook: zero the per-component counters."""
    with _lock:
        _by_component.clear()


# Directories under a node root that atomic writers put temp files in.
# data/ holds fileset volumes + digests, snapshots/ the snapshot metas,
# checkpoint/ the aggregator arena (mkstemp names: ``<name>.tmpXXXXXX``),
# commitlogs/ is append-only today but swept for future-proofing.
_SWEEP_DIRS = ("data", "snapshots", "commitlogs", "checkpoint")


def sweep_temp_files(root) -> list[str]:
    """Remove atomic-write temp files left by a crash mid-write.

    Both temp shapes are covered: ``fs._write_atomic``'s fixed
    ``<name>.tmp`` suffix and the aggregator checkpoint's
    ``mkstemp``-randomized ``<name>.tmpXXXXXX``.  A temp file is dead
    by construction — the ``os.replace`` that would have published it
    never ran — so unconditional removal is safe.  Returns the removed
    paths (for the bootstrap log line).
    """
    removed: list[str] = []
    root = Path(root)
    for sub in _SWEEP_DIRS:
        base = root / sub
        if not base.is_dir():
            continue
        for tmp in sorted(base.rglob("*.tmp*")):
            if not tmp.is_file():
                continue
            try:
                tmp.unlink()
                removed.append(str(tmp))
            except OSError:
                pass
    return removed
