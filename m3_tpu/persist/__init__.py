"""Durable artifacts: filesets, commitlog, snapshots, digests.

Corruption contract (this package's robustness story): every integrity
check raises a typed :class:`~m3_tpu.persist.corruption.CorruptionError`
(a ``ValueError`` subclass) carrying path/component/check, the storage
layer routes it to :mod:`m3_tpu.persist.quarantine`, and the scrubber +
peer repair re-converge the hole — enforced statically by m3lint's
``corruption-typed`` rule.
"""

from m3_tpu.persist.corruption import (
    ChecksumMismatch, CorruptionError, FormatCorruption,
)

__all__ = ["ChecksumMismatch", "CorruptionError", "FormatCorruption"]
