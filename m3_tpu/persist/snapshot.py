"""Snapshots: periodic durable captures of the in-memory buffer.

Equivalent of the reference's snapshot filesets + snapshot metadata files
(`src/dbnode/storage/series/buffer.go:537 Snapshot`,
`src/dbnode/persist/fs/snapshot_metadata_write.go` /
`snapshot_metadata_read.go`): the mediator periodically persists every
open (unsealed) block window so that crash recovery replays only the
commitlog *tail* written after the snapshot, not the whole WAL.

Layout under <root>/snapshots/:

    <seq>/data/<namespace>/<shard>/fileset-...   ordinary filesets
                                                 (same writer/reader as
                                                 persist/fs — the stream
                                                 bytes are exact M3TSZ)
    meta-<seq>.db                                metadata, written LAST

The metadata file carries (seq, commitlog_seq) and is checksummed; its
presence gates the snapshot's visibility exactly like a fileset's
checkpoint file (crash mid-snapshot leaves no meta → invisible, the
previous snapshot remains authoritative).  `commitlog_seq` is the
sequence number of the commitlog file that was ACTIVE when the snapshot
began — recovery = load snapshot + replay logs with seq >= commitlog_seq
(duplicates resolve in the buffer's last-write-wins dedupe).
"""

from __future__ import annotations

import os
import shutil
import struct
from dataclasses import dataclass
from pathlib import Path

from m3_tpu.persist.capacity import capacity_guard
from m3_tpu.persist.corruption import ChecksumMismatch, FormatCorruption
from m3_tpu.persist.digest import digest

_META_MAGIC = b"M3TS"
# record layout: magic (4) + seq u64 + commitlog_seq i64 + adler32-of-first-20


def snapshots_root(root) -> Path:
    return Path(root) / "snapshots"


def snapshot_data_root(root, seq: int) -> Path:
    """Root passed to DataFileSetWriter/Reader for snapshot `seq`."""
    return snapshots_root(root) / str(seq)


@dataclass(frozen=True)
class SnapshotMetadata:
    seq: int
    commitlog_seq: int

    def to_bytes(self) -> bytes:
        body = _META_MAGIC + struct.pack("<Qq", self.seq, self.commitlog_seq)
        return body + struct.pack("<I", digest(body))

    @classmethod
    def from_bytes(cls, b: bytes, path=None) -> "SnapshotMetadata":
        if len(b) != 24 or b[:4] != _META_MAGIC:
            raise FormatCorruption("bad snapshot metadata", path=path,
                                   component="snapshot.meta",
                                   check="meta-magic")
        seq, clseq = struct.unpack_from("<Qq", b, 4)
        (csum,) = struct.unpack_from("<I", b, 20)
        if digest(b[:20]) != csum:
            raise ChecksumMismatch("snapshot metadata checksum mismatch",
                                   path=path, component="snapshot.meta",
                                   check="meta-checksum")
        return cls(seq, clseq)


def meta_path(root, seq: int) -> Path:
    return snapshots_root(root) / f"meta-{seq}.db"


def next_snapshot_seq(root) -> int:
    d = snapshots_root(root)
    if not d.exists():
        return 0
    seqs = [int(p.stem.split("-")[1]) for p in d.glob("meta-*.db")]
    for p in d.iterdir():  # incomplete (meta-less) dirs still hold the seq
        if p.is_dir() and p.name.isdigit():
            seqs.append(int(p.name))
    return max(seqs, default=-1) + 1


def commit_snapshot(root, seq: int, commitlog_seq: int) -> None:
    """Write the metadata file — the snapshot's atomic commit point."""
    d = snapshots_root(root)
    d.mkdir(parents=True, exist_ok=True)
    final = meta_path(root, seq)
    tmp = final.with_suffix(".tmp")
    # fsync before the rename (the meta gates the whole snapshot's
    # visibility — a published-but-unsynced meta would be a torn commit
    # point after power loss), and classify ENOSPC on the way.
    with capacity_guard(path=final, component="snapshot", op="write",
                        cleanup=(tmp,)):
        with open(tmp, "wb") as f:
            f.write(SnapshotMetadata(seq, commitlog_seq).to_bytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)


def list_snapshots(root) -> list[SnapshotMetadata]:
    """Complete (committed) snapshots, oldest first; corrupt metas are
    skipped like checkpoint-less filesets."""
    d = snapshots_root(root)
    if not d.exists():
        return []
    out = []
    for p in sorted(d.glob("meta-*.db"), key=lambda p: int(p.stem.split("-")[1])):
        try:
            out.append(SnapshotMetadata.from_bytes(p.read_bytes(), path=p))
        except ValueError:  # CorruptionError — cleanup reaps it
            continue
    return out


def latest_snapshot(root) -> SnapshotMetadata | None:
    snaps = list_snapshots(root)
    return snaps[-1] if snaps else None


def remove_snapshot(root, seq: int) -> None:
    """Delete one snapshot (meta first so it can never be half-visible)."""
    meta_path(root, seq).unlink(missing_ok=True)
    shutil.rmtree(snapshot_data_root(root, seq), ignore_errors=True)


def prune_snapshots(root, keep: int = 1) -> int:
    """Remove all but the newest `keep` complete snapshots plus any
    uncommitted snapshot directories (crash leftovers) and any snapshot
    whose metadata file is CORRUPT — ``latest_snapshot`` skips those,
    so without this sweep the meta file (and its data dir) would leak
    on disk forever.  Returns count removed (reference cleanup.go
    snapshot/metadata cleanup)."""
    removed = 0
    d = snapshots_root(root)
    if d.exists():
        for p in d.glob("meta-*.db"):
            seq_s = p.stem.split("-")[1]
            try:
                raw = p.read_bytes()
            except OSError:
                # Unreadable ≠ corrupt: a transient EIO/race here must
                # NOT delete a snapshot whose read would succeed next
                # pass (its covering commitlogs may already be gone).
                continue
            try:
                SnapshotMetadata.from_bytes(raw, path=p)
            except ValueError as e:  # CorruptionError: verifiably rotten
                if seq_s.isdigit():
                    # Quarantine, don't destroy: the meta is rotten but
                    # the data filesets may be fully intact — at rf=1
                    # they can be the only copy of what the snapshot
                    # covered (the WAL it superseded is already reaped).
                    from m3_tpu.persist.quarantine import quarantine_snapshot

                    quarantine_snapshot(root, int(seq_s), e)
                else:
                    p.unlink(missing_ok=True)
                removed += 1
    snaps = list_snapshots(root)
    for m in snaps[:-keep] if keep else snaps:
        remove_snapshot(root, m.seq)
        removed += 1
    if d.exists():
        live = {m.seq for m in list_snapshots(root)}
        for p in d.iterdir():
            if p.is_dir() and p.name.isdigit() and int(p.name) not in live:
                shutil.rmtree(p, ignore_errors=True)
                removed += 1
    return removed
