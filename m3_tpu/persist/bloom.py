"""Vectorized bloom filter over series IDs.

The reference writes a bloom filter file per fileset so reads can skip
filesets that cannot contain an ID (`src/dbnode/persist/fs/bloom_filter.go`,
written by `write.go`; M3 uses a k-hash bloom sized from (n, false-positive
rate)).  This one uses double hashing h1 + i*h2 over 64-bit FNV-1a — built
as numpy batch ops so constructing a filter over 100K IDs at flush is a
handful of vector instructions, not 100K hash-object calls.
"""

from __future__ import annotations

import math
import struct

import numpy as np

from m3_tpu.persist.corruption import FormatCorruption

_FNV_OFFSET = np.uint64(14695981039346656037)
_FNV_PRIME = np.uint64(1099511628211)


def _fnv1a_batch(ids: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """64-bit FNV-1a of each ID, plus a second independent hash (FNV over
    the reversed bytes), vectorized over a padded (N, L) byte matrix."""
    n = len(ids)
    if n == 0:
        return np.zeros(0, np.uint64), np.zeros(0, np.uint64)
    lens = np.fromiter((len(b) for b in ids), np.int64, n)
    L = max(1, int(lens.max()))
    mat = np.zeros((n, L), np.uint8)
    for i, b in enumerate(ids):
        mat[i, : len(b)] = np.frombuffer(b, np.uint8)
    mask = np.arange(L)[None, :] < lens[:, None]

    with np.errstate(over="ignore"):
        h1 = np.full(n, _FNV_OFFSET)
        h2 = np.full(n, _FNV_OFFSET)
        rev = mat[:, ::-1]
        rev_mask = mask[:, ::-1]
        for j in range(L):
            sel = mask[:, j]
            h1 = np.where(sel, (h1 ^ mat[:, j].astype(np.uint64)) * _FNV_PRIME, h1)
            sel_r = rev_mask[:, j]
            h2 = np.where(sel_r, (h2 ^ rev[:, j].astype(np.uint64)) * _FNV_PRIME, h2)
    # h2 must be odd so the double-hash stride cycles the whole table.
    return h1, h2 | np.uint64(1)


class BloomFilter:
    MAGIC = b"M3TB"

    def __init__(self, m_bits: int, k: int, bits: np.ndarray | None = None):
        self.m = m_bits
        self.k = k
        nwords = (m_bits + 63) // 64
        self.bits = bits if bits is not None else np.zeros(nwords, np.uint64)

    @classmethod
    def from_estimate(cls, n: int, fp_rate: float = 0.02) -> "BloomFilter":
        n = max(1, n)
        m = max(64, int(-n * math.log(fp_rate) / (math.log(2) ** 2)))
        k = max(1, round(m / n * math.log(2)))
        return cls(m, k)

    def _positions(self, ids: list[bytes]) -> np.ndarray:
        h1, h2 = _fnv1a_batch(ids)
        i = np.arange(self.k, dtype=np.uint64)[None, :]
        with np.errstate(over="ignore"):
            return ((h1[:, None] + i * h2[:, None]) % np.uint64(self.m)).astype(
                np.int64
            )

    def add_batch(self, ids: list[bytes]) -> None:
        pos = self._positions(ids).ravel()
        np.bitwise_or.at(
            self.bits, pos // 64, np.uint64(1) << (pos % 64).astype(np.uint64)
        )

    def contains_batch(self, ids: list[bytes]) -> np.ndarray:
        pos = self._positions(ids)
        word = self.bits[pos // 64]
        bit = (word >> (pos % 64).astype(np.uint64)) & np.uint64(1)
        return bit.all(axis=1)

    def contains(self, mid: bytes) -> bool:
        return bool(self.contains_batch([mid])[0])

    def to_bytes(self) -> bytes:
        return (
            self.MAGIC
            + struct.pack("<QI", self.m, self.k)
            + self.bits.tobytes()
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "BloomFilter":
        if data[:4] != cls.MAGIC:
            raise FormatCorruption("bad bloom filter magic",
                                   component="bloom", check="bloom-magic")
        m, k = struct.unpack_from("<QI", data, 4)
        bits = np.frombuffer(data[16:], np.uint64).copy()
        return cls(m, k, bits)
