"""Adler32 digests guarding every persisted file.

The reference stamps each fileset file with an adler32 digest collected in
a digests file, and writes a checkpoint file (digest of the digests file)
last to gate fileset visibility (`src/dbnode/digest/digest.go:24-37`,
`src/dbnode/persist/fs/files.go:618-624`).  Same scheme here.
"""

from __future__ import annotations

import struct
import zlib


def digest(data: bytes) -> int:
    return zlib.adler32(data) & 0xFFFFFFFF


def digest_file(path) -> int:
    with open(path, "rb") as f:
        return digest(f.read())


def pack_digest(d: int) -> bytes:
    return struct.pack("<I", d)


def unpack_digest(b: bytes) -> int:
    return struct.unpack("<I", b[:4])[0]
