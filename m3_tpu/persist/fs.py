"""Immutable fileset I/O: the durable form of a sealed block.

Structural equivalent of the reference's per-(shard, blockStart, volume)
fileset (`src/dbnode/persist/fs/files.go:618-624`, writer
`write.go`/`types.go:87-102 WriteAll`, reader `read.go`, binary-search
index `index_lookup.go`): an **info** file (block metadata), a **data**
file of concatenated compressed segments, an **index** file of per-series
entries sorted by ID, a **summaries** file sampling every Nth index entry,
a **bloom** filter file, a **digest** file of adler32s, and a
**checkpoint** file written last whose presence gates fileset visibility
(crash mid-flush leaves no checkpoint → the fileset is invisible and
re-flushed, the reference's atomicity story).

The byte framing is this framework's own (struct-packed little-endian, no
msgpack); the *stream bytes inside the data file are exact M3TSZ* so a
fileset round-trips the codec's golden contract.
"""

from __future__ import annotations

import os
import struct
import threading
from bisect import bisect_right
from dataclasses import dataclass
from pathlib import Path
from typing import Iterator

from m3_tpu.persist.bloom import BloomFilter
from m3_tpu.persist.digest import digest, digest_file, pack_digest, unpack_digest

INFO_MAGIC = b"M3TI"
INDEX_MAGIC = b"M3TX"
VERSION = 1
SUMMARY_EVERY = 64

FILE_TYPES = ("info", "index", "data", "summaries", "bloom")


def fileset_dir(root, namespace: str, shard: int) -> Path:
    return Path(root) / "data" / namespace / str(shard)


def fileset_path(root, namespace: str, shard: int, block_start: int, volume: int, ftype: str) -> Path:
    return fileset_dir(root, namespace, shard) / (
        f"fileset-{block_start}-{volume}-{ftype}.db"
    )


@dataclass(frozen=True)
class FileSetInfo:
    block_start: int
    block_size: int
    volume: int
    num_series: int

    def to_bytes(self) -> bytes:
        return INFO_MAGIC + struct.pack(
            "<IqqIQ", VERSION, self.block_start, self.block_size, self.volume, self.num_series
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "FileSetInfo":
        if b[:4] != INFO_MAGIC:
            raise ValueError("bad info magic")
        ver, bs, bsz, vol, n = struct.unpack_from("<IqqIQ", b, 4)
        if ver != VERSION:
            raise ValueError(f"unsupported fileset version {ver}")
        return cls(bs, bsz, vol, n)


@dataclass(frozen=True)
class IndexEntry:
    id: bytes
    offset: int
    length: int
    checksum: int  # adler32 of the data segment


def _write_atomic(path: Path, data: bytes) -> None:
    tmp = path.with_suffix(".tmp")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class DataFileSetWriter:
    """Writes one complete fileset; `write_all` is all-or-nothing
    (reference DataFileSetWriter.WriteAll, persist/fs/types.go:87-102)."""

    def __init__(self, root, namespace: str, shard: int, block_start: int,
                 block_size: int, volume: int = 0):
        self.root = root
        self.namespace = namespace
        self.shard = shard
        self.block_start = block_start
        self.block_size = block_size
        self.volume = volume

    def write_all(self, series: list[tuple[bytes, bytes]]) -> None:
        """series: (id, m3tsz stream) pairs; empty streams are skipped."""
        series = sorted((s for s in series if s[1]), key=lambda kv: kv[0])
        d = fileset_dir(self.root, self.namespace, self.shard)
        d.mkdir(parents=True, exist_ok=True)
        p = lambda t: fileset_path(
            self.root, self.namespace, self.shard, self.block_start, self.volume, t
        )

        data_parts: list[bytes] = []
        index_parts: list[bytes] = [INDEX_MAGIC + struct.pack("<Q", len(series))]
        summary_parts: list[bytes] = []
        off = 0
        for i, (sid, stream) in enumerate(series):
            entry = struct.pack("<I", len(sid)) + sid + struct.pack(
                "<QII", off, len(stream), digest(stream)
            )
            if i % SUMMARY_EVERY == 0:
                summary_parts.append(
                    struct.pack("<I", len(sid)) + sid + struct.pack("<Q", i)
                )
            index_parts.append(entry)
            data_parts.append(stream)
            off += len(stream)

        bloom = BloomFilter.from_estimate(len(series))
        bloom.add_batch([sid for sid, _ in series])

        contents = {
            "info": FileSetInfo(
                self.block_start, self.block_size, self.volume, len(series)
            ).to_bytes(),
            "index": b"".join(index_parts),
            "data": b"".join(data_parts),
            "summaries": b"".join(summary_parts),
            "bloom": bloom.to_bytes(),
        }
        for t in FILE_TYPES:
            _write_atomic(p(t), contents[t])
        digests = b"".join(pack_digest(digest(contents[t])) for t in FILE_TYPES)
        _write_atomic(p("digest"), digests)
        # Checkpoint LAST: its digest-of-digests gates visibility.
        _write_atomic(p("checkpoint"), pack_digest(digest(digests)))


class DataFileSetReader:
    """Reader with the reference's lookup ladder: bloom filter →
    summaries → binary-searched index → data segment + checksum verify
    (persist/fs/read.go, index_lookup.go, seek.go).  Data segments come
    from an mmap of the data file (`persist/fs/mmap_util.go` role):
    page-cache backed, no per-read seek state, so concurrent reads on a
    shared reader are safe without a lock."""

    def __init__(self, root, namespace: str, shard: int, block_start: int, volume: int):
        self.root = root
        self.namespace = namespace
        self.shard = shard
        self.block_start = block_start
        self.volume = volume
        p = lambda t: fileset_path(root, namespace, shard, block_start, volume, t)
        if not p("checkpoint").exists():
            raise FileNotFoundError(f"no checkpoint for {p('checkpoint')}")
        digests_raw = p("digest").read_bytes()
        if unpack_digest(p("checkpoint").read_bytes()) != digest(digests_raw):
            raise ValueError("checkpoint/digest mismatch")
        for i, t in enumerate(FILE_TYPES):
            if digest_file(p(t)) != unpack_digest(digests_raw[i * 4 :]):
                raise ValueError(f"digest mismatch for {t} file")
        self.info = FileSetInfo.from_bytes(p("info").read_bytes())
        self._index = self._parse_index(p("index").read_bytes())
        self._ids = [e.id for e in self._index]
        # Data segments are served from a lazily-created mmap of the
        # data file: the page cache owns residency (a long-lived reader
        # pins address space, not RSS), lookups are stateless slices
        # (thread-safe), and the hot path pays no open/seek per segment
        # — the properties the reference gets from mmap'd seekers.
        self._data_path = p("data")
        self._data_f = None
        self._data_mm = None
        self._data_init = threading.Lock()
        self.bloom = BloomFilter.from_bytes(p("bloom").read_bytes())

    def _data(self):
        if self._data_mm is None:
            import mmap as _mmap

            # Initialization is the only mutation; reads thereafter are
            # lock-free slices.  Without the lock a first-read race
            # leaks the loser's fd + mmap.
            with self._data_init:
                if self._data_mm is None:
                    self._data_f = open(self._data_path, "rb")
                    try:
                        self._data_mm = _mmap.mmap(
                            self._data_f.fileno(), 0,
                            access=_mmap.ACCESS_READ,
                        )
                    except ValueError:  # zero-length file (empty fileset)
                        self._data_mm = b""
        return self._data_mm

    def close(self) -> None:
        if self._data_mm is not None and not isinstance(self._data_mm, bytes):
            self._data_mm.close()
        self._data_mm = None
        if self._data_f is not None:
            self._data_f.close()
            self._data_f = None

    def __del__(self):  # belt-and-braces for transient readers
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    @staticmethod
    def _parse_index(raw: bytes) -> list[IndexEntry]:
        if raw[:4] != INDEX_MAGIC:
            raise ValueError("bad index magic")
        (n,) = struct.unpack_from("<Q", raw, 4)
        out, pos = [], 12
        for _ in range(n):
            (idlen,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            sid = raw[pos : pos + idlen]
            pos += idlen
            off, length, csum = struct.unpack_from("<QII", raw, pos)
            pos += 16
            out.append(IndexEntry(sid, off, length, csum))
        return out

    def read(self, sid: bytes) -> bytes | None:
        if not self.bloom.contains(sid):
            return None
        i = bisect_right(self._ids, sid) - 1
        if i < 0 or self._ids[i] != sid:
            return None
        e = self._index[i]
        seg = bytes(self._data()[e.offset : e.offset + e.length])
        if digest(seg) != e.checksum:
            raise ValueError(f"segment checksum mismatch for {sid!r}")
        return seg

    def read_all(self) -> Iterator[tuple[bytes, bytes]]:
        mm = self._data()
        for e in self._index:  # index entries are offset-ordered
            seg = bytes(mm[e.offset : e.offset + e.length])
            if digest(seg) != e.checksum:
                raise ValueError(f"segment checksum mismatch for {e.id!r}")
            yield e.id, seg

    def __len__(self) -> int:
        return len(self._index)


def list_fileset_volumes(root, namespace: str, shard: int) -> list[tuple[int, int]]:
    """EVERY checkpointed (block_start, volume) pair, including superseded
    volumes — the cleanup path's view (reference files.go enumerates all
    volumes; cleanup.go deletes out-of-retention and past-volume sets)."""
    d = fileset_dir(root, namespace, shard)
    if not d.exists():
        return []
    out = []
    for f in d.glob("fileset-*-checkpoint.db"):
        parts = f.stem.split("-")
        out.append((int(parts[1]), int(parts[2])))
    return sorted(out)


def remove_fileset(root, namespace: str, shard: int, block_start: int, volume: int) -> None:
    """Delete one fileset volume, checkpoint FIRST so a crash mid-delete
    leaves an invisible (not half-readable) fileset."""
    for t in ("checkpoint", "digest") + FILE_TYPES:
        fileset_path(root, namespace, shard, block_start, volume, t).unlink(
            missing_ok=True
        )


def list_filesets(root, namespace: str, shard: int) -> list[tuple[int, int]]:
    """(block_start, volume) pairs with a checkpoint present, sorted;
    only the max volume per block is returned (reference files.go
    volume semantics: higher volume supersedes)."""
    d = fileset_dir(root, namespace, shard)
    if not d.exists():
        return []
    best: dict[int, int] = {}
    for f in d.glob("fileset-*-checkpoint.db"):
        parts = f.stem.split("-")
        bs, vol = int(parts[1]), int(parts[2])
        best[bs] = max(best.get(bs, -1), vol)
    return sorted(best.items())
