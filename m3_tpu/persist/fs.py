"""Immutable fileset I/O: the durable form of a sealed block.

Structural equivalent of the reference's per-(shard, blockStart, volume)
fileset (`src/dbnode/persist/fs/files.go:618-624`, writer
`write.go`/`types.go:87-102 WriteAll`, reader `read.go`, binary-search
index `index_lookup.go`): an **info** file (block metadata), a **data**
file of concatenated compressed segments, an **index** file of per-series
entries sorted by ID, a **summaries** file sampling every Nth index entry,
a **bloom** filter file, a **digest** file of adler32s, and a
**checkpoint** file written last whose presence gates fileset visibility
(crash mid-flush leaves no checkpoint → the fileset is invisible and
re-flushed, the reference's atomicity story).

The byte framing is this framework's own (struct-packed little-endian, no
msgpack); the *stream bytes inside the data file are exact M3TSZ* so a
fileset round-trips the codec's golden contract.
"""

from __future__ import annotations

import os
import struct
import threading
from bisect import bisect_right
from dataclasses import dataclass
from pathlib import Path
from typing import Iterator

from m3_tpu.persist.bloom import BloomFilter
from m3_tpu.persist.capacity import capacity_guard, inject
from m3_tpu.persist.corruption import ChecksumMismatch, FormatCorruption
from m3_tpu.persist.digest import digest, digest_file, pack_digest, unpack_digest
from m3_tpu.x import fault

INFO_MAGIC = b"M3TI"
INDEX_MAGIC = b"M3TX"
# v2: summaries entries carry the index-file byte offset (was the entry
# ordinal, which nothing could seek with) — the reader's lookup ladder
# depends on it, so v1 filesets are rejected rather than mis-probed.
VERSION = 2
SUMMARY_EVERY = 64
INDEX_HEADER_LEN = 12  # INDEX_MAGIC + uint64 entry count

FILE_TYPES = ("info", "index", "data", "summaries", "bloom")


def fileset_dir(root, namespace: str, shard: int) -> Path:
    return Path(root) / "data" / namespace / str(shard)


def fileset_path(root, namespace: str, shard: int, block_start: int, volume: int, ftype: str) -> Path:
    return fileset_dir(root, namespace, shard) / (
        f"fileset-{block_start}-{volume}-{ftype}.db"
    )


@dataclass(frozen=True)
class FileSetInfo:
    block_start: int
    block_size: int
    volume: int
    num_series: int

    def to_bytes(self) -> bytes:
        return INFO_MAGIC + struct.pack(
            "<IqqIQ", VERSION, self.block_start, self.block_size, self.volume, self.num_series
        )

    @classmethod
    def from_bytes(cls, b: bytes, path=None) -> "FileSetInfo":
        if b[:4] != INFO_MAGIC:
            raise FormatCorruption("bad info magic", path=path,
                                   component="fileset", check="info-magic")
        try:
            ver, bs, bsz, vol, n = struct.unpack_from("<IqqIQ", b, 4)
        except struct.error as e:
            raise FormatCorruption(f"torn info file: {e}", path=path,
                                   component="fileset", check="info-torn")
        if ver != VERSION:
            raise FormatCorruption(f"unsupported fileset version {ver}",
                                   path=path, component="fileset",
                                   check="info-version")
        return cls(bs, bsz, vol, n)


@dataclass(frozen=True)
class IndexEntry:
    id: bytes
    offset: int
    length: int
    checksum: int  # adler32 of the data segment


def _write_atomic(path: Path, data: bytes) -> None:
    tmp = path.with_suffix(".tmp")
    # ENOSPC/EDQUOT here become typed DiskCapacityError and the temp
    # file is unlinked on the way out — a full disk never publishes a
    # half-written artifact and never litters beside the real one.
    with capacity_guard(path=path, component="fileset", op="write",
                        cleanup=(tmp,)):
        inject("fileset.write")
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)


class DataFileSetWriter:
    """Writes one complete fileset; `write_all` is all-or-nothing
    (reference DataFileSetWriter.WriteAll, persist/fs/types.go:87-102)."""

    def __init__(self, root, namespace: str, shard: int, block_start: int,
                 block_size: int, volume: int = 0):
        self.root = root
        self.namespace = namespace
        self.shard = shard
        self.block_start = block_start
        self.block_size = block_size
        self.volume = volume

    def write_all(self, series: list[tuple[bytes, bytes]]) -> None:
        """series: (id, m3tsz stream) pairs; empty streams are skipped."""
        series = sorted((s for s in series if s[1]), key=lambda kv: kv[0])
        d = fileset_dir(self.root, self.namespace, self.shard)
        d.mkdir(parents=True, exist_ok=True)
        p = lambda t: fileset_path(
            self.root, self.namespace, self.shard, self.block_start, self.volume, t
        )

        data_parts: list[bytes] = []
        index_parts: list[bytes] = [INDEX_MAGIC + struct.pack("<Q", len(series))]
        summary_parts: list[bytes] = []
        off = 0
        index_off = INDEX_HEADER_LEN
        for i, (sid, stream) in enumerate(series):
            entry = struct.pack("<I", len(sid)) + sid + struct.pack(
                "<QII", off, len(stream), digest(stream)
            )
            if i % SUMMARY_EVERY == 0:
                # Each summary carries the entry's BYTE OFFSET in the
                # index file, so the reader can seek straight to it and
                # scan at most SUMMARY_EVERY entries — the reference's
                # index_lookup.go ladder (open cost O(summaries)).
                summary_parts.append(
                    struct.pack("<I", len(sid)) + sid
                    + struct.pack("<Q", index_off)
                )
            index_parts.append(entry)
            data_parts.append(stream)
            off += len(stream)
            index_off += len(entry)

        bloom = BloomFilter.from_estimate(len(series))
        bloom.add_batch([sid for sid, _ in series])

        contents = {
            "info": FileSetInfo(
                self.block_start, self.block_size, self.volume, len(series)
            ).to_bytes(),
            "index": b"".join(index_parts),
            "data": b"".join(data_parts),
            "summaries": b"".join(summary_parts),
            "bloom": bloom.to_bytes(),
        }
        for t in FILE_TYPES:
            _write_atomic(p(t), contents[t])
        digests = b"".join(pack_digest(digest(contents[t])) for t in FILE_TYPES)
        _write_atomic(p("digest"), digests)
        # Checkpoint LAST: its digest-of-digests gates visibility.
        _write_atomic(p("checkpoint"), pack_digest(digest(digests)))


class DataFileSetReader:
    """Reader with the reference's lookup ladder: bloom filter →
    summaries (every ``SUMMARY_EVERY``-th id + its byte offset in the
    index file) → forward scan of at most ``SUMMARY_EVERY`` raw index
    entries → data segment + checksum verify (persist/fs/read.go,
    index_lookup.go, seek.go).

    The index is mmap'd and parsed LAZILY around the probe point: open
    cost is O(summaries) object work (the per-file adler32 verification
    still streams each file once, C-speed, no heap), and a long-lived
    reader holds no per-entry Python objects — at 100K+ series per
    (shard, block) the eager parse this replaces was exactly the cost
    the reference's summaries exist to avoid.  Data and index segments
    come from mmaps (`persist/fs/mmap_util.go` role): page-cache
    backed, stateless slices, so concurrent reads on a shared reader
    are safe without a lock."""

    def __init__(self, root, namespace: str, shard: int, block_start: int, volume: int):
        self.root = root
        self.namespace = namespace
        self.shard = shard
        self.block_start = block_start
        self.volume = volume
        p = lambda t: fileset_path(root, namespace, shard, block_start, volume, t)
        if not p("checkpoint").exists():
            raise FileNotFoundError(f"no checkpoint for {p('checkpoint')}")
        try:
            self._open_verified(p)
        except FileNotFoundError as e:
            # Deletion removes the checkpoint FIRST (remove_fileset /
            # quarantine_fileset), so checkpoint-present-but-file-
            # missing is genuine damage, not a cleanup race — type it
            # so scrub/read handlers quarantine instead of skipping.
            if p("checkpoint").exists():
                raise FormatCorruption(
                    f"fileset file missing with checkpoint present: "
                    f"{e.filename}", path=e.filename, component="fileset",
                    check="missing-file")
            raise  # checkpoint vanished since the check: a real race

    def _open_verified(self, p) -> None:
        digests_raw = p("digest").read_bytes()
        checkpoint_raw = p("checkpoint").read_bytes()
        if len(checkpoint_raw) < 4 or len(digests_raw) < 4 * len(FILE_TYPES):
            raise FormatCorruption(
                "torn checkpoint/digest file", path=p("checkpoint"),
                component="fileset", check="checkpoint-torn")
        if unpack_digest(checkpoint_raw) != digest(digests_raw):
            raise ChecksumMismatch(
                "checkpoint/digest mismatch", path=p("checkpoint"),
                component="fileset", check="checkpoint")
        for i, t in enumerate(FILE_TYPES):
            if digest_file(p(t)) != unpack_digest(digests_raw[i * 4 :]):
                raise ChecksumMismatch(
                    f"digest mismatch for {t} file", path=p(t),
                    component="fileset", check=f"digest:{t}")
        self.info = FileSetInfo.from_bytes(p("info").read_bytes(),
                                           path=p("info"))
        self._data_path = p("data")
        self._index_path = p("index")
        self._data_f = None
        self._data_mm = None
        self._index_f = None
        self._index_mm = None
        self._mm_init = threading.Lock()
        # Summaries: parallel sorted (ids, index-file byte offsets).
        self._sum_ids: list[bytes] = []
        self._sum_offs: list[int] = []
        raw = p("summaries").read_bytes()
        pos = 0
        while pos < len(raw):
            (idlen,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            self._sum_ids.append(raw[pos : pos + idlen])
            pos += idlen
            self._sum_offs.append(struct.unpack_from("<Q", raw, pos)[0])
            pos += 8
        self.bloom = BloomFilter.from_bytes(p("bloom").read_bytes())

    def _mm(self, path: Path, attr_f: str, attr_mm: str):
        if getattr(self, attr_mm) is None:
            import mmap as _mmap

            # Initialization is the only mutation; reads thereafter are
            # lock-free slices.  Without the lock a first-read race
            # leaks the loser's fd + mmap.
            with self._mm_init:
                if getattr(self, attr_mm) is None:
                    f = open(path, "rb")
                    setattr(self, attr_f, f)
                    try:
                        setattr(self, attr_mm, _mmap.mmap(
                            f.fileno(), 0, access=_mmap.ACCESS_READ))
                    except ValueError:  # zero-length file (empty fileset)
                        setattr(self, attr_mm, b"")
        return getattr(self, attr_mm)

    def _data(self):
        return self._mm(self._data_path, "_data_f", "_data_mm")

    def _index_raw(self):
        mm = self._mm(self._index_path, "_index_f", "_index_mm")
        if len(mm) and bytes(mm[:4]) != INDEX_MAGIC:
            raise FormatCorruption("bad index magic", path=self._index_path,
                                   component="fileset", check="index-magic")
        return mm

    def close(self) -> None:
        for attr_mm, attr_f in (("_data_mm", "_data_f"),
                                ("_index_mm", "_index_f")):
            mm = getattr(self, attr_mm)
            if mm is not None and not isinstance(mm, bytes):
                mm.close()
            setattr(self, attr_mm, None)
            f = getattr(self, attr_f)
            if f is not None:
                f.close()
                setattr(self, attr_f, None)

    def __del__(self):  # belt-and-braces for transient readers
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    @staticmethod
    def _entry_at(raw, pos: int) -> tuple[IndexEntry, int]:
        """Parse one index entry at byte ``pos``; returns (entry, next_pos)."""
        (idlen,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        sid = bytes(raw[pos : pos + idlen])
        pos += idlen
        off, length, csum = struct.unpack_from("<QII", raw, pos)
        return IndexEntry(sid, off, length, csum), pos + 16

    def entries(self) -> Iterator[IndexEntry]:
        """Stream every index entry in id order without materializing
        the index (repair/verify tooling path)."""
        raw = self._index_raw()
        if not len(raw):
            return
        (n,) = struct.unpack_from("<Q", raw, 4)
        pos = INDEX_HEADER_LEN
        for _ in range(n):
            e, pos = self._entry_at(raw, pos)
            yield e

    def _lookup(self, sid: bytes) -> IndexEntry | None:
        """Summaries-guided probe: binary-search the in-memory summary
        ids, then scan forward over raw index bytes — at most
        SUMMARY_EVERY entries parsed per miss (index_lookup.go)."""
        j = bisect_right(self._sum_ids, sid) - 1
        if j < 0:
            return None
        raw = self._index_raw()
        pos = self._sum_offs[j]
        end = (self._sum_offs[j + 1] if j + 1 < len(self._sum_offs)
               else len(raw))
        while pos < end:
            e, pos = self._entry_at(raw, pos)
            if e.id == sid:
                return e
            if e.id > sid:  # sorted: gone past
                return None
        return None

    def read(self, sid: bytes) -> bytes | None:
        if not self.bloom.contains(sid):
            return None
        e = self._lookup(sid)
        if e is None:
            return None
        seg = bytes(self._data()[e.offset : e.offset + e.length])
        # ``fileset.read`` faultpoint: corrupt mode flips one byte of
        # the segment BEFORE the checksum verify, so dtest can exercise
        # the detect→quarantine→repair loop without touching disk.
        _, seg = fault.mangle("fileset.read", seg)
        if digest(seg) != e.checksum:
            raise ChecksumMismatch(
                f"segment checksum mismatch for {sid!r}",
                path=self._data_path, component="fileset",
                check="segment-checksum")
        return seg

    def read_all(self) -> Iterator[tuple[bytes, bytes]]:
        mm = self._data()
        for e in self.entries():  # index entries are offset-ordered
            seg = bytes(mm[e.offset : e.offset + e.length])
            _, seg = fault.mangle("fileset.read", seg)
            if digest(seg) != e.checksum:
                raise ChecksumMismatch(
                    f"segment checksum mismatch for {e.id!r}",
                    path=self._data_path, component="fileset",
                    check="segment-checksum")
            yield e.id, seg

    def __len__(self) -> int:
        return self.info.num_series


def list_fileset_volumes(root, namespace: str, shard: int) -> list[tuple[int, int]]:
    """EVERY checkpointed (block_start, volume) pair, including superseded
    volumes — the cleanup path's view (reference files.go enumerates all
    volumes; cleanup.go deletes out-of-retention and past-volume sets)."""
    d = fileset_dir(root, namespace, shard)
    if not d.exists():
        return []
    out = []
    for f in d.glob("fileset-*-checkpoint.db"):
        parts = f.stem.split("-")
        out.append((int(parts[1]), int(parts[2])))
    return sorted(out)


def remove_fileset(root, namespace: str, shard: int, block_start: int, volume: int) -> None:
    """Delete one fileset volume, checkpoint FIRST so a crash mid-delete
    leaves an invisible (not half-readable) fileset."""
    for t in ("checkpoint", "digest") + FILE_TYPES:
        fileset_path(root, namespace, shard, block_start, volume, t).unlink(
            missing_ok=True
        )


def list_filesets(root, namespace: str, shard: int) -> list[tuple[int, int]]:
    """(block_start, volume) pairs with a checkpoint present, sorted;
    only the max volume per block is returned (reference files.go
    volume semantics: higher volume supersedes)."""
    d = fileset_dir(root, namespace, shard)
    if not d.exists():
        return []
    best: dict[int, int] = {}
    for f in d.glob("fileset-*-checkpoint.db"):
        parts = f.stem.split("-")
        bs, vol = int(parts[1]), int(parts[2])
        best[bs] = max(best.get(bs, -1), vol)
    return sorted(best.items())
