"""Quarantine: pull corrupt artifacts out of the live tree, atomically.

The reference deletes or re-fetches corrupt filesets; operators of real
clusters want the evidence kept (what rotted, when, which check caught
it) for postmortems and hardware triage.  A quarantined fileset volume
moves to::

    <root>/quarantine/<label>/<namespace>/<shard>/<block_start>-<volume>[-k]/
        fileset-...-checkpoint.db        (moved first: visibility gate)
        fileset-...-digest.db
        fileset-...-{info,index,data,summaries,bloom}.db
        reason.json                      (written last: entry commit)

``label`` is ``data`` for live filesets or ``snapshot-<seq>`` for
snapshot filesets.  The *checkpoint moves first*, mirroring
``remove_fileset``'s delete order: the instant it is gone the fileset
is invisible to ``list_filesets``, so a crash mid-quarantine leaves an
invisible (never half-readable) volume — the same atomicity story as
flush.  Moves are same-filesystem ``os.replace`` renames.

``reason.json`` carries the typed-error detail
(:meth:`CorruptionError.describe`) plus the coordinates, so the
``/health`` inventory and the scrubber's repair pass can enumerate
holes without re-verifying anything.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

from m3_tpu.persist.capacity import capacity_guard
from m3_tpu.persist.corruption import CorruptionError
from m3_tpu.persist.fs import FILE_TYPES, fileset_path

REASON_FILE = "reason.json"


def quarantine_root(root) -> Path:
    return Path(root) / "quarantine"


def _unique_dir(base: Path) -> Path:
    """First non-existing ``base``[, ``base-2``, ``base-3``...] — the
    same (block, volume) can rot, heal via repair, and rot again."""
    if not base.exists():
        return base
    k = 2
    while (d := base.with_name(f"{base.name}-{k}")).exists():
        k += 1
    return d


def _reason(err, extra: dict) -> dict:
    detail = (err.describe() if isinstance(err, CorruptionError)
              else {"error_type": type(err).__name__, "error": str(err)}
              if err is not None else {})
    detail.update(extra)
    detail["quarantined_at"] = time.time()
    return detail


def quarantine_fileset(src_root, namespace: str, shard: int, block_start: int,
                       volume: int, err=None, *, qroot=None,
                       label: str = "data") -> Path | None:
    """Move one fileset volume into the quarantine tree; returns the
    quarantine directory, or None when no files existed to move.

    ``src_root`` is where the fileset lives (the data root, or a
    snapshot's data root); ``qroot`` is the database root owning the
    quarantine tree (defaults to ``src_root``)."""
    qdir = _unique_dir(
        quarantine_root(qroot if qroot is not None else src_root)
        / label / namespace / str(shard) / f"{block_start}-{volume}"
    )
    moved: list[str] = []
    # Checkpoint FIRST: once it is gone the volume is invisible, so a
    # crash mid-move can never leave a half-readable fileset behind.
    # Renames are same-filesystem (no new data blocks) but the reason
    # file is a fresh write, and directory entries cost metadata blocks
    # — on a truly full disk even these classify as capacity errors.
    with capacity_guard(path=qdir, component="quarantine", op="move"):
        for t in ("checkpoint", "digest") + FILE_TYPES:
            src = fileset_path(src_root, namespace, shard, block_start,
                               volume, t)
            if src.exists():
                qdir.mkdir(parents=True, exist_ok=True)
                os.replace(src, qdir / src.name)
                moved.append(src.name)
        if not moved:
            return None
        reason = _reason(err, {
            "kind": "fileset", "label": label, "namespace": namespace,
            "shard": shard, "block_start": block_start, "volume": volume,
            "files": moved,
        })
        (qdir / REASON_FILE).write_text(json.dumps(reason, indent=1))
    return qdir


def quarantine_snapshot(root, seq: int, err=None) -> Path | None:
    """Move one snapshot (meta file + data dir) into the quarantine
    tree — the META moves first, the snapshot's atomic visibility gate
    (mirror of the checkpoint-first fileset move).  Corrupt-meta
    snapshots keep their (possibly intact) data filesets as evidence
    instead of being destroyed; returns the quarantine dir or None when
    nothing existed."""
    meta = Path(root) / "snapshots" / f"meta-{seq}.db"
    data = Path(root) / "snapshots" / str(seq)
    qdir = _unique_dir(quarantine_root(root) / "snapshots" / str(seq))
    moved: list[str] = []
    with capacity_guard(path=qdir, component="quarantine", op="move"):
        for src in (meta, data):
            if src.exists():
                qdir.mkdir(parents=True, exist_ok=True)
                os.replace(src, qdir / src.name)
                moved.append(src.name)
        if not moved:
            return None
        reason = _reason(err, {"kind": "snapshot", "seq": seq, "files": moved})
        (qdir / REASON_FILE).write_text(json.dumps(reason, indent=1))
    return qdir


def list_quarantined(root) -> list[dict]:
    """Every quarantine entry's reason dict (plus its ``dir``), sorted
    by directory — the ``/health`` inventory and the scrubber's
    repair-pass worklist."""
    q = quarantine_root(root)
    if not q.exists():
        return []
    out = []
    for rf in sorted(q.rglob(REASON_FILE)):
        try:
            reason = json.loads(rf.read_text())
        except (OSError, json.JSONDecodeError):
            reason = {"kind": "unreadable-reason"}
        reason["dir"] = str(rf.parent)
        out.append(reason)
    return out
