"""One parsing home for XLA program text (compiled HLO + StableHLO).

Two gates read the same module texts every tier-1 run: ``cli costs``
fingerprints the compiled-HLO opcode mix, and ``cli irlint`` runs typed
IR rules over both the StableHLO (formulation level — what WE wrote,
platform-independent) and the compiled HLO (post-optimization — what
XLA kept, e.g. constants it folded).  A drifted second copy of the
instruction grammar would let the two gates disagree about the same
text, so every regex lives here and both delegate.

Nothing in this module imports jax or touches devices: inputs are the
strings ``lowered.as_text()`` (StableHLO/MLIR) and
``compiled.as_text()`` (HLO) hand over.

Grammar notes (pinned by tests, revisit on an XLA upgrade):

* a compiled-HLO instruction line is
  ``[ROOT ]%name = <shape|(tuple)> opcode(...)``; nested computations
  use the same line shape, so one regex censuses the whole module;
* an HLO shape token is ``f64[2,3]`` / ``s32[]`` — element type then
  bracketed dims (layout ``{...}`` suffix ignored);
* a StableHLO tensor type is ``tensor<8192xi64>`` / ``tensor<f64>``;
* StableHLO custom calls appear both as the pretty form
  ``stablehlo.custom_call @Target(...)`` and the generic form with a
  ``call_target_name = "Target"`` attribute.
"""

from __future__ import annotations

import re
from typing import Dict, List

__all__ = [
    "HLO_INSTR_RE", "HLO_SHAPE_RE", "STABLEHLO_TENSOR_RE",
    "custom_call_targets", "folded_constants", "op_histogram",
    "shape_elements", "stablehlo_custom_call_targets",
    "stablehlo_op_count", "stablehlo_type_census",
]


# Compiled-HLO instruction line: `  [ROOT ]%name = shape opcode(...)`.
# Group 1 is the result shape (possibly a `(tuple)`), group 2 the opcode.
HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[^\s=]+\s*=\s*(\([^)]*\)|\S+)\s+([a-z][a-z0-9-]*)\(",
    re.MULTILINE)

# One element-typed shape token inside an HLO type: `f64[1024,8]`, `s32[]`.
HLO_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+)\[([0-9,]*)\]")

# One StableHLO tensor type: `tensor<1024x8xf64>`, `tensor<i64>`.
STABLEHLO_TENSOR_RE = re.compile(r"tensor<(?:[0-9?]+x)*([a-z][a-z0-9]*)>")

_HLO_CUSTOM_CALL_RE = re.compile(r'custom_call_target="([^"]+)"')
_SHLO_CUSTOM_CALL_PRETTY_RE = re.compile(
    r"stablehlo\.custom_call\s+@([\w$.]+)")
_SHLO_CUSTOM_CALL_GENERIC_RE = re.compile(
    r'call_target_name\s*=\s*"([^"]+)"')


def op_histogram(hlo_text: str,
                 include_tuple_shaped: bool = False) -> Dict[str, int]:
    """Opcode-class histogram of a compiled HLO module (entry + nested
    computations).  Deterministic for a given (program, platform, XLA
    version) — the op-mix fingerprint that catches "same flops, worse
    formulation" regressions (e.g. a dense op turning into scatter).

    The default SKIPS tuple-shaped instructions (``(s64[8], f64[8])
    sort(...)``): the frozen COSTS baselines pin that census, so the
    default can only change together with a re-baseline.  irlint's
    transfer census passes ``include_tuple_shaped=True`` — the ops it
    hunts (infeed, recv) are exactly the tuple-shaped ones."""
    hist: Dict[str, int] = {}
    for m in HLO_INSTR_RE.finditer(hlo_text):
        if not include_tuple_shaped and m.group(1).startswith("("):
            continue
        op = m.group(2)
        hist[op] = hist.get(op, 0) + 1
    return dict(sorted(hist.items()))


def shape_elements(dims: str) -> int:
    """Element count of an HLO dims string (``"1024,8"`` → 8192;
    ``""`` — a scalar — → 1)."""
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n


def custom_call_targets(hlo_text: str) -> Dict[str, int]:
    """Per-target custom-call counts of a compiled HLO module."""
    out: Dict[str, int] = {}
    for m in _HLO_CUSTOM_CALL_RE.finditer(hlo_text):
        out[m.group(1)] = out.get(m.group(1), 0) + 1
    return dict(sorted(out.items()))


def stablehlo_custom_call_targets(stablehlo_text: str) -> Dict[str, int]:
    """Per-target custom-call counts of a StableHLO module (both the
    pretty ``@Target`` form and the generic-form attribute)."""
    out: Dict[str, int] = {}
    for rx in (_SHLO_CUSTOM_CALL_PRETTY_RE, _SHLO_CUSTOM_CALL_GENERIC_RE):
        for m in rx.finditer(stablehlo_text):
            out[m.group(1)] = out.get(m.group(1), 0) + 1
    return dict(sorted(out.items()))


def stablehlo_op_count(stablehlo_text: str, op: str) -> int:
    """Occurrences of one StableHLO op (``"scatter"`` counts
    ``stablehlo.scatter`` only — ``select_and_scatter`` is a different
    token and does not match)."""
    return len(re.findall(
        r"\bstablehlo\." + re.escape(op) + r"\b", stablehlo_text))


def stablehlo_type_census(stablehlo_text: str) -> Dict[str, int]:
    """Tensor-type token census of a StableHLO module: how many times
    each element type appears in a ``tensor<...>`` type.  Counts
    operand AND result positions — deliberately redundant, so a silent
    i32→i64 / f32→f64 promotion moves the census even when the op count
    is unchanged."""
    out: Dict[str, int] = {}
    for m in STABLEHLO_TENSOR_RE.finditer(stablehlo_text):
        t = m.group(1)
        out[t] = out.get(t, 0) + 1
    return dict(sorted(out.items()))


def folded_constants(hlo_text: str, min_elements: int) -> List[dict]:
    """Constant instructions of at least ``min_elements`` elements in a
    compiled HLO module — literals XLA kept AFTER folding, the class an
    AST-level constant-bloat rule cannot see once a builder function
    folds them (PR 7's 1MB decode control table)."""
    out: List[dict] = []
    for m in HLO_INSTR_RE.finditer(hlo_text):
        if m.group(2) != "constant":
            continue
        sm = HLO_SHAPE_RE.search(m.group(1))
        if sm is None:
            continue
        n = shape_elements(sm.group(2))
        if n >= min_elements:
            out.append({"dtype": sm.group(1),
                        "shape": sm.group(2) or "scalar",
                        "elements": n})
    out.sort(key=lambda c: (-c["elements"], c["dtype"], c["shape"]))
    return out
