"""Process-global fault-injection registry.

The reference hardens its wire layer with dtests that kill processes;
what it cannot do from outside is exercise the *partial* failures —
a dropped request, a slow fsync, a corrupt frame — deterministically.
This module plants named **faultpoints** at the socket and disk
boundaries (``kv_remote.call``, ``rpc.call``, ``rpc.server``,
``ingest_tcp.frame``, ``replication.collective``, ``commitlog.flush``)
and lets tests/dtest arm them with one of four modes:

* ``drop``    — the operation is lost: socket sites close the
  connection and raise; the commitlog flush site silently skips the
  fsync (the torn-write crash case).
* ``delay``   — sleep ``ms`` before proceeding (slow peer / slow disk).
* ``error``   — raise :class:`FaultInjected` (an ``OSError`` /
  ``ConnectionError`` subclass, so transport-level handlers and retry
  classifiers treat it exactly like a real failure).
* ``corrupt`` — flip one byte of the payload passing through
  :func:`mangle` (checksum/torn-frame paths).

Determinism: every armed spec owns a :class:`random.Random` seeded by
``(seed, point name, mode)`` as a *string* (string seeding is stable
across processes — no hash randomization), so a scenario replays
identically.  Each spec fires at most ``n`` times (default unlimited),
with probability ``p``, skipping the first ``after`` passes.

Arming:
* code — ``arm("kv_remote.call", "drop", p=0.3, seed=7)`` or the
  ``with armed(...):`` context manager (tests);
* env — ``M3_FAULTPOINTS="kv_remote.call=drop:p=0.3;kv_remote.call=
  delay:ms=20"`` parsed at import, so dtest node subprocesses inherit
  faults through their environment.

Call sites pay one dict lookup when nothing is armed — the registry is
free in production.  Per-point counters (passes/triggers per mode) are
exported through ``m3_tpu.x.register_metrics`` and asserted by the
dtest scenarios.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, List

__all__ = [
    "FaultInjected", "FaultSpec", "arm", "armed", "arm_from_env",
    "disarm", "fire", "mangle", "counters", "reset_counters", "points",
]


class FaultInjected(ConnectionError):
    """Raised by error-mode faultpoints.  ``ConnectionError`` (hence
    ``OSError``) so socket sites' existing handlers and the retry
    classifier treat it as a genuine transport/disk failure."""


MODES = ("drop", "delay", "error", "corrupt")


class FaultSpec:
    """One armed behavior on one point; a point may hold several."""

    __slots__ = ("point", "mode", "p", "n", "after", "delay_s", "_rng",
                 "_passes", "triggers", "_lock")

    def __init__(self, point: str, mode: str, p: float = 1.0,
                 n: int | None = None, after: int = 0,
                 delay_ms: float = 0.0, seed: int = 0):
        if mode not in MODES:
            raise ValueError(f"fault mode {mode!r}: must be one of {MODES}")
        self.point = point
        self.mode = mode
        self.p = float(p)
        self.n = n
        self.after = int(after)
        self.delay_s = float(delay_ms) / 1000.0
        # String seeding is deterministic across processes (sha512 of
        # the string, no PYTHONHASHSEED involvement).
        self._rng = random.Random(f"{seed}:{point}:{mode}")
        self._passes = 0
        self.triggers = 0
        self._lock = threading.Lock()

    def should_trigger(self) -> bool:
        with self._lock:
            self._passes += 1
            if self._passes <= self.after:
                return False
            if self.n is not None and self.triggers >= self.n:
                return False
            if self.p < 1.0 and self._rng.random() >= self.p:
                return False
            self.triggers += 1
        # Trigger totals outlive the spec: scenarios disarm (the
        # `armed` context exits) BEFORE asserting counters.
        key = f"{self.point}.{self.mode}_triggers"
        with _lock:
            _trigger_totals[key] = _trigger_totals.get(key, 0) + 1
        return True


_lock = threading.Lock()
_points: Dict[str, List[FaultSpec]] = {}
_passes: Dict[str, int] = {}
_trigger_totals: Dict[str, int] = {}


def arm(point: str, mode: str, **kw) -> FaultSpec:
    """Arm one fault spec on ``point``; returns it (for its counter)."""
    spec = FaultSpec(point, mode, **kw)
    with _lock:
        _points.setdefault(point, []).append(spec)
    return spec


def disarm(point: str | None = None) -> None:
    """Disarm one point, or every point when ``point`` is None."""
    with _lock:
        if point is None:
            _points.clear()
        else:
            _points.pop(point, None)


class armed:
    """``with fault.armed("p", "drop", p=0.5):`` — arm for a scope and
    ALWAYS disarm that point on exit (test hygiene)."""

    def __init__(self, point: str, mode: str, **kw):
        self._args = (point, mode, kw)
        self.spec: FaultSpec | None = None

    def __enter__(self) -> FaultSpec:
        point, mode, kw = self._args
        self.spec = arm(point, mode, **kw)
        return self.spec

    def __exit__(self, *exc) -> None:
        point = self._args[0]
        with _lock:
            specs = _points.get(point)
            if specs is not None:
                try:
                    specs.remove(self.spec)
                except ValueError:
                    pass
                if not specs:
                    del _points[point]


def fire(point: str, sleep: Callable[[float], None] = time.sleep) -> str | None:
    """Evaluate the armed specs at ``point``.

    Returns ``"drop"`` when a drop-mode spec triggers (the SITE decides
    what a drop means at its boundary), ``None`` otherwise.  Delay-mode
    sleeps inline; error-mode raises :class:`FaultInjected`.  Corrupt
    specs are ignored here — byte-carrying sites use :func:`mangle`.
    """
    specs = _points.get(point)
    if not specs:
        return None
    with _lock:
        _passes[point] = _passes.get(point, 0) + 1
        snapshot = list(specs)
    action = None
    for spec in snapshot:
        if spec.mode == "corrupt" or not spec.should_trigger():
            continue
        if spec.mode == "delay":
            sleep(spec.delay_s)
        elif spec.mode == "error":
            raise FaultInjected(f"injected fault at {point}")
        elif spec.mode == "drop":
            action = "drop"
    return action


def mangle(point: str, data: bytes,
           sleep: Callable[[float], None] = time.sleep) -> tuple:
    """:func:`fire` for byte-carrying boundaries: evaluates corrupt
    specs too.  Returns ``(action, data)`` where a triggered corrupt
    spec has one byte flipped at a deterministic (seeded) offset."""
    specs = _points.get(point)
    if not specs:
        return None, data
    action = fire(point, sleep=sleep)
    with _lock:
        snapshot = list(specs)
    for spec in snapshot:
        if spec.mode != "corrupt" or not spec.should_trigger():
            continue
        if data:
            i = spec._rng.randrange(len(data))
            data = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
    return action, data


def arm_from_env(env: str | None = None) -> int:
    """Parse ``M3_FAULTPOINTS`` (or ``env``) and arm the result.

    Grammar: ``point=mode[:key=value]*`` joined by ``;``.  Keys:
    ``p`` (probability), ``n`` (max triggers), ``ms`` (delay),
    ``after`` (skip first k passes), ``seed``.  Returns the number of
    specs armed.  A malformed entry raises ValueError — a typo silently
    arming nothing would invalidate the scenario the flag exists for.
    """
    raw = os.environ.get("M3_FAULTPOINTS", "") if env is None else env
    count = 0
    for entry in raw.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        head, *opts = entry.split(":")
        point, sep, mode = head.partition("=")
        if not sep or not point or not mode:
            raise ValueError(f"M3_FAULTPOINTS entry {entry!r}: "
                             "expected point=mode[:key=value]*")
        kw: dict = {}
        for opt in opts:
            k, sep, v = opt.partition("=")
            if not sep:
                raise ValueError(f"M3_FAULTPOINTS option {opt!r} in {entry!r}")
            if k == "p":
                kw["p"] = float(v)
            elif k == "n":
                kw["n"] = int(v)
            elif k == "ms":
                kw["delay_ms"] = float(v)
            elif k == "after":
                kw["after"] = int(v)
            elif k == "seed":
                kw["seed"] = int(v)
            else:
                raise ValueError(f"M3_FAULTPOINTS key {k!r} in {entry!r}")
        arm(point, mode, **kw)
        count += 1
    return count


def counters() -> Dict[str, int]:
    """Flat ``{"<point>.passes": n, "<point>.<mode>_triggers": n}``.
    Trigger totals survive disarm — scenarios assert them after their
    ``armed`` context has exited."""
    with _lock:
        out: Dict[str, int] = dict(_trigger_totals)
        for point, n in _passes.items():
            out[f"{point}.passes"] = n
    return out


def reset_counters() -> None:
    with _lock:
        _passes.clear()
        _trigger_totals.clear()
        for specs in _points.values():
            for spec in specs:
                spec.triggers = 0
                spec._passes = 0


def points() -> List[str]:
    with _lock:
        return sorted(_points)


# Node subprocesses inherit faults through the environment (the dtest
# harness passes env= through NodeProcess).
arm_from_env()
