"""Process-global fault-injection registry.

The reference hardens its wire layer with dtests that kill processes;
what it cannot do from outside is exercise the *partial* failures —
a dropped request, a slow fsync, a corrupt frame — deterministically.
This module plants named **faultpoints** at the socket and disk
boundaries (``kv_remote.call``, ``rpc.call``, ``rpc.server``,
``ingest_tcp.frame``, ``replication.collective``, ``commitlog.flush``)
and lets tests/dtest arm them with one of four modes:

* ``drop``    — the operation is lost: socket sites close the
  connection and raise; the commitlog flush site silently skips the
  fsync (the torn-write crash case).
* ``delay``   — sleep ``ms`` before proceeding (slow peer / slow disk).
* ``error``   — raise :class:`FaultInjected` (an ``OSError`` /
  ``ConnectionError`` subclass, so transport-level handlers and retry
  classifiers treat it exactly like a real failure).
* ``corrupt`` — flip one byte of the payload passing through
  :func:`mangle` (checksum/torn-frame paths).

Determinism: every armed spec owns a :class:`random.Random` seeded by
``(seed, point name, mode)`` as a *string* (string seeding is stable
across processes — no hash randomization), so a scenario replays
identically.  Each spec fires at most ``n`` times (default unlimited),
with probability ``p``, skipping the first ``after`` passes.

Arming:
* code — ``arm("kv_remote.call", "drop", p=0.3, seed=7)`` or the
  ``with armed(...):`` context manager (tests);
* env — ``M3_FAULTPOINTS="kv_remote.call=drop:p=0.3;kv_remote.call=
  delay:ms=20"`` parsed at import, so dtest node subprocesses inherit
  faults through their environment;
* wire — ``POST /api/v1/debug/faults`` (admin + main API) carries the
  SAME spec grammar in ``{"arm": "..."}`` so a chaos scheduler can
  re-arm a LIVE node mid-run without a restart.  :func:`parse_faults`
  is the one parser behind both; :func:`apply_request` /
  :func:`registry_response` are the shared HTTP builders (the
  ``tracing.traces_response`` pattern).

The registry is thread-safe end to end (arm/disarm/snapshot/fire run
concurrently with handler threads) and **counters survive re-arming**:
per-point passes and per-mode trigger totals live OUTSIDE the specs, so
``disarm(); arm(...)`` — the admin endpoint's re-arm shape — never
zeroes what a scenario will assert on.  Call sites pay one dict lookup
when nothing is armed — the registry is free in production.  Per-point
counters (passes/triggers per mode) are exported through
``m3_tpu.x.register_metrics`` and asserted by the dtest scenarios.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, List

__all__ = [
    "FaultInjected", "FaultSpec", "arm", "armed", "arm_from_env",
    "arm_many", "apply_request", "disarm", "fire", "mangle", "counters",
    "parse_faults", "registry_response", "reset_counters", "points",
    "snapshot",
]


class FaultInjected(ConnectionError):
    """Raised by error-mode faultpoints.  ``ConnectionError`` (hence
    ``OSError``) so socket sites' existing handlers and the retry
    classifier treat it as a genuine transport/disk failure."""


MODES = ("drop", "delay", "error", "corrupt")


class FaultSpec:
    """One armed behavior on one point; a point may hold several."""

    __slots__ = ("point", "mode", "p", "n", "after", "delay_s", "seed",
                 "_rng", "_passes", "triggers", "_lock")

    def __init__(self, point: str, mode: str, p: float = 1.0,
                 n: int | None = None, after: int = 0,
                 delay_ms: float = 0.0, seed: int = 0):
        if mode not in MODES:
            raise ValueError(f"fault mode {mode!r}: must be one of {MODES}")
        self.point = point
        self.mode = mode
        self.p = float(p)
        self.n = n
        self.after = int(after)
        self.delay_s = float(delay_ms) / 1000.0
        self.seed = int(seed)
        # String seeding is deterministic across processes (sha512 of
        # the string, no PYTHONHASHSEED involvement).
        self._rng = random.Random(f"{seed}:{point}:{mode}")
        self._passes = 0
        self.triggers = 0
        self._lock = threading.Lock()

    def should_trigger(self) -> bool:
        with self._lock:
            self._passes += 1
            if self._passes <= self.after:
                return False
            if self.n is not None and self.triggers >= self.n:
                return False
            if self.p < 1.0 and self._rng.random() >= self.p:
                return False
            self.triggers += 1
        # Trigger totals outlive the spec: scenarios disarm (the
        # `armed` context exits) BEFORE asserting counters.
        key = f"{self.point}.{self.mode}_triggers"
        with _lock:
            _trigger_totals[key] = _trigger_totals.get(key, 0) + 1
        return True

    def to_dict(self) -> dict:
        """Wire shape of one armed spec (GET /api/v1/debug/faults)."""
        with self._lock:
            return {
                "point": self.point, "mode": self.mode, "p": self.p,
                "n": self.n, "after": self.after,
                "ms": self.delay_s * 1000.0, "seed": self.seed,
                "passes": self._passes, "triggers": self.triggers,
            }


_lock = threading.Lock()
_points: Dict[str, List[FaultSpec]] = {}
_passes: Dict[str, int] = {}
_trigger_totals: Dict[str, int] = {}


def arm(point: str, mode: str, **kw) -> FaultSpec:
    """Arm one fault spec on ``point``; returns it (for its counter)."""
    spec = FaultSpec(point, mode, **kw)
    with _lock:
        _points.setdefault(point, []).append(spec)
    return spec


def disarm(point: str | None = None) -> None:
    """Disarm one point, or every point when ``point`` is None."""
    with _lock:
        if point is None:
            _points.clear()
        else:
            _points.pop(point, None)


class armed:
    """``with fault.armed("p", "drop", p=0.5):`` — arm for a scope and
    ALWAYS disarm that point on exit (test hygiene)."""

    def __init__(self, point: str, mode: str, **kw):
        self._args = (point, mode, kw)
        self.spec: FaultSpec | None = None

    def __enter__(self) -> FaultSpec:
        point, mode, kw = self._args
        self.spec = arm(point, mode, **kw)
        return self.spec

    def __exit__(self, *exc) -> None:
        point = self._args[0]
        with _lock:
            specs = _points.get(point)
            if specs is not None:
                try:
                    specs.remove(self.spec)
                except ValueError:
                    pass
                if not specs:
                    del _points[point]


def fire(point: str, sleep: Callable[[float], None] = time.sleep) -> str | None:
    """Evaluate the armed specs at ``point``.

    Returns ``"drop"`` when a drop-mode spec triggers (the SITE decides
    what a drop means at its boundary), ``None`` otherwise.  Delay-mode
    sleeps inline; error-mode raises :class:`FaultInjected`.  Corrupt
    specs are ignored here — byte-carrying sites use :func:`mangle`.
    """
    specs = _points.get(point)
    if not specs:
        return None
    with _lock:
        _passes[point] = _passes.get(point, 0) + 1
        snapshot = list(specs)
    action = None
    for spec in snapshot:
        if spec.mode == "corrupt" or not spec.should_trigger():
            continue
        if spec.mode == "delay":
            sleep(spec.delay_s)
        elif spec.mode == "error":
            raise FaultInjected(f"injected fault at {point}")
        elif spec.mode == "drop":
            action = "drop"
    return action


def mangle(point: str, data: bytes,
           sleep: Callable[[float], None] = time.sleep) -> tuple:
    """:func:`fire` for byte-carrying boundaries: evaluates corrupt
    specs too.  Returns ``(action, data)`` where a triggered corrupt
    spec has one byte flipped at a deterministic (seeded) offset."""
    specs = _points.get(point)
    if not specs:
        return None, data
    action = fire(point, sleep=sleep)
    with _lock:
        snapshot = list(specs)
    for spec in snapshot:
        if spec.mode != "corrupt" or not spec.should_trigger():
            continue
        if data:
            i = spec._rng.randrange(len(data))
            data = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
    return action, data


def parse_faults(raw: str) -> List[tuple]:
    """Parse the fault-spec grammar into ``[(point, mode, kwargs)]``
    WITHOUT arming anything (validation happens before mutation — a
    half-armed malformed request would leave the node in a state the
    caller never asked for).

    Grammar: ``point=mode[:key=value]*`` joined by ``;``.  Keys:
    ``p`` (probability), ``n`` (max triggers), ``ms`` (delay),
    ``after`` (skip first k passes), ``seed``.  One grammar everywhere:
    the ``M3_FAULTPOINTS`` env var at process start and the
    ``/api/v1/debug/faults`` admin body mid-run parse through this
    exact function.  A malformed entry raises ValueError — a typo
    silently arming nothing would invalidate the scenario the flag
    exists for.
    """
    out: List[tuple] = []
    for entry in raw.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        head, *opts = entry.split(":")
        point, sep, mode = head.partition("=")
        if not sep or not point or not mode:
            raise ValueError(f"faultpoints entry {entry!r}: "
                             "expected point=mode[:key=value]*")
        if mode not in MODES:
            raise ValueError(f"faultpoints entry {entry!r}: mode {mode!r} "
                             f"must be one of {MODES}")
        kw: dict = {}
        for opt in opts:
            k, sep, v = opt.partition("=")
            if not sep:
                raise ValueError(f"faultpoints option {opt!r} in {entry!r}")
            if k == "p":
                kw["p"] = float(v)
            elif k == "n":
                kw["n"] = int(v)
            elif k == "ms":
                kw["delay_ms"] = float(v)
            elif k == "after":
                kw["after"] = int(v)
            elif k == "seed":
                kw["seed"] = int(v)
            else:
                raise ValueError(f"faultpoints key {k!r} in {entry!r}")
        out.append((point, mode, kw))
    return out


def arm_many(raw: str) -> int:
    """Parse-then-arm one spec string; returns the number of specs
    armed.  All-or-nothing: a grammar error arms NOTHING."""
    specs = parse_faults(raw)
    for point, mode, kw in specs:
        arm(point, mode, **kw)
    return len(specs)


def arm_from_env(env: str | None = None) -> int:
    """Arm from ``M3_FAULTPOINTS`` (or ``env``); see :func:`parse_faults`
    for the grammar."""
    raw = os.environ.get("M3_FAULTPOINTS", "") if env is None else env
    return arm_many(raw)


def snapshot() -> List[dict]:
    """Every armed spec as a dict (point/mode/knobs + live pass/trigger
    counts), sorted by point then mode — the readable half of the
    runtime re-arm surface."""
    with _lock:
        specs = [s for lst in _points.values() for s in lst]
    return sorted((s.to_dict() for s in specs),
                  key=lambda d: (d["point"], d["mode"]))


# -- HTTP builders (admin + main API /api/v1/debug/faults) -------------------
#
# Shared by server/admin_api.py and server/http_api.py exactly like
# tracing.traces_response: two ports, one behavior, no drift.


def registry_response() -> dict:
    """GET body: armed specs + the process counters (passes survive
    disarm, trigger totals survive re-arm)."""
    return {"armed": snapshot(), "counters": counters()}


def apply_request(body: dict) -> dict:
    """POST body → mutate the registry, return the post-state.

    ``{"disarm": true | ["point", ...], "reset_counters": bool,
    "arm": "point=mode[:key=value]*;..."}`` — disarm applies FIRST so
    one request is a complete re-arm (the chaos scheduler's
    window-transition shape), and counters are PRESERVED unless
    ``reset_counters`` asks otherwise.  Unknown keys raise (a typo'd
    request must not silently no-op)."""
    unknown = set(body) - {"arm", "disarm", "reset_counters"}
    if unknown:
        raise ValueError(f"debug/faults: unknown keys {sorted(unknown)}")
    # validate BEFORE mutating: a bad arm spec must not leave the node
    # disarmed when the caller asked for an atomic re-arm
    specs = parse_faults(body.get("arm") or "")
    dis = body.get("disarm")
    # a bare string would iterate per CHARACTER and disarm nothing
    # (disarm() pops unknown points silently) — the silent no-op this
    # endpoint exists to prevent
    if not (dis is None or isinstance(dis, (bool, list, tuple))):
        raise ValueError(
            "debug/faults: 'disarm' must be true or a list of points")
    if dis is True:
        disarm()
    elif dis:
        for point in dis:
            disarm(str(point))
    if body.get("reset_counters"):
        reset_counters()
    for point, mode, kw in specs:
        arm(point, mode, **kw)
    out = registry_response()
    out["armed_count"] = len(specs)
    return out


def counters() -> Dict[str, int]:
    """Flat ``{"<point>.passes": n, "<point>.<mode>_triggers": n}``.
    Trigger totals survive disarm — scenarios assert them after their
    ``armed`` context has exited."""
    with _lock:
        out: Dict[str, int] = dict(_trigger_totals)
        for point, n in _passes.items():
            out[f"{point}.passes"] = n
    return out


def reset_counters() -> None:
    with _lock:
        _passes.clear()
        _trigger_totals.clear()
        for specs in _points.values():
            for spec in specs:
                spec.triggers = 0
                spec._passes = 0


def points() -> List[str]:
    with _lock:
        return sorted(_points)


# Node subprocesses inherit faults through the environment (the dtest
# harness passes env= through NodeProcess).
arm_from_env()
