"""End-to-end query deadlines + cooperative cancellation.

The read-path mirror of ``x/retry``'s write-path contract: every query
carries ONE absolute expiry from the HTTP front door down through the
engine, the fanout, and each wire hop, so overload degrades predictably
instead of stacking unbounded waits behind a slow peer.  Equivalent of
the reference's context deadline threading (`context.Context` flowing
query/api → executor → m3db session → TChannel call timeouts) distilled
to a small explicit object:

* :class:`Deadline` — absolute expiry (monotonic clock) + a cooperative
  cancel flag.  ``remaining()`` is the budget left; ``check()`` raises
  the typed :class:`DeadlineExceeded` (or :class:`QueryCancelled`) the
  HTTP layer maps to 504; ``socket_timeout()`` derives per-call socket
  timeouts from the remaining budget so a wire hop can never outlive
  its query.
* **Context propagation** — ``bind(dl)`` installs the deadline for the
  current thread of execution (`contextvars`); ``current()`` reads it.
  Storage seams (`query/remote.py`, `server/rpc.py`) consult
  ``current()`` so the `fetch_raw` signature stays unchanged end to
  end.  Worker threads do NOT inherit context — fan-out code re-binds
  explicitly (`query/fanout.py`).
* **Wire form** — the *remaining* budget travels as milliseconds in the
  QUERY_FETCH / RPC_REQ_DL frames (relative, not absolute: peers' clocks
  need not agree), so the server stops work for a query whose client
  already gave up.
* **Query annotations** — a bound deadline accumulates ``warnings``
  (partial-result policy: a non-required fanout source that missed the
  deadline) and per-phase timings (``phase("fetch")``), both surfaced
  by the slow-query log and the HTTP ``warnings`` field.

Counters (``deadline.exceeded`` / ``deadline.cancelled``) follow the
fault/retry pattern: module-global, mirrored onto /metrics by
``m3_tpu.x.register_metrics`` (as ``query_deadline_exceeded_total``),
asserted by the overload dtest.  They count QUERIES, not exception
objects: one bump per :class:`Deadline` at first local detection
(:meth:`Deadline.exceeded`), never on bare construction — so fanout
stragglers, per-replica checks and wire-decoded remote trips cannot
inflate the totals.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Callable, Dict, List

__all__ = [
    "Deadline", "DeadlineExceeded", "QueryCancelled", "bind", "current",
    "check_current", "socket_timeout", "remaining_ms", "counters",
    "reset_counters", "decode_wire_error",
]

_lock = threading.Lock()
_counters: Dict[str, int] = {}


def _bump(key: str) -> None:
    with _lock:
        _counters[key] = _counters.get(key, 0) + 1


def counters() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        _counters.clear()


class DeadlineExceeded(RuntimeError):
    """The query's end-to-end budget ran out (HTTP 504).

    Deliberately NOT an ``OSError``/``TimeoutError`` subclass: transport
    handlers (reconnect-and-retry on ``OSError``) and the retry
    classifier must not treat an exhausted budget as a transient
    transport blip — retrying cannot un-expire a deadline.

    Constructing one does NOT bump the counters: ``deadline.exceeded``
    counts QUERIES (once per :class:`Deadline`, at first local
    detection, via :meth:`Deadline.exceeded`), not exception objects —
    a fanout with three stragglers is still one blown deadline, and a
    remote peer's trip decoded off the wire was already counted by the
    peer that detected it."""

    def __init__(self, msg: str = "deadline exceeded"):
        super().__init__(msg)


class QueryCancelled(DeadlineExceeded):
    """Cooperative cancellation observed (client went away / operator
    kill): same control flow as an expired deadline, typed apart for
    logs and counters."""

    def __init__(self, msg: str = "query cancelled"):
        super().__init__(msg)


class Deadline:
    """Absolute expiry + cooperative cancel flag, shared by every stage
    of one query.  Thread-safe: fan-out worker threads check and
    annotate the same instance."""

    __slots__ = ("timeout_s", "_expiry", "_clock", "_cancelled", "_mu",
                 "warnings", "phases", "started", "_counted")

    def __init__(self, timeout_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.timeout_s = float(timeout_s)
        self._clock = clock
        self.started = clock()
        self._expiry = self.started + self.timeout_s
        self._cancelled = False
        self._mu = threading.Lock()
        self._counted = False
        self.warnings: List[str] = []
        self.phases: Dict[str, float] = {}

    @classmethod
    def from_timeout(cls, timeout_s: float, clock=time.monotonic) -> "Deadline":
        return cls(timeout_s, clock)

    # -- budget ------------------------------------------------------------

    def remaining(self) -> float:
        """Seconds left; negative once expired."""
        return self._expiry - self._clock()

    def elapsed(self) -> float:
        return self._clock() - self.started

    @property
    def expired(self) -> bool:
        return self._cancelled or self.remaining() <= 0.0

    def cancel(self) -> None:
        """Cooperative cancel: the next ``check()`` on ANY thread
        sharing this deadline raises ``QueryCancelled``."""
        self._cancelled = True

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def check(self, what: str = "query") -> None:
        """Raise if cancelled or expired — the cooperative cancellation
        point, cheap enough for per-eval-node / per-loop placement."""
        if self._cancelled:
            raise self.exceeded(f"{what}: cancelled")
        if self.remaining() <= 0.0:
            raise self.exceeded(
                f"{what}: deadline exceeded "
                f"({self.timeout_s:.3f}s budget spent)")

    def exceeded(self, msg: str) -> DeadlineExceeded:
        """The typed error for THIS deadline's expiry/cancellation,
        counted once per deadline no matter how many stages observe it
        (``deadline.exceeded``/``deadline.cancelled`` count queries,
        not exception objects)."""
        with self._mu:
            counted, self._counted = self._counted, True
        if not counted:
            _bump("deadline.cancelled" if self._cancelled
                  else "deadline.exceeded")
        return (QueryCancelled(msg) if self._cancelled
                else DeadlineExceeded(msg))

    def socket_timeout(self, cap: float | None = None) -> float:
        """Per-call socket timeout from the remaining budget, optionally
        capped (a generous legacy constant must never EXTEND a
        deadline).  Raises instead of returning a non-positive
        timeout."""
        rem = self.remaining()
        if self._cancelled or rem <= 0.0:
            self.check("wire call")
        return rem if cap is None else min(rem, cap)

    # -- wire form ---------------------------------------------------------

    def remaining_ms(self) -> int:
        """Relative budget for the wire (ms, floor 0)."""
        return max(0, int(self.remaining() * 1000))

    # -- annotations -------------------------------------------------------

    def add_warning(self, msg: str) -> None:
        with self._mu:
            self.warnings.append(msg)

    @contextlib.contextmanager
    def phase(self, name: str):
        """Accumulate wall time into ``phases[name]`` (slow-query log
        breakdown: how much of the budget each stage ate)."""
        t0 = self._clock()
        try:
            yield self
        finally:
            dt = self._clock() - t0
            with self._mu:
                self.phases[name] = self.phases.get(name, 0.0) + dt


# -- wire error decoding ----------------------------------------------------


def decode_wire_error(msg: str) -> Exception | None:
    """Typed OVERLOAD errors crossing a wire error payload
    (``TypeName: message``) → the exception to re-raise client-side,
    or None when the message is not an overload error.  The single
    mapping shared by the query-federation and rpc protocols, so a
    remote limit trip stays a 429 and a remote deadline trip a 504 on
    BOTH — adding the next typed error here covers every wire at once.
    The returned ``DeadlineExceeded`` is constructed bare (uncounted):
    the peer that detected the trip already counted it."""
    if msg.startswith("QueryLimitExceeded:"):
        from m3_tpu.storage.limits import QueryLimitExceeded

        return QueryLimitExceeded.from_message(msg)
    if msg.startswith(("DeadlineExceeded:", "QueryCancelled:")):
        return DeadlineExceeded(f"remote peer: {msg}")
    return None


# -- context propagation ----------------------------------------------------

_current: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "m3_query_deadline", default=None)


def current() -> Deadline | None:
    """The deadline bound to this thread of execution, or None."""
    return _current.get()


@contextlib.contextmanager
def bind(dl: Deadline | None):
    """Install ``dl`` as the current deadline for the scope.  Binding
    None is a no-op scope (callers need no conditional).  New threads
    never inherit the binding — fan-out workers re-bind explicitly."""
    token = _current.set(dl)
    try:
        yield dl
    finally:
        _current.reset(token)


def check_current(what: str = "query") -> None:
    """``check()`` on the bound deadline, no-op when none is bound —
    the one-liner evaluation loops use between nodes/steps."""
    dl = _current.get()
    if dl is not None:
        dl.check(what)


def socket_timeout(cap: float) -> float:
    """Per-call socket timeout for the bound deadline: the remaining
    budget capped at ``cap``, or ``cap`` itself when no deadline is
    bound.  Raises ``DeadlineExceeded`` when the budget is already
    spent — wire clients call this BEFORE dialing/sending."""
    dl = _current.get()
    if dl is None:
        return cap
    return dl.socket_timeout(cap)


def remaining_ms(default: int = -1) -> int:
    """Wire form of the bound deadline's budget; ``default`` (-1 = no
    deadline) when none is bound."""
    dl = _current.get()
    return default if dl is None else dl.remaining_ms()
