"""Admission control: bounded concurrent-query slots + a bounded wait
queue with queue timeout.

The read-path twin of PR 1's ingest load-shed (``INGEST_BACKOFF``):
under overload the query front door sheds EARLY with a typed
:class:`QueryShedError` (HTTP 503 + Retry-After) instead of queueing
unboundedly until every thread is wedged behind slow storage — the
degrade-predictably contract of the reference's per-query limits and
coordinator concurrency gates.

Shape: ``max_concurrent`` slots; up to ``max_queue`` callers may wait
``queue_timeout_s`` for a slot (bounded by the query's own deadline —
no point waiting longer than the caller will exist); everyone else is
shed immediately.  ``admit()`` is a context manager so release is
exception-safe; gauges/counters (`active`, `waiting`, `shed_total`,
`admitted_total`, `queue_timeout_total`) are mirrored onto /metrics by
the server assembly (``query_active``, ``query_shed_total``) and
asserted by the overload dtest's burst scenario.
"""

from __future__ import annotations

import contextlib
import threading
import time


class QueryShedError(RuntimeError):
    """Admission denied: the node is at its concurrent-query capacity
    and the wait queue is full (or the wait timed out).  The HTTP layer
    maps this to 503 with ``Retry-After: ceil(retry_after_s)``."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class AdmissionController:
    """Semaphore-gated query slots with a bounded, timed wait queue.

    ``max_concurrent <= 0`` disables gating entirely (the limits-style
    "0 = off" convention) — ``admit()`` is then a free no-op scope."""

    def __init__(self, max_concurrent: int = 0, max_queue: int = 0,
                 queue_timeout_s: float = 1.0,
                 clock=time.monotonic):
        self.max_concurrent = int(max_concurrent)
        self.max_queue = int(max_queue)
        self.queue_timeout_s = float(queue_timeout_s)
        self._clock = clock
        self._cv = threading.Condition(threading.Lock())
        self._active = 0
        self._waiting = 0
        self.admitted_total = 0
        self.shed_total = 0
        self.queue_timeout_total = 0

    # -- observability -----------------------------------------------------

    @property
    def active(self) -> int:
        return self._active

    @property
    def waiting(self) -> int:
        return self._waiting

    def metrics(self) -> dict:
        with self._cv:
            return {
                "active": self._active,
                "waiting": self._waiting,
                "admitted_total": self.admitted_total,
                "shed_total": self.shed_total,
                "queue_timeout_total": self.queue_timeout_total,
                "max_concurrent": self.max_concurrent,
                "max_queue": self.max_queue,
            }

    # -- live resize (the self-healing actuator seam) ----------------------

    def resize(self, max_concurrent: int | None = None,
               max_queue: int | None = None) -> dict:
        """Live-retune capacity (the x/controller actuator seam).

        Takes effect for the NEXT admit: active holders keep their
        slots (shrinking never evicts — the count drains naturally),
        and growing wakes every queued waiter so freed headroom is
        claimed immediately.  Returns the post-resize metrics doc."""
        with self._cv:
            if max_concurrent is not None:
                self.max_concurrent = int(max_concurrent)
            if max_queue is not None:
                self.max_queue = int(max_queue)
            self._cv.notify_all()
        return self.metrics()

    # -- gate --------------------------------------------------------------

    @contextlib.contextmanager
    def admit(self, deadline=None):
        """Hold one query slot for the scope.  Raises
        :class:`QueryShedError` when the node is saturated; waits at
        most ``queue_timeout_s`` (and never past ``deadline``) for a
        slot when the queue has room."""
        if self.max_concurrent <= 0:
            yield self
            return
        self._acquire(deadline)
        try:
            yield self
        finally:
            self._release()

    def _acquire(self, deadline) -> None:
        with self._cv:
            if self._active < self.max_concurrent:
                self._active += 1
                self.admitted_total += 1
                return
            if self._waiting >= self.max_queue:
                self.shed_total += 1
                raise QueryShedError(
                    f"query shed: {self._active} active, "
                    f"{self._waiting} queued (capacity "
                    f"{self.max_concurrent}+{self.max_queue})",
                    retry_after_s=self.queue_timeout_s)
            budget = self.queue_timeout_s
            if deadline is not None:
                budget = min(budget, deadline.remaining())
            expiry = self._clock() + budget
            self._waiting += 1
            try:
                while self._active >= self.max_concurrent:
                    wait = expiry - self._clock()
                    if wait <= 0.0:
                        self.shed_total += 1
                        self.queue_timeout_total += 1
                        raise QueryShedError(
                            f"query shed: queued {budget:.3f}s without "
                            f"a free slot ({self.max_concurrent} busy)",
                            retry_after_s=self.queue_timeout_s)
                    self._cv.wait(wait)
                self._active += 1
                self.admitted_total += 1
            finally:
                self._waiting -= 1

    def _release(self) -> None:
        with self._cv:
            self._active -= 1
            self._cv.notify()
