"""Per-peer circuit breakers: fail fast on a dead or drowning peer.

Reference role: the M3 client's per-host health state (`host_queue`
connection health + the coordinator's remote-storage error thresholds)
— a peer that keeps failing or blowing deadlines stops being dialed at
all for a cool-down, so every query stops paying the full timeout to
rediscover the same dead region (the classic closed → open → half-open
breaker).

* **closed** — calls flow; ``failure_threshold`` CONSECUTIVE failures
  (transport errors or deadline blowouts — application errors from a
  responsive peer do NOT count) trip it open.
* **open** — calls raise :class:`BreakerOpenError` immediately for
  ``reset_timeout_s``; the fanout treats that like any per-source
  failure, so a dead region costs nothing instead of a full deadline.
* **half-open** — after the cool-down, ONE probe call passes; success
  closes the breaker, failure re-opens it (fresh cool-down).

Breakers are shared per peer through :func:`breaker_for` (a process
registry keyed by peer name) so `RemoteStorage`, the session read
fan-out, and the rpc ``RemoteDatabase`` all see one health state per
endpoint.  States are mirrored onto /metrics by
``m3_tpu.x.register_metrics`` as ``breaker_state{peer=...}``
(0=closed, 1=half-open, 2=open) plus open/trip counters — the overload
dtest asserts the slow replica's breaker opening from outside the
process.

Round 12 generalized the registry to NAMESPACED keys: the name is
still the registry key, but breakers carry a ``kind`` — ``"peer"``
(every pre-existing caller, unchanged) or ``"stage"`` (the device
guard's per-hot-path-stage breakers, keyed ``stage:<name>`` by
``x.devguard``) — and ``breaker_state`` gains a matching ``kind``
label so a dashboard can split peer health from device-stage health
without parsing key prefixes.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict

__all__ = ["BreakerOpenError", "CircuitBreaker", "breaker_for",
           "all_breakers", "reset_registry", "counters", "reset_counters"]

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpenError(RuntimeError):
    """Call refused: the peer's breaker is open.  A ``RuntimeError`` so
    the retry classifier never retries into an open breaker; fan-outs
    count it as that peer's failure like any other."""

    def __init__(self, peer: str, retry_in_s: float):
        super().__init__(
            f"circuit breaker open for peer {peer} "
            f"(retry in {max(retry_in_s, 0.0):.2f}s)")
        self.peer = peer
        self.retry_in_s = retry_in_s


_lock = threading.Lock()
_counters: Dict[str, int] = {}
_registry: Dict[str, "CircuitBreaker"] = {}


def _bump(name: str, key: str) -> None:
    with _lock:
        k = f"{name}.{key}"
        _counters[k] = _counters.get(k, 0) + 1


def counters() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        _counters.clear()


class CircuitBreaker:
    """One peer's breaker; safe for concurrent use."""

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic,
                 is_failure: Callable[[BaseException], bool] | None = None,
                 kind: str = "peer"):
        self.name = name
        self.kind = kind
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self._clock = clock
        self._is_failure = is_failure or default_breaker_failure
        self._mu = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False

    # -- observability -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._mu:
            return self._effective_state()

    @property
    def state_code(self) -> int:
        return _STATE_CODE[self.state]

    def _effective_state(self) -> str:
        # callers hold self._mu
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.reset_timeout_s):
            return HALF_OPEN
        return self._state

    # -- state machine -----------------------------------------------------

    def allow(self) -> None:
        """Gate one call; raises :class:`BreakerOpenError` when open (or
        when half-open with the probe slot already taken)."""
        with self._mu:
            st = self._effective_state()
            if st == CLOSED:
                return
            if st == HALF_OPEN and not self._probing:
                self._probing = True  # this caller is the probe
                return
            retry_in = (self._opened_at + self.reset_timeout_s
                        - self._clock())
            _bump(self.name, "rejected")
        raise BreakerOpenError(self.name, retry_in)

    def record_success(self) -> None:
        with self._mu:
            if self._state != CLOSED:
                _bump(self.name, "closed")
            self._state = CLOSED
            self._consecutive = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._mu:
            self._consecutive += 1
            was = self._effective_state()
            if (was == HALF_OPEN
                    or self._consecutive >= self.failure_threshold):
                if was != OPEN:
                    _bump(self.name, "opened")
                self._state = OPEN
                self._opened_at = self._clock()
                self._probing = False

    def force_open(self) -> None:
        """Administratively trip the breaker NOW (the x/controller
        evacuation verb).  Recovery is the normal path: after
        ``reset_timeout_s`` the breaker half-opens and a successful
        probe closes it — forced entry, earned exit."""
        with self._mu:
            if self._effective_state() != OPEN:
                _bump(self.name, "opened")
            self._state = OPEN
            self._opened_at = self._clock()
            self._probing = False

    def call(self, fn: Callable[[], object]):
        """``allow()`` → ``fn()`` → record.  Exceptions classified by
        ``is_failure`` count toward the trip threshold; application
        errors from a live peer reset it (the peer answered)."""
        self.allow()
        try:
            result = fn()
        except BaseException as e:
            if self._is_failure(e):
                self.record_failure()
            else:
                self.record_success()
            raise
        self.record_success()
        return result


def default_breaker_failure(e: BaseException) -> bool:
    """Peer-health failures: transport errors and deadline blowouts.
    Application errors (``RemoteError``, limit trips) come from a
    RESPONSIVE peer and must not open its breaker."""
    from m3_tpu.x.deadline import DeadlineExceeded

    return isinstance(e, (ConnectionError, TimeoutError, OSError,
                          DeadlineExceeded))


# -- process registry (one breaker per peer, shared by every client) --------


def breaker_for(peer: str, failure_threshold: int = 5,
                reset_timeout_s: float = 10.0,
                clock: Callable[[], float] = time.monotonic,
                kind: str = "peer") -> CircuitBreaker:
    """The process-wide breaker for ``peer``, created on first use.
    Threshold/timeout/kind apply on creation only — all sharers see
    one state.  ``kind`` labels the breaker_state metric ("peer" for
    every wire caller; "stage" for x.devguard's per-stage breakers)."""
    with _lock:
        br = _registry.get(peer)
        if br is None:
            br = CircuitBreaker(peer, failure_threshold, reset_timeout_s,
                                clock, kind=kind)
            _registry[peer] = br
        return br


def all_breakers() -> Dict[str, CircuitBreaker]:
    with _lock:
        return dict(_registry)


def reset_registry() -> None:
    """Test hygiene: drop every registered breaker (and its state)."""
    with _lock:
        _registry.clear()
