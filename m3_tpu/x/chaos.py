"""Deterministic chaos scheduler: a scripted fault timeline for soaks.

The fault substrate built across PRs 1-5 (x/fault faultpoints, SIGKILL
dtests, fileset corruption + quarantine, rolling replace) is armed
point-by-point by individual scenarios.  A soak needs the opposite
shape: ONE seeded script that drives *many* fault families against a
live cluster on a fixed clock, so two runs of the same seed produce the
same chaos and an SLO artifact is comparable run-over-run.

Two pieces:

* :class:`ChaosEvent` / :func:`parse_timeline` — the declarative
  timeline.  Each event fires at a fixed offset from scheduler start:

  =============  ==========================================================
  action         meaning (ops method called)
  =============  ==========================================================
  ``phase``      marks an SLO phase boundary (no cluster mutation); the
                 soak buckets latency between consecutive phase marks
  ``kill``       SIGKILL a node (``ops.kill(node)``)
  ``restart``    start a killed node (``ops.restart(node)``)
  ``wire_fault`` arm faultpoints on a LIVE node through its
                 ``POST /api/v1/debug/faults`` (``ops.arm_faults``);
                 ``arg`` is the M3_FAULTPOINTS-grammar spec string
  ``device_fault``  arm DEVICE-boundary faultpoints (``device.compile``
                 / ``device.dispatch`` / ``device.transfer`` — the
                 x/devguard seam) on a live node, same endpoint and
                 grammar as ``wire_fault``; every point must be in the
                 ``device.`` namespace (eager-validated) so a timeline
                 cannot silently arm a wire point under the device
                 phase label.  Error-mode triggers surface as typed
                 DeviceOOM/CompileFailure/DeviceLost and trip the
                 per-stage fallback breakers — no real TPU needed.
  ``sustained``  a burn-window-length fault as ONE entry: arm the
                 ``arg`` spec at ``at_s``, hold ``hold_s`` seconds,
                 auto-disarm.  Expanded by the scheduler into the
                 arm + ``clear_faults`` pair (wire or device arm is
                 inferred from the point namespaces; mixing the two
                 in one spec is rejected eagerly), so ops adapters
                 need no new verbs and the log still shows the exact
                 fault window.  This is the self-healing soak's
                 primitive: long enough to drive an SLO burn window,
                 gone again so recovery is provable.
  ``clear_faults``  disarm every faultpoint on a node (same endpoint)
  ``corrupt``    byte-flip a flushed fileset volume on a node's disk
                 (``ops.corrupt(node, seed)`` — quarantine/scrub must
                 recover it)
  ``replace``    rolling node replace: retire ``node``, bring in the
                 spare (``ops.replace(node)`` drives the admin
                 placement/replace verb + the migration path)
  ``disk_pressure``  ballast-fill a node's storage root until its free
                 ratio drops to ``arg`` (a float in (0, 1)); the node's
                 disk ledger must cross its watermarks, shed typed, and
                 keep serving (``ops.disk_fill(node, target)``).  With
                 ``hold_s`` the scheduler auto-appends the matching
                 ``disk_release`` — the sustained-window idiom.
  ``disk_release``  delete the ballast again
                 (``ops.disk_release(node)``) so relax-back is provable
  =============  ==========================================================

* :class:`ChaosScheduler` — executes the timeline against an *ops*
  adapter (the soak cluster; tests pass a fake) on an injectable
  clock/sleep, recording every execution (offset asked, offset fired,
  ok/error) into :attr:`log` — the artifact's chaos section is that log
  verbatim, so a reader can line fault windows up with SLO phases.

Determinism contract: the timeline is explicit (no random event
choices); the run ``seed`` namespaces whatever randomness the events
*use* — faultpoint specs without an explicit ``seed=`` get
``seed=<run_seed + index>`` appended, corruption byte offsets derive
from ``(seed, event index)``.  Same seed + same timeline = same chaos.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List

from m3_tpu.x import fault

__all__ = ["ChaosEvent", "ChaosScheduler", "expand_sustained",
           "parse_timeline"]

ACTIONS = ("phase", "kill", "restart", "wire_fault", "device_fault",
           "sustained", "clear_faults", "corrupt", "replace",
           "disk_pressure", "disk_release")


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    at_s: float          # offset from scheduler start
    action: str          # one of ACTIONS
    node: int | None = None  # target node index (phase: None)
    arg: str = ""        # wire_fault: spec string; phase: phase label
    hold_s: float = 0.0  # sustained only: seconds armed before disarm

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"chaos action {self.action!r}: must be one of {ACTIONS}")
        if self.action == "phase" and not self.arg:
            raise ValueError("phase events need a label in 'arg'")
        if self.action != "phase" and self.node is None:
            raise ValueError(f"{self.action} event needs a 'node'")
        if self.action not in ("sustained", "disk_pressure") and self.hold_s:
            raise ValueError(
                f"{self.action} events take no 'hold_s' (sustained only)")
        if self.action == "wire_fault":
            fault.parse_faults(self.arg)  # validate at BUILD time
        if self.action == "device_fault":
            specs = fault.parse_faults(self.arg)  # eager, like wire_fault
            bad = [p for p, _, _ in specs if not p.startswith("device.")]
            if bad:
                raise ValueError(
                    f"device_fault event arms non-device points {bad}: "
                    "use wire_fault for wire-boundary points")
            if not specs:
                raise ValueError("device_fault events need a spec in 'arg'")
        if self.action == "sustained":
            if self.hold_s <= 0:
                raise ValueError("sustained events need 'hold_s' > 0")
            self._arm_action()  # eager: spec parses, namespaces uniform
        if self.action == "disk_pressure":
            # arg = target free RATIO after the fill; eager-validated so
            # a fat-fingered percentage (e.g. "15") fails at parse time.
            try:
                target = float(self.arg)
            except ValueError:
                raise ValueError(
                    "disk_pressure 'arg' must be a target free ratio, "
                    f"got {self.arg!r}") from None
            if not 0.0 < target < 1.0:
                raise ValueError(
                    "disk_pressure target free ratio must be in (0, 1), "
                    f"got {target}")

    def _arm_action(self) -> str:
        """The concrete arm verb a ``sustained`` event expands to,
        inferred from the spec's point namespaces (eager-validated:
        device and wire points cannot share one sustained window —
        their phase labels and mitigation paths differ)."""
        specs = fault.parse_faults(self.arg)
        if not specs:
            raise ValueError("sustained events need a spec in 'arg'")
        device = [p.startswith("device.") for p, _, _ in specs]
        if any(device) and not all(device):
            raise ValueError(
                "sustained event mixes device and wire points: "
                "use two events")
        return "device_fault" if all(device) else "wire_fault"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_timeline(spec: dict) -> tuple[int, List[ChaosEvent]]:
    """``{"seed": N, "events": [{"at_s": ..., "action": ..., ...}]}``
    → ``(seed, events sorted by offset)``.  Validation is eager and
    total: a typo'd action or a malformed faultpoint spec fails at
    parse time, never mid-soak."""
    unknown = set(spec) - {"seed", "events"}
    if unknown:
        raise ValueError(f"chaos timeline: unknown keys {sorted(unknown)}")
    events = []
    for i, e in enumerate(spec.get("events", ())):
        bad = set(e) - {"at_s", "action", "node", "arg", "hold_s"}
        if bad:
            raise ValueError(f"chaos event #{i}: unknown keys {sorted(bad)}")
        events.append(ChaosEvent(
            at_s=float(e["at_s"]), action=e["action"],
            node=e.get("node"), arg=e.get("arg", ""),
            hold_s=float(e.get("hold_s", 0.0))))
    return int(spec.get("seed", 0)), sorted(events, key=lambda e: e.at_s)


def expand_sustained(events: List[ChaosEvent]) -> List[ChaosEvent]:
    """Replace every ``sustained`` event with its concrete
    arm + ``clear_faults`` pair (arm verb from the spec's namespaces,
    disarm at ``at_s + hold_s``), re-sorted.  Ops adapters therefore
    never see ``sustained`` — the scheduler applies this expansion, and
    the log records the exact armed window as two entries."""
    out: List[ChaosEvent] = []
    for ev in events:
        if ev.action == "sustained":
            out.append(ChaosEvent(at_s=ev.at_s, action=ev._arm_action(),
                                  node=ev.node, arg=ev.arg))
            out.append(ChaosEvent(at_s=ev.at_s + ev.hold_s,
                                  action="clear_faults", node=ev.node))
        elif ev.action == "disk_pressure" and ev.hold_s:
            # Same windowing idiom for disk pressure: fill now, release
            # at at_s + hold_s, so relax-back is part of the timeline.
            out.append(ChaosEvent(at_s=ev.at_s, action="disk_pressure",
                                  node=ev.node, arg=ev.arg))
            out.append(ChaosEvent(at_s=ev.at_s + ev.hold_s,
                                  action="disk_release", node=ev.node))
        else:
            out.append(ev)
    return sorted(out, key=lambda e: e.at_s)


def _seeded_spec(spec: str, seed: int) -> str:
    """Append ``seed=`` to every faultpoint entry that lacks one, so a
    timeline's wire faults replay identically under the run seed."""
    out = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if not any(opt.startswith("seed=") for opt in entry.split(":")[1:]):
            entry = f"{entry}:seed={seed}"
        out.append(entry)
    return ";".join(out)


class ChaosScheduler:
    """Run a timeline against an ops adapter on a background thread.

    ``ops`` must provide ``kill(node)``, ``restart(node)``,
    ``arm_faults(node, spec)``, ``clear_faults(node)``,
    ``corrupt(node, seed)``, ``replace(node)``,
    ``disk_fill(node, target)``, ``disk_release(node)``, and
    ``phase(label)``.
    An event whose op RAISES is recorded in :attr:`log` with its error
    and the run continues — one failed injection must not silently
    cancel the rest of the chaos (the artifact shows exactly what
    fired).  ``clock``/``sleep`` are injectable so unit tests replay a
    timeline on a fake clock in microseconds.
    """

    def __init__(self, timeline: List[ChaosEvent], ops, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] | None = None):
        self.timeline = expand_sustained(
            sorted(timeline, key=lambda e: e.at_s))
        self.ops = ops
        self.seed = int(seed)
        self._clock = clock
        self._stop = threading.Event()
        # default sleep is interruptible via stop() — a soak teardown
        # must not wait out a multi-minute quiet window in the timeline
        self._sleep = sleep if sleep is not None else (
            lambda s: self._stop.wait(s))
        self._thread: threading.Thread | None = None
        self.log: List[dict] = []
        self._log_lock = threading.Lock()

    # -- execution ---------------------------------------------------------

    def run(self) -> List[dict]:
        """Execute synchronously (tests / in-thread callers)."""
        t0 = self._clock()
        for i, ev in enumerate(self.timeline):
            delay = ev.at_s - (self._clock() - t0)
            if delay > 0:
                self._sleep(delay)
            if self._stop.is_set():
                break
            self._fire(i, ev, t0)
        return self.log

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)

    def join(self, timeout_s: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout_s)

    @property
    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()

    def _fire(self, index: int, ev: ChaosEvent, t0: float) -> None:
        rec = dict(ev.to_dict(), fired_at_s=round(self._clock() - t0, 3),
                   ok=True)
        try:
            if ev.action == "phase":
                self.ops.phase(ev.arg)
            elif ev.action == "kill":
                self.ops.kill(ev.node)
            elif ev.action == "restart":
                self.ops.restart(ev.node)
            elif ev.action in ("wire_fault", "device_fault"):
                self.ops.arm_faults(
                    ev.node, _seeded_spec(ev.arg, self.seed + index))
            elif ev.action == "clear_faults":
                self.ops.clear_faults(ev.node)
            elif ev.action == "corrupt":
                self.ops.corrupt(ev.node, self.seed + index)
            elif ev.action == "replace":
                self.ops.replace(ev.node)
            elif ev.action == "disk_pressure":
                self.ops.disk_fill(ev.node, float(ev.arg))
            elif ev.action == "disk_release":
                self.ops.disk_release(ev.node)
        except Exception as e:  # noqa: BLE001 — recorded, run continues
            rec["ok"] = False
            rec["error"] = f"{type(e).__name__}: {e}"
        with self._log_lock:
            self.log.append(rec)
