"""Device-hop profiler: per-stage host↔device transfer/compile/dispatch
accounting — tracewatch's COUNTING sibling.

``x/tracewatch.py`` is the sanitizer: it forbids transfers and raises
on retraces.  This module is the accountant: while armed it counts
every host↔device transfer (count + bytes), every XLA compile, and
every jitted dispatch, attributing each to the innermost named **hop**
(``with hopwatch.hop("arena_ingest"): ...``).  ROADMAP item 1 claims
the node hot path pays five host hops — wire parse → arena ingest →
drain → encoder re-upload → fileset bytes; ``cli hops`` drives the
pinned corpus through exactly that path under this profiler and commits
the per-hop ledger (PIPELINE_r09.json), turning the claim into the
before-artifact the pipeline rebuild will be judged against.

Interception points (each a wrapper that counts and delegates — never
raises, never copies):

* **device→host** — ``jax.device_get``, the ``np.asarray``/``np.array``
  /``np.ascontiguousarray``/``np.asanyarray`` module entry points, and
  ``ArrayImpl.__array__`` (the same seams tracewatch guards, for the
  same reason: numpy's buffer-protocol fast path bypasses anything
  less).  Bytes = the source array's ``nbytes``.
* **host→device** — ``jax.device_put`` plus the ``jnp.asarray``/
  ``jnp.array`` runtime path (a numpy/scalar operand OUTSIDE a trace is
  a real upload; tracer operands are symbolic and skipped).
* **compiles** — the ``jax_log_compiles`` pxla logging record, exactly
  tracewatch's seam, counted per hop (compile-vs-steady wall time falls
  out of running a pipeline twice: pass 1 pays compiles, pass 2 is
  steady state — ``cli hops`` reports both).
* **dispatches** — the armed ``jax.jit`` factory returns a counting
  proxy whose ``__call__`` bumps the current hop before delegating
  (``__wrapped__``/``lower``/``clear_cache`` pass through).

Arming mirrors tracewatch/lockcheck: code — ``install()``/
``uninstall()``; env — ``M3_HOPWATCH=1`` arms at import (``m3_tpu.x``
imports this module, so bench children and dtest node subprocesses
inherit arming through their environment).  Totals accumulate process-
wide whether or not a hop is open (unattributed work lands on the
``"(unattributed)"`` hop); ``snapshot()``/``since()`` bracket a timed
region the way tracewatch's retrace snapshot does, which is how bench
stages record per-stage transfer deltas next to ``compile_s``/
``retraces``.

Honesty notes:

* Wrappers compose with tracewatch's (each saves whatever was current
  at install time); install order only affects which wrapper runs
  first, not the counts.
* ``nbytes`` of a sharded array counts the LOGICAL bytes, not
  per-device replicas.
* Dispatch counting only sees functions jitted while armed — arm
  before importing/jitting the code under test (the env seam does).
"""

from __future__ import annotations

import contextlib
import logging
import os
import re
import threading
from dataclasses import dataclass, field
from typing import Dict

__all__ = [
    "HopStats", "install", "uninstall", "installed", "reset", "hop",
    "stats", "totals", "snapshot", "since", "current_hop",
]

_UNATTRIBUTED = "(unattributed)"

_mu = threading.Lock()
_installed = False
_tls = threading.local()
_ORIG: dict = {}

_COMPILE_RE = re.compile(r"^Compiling ([^\s]+) with global shapes and types")
_PXLA_LOGGER = "jax._src.interpreters.pxla"
_NP_SEAMS = ("asarray", "array", "ascontiguousarray", "asanyarray")


@dataclass
class HopStats:
    """One named hop's ledger (all counters process-lifetime while
    armed; wall_s accumulates over every ``hop()`` entry)."""

    wall_s: float = 0.0
    entries: int = 0
    h2d_count: int = 0
    h2d_bytes: int = 0
    d2h_count: int = 0
    d2h_bytes: int = 0
    compiles: int = 0
    dispatches: int = 0

    def to_dict(self) -> dict:
        return {
            "wall_s": round(self.wall_s, 6), "entries": self.entries,
            "h2d_count": self.h2d_count, "h2d_bytes": self.h2d_bytes,
            "d2h_count": self.d2h_count, "d2h_bytes": self.d2h_bytes,
            "compiles": self.compiles, "dispatches": self.dispatches,
        }


_hops: Dict[str, HopStats] = {}
_totals = HopStats()


def current_hop() -> str:
    stack = getattr(_tls, "hops", None)
    return stack[-1] if stack else _UNATTRIBUTED


def _stat(name: str) -> HopStats:
    # caller holds _mu
    st = _hops.get(name)
    if st is None:
        st = _hops[name] = HopStats()
    return st


def _count(kind: str, n: int = 1, nbytes: int = 0) -> None:
    if not _installed:
        return
    name = current_hop()
    with _mu:
        for st in (_stat(name), _totals):
            if kind == "h2d":
                st.h2d_count += n
                st.h2d_bytes += nbytes
            elif kind == "d2h":
                st.d2h_count += n
                st.d2h_bytes += nbytes
            elif kind == "compile":
                st.compiles += n
            elif kind == "dispatch":
                st.dispatches += n


@contextlib.contextmanager
def hop(name: str):
    """Attribute everything in this thread to ``name`` for the scope
    (nestable: the innermost hop wins, like a span stack)."""
    import time

    stack = getattr(_tls, "hops", None)
    if stack is None:
        stack = _tls.hops = []
    stack.append(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        stack.pop()
        with _mu:
            st = _stat(name)
            st.wall_s += dt
            st.entries += 1


# -- interception seams ------------------------------------------------------


def _nbytes(x) -> int:
    try:
        return int(getattr(x, "nbytes", 0) or 0)
    except Exception:  # noqa: BLE001 — accounting must never raise
        return 0


def _tree_nbytes(x) -> int:
    try:
        import jax

        return sum(_nbytes(leaf) for leaf in jax.tree_util.tree_leaves(x))
    except Exception:  # noqa: BLE001
        return _nbytes(x)


def _is_device_array(x) -> bool:
    cls = _ORIG.get("_array_cls")
    return cls is not None and isinstance(x, cls)


def _is_host_operand(x) -> bool:
    """A real host→device upload operand: numpy array (or nested
    list/tuple of them) — NOT a tracer (symbolic, inside a trace) and
    NOT already a device array."""
    import numpy as np

    if isinstance(x, np.ndarray):
        return True
    return False


class _CompileHandler(logging.Handler):
    def emit(self, record: logging.LogRecord) -> None:
        if _COMPILE_RE.match(record.getMessage()):
            _count("compile")


_handler = _CompileHandler(level=logging.WARNING)


class _CountingJit:
    """Transparent proxy over a jitted callable: ``__call__`` counts a
    dispatch on the current hop, everything else delegates."""

    __slots__ = ("_fn",)

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, *a, **kw):
        _count("dispatch")
        return self._fn(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._fn, name)


def _patch() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    if "device_get" in _ORIG:
        return

    try:
        import jaxlib.xla_extension as xe

        _ORIG["_array_cls"] = xe.ArrayImpl
    except Exception:  # pragma: no cover - exotic jaxlib layout
        _ORIG["_array_cls"] = jax.Array

    _ORIG["device_get"] = jax.device_get

    def counting_device_get(x):
        _count("d2h", 1, _tree_nbytes(x))
        return _ORIG["device_get"](x)

    jax.device_get = counting_device_get

    _ORIG["device_put"] = jax.device_put

    def counting_device_put(x, *a, **kw):
        # skip when reached THROUGH a counted jnp.asarray/jnp.array
        # call — one upload, one count
        if not getattr(_tls, "in_jnp", False):
            _count("h2d", 1, _tree_nbytes(x))
        return _ORIG["device_put"](x, *a, **kw)

    jax.device_put = counting_device_put

    def _wrap_np(name: str):
        orig = getattr(np, name)

        def counting(a, *args, **kw):
            if _is_device_array(a):
                _count("d2h", 1, _nbytes(a))
            return orig(a, *args, **kw)

        counting.__name__ = name
        counting.__wrapped__ = orig
        return orig, counting

    for name in _NP_SEAMS:
        orig, counting = _wrap_np(name)
        _ORIG[f"np.{name}"] = orig
        setattr(np, name, counting)

    try:
        arr = _ORIG["_array_cls"]
        _ORIG["__array__"] = arr.__array__

        def counting_array(self, *a, **kw):
            _count("d2h", 1, _nbytes(self))
            return _ORIG["__array__"](self, *a, **kw)

        arr.__array__ = counting_array
    except Exception:  # pragma: no cover
        _ORIG.pop("__array__", None)

    # jnp.asarray/jnp.array: the library-internal upload path (arena
    # ingest, encoder re-upload).  Only a concrete host operand outside
    # a trace is an upload — tracers are symbolic, device arrays free.
    # Reentrancy-guarded: jnp.asarray delegates to jnp.array, and one
    # upload must count once.
    for name in ("asarray", "array"):
        orig_jnp = getattr(jnp, name)

        def _make(orig_fn):
            def counting_jnp(a, *args, **kw):
                # np.ndarray only: tracers (symbolic) and device arrays
                # (already resident) fail the check and count nothing
                if _is_host_operand(a) and not getattr(
                        _tls, "in_jnp", False):
                    _count("h2d", 1, _nbytes(a))
                _tls.in_jnp = True
                try:
                    return orig_fn(a, *args, **kw)
                finally:
                    _tls.in_jnp = False

            counting_jnp.__wrapped__ = orig_fn
            return counting_jnp

        _ORIG[f"jnp.{name}"] = orig_jnp
        setattr(jnp, name, _make(orig_jnp))

    # dispatch counting: the armed jit factory wraps its result
    _ORIG["jit"] = jax.jit

    def counting_jit(fun=None, **kw):
        if fun is None:
            def deco(f):
                return _CountingJit(_ORIG["jit"](f, **kw))
            return deco
        return _CountingJit(_ORIG["jit"](fun, **kw))

    jax.jit = counting_jit

    _ORIG["log_compiles"] = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    pxla = logging.getLogger(_PXLA_LOGGER)
    pxla.addHandler(_handler)
    # quiet the dispatch-phase timing spam jax_log_compiles flips on,
    # and keep the pxla record from reaching the root last-resort
    # printer (same hygiene as tracewatch.install) — only the counter
    # consumes it
    dispatch = logging.getLogger("jax._src.dispatch")
    _ORIG["dispatch_level"] = dispatch.level
    dispatch.setLevel(logging.ERROR)
    _ORIG["pxla_propagate"] = pxla.propagate
    pxla.propagate = False


def _unpatch() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    if "device_get" in _ORIG:
        jax.device_get = _ORIG.pop("device_get")
    if "device_put" in _ORIG:
        jax.device_put = _ORIG.pop("device_put")
    for name in _NP_SEAMS:
        orig = _ORIG.pop(f"np.{name}", None)
        if orig is not None:
            setattr(np, name, orig)
    for name in ("asarray", "array"):
        orig = _ORIG.pop(f"jnp.{name}", None)
        if orig is not None:
            setattr(jnp, name, orig)
    if "__array__" in _ORIG:
        _ORIG["_array_cls"].__array__ = _ORIG.pop("__array__")
    if "jit" in _ORIG:
        jax.jit = _ORIG.pop("jit")
    pxla = logging.getLogger(_PXLA_LOGGER)
    pxla.removeHandler(_handler)
    if "pxla_propagate" in _ORIG:
        pxla.propagate = _ORIG.pop("pxla_propagate")
    if "dispatch_level" in _ORIG:
        logging.getLogger("jax._src.dispatch").setLevel(
            _ORIG.pop("dispatch_level"))
    if "log_compiles" in _ORIG:
        jax.config.update("jax_log_compiles", _ORIG.pop("log_compiles"))
    _ORIG.pop("_array_cls", None)


# -- lifecycle ---------------------------------------------------------------


def install() -> None:
    """Arm the profiler (idempotent).  Counting starts immediately;
    open ``hop()`` scopes to attribute."""
    global _installed
    if _installed:
        return
    _patch()
    _installed = True


def uninstall() -> None:
    """Disarm and restore every seam (ledgers survive for inspection;
    ``reset()`` clears them)."""
    global _installed
    if not _installed:
        return
    _unpatch()
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    global _totals
    with _mu:
        _hops.clear()
        _totals = HopStats()


def stats() -> Dict[str, dict]:
    """Per-hop ledgers, as plain dicts (artifact-ready)."""
    with _mu:
        return {name: st.to_dict() for name, st in sorted(_hops.items())}


def totals() -> dict:
    with _mu:
        return _totals.to_dict()


def snapshot() -> dict:
    """Opaque marker for :func:`since`: bench stages bracket their
    steady-state loops with these, recording the per-stage transfer
    delta next to ``compile_s``/``retraces``."""
    return totals()


def since(snap: dict) -> dict:
    """Process-wide transfer/dispatch delta since ``snap`` (wall_s and
    entries excluded — they are hop-scoped)."""
    now = totals()
    return {k: now[k] - snap[k]
            for k in ("h2d_count", "h2d_bytes", "d2h_count", "d2h_bytes",
                      "compiles", "dispatches")}


# bench children / dtest node subprocesses inherit arming through their
# environment, exactly like M3_TRACEWATCH (m3_tpu.x imports this module).
if os.environ.get("M3_HOPWATCH"):
    install()
