"""Rule families 10-13 — compile stability & transfer hygiene for the
jit/pjit/shard_map/pallas hot path (the static twin of
``m3_tpu/x/tracewatch.py``).

PR 6 multiplied the traced surface (two-phase decode, Pallas gather,
series-sharded decode) and nothing guarded it against the silent perf
killers: a shape- or dtype-churning argument retraces per call
(100-10000x the steady-state cost), an ``np.asarray`` in a hot loop
round-trips device memory through the host, a weak-typed literal
doubles a funnel's kernel width, and a large closure-captured array is
constant-folded into the HLO of every compilation.  Each family flags
one of those classes at the AST level, scoped by the same jit
reachability propagation ``purity.py`` seeds (extended through
``functools.partial``/``vmap``/``lax.scan`` function arguments, the
idiom every scan body in ``encoding/m3tsz_jax.py`` uses):

* ``retrace-risk`` — Python control flow on non-static parameters of a
  jitted def (data-dependent ``if``/``while`` either dies in trace or
  forces a retrace-per-value pattern upstream); ``int()``/``bool()``/
  ``float()`` coercions of non-static parameters and ``.item()`` calls
  (concretization: a transfer AND a trace-time freeze); non-literal
  ``static_argnums``/``static_argnames`` specs (a spec that varies per
  call retraces per call); and ``os.environ`` reads under the tracer —
  the config seam is FROZEN into the first compile and silently stops
  responding (the M3_ENCODE_PLACE/M3_DECODE_CHAINS bug this family was
  built on: flipping the env after the first call changed NOTHING
  in-process because the jit cache keyed on the static args, not the
  env).
* ``transfer-hygiene`` — ``np.*``/``numpy.*`` calls, ``print``,
  ``jax.device_get`` and ``.tolist()`` under the tracer (host
  transfers / trace-time constants); ``jax.device_get`` in device
  modules outside the declared host boundary; and timed regions
  (functions pairing ``time.perf_counter()`` around jax work) without
  a ``block_until_ready`` — async dispatch means such a region times
  the ENQUEUE, not the work.
* ``dtype-stability`` — same-kind narrowing ``astype`` round-trips
  (``.astype(i32).astype(i64)`` destroys bits, then hides it);
  ``jnp.asarray(<literal>)`` without ``dtype=`` (a weak-typed scalar
  entering funnel arithmetic follows whatever promotion the other
  operand brings — the x64 flag decides the result width, not the
  code); float literals in bitwise/shift arithmetic (always a bug: the
  packed32/funnel paths are integer by contract).
* ``constant-bloat`` — module-level numpy arrays of >= 4096 elements
  (sized by const-folding the constructor shape, through one level of
  builder-function indirection) referenced under the tracer: the array
  is baked into the jaxpr as a literal and re-materialized in the HLO
  of EVERY compilation — per shape, per backend — instead of being
  passed once as a device argument.  ``Context.large_constants`` names
  known offenders for cross-module references.

Everything is scoped so the committed baseline stays EMPTY: the rules
encode this repo's contracts, and every real finding they surfaced was
fixed in the round that introduced them (see TESTING.md "Compile
stability & transfer hygiene").
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted
from m3_tpu.x.lint.purity import (
    _JIT_NAMES, _is_jit_expr, _last_attr, jit_reachable,
)

# -- shared helpers ----------------------------------------------------------


def _param_names(fn: ast.AST) -> set:
    args = getattr(fn, "args", None)
    if args is None:
        return set()
    out = {a.arg for a in list(args.posonlyargs) + list(args.args)
           + list(args.kwonlyargs)}
    if args.vararg:
        out.add(args.vararg.arg)
    if args.kwarg:
        out.add(args.kwarg.arg)
    return out


def _own_statements(fn: ast.AST):
    """Walk fn's body WITHOUT descending into nested function/lambda
    bodies (their parameters shadow; rules that reason about fn's own
    parameters must not misattribute)."""
    body = getattr(fn, "body", [])
    stack = list(body) if isinstance(body, list) else [body]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _dynamic_names_in(test: ast.AST, dyn: set) -> set:
    """Non-static parameter names referenced by a branch test,
    excluding structural uses: ``x is None`` comparisons and
    ``x.shape``/``x.ndim``/``x.dtype``/``x.size`` attribute reads
    (static under the tracer)."""
    skip: set = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            for side in [node.left] + list(node.comparators):
                if isinstance(side, ast.Name):
                    skip.add(id(side))
        elif (isinstance(node, ast.Attribute)
              and node.attr in ("shape", "ndim", "dtype", "size")
              and isinstance(node.value, ast.Name)):
            skip.add(id(node.value))
    hits = set()
    for node in ast.walk(test):
        if (isinstance(node, ast.Name) and node.id in dyn
                and id(node) not in skip):
            hits.add(node.id)
    return hits


# -- retrace-risk ------------------------------------------------------------

_COERCIONS = ("int", "bool", "float")
_ENV_READS = ("os.environ.get", "os.getenv")


def check_retrace(unit: FileUnit, ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    tree = unit.tree

    # Non-literal static specs at any jit decorator/callsite.
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted(node.func)
        is_jit = (fn is not None and _last_attr(fn) in _JIT_NAMES) or (
            fn is not None and _last_attr(fn) == "partial" and node.args
            and _is_jit_expr(node.args[0]))
        if not is_jit:
            continue
        for kw in node.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            if not all(isinstance(e, ast.Constant) for e in elts):
                findings.append(Finding(
                    "retrace-risk", unit.path, v.lineno,
                    f"non-literal {kw.arg} spec: a static spec computed "
                    f"per call retraces per call (and an array-valued "
                    f"static is unhashable — TypeError at best, silent "
                    f"retrace churn at worst)"))

    for fn, statics, direct in jit_reachable(tree,
                                             include_partial_args=True):
        fname = getattr(fn, "name", "<lambda>")
        params = _param_names(fn)
        dyn = params - statics if statics is not None else params

        # Data-dependent Python control flow: only where the static
        # set is KNOWN (directly decorated defs) — helpers reached
        # through partial/call-graph may receive static values.
        if direct and statics is not None:
            for node in _own_statements(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                hits = _dynamic_names_in(node.test, dyn)
                if hits:
                    findings.append(Finding(
                        "retrace-risk", unit.path, node.lineno,
                        f"{fname}() branches on traced argument(s) "
                        f"{sorted(hits)} in Python control flow — "
                        f"concretization error under jit, or a "
                        f"retrace-per-value pattern; use lax.cond/"
                        f"jnp.where or mark the argument static"))

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                findings.append(Finding(
                    "retrace-risk", unit.path, node.lineno,
                    f"{fname}() calls .item() under the tracer: "
                    f"device->host concretization per trace"))
                continue
            callee = dotted(node.func)
            if callee is None:
                continue
            if callee in _ENV_READS or callee.startswith("os.environ"):
                findings.append(Finding(
                    "retrace-risk", unit.path, node.lineno,
                    f"{fname}() reads os.environ under the tracer: the "
                    f"value is FROZEN into the first compile and the "
                    f"env seam silently stops responding — resolve the "
                    f"config in a host wrapper and pass it as a static "
                    f"argument"))
            elif (direct and statics is not None and callee in _COERCIONS
                    and node.args):
                hits = _dynamic_names_in(node.args[0], dyn)
                if hits:
                    findings.append(Finding(
                        "retrace-risk", unit.path, node.lineno,
                        f"{fname}() coerces traced argument(s) "
                        f"{sorted(hits)} with {callee}(): concretizes "
                        f"the tracer (host sync + trace-time freeze)"))
    return findings


# -- transfer-hygiene --------------------------------------------------------

_HOST_CALLS = ("jax.device_get",)
_NP_PREFIXES = ("np.", "numpy.")
# numpy namespaces that are pure metadata/static math (legal at trace
# time: they produce Python scalars/dtypes from static values, not
# array traffic)
_NP_STATIC_OK = ("np.dtype", "numpy.dtype", "np.iinfo", "numpy.iinfo",
                 "np.finfo", "numpy.finfo")


def check_transfer(unit: FileUnit, ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    tree = unit.tree
    in_device_scope = ctx.wants_jax(unit.path)
    host_boundary = ctx.is_host_boundary(unit.path)

    for fn, _statics, _direct in jit_reachable(tree,
                                               include_partial_args=True):
        fname = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted(node.func)
            if callee is None:
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "tolist"):
                    findings.append(Finding(
                        "transfer-hygiene", unit.path, node.lineno,
                        f"{fname}() calls .tolist() under the tracer: "
                        f"full device->host materialization"))
                continue
            if callee in _HOST_CALLS:
                findings.append(Finding(
                    "transfer-hygiene", unit.path, node.lineno,
                    f"{fname}() calls {callee} under the tracer: "
                    f"device->host transfer at trace time"))
            elif callee == "print":
                findings.append(Finding(
                    "transfer-hygiene", unit.path, node.lineno,
                    f"{fname}() calls print() under the tracer: runs "
                    f"once at trace time (and forces a transfer on a "
                    f"traced value) — use jax.debug.print"))
            elif (callee.startswith(_NP_PREFIXES)
                  and not callee.startswith(_NP_STATIC_OK)):
                findings.append(Finding(
                    "transfer-hygiene", unit.path, node.lineno,
                    f"{fname}() calls {callee} under the tracer: numpy "
                    f"work runs on host at trace time (a traced operand "
                    f"is a transfer/concretization; a constant belongs "
                    f"outside the jit or behind jnp)"))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "tolist":
                findings.append(Finding(
                    "transfer-hygiene", unit.path, node.lineno,
                    f"{fname}() calls .tolist() under the tracer: "
                    f"full device->host materialization"))

    # device modules must reach the host through the declared boundary
    if in_device_scope and not host_boundary:
        reachable_ids = {id(n) for fn, _s, _d in jit_reachable(
            tree, include_partial_args=True) for n in ast.walk(fn)}
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call) and id(node) not in reachable_ids
                    and dotted(node.func) in _HOST_CALLS):
                findings.append(Finding(
                    "transfer-hygiene", unit.path, node.lineno,
                    f"jax.device_get outside the declared host-boundary "
                    f"modules ({', '.join(ctx.jax_host_boundary)}): "
                    f"device modules return device arrays; the host "
                    f"boundary owns the transfer"))

    # timed regions must synchronize what they time
    if ctx.wants_timed(unit.path):
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            perf_lines = []
            has_sync = False
            has_jax = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    callee = dotted(sub.func)
                    if callee == "time.perf_counter":
                        perf_lines.append(sub.lineno)
                    elif callee is not None and (
                            callee.startswith(("jax.", "jnp."))
                            or "block_until_ready" in callee):
                        has_jax = True
                        if "block_until_ready" in callee:
                            has_sync = True
                    elif (isinstance(sub.func, ast.Attribute)
                          and sub.func.attr == "block_until_ready"):
                        has_sync = True
            if len(perf_lines) >= 2 and has_jax and not has_sync:
                findings.append(Finding(
                    "transfer-hygiene", unit.path, min(perf_lines),
                    f"{node.name}() times jax work between "
                    f"perf_counter() calls without block_until_ready: "
                    f"async dispatch means this measures the enqueue, "
                    f"not the computation"))
    return findings


# -- dtype-stability ---------------------------------------------------------

# dtype token -> (kind, bit width); covers jnp/np spellings and the
# repo's module aliases (I32/I64/U32/U64 in the codec/kernel modules).
_DTYPE_TOKENS = {}
for _k, _pfx in (("i", "int"), ("u", "uint"), ("f", "float")):
    for _w in (8, 16, 32, 64):
        _DTYPE_TOKENS[f"{_pfx}{_w}"] = (_k, _w)
for _alias, _tok in (("I32", ("i", 32)), ("I64", ("i", 64)),
                     ("U32", ("u", 32)), ("U64", ("u", 64)),
                     ("F32", ("f", 32)), ("F64", ("f", 64))):
    _DTYPE_TOKENS[_alias] = _tok


def _dtype_of(node: ast.AST):
    d = dotted(node)
    if d is None:
        return None
    return _DTYPE_TOKENS.get(_last_attr(d)) or _DTYPE_TOKENS.get(d)


def _is_literal_scalar(node: ast.AST) -> bool:
    """A bare Python number (possibly through unary minus / arithmetic
    of literals): the weak-typed scalar shape."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float))
    if isinstance(node, ast.UnaryOp):
        return _is_literal_scalar(node.operand)
    if isinstance(node, ast.BinOp):
        return (_is_literal_scalar(node.left)
                and _is_literal_scalar(node.right))
    return False


def check_dtype_stability(unit: FileUnit, ctx: Context) -> List[Finding]:
    if not ctx.wants_dtype(unit.path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(unit.tree):
        # .astype(N).astype(W): same-kind narrowing round-trip
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype" and node.args):
            outer = _dtype_of(node.args[0])
            inner_call = node.func.value
            if (outer is not None and isinstance(inner_call, ast.Call)
                    and isinstance(inner_call.func, ast.Attribute)
                    and inner_call.func.attr == "astype"
                    and inner_call.args):
                inner = _dtype_of(inner_call.args[0])
                if (inner is not None and inner[0] == outer[0]
                        and inner[1] < outer[1]):
                    findings.append(Finding(
                        "dtype-stability", unit.path, node.lineno,
                        f"astype round-trip narrows to "
                        f"{inner[0]}{inner[1]} then widens to "
                        f"{outer[0]}{outer[1]}: the high bits are "
                        f"already gone — cast once to the wide type"))
        # jnp.asarray(<literal>) without dtype: weak-typed scalar
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "asarray"):
            mod = dotted(node.func.value)
            if (mod in ("jnp", "jax.numpy") and node.args
                    and _is_literal_scalar(node.args[0])
                    and not any(k.arg == "dtype" for k in node.keywords)
                    and len(node.args) < 2):
                findings.append(Finding(
                    "dtype-stability", unit.path, node.lineno,
                    f"jnp.asarray(<literal>) without dtype= in a "
                    f"bit-exactness module: a weak-typed scalar takes "
                    f"whatever width promotion hands it (the x64 flag "
                    f"decides, not the code)"))
        # float literal in bitwise/shift arithmetic
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.LShift, ast.RShift, ast.BitAnd, ast.BitOr,
                          ast.BitXor)):
            for side in (node.left, node.right):
                if (isinstance(side, ast.Constant)
                        and isinstance(side.value, float)):
                    findings.append(Finding(
                        "dtype-stability", unit.path, node.lineno,
                        f"float literal in bitwise/shift arithmetic: "
                        f"the packed32/funnel paths are integer by "
                        f"contract"))
    return findings


# -- constant-bloat ----------------------------------------------------------

_BLOAT_ELEMENTS = 4096
_NP_CTORS = ("zeros", "ones", "empty", "full", "arange")


def _const_int(node: ast.AST):
    """Best-effort constant folding of int expressions (literals,
    +-*//, <<, **) — enough for np.arange(1 << 18) shapes."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        a, b = _const_int(node.left), _const_int(node.right)
        if a is None or b is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.FloorDiv):
                return a // b if b else None
            if isinstance(node.op, ast.LShift):
                return a << b if 0 <= b < 64 else None
            if isinstance(node.op, ast.Pow):
                return a ** b if 0 <= b < 64 else None
        except (OverflowError, ValueError):
            return None
    return None


def _ctor_elements(call: ast.Call):
    """Element-count estimate for an np.<ctor>(shape, ...) call."""
    fn = dotted(call.func)
    if fn is None or _last_attr(fn) not in _NP_CTORS:
        return None
    if not fn.startswith(("np.", "numpy.")):
        return None
    if not call.args:
        return None
    shape = call.args[0]
    dims = shape.elts if isinstance(shape, (ast.Tuple, ast.List)) else [shape]
    total = 1
    for d in dims:
        v = _const_int(d)
        if v is None:
            return None
        total *= max(v, 0)
    return total


def _large_module_arrays(tree: ast.AST) -> dict:
    """{name: estimated elements} for module-level assignments whose
    RHS is (or builds, through one local builder function) a numpy
    array of >= _BLOAT_ELEMENTS elements."""
    builders: dict = {}
    for node in tree.body if hasattr(tree, "body") else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            worst = 0
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    est = _ctor_elements(sub)
                    if est:
                        worst = max(worst, est)
            builders[node.name] = worst
    out: dict = {}
    for node in getattr(tree, "body", []):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        est = None
        if isinstance(node.value, ast.Call):
            est = _ctor_elements(node.value)
            if est is None:
                callee = dotted(node.value.func)
                if callee in builders:
                    est = builders[callee]
        if est is not None and est >= _BLOAT_ELEMENTS:
            out[tgt.id] = est
    return out


def check_constant_bloat(unit: FileUnit, ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    tree = unit.tree
    large = _large_module_arrays(tree)
    known = set(ctx.large_constants)
    if not large and not known:
        return []
    for fn, _statics, _direct in jit_reachable(tree,
                                               include_partial_args=True):
        fname = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            name = None
            if isinstance(node, ast.Name) and node.id in large:
                name, est = node.id, large[node.id]
            elif (isinstance(node, ast.Attribute) and node.attr in known
                  and not isinstance(getattr(node, "ctx", None), ast.Store)):
                name, est = node.attr, None
            elif isinstance(node, ast.Name) and node.id in known:
                name, est = node.id, None
            if name is None:
                continue
            size = f"~{est} elements" if est else "a registered large array"
            findings.append(Finding(
                "constant-bloat", unit.path, node.lineno,
                f"{fname}() captures module-level array {name} "
                f"({size}) under the tracer: constant-folded into the "
                f"HLO of EVERY compilation (re-baked per shape/backend) "
                f"— device_put once and pass it as an argument"))
    return findings


# -- rationale + examples for `cli lint --explain` ---------------------------

EXPLAIN = {
    "retrace-risk": {
        "why": (
            "A jitted function recompiles whenever a traced argument's "
            "shape/dtype changes or a static argument's VALUE changes; "
            "Python control flow on tracers either dies "
            "(ConcretizationTypeError) or forces the caller to feed "
            "concrete values — a retrace per value.  os.environ reads "
            "under the tracer are the dual failure: the config is "
            "frozen into the first compile and the seam silently stops "
            "responding (this repo shipped that bug twice: "
            "M3_ENCODE_PLACE and M3_DECODE_CHAINS were trace-frozen "
            "until round 7).  Runtime twin: M3_TRACEWATCH=1 counts "
            "compiles per function and raises past the budget."),
        "bad": ("@jax.jit\n"
                "def f(x, n):\n"
                "    if n > 4:          # traced arg in Python control flow\n"
                "        return x * 2\n"
                "    return x\n"),
        "good": ("@functools.partial(jax.jit, static_argnames=('n',))\n"
                 "def f(x, n):\n"
                 "    if n > 4:          # n is static: branch at trace time\n"
                 "        return x * 2\n"
                 "    return x\n"),
    },
    "transfer-hygiene": {
        "why": (
            "np.asarray/print/.tolist()/jax.device_get on a traced "
            "value concretizes it: a device->host transfer plus a "
            "trace-time freeze.  In timed regions the same transfers "
            "(or a missing block_until_ready) corrupt the measurement "
            "— async dispatch returns before the work runs, so the "
            "loop times the enqueue.  Runtime twin: "
            "tracewatch.no_transfers() raises on device->host copies "
            "inside guarded/timed regions."),
        "bad": ("@jax.jit\n"
                "def f(x):\n"
                "    return np.asarray(x).sum()   # transfer at trace time\n"),
        "good": ("@jax.jit\n"
                 "def f(x):\n"
                 "    return jnp.sum(x)           # stays on device\n"),
    },
    "dtype-stability": {
        "why": (
            "The M3TSZ contract is defined over exact 64-bit patterns. "
            "A weak-typed literal follows whatever promotion the other "
            "operand brings (the x64 FLAG decides the width, not the "
            "code), a narrowing astype round-trip silently destroys "
            "high bits, and a float literal in funnel arithmetic "
            "promotes an integer lane wholesale — each one doubles or "
            "corrupts kernel width without a test failing until a "
            "stream crosses 2^32."),
        "bad": ("x = jnp.asarray(5)                   # weak: i32 or i64?\n"
                "y = v.astype(jnp.int32).astype(jnp.int64)  # bits gone\n"),
        "good": ("x = jnp.asarray(5, jnp.int32)\n"
                 "y = v.astype(jnp.int64)\n"),
    },
    "constant-bloat": {
        "why": (
            "A concrete array referenced under the tracer is embedded "
            "in the jaxpr as a literal and re-materialized in the HLO "
            "of every compilation — per shape, per backend, per chains "
            "tail.  For the decode control table that was ~1MB of "
            "constants re-baked into every decode compile.  Pass large "
            "arrays as arguments (device_put once, thread through the "
            "jit signature) so XLA sees a parameter, not a literal."),
        "bad": ("TBL = np.arange(1 << 18)\n"
                "@jax.jit\n"
                "def f(i):\n"
                "    return jnp.asarray(TBL)[i]   # 1MB baked per compile\n"),
        "good": ("TBL = np.arange(1 << 18)\n"
                 "@jax.jit\n"
                 "def f(tbl, i):\n"
                 "    return tbl[i]               # parameter, not literal\n"
                 "# caller: f(jax.device_put(TBL), i)\n"),
    },
}
