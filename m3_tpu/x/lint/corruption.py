"""Rule family 7 — typed corruption errors (``corruption-typed``).

The corruption-resilience PR's invariant, made permanent: every
digest/checksum/magic verify site under ``m3_tpu/persist/`` must raise
:class:`m3_tpu.persist.corruption.CorruptionError` (or a subclass), not
a bare ``ValueError``.  The storage layer's quarantine/degrade/repair
handlers catch exactly the typed class — a bare ``ValueError`` added at
a new verify site next quarter would sail PAST them and abort a
bootstrap or fail a query, silently undoing the detect→quarantine→
repair contract.  This rule turns that regression into a gate failure.

A raise is classified as a *verify site* when either holds:

* the raised message (any string literal in the ``ValueError(...)``
  call, including f-string fragments) talks about integrity —
  corrupt/checksum/digest/magic/mismatch/torn/truncated/version;
* the enclosing ``if`` test performs an integrity comparison — calls
  ``digest``/``digest_file``/``unpack_digest``/``adler32`` or compares
  against a ``*_MAGIC`` constant (``INFO_MAGIC``, ``cls.MAGIC``...).

Ordinary argument validation (``raise ValueError("n must be >= 0")``)
matches neither and stays legal.
"""

from __future__ import annotations

import ast
import re
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

_MSG_RE = re.compile(
    r"corrupt|checksum|digest|magic|mismatch|torn|truncat|version", re.I
)
_DIGEST_FNS = {"digest", "digest_file", "unpack_digest", "adler32"}


def _string_fragments(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def _integrity_message(call: ast.Call) -> bool:
    return any(_MSG_RE.search(s) for arg in call.args
               for s in _string_fragments(arg))


def _integrity_test(test: ast.AST) -> bool:
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call):
            callee = dotted(sub.func)
            name = callee.rsplit(".", 1)[-1] if callee else None
            if name in _DIGEST_FNS:
                return True
        if isinstance(sub, ast.Name) and sub.id.endswith("_MAGIC"):
            return True
        if isinstance(sub, ast.Attribute) and sub.attr.endswith("MAGIC"):
            return True
    return False


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    if not ctx.is_persist_module(unit.path):
        return []
    findings: List[Finding] = []

    def visit(node: ast.AST, if_tests: tuple) -> None:
        if isinstance(node, ast.If):
            for child in node.body:
                visit(child, if_tests + (node.test,))
            for child in node.orelse:
                visit(child, if_tests)
            return
        if isinstance(node, ast.Raise):
            exc = node.exc
            if (isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name)
                    and exc.func.id == "ValueError"):
                verify = _integrity_message(exc) or any(
                    _integrity_test(t) for t in if_tests
                )
                if verify:
                    findings.append(Finding(
                        "corruption-typed", unit.path, node.lineno,
                        "integrity verify raises bare ValueError — raise "
                        "m3_tpu.persist.corruption.CorruptionError (a "
                        "ValueError subclass) so quarantine/repair handlers "
                        "see it"))
            return
        for child in ast.iter_child_nodes(node):
            visit(child, if_tests)

    visit(unit.tree, ())
    return findings


EXPLAIN = {
    "corruption-typed": {
        "why": (
            "Digest/checksum/magic verify sites under persist/ must "
            "raise the typed CorruptionError hierarchy: the quarantine/"
            "scrub/repair machinery dispatches on it, and a bare "
            "ValueError turns detected corruption into an undiagnosed "
            "crash instead of a quarantined volume."),
        "bad": ("if digest != expect:\n"
                "    raise ValueError('bad digest')\n"),
        "good": ("if digest != expect:\n"
                 "    raise ChecksumMismatch(path, 'digest', expect)\n"),
    },
}
