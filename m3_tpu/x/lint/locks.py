"""Rule family 1 — lock discipline (``lock-discipline``).

Static model of the race class ``tests/test_race.py`` stress-tests at
runtime: per class, which ``self._*`` attributes are accessed under
``with self.<lock>`` and which are written outside any lock.

Two triggers:

* **mixed access** — an attribute touched (read or written) under a
  lock block somewhere in the class is WRITTEN outside any lock block
  in another method.  This is exactly the ``IngestServer._closing``
  shape: the shed gate reads it under ``_q_lock`` while shutdown
  assigns it bare, so a handler can miss the closing edge.
* **unguarded read-modify-write** — ``self.x += ...`` outside any lock
  block, in a class that owns locks.  ``+=`` on an attribute is a
  load/op/store triple in CPython; two threads interleave and one
  increment vanishes (the flush-stats counter shape).

``__init__`` is exempt (single-threaded construction), as are the lock
attributes themselves.  Lock attributes are recognized both by
construction (``self.x = threading.Lock()``/``RLock()``) and by name
(``*lock``, ``*mutex``, ``_mu``/``_wmu``-style).
"""

from __future__ import annotations

import ast
import re
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

_LOCK_NAME_RE = re.compile(r"(lock|mutex)$|^_?w?mu$")
_INIT_METHODS = {"__init__", "__new__", "__post_init__"}


def _lock_attrs(cls: ast.ClassDef) -> set:
    """self attributes that hold locks: constructed as threading locks
    anywhere in the class, or named like one."""
    attrs = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = dotted(node.value.func) or ""
            if callee in ("threading.Lock", "threading.RLock", "Lock",
                          "RLock", "threading.Condition", "Condition"):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        attrs.add(t.attr)
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            if node.value.id == "self" and _LOCK_NAME_RE.search(node.attr):
                attrs.add(node.attr)
    return attrs


class _Access:
    __slots__ = ("attr", "write", "aug", "guarded", "line", "method")

    def __init__(self, attr, write, aug, guarded, line, method):
        self.attr = attr
        self.write = write
        self.aug = aug
        self.guarded = guarded
        self.line = line
        self.method = method


def _is_lock_guard(item: ast.withitem, lock_attrs: set) -> bool:
    """``with self.<lockattr>:`` (or ``cls_obj.<lockattr>``) — any
    with-statement over a lock-named attribute counts as a guard."""
    expr = item.context_expr
    if isinstance(expr, ast.Attribute):
        if expr.attr in lock_attrs or _LOCK_NAME_RE.search(expr.attr):
            return True
    if isinstance(expr, ast.Name) and _LOCK_NAME_RE.search(expr.id):
        return True
    # fault.armed(...)/lock.acquire() style guards are not lock scopes
    return False


def _collect(method: ast.FunctionDef, lock_attrs: set, out: List[_Access]):
    def visit(node: ast.AST, guarded: bool):
        if isinstance(node, ast.With):
            g = guarded or any(_is_lock_guard(i, lock_attrs)
                               for i in node.items)
            for child in node.body:
                visit(child, g)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs (callbacks, closures) run on unknown threads
            # at unknown times — analyze them as unguarded scopes
            for child in node.body:
                visit(child, False)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                _record(t, node, guarded, aug=False)
        elif isinstance(node, ast.AugAssign):
            _record(node.target, node, guarded, aug=True)
        elif isinstance(node, ast.Attribute):
            _record_load(node, guarded)
        for child in ast.iter_child_nodes(node):
            visit(child, guarded)

    def _record(target, stmt, guarded, aug):
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            out.append(_Access(target.attr, True, aug, guarded,
                               stmt.lineno, method.name))

    def _record_load(node, guarded):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and isinstance(node.ctx, ast.Load)):
            out.append(_Access(node.attr, False, False, guarded,
                               node.lineno, method.name))

    for stmt in method.body:
        visit(stmt, False)


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for cls in [n for n in ast.walk(unit.tree) if isinstance(n, ast.ClassDef)]:
        lock_attrs = _lock_attrs(cls)
        if not lock_attrs:
            continue
        accesses: List[_Access] = []
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _collect(item, lock_attrs, accesses)
        guarded_attrs = {}
        for a in accesses:
            if a.guarded and a.attr not in lock_attrs:
                guarded_attrs.setdefault(a.attr, a)
        seen = set()
        for a in accesses:
            if (not a.write or a.guarded or a.attr in lock_attrs
                    or a.method in _INIT_METHODS):
                continue
            if a.attr in guarded_attrs:
                g = guarded_attrs[a.attr]
                msg = (f"{cls.name}.{a.attr}: written without a lock in "
                       f"{a.method}() but accessed under a lock in "
                       f"{g.method}()")
            elif a.aug:
                msg = (f"{cls.name}.{a.attr}: non-atomic augmented write "
                       f"outside any lock in {a.method}() (class owns "
                       f"locks: {', '.join(sorted(lock_attrs))})")
            else:
                continue
            dedup = (msg, a.line)
            if dedup in seen:
                continue
            seen.add(dedup)
            findings.append(Finding("lock-discipline", unit.path, a.line, msg))
    return findings


EXPLAIN = {
    "lock-discipline": {
        "why": (
            "A class that guards self._* state with a lock must guard "
            "EVERY access: one unguarded write (or read-modify-write "
            "like +=) races every guarded reader, and CPython has no "
            "-race to catch it.  Runtime twin: M3_LOCKCHECK=1 "
            "(x/lockcheck.py) catches ordering inversions; this rule "
            "catches coverage holes."),
        "bad": ("class C:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self._n = 0\n"
                "    def read(self):\n"
                "        with self._lock:\n"
                "            return self._n\n"
                "    def bump(self):\n"
                "        self._n += 1      # unguarded RMW vs guarded read\n"),
        "good": ("    def bump(self):\n"
                 "        with self._lock:\n"
                 "            self._n += 1\n"),
    },
}
