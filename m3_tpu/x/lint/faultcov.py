"""Rule family 4 — fault/retry coverage (``fault-coverage``).

PR 1's invariant, made permanent: every raw socket/disk primitive in
the wire layer (``server/``, ``client/``, ``cluster/``, ``msg/``,
``persist/commitlog.py``) flows through a faultpoint so dtest can
inject drop/delay/error/corrupt at that exact boundary.  A bare
``sock.sendall`` added next quarter is a boundary the fault tier can
no longer reach — this rule makes that a gate failure, not a review
catch.

Exemptions:

* ``msg/protocol.py`` — the designated low-level framing seam
  (``send_frame``/``_recv_exact``); call sites reach it behind their
  own named faultpoints (``kv_remote.call``, ``ingest_tcp.frame``...).
* functions that call ``fault.fire``/``fault.mangle`` themselves — the
  primitive is already behind a faultpoint in that scope
  (``CommitLogWriter._flush_fsync``).
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

_RAW_METHODS = {"sendall": "socket send", "recv": "socket recv",
                "recv_into": "socket recv", "sendto": "socket send"}
_RAW_DOTTED = {"os.fsync": "fsync", "os.fdatasync": "fsync"}
_FAULT_CALLS = {"fault.fire", "fault.mangle", "fire", "mangle"}


def _fires_faultpoint(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = dotted(node.func)
            if callee in _FAULT_CALLS:
                return True
    return False


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    if not ctx.is_wire_module(unit.path):
        return []
    if unit.path in ctx.fault_helper_files:
        return []
    findings: List[Finding] = []
    funcs = [n for n in ast.walk(unit.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    covered = {id(fn) for fn in funcs if _fires_faultpoint(fn)}
    # map each call node to its innermost enclosing function (ast.walk
    # is breadth-first, so nested defs are processed after — and
    # overwrite — their enclosing def)
    enclosing: dict = {}
    for fn in funcs:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                # innermost wins: later (nested) functions overwrite
                enclosing[id(node)] = fn
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        what = None
        if isinstance(node.func, ast.Attribute):
            what = _RAW_METHODS.get(node.func.attr)
        callee = dotted(node.func)
        if what is None and callee in _RAW_DOTTED:
            what = _RAW_DOTTED[callee]
        if what is None:
            continue
        fn = enclosing.get(id(node))
        if fn is not None and id(fn) in covered:
            continue
        where = f"{fn.name}()" if fn is not None else "module level"
        findings.append(Finding(
            "fault-coverage", unit.path, node.lineno,
            f"raw {what} in {where} outside a faultpoint-wrapped helper "
            f"— wire I/O must stay reachable by m3_tpu.x.fault"))
    return findings


EXPLAIN = {
    "fault-coverage": {
        "why": (
            "Raw sendall/recv/fsync in wire modules bypasses the "
            "faultpoint seams (x/fault.py), so the fault tier cannot "
            "inject drops/delays/corruption there — the path ships "
            "untested against the failures it WILL see.  PR 1's "
            "invariant, made permanent."),
        "bad": "sock.sendall(frame)              # invisible to fault tier\n",
        "good": ("protocol.send_frame(sock, frame)  # faultpoint-wrapped "
                 "helper\n"),
    },
}
