"""m3lint core: findings, suppressions, baseline ratchet, the driver.

The analyzer is deliberately *codebase-aware*: its rules encode this
repo's concurrency/wire/bit-exactness contracts (see the rule modules),
not generic style.  Everything runs on stdlib ``ast`` — no third-party
dependency, so the gate works in every environment the tests do.

Baseline ratchet: findings are compared against a checked-in baseline
(`m3_tpu/tools/lint_baseline.json`) as a MULTISET of
``(rule, path, message)`` keys (line numbers are recorded for humans but
ignored in comparison, so unrelated edits that shift lines do not churn
the gate).  The gate fails on NEW findings *and* on stale baseline
entries — a fixed finding must shrink the baseline (``--update-baseline``),
so the debt curve only ratchets down.

Suppression: a finding on line N is suppressed by a trailing comment on
that line (or the line above):

    self.hits += 1  # m3lint: disable=lock-discipline
    # m3lint: disable=wire-exhaustive  (next line suppressed)

``# m3lint: disable-file=<rule>`` within the first ten lines suppresses
the rule for the whole file.  Suppressions are for *reviewed* false
positives; new debt belongs in the baseline where it is counted.
"""

from __future__ import annotations

import ast
import json
import re
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, List

RULES = (
    "lock-discipline",
    "jit-purity",
    "explicit-dtype",
    "wire-exhaustive",
    "fault-coverage",
    "resource-hygiene",
    "corruption-typed",
    "placement-cas",
    "deadline-aware",
    # the jax compile-stability/transfer families (jaxlint.py) — the
    # static twin of x/tracewatch.py
    "retrace-risk",
    "transfer-hygiene",
    "dtype-stability",
    "constant-bloat",
    # round 10: instrument-callsite hygiene (metrics_rule.py) —
    # per-call interning on hot paths, unbounded tag cardinality
    "metric-hygiene",
    # round 12: device-boundary guard coverage (devguard_rule.py) —
    # hot-path jit dispatches must run behind x.devguard
    "device-guard",
    # round 17: device-program registry completeness (registry_rule.py)
    # — devguard entry points × membudget components × costwatch
    # stages must describe the same program set
    "registry-complete",
    # round 18: self-healing actuator discipline (actuator_rule.py) —
    # control-plane knobs (admission capacity, membudget budget,
    # breaker thresholds/state, forced fallback) mutate only through
    # x/controller.py's typed actuator registry
    "actuator-typed",
    # round 20: typed disk-capacity errors (capacity_rule.py) —
    # durable write ops in persist/ (+ the aggregator checkpoint) run
    # inside capacity_guard so ENOSPC/EDQUOT classify into
    # DiskCapacityError with temp cleanup and counters, never escape
    # as raw OSError
    "enospc-typed",
)

_SUPPRESS_RE = re.compile(r"#\s*m3lint:\s*disable=([\w,-]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*m3lint:\s*disable-file=([\w,-]+)")


@dataclass(frozen=True, order=True)
class Finding:
    rule: str
    path: str      # posix path relative to the repo root (e.g. m3_tpu/x/fault.py)
    line: int
    message: str

    @property
    def key(self):
        """Baseline identity: line numbers drift with unrelated edits,
        (rule, path, message) survives them."""
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Context:
    """Scope knobs the rules consult.  Paths are posix, relative to the
    repo root; prefixes select rule applicability per file.  The corpus
    tests pass permissive prefixes so every rule fires on the seeded
    violations regardless of where the corpus lives."""

    # round 8: aggregator/ joined the dtype scope — the packed arena's
    # word formats (u64 lanes, orderable-f32 words, o16 minmax) are
    # bit-layout contracts exactly like the codec's
    dtype_prefixes: tuple = ("m3_tpu/encoding/", "m3_tpu/parallel/",
                             "m3_tpu/aggregator/")
    # round 12: dtest/ joined the wire scope — the soak/chaos harness
    # drives live clusters, and a raw socket op in IT would be a fault
    # injection the faultpoint registry can't see or replay (chaos must
    # stay scripted through named faultpoints, not ad-hoc socket pokes)
    wire_prefixes: tuple = ("m3_tpu/server/", "m3_tpu/client/",
                            "m3_tpu/cluster/", "m3_tpu/msg/",
                            "m3_tpu/dtest/")
    wire_files: tuple = ("m3_tpu/persist/commitlog.py",)
    # The framing module IS the designated low-level seam: raw socket
    # ops are legal only here (everything else reaches them through
    # send_frame/recv_frame behind a named faultpoint).
    fault_helper_files: tuple = ("m3_tpu/msg/protocol.py",)
    # files whose module-level small-int constants must be registered
    # in a wirecheck dispatch family (the family-table ratchet)
    constant_files: tuple = ("m3_tpu/msg/protocol.py",
                             "m3_tpu/server/rpc.py",
                             "m3_tpu/server/ingest_tcp.py",
                             "m3_tpu/cluster/kv_remote.py",
                             "m3_tpu/query/remote.py")
    # files whose digest/checksum/magic verify sites must raise the
    # typed CorruptionError hierarchy, never a bare ValueError
    persist_prefixes: tuple = ("m3_tpu/persist/",)
    # the blessed home of raw placement-key KV mutations; everywhere
    # else must go through PlacementService (placement-cas rule)
    placement_files: tuple = ("m3_tpu/cluster/placement.py",)
    # query-path modules whose blocking wire calls must flow through a
    # deadline-accepting helper (deadline-aware rule); prefixes let the
    # seeded corpus opt in wholesale
    deadline_files: tuple = ("m3_tpu/query/remote.py",
                             "m3_tpu/server/rpc.py",
                             "m3_tpu/client/session.py")
    deadline_prefixes: tuple = ()
    # the numeric/device layer the jax families police (transfer-
    # hygiene's module-scope checks); bench.py sits outside the linted
    # package and is covered by the runtime twin (tracewatch) instead
    jax_prefixes: tuple = ("m3_tpu/encoding/", "m3_tpu/parallel/",
                          "m3_tpu/aggregator/")
    # declared host boundaries: the scalar codec and the ops tools own
    # device->host transfers; everything else returns device arrays
    jax_host_boundary: tuple = ("m3_tpu/tools/", "m3_tpu/encoding/m3tsz.py")
    # modules whose perf_counter-timed regions must block_until_ready
    timed_prefixes: tuple = ("m3_tpu/tools/",)
    # request-serving trees where instrument interning must be hoisted
    # out of loops/handlers and tag values must be literals
    # (metric-hygiene rule); maintenance paths may intern lazily.
    # round 14: the self-monitoring loop joined the scope — selfmon
    # converts SCRAPED samples into storage writes every tick, and a
    # label passthrough into `.tagged({...})` there would intern one
    # registry series per scraped label value (the exact unbounded-
    # cardinality leak the rule exists to stop); coordinator/ joined
    # because the downsampler sits on the same per-batch ingest path
    metric_prefixes: tuple = ("m3_tpu/server/", "m3_tpu/query/",
                              "m3_tpu/instrument/selfmon.py",
                              "m3_tpu/coordinator/")
    # known large host arrays (constant-bloat flags references to these
    # under the tracer even across modules, where size can't be folded)
    large_constants: tuple = ("_VALUE_CTRL_TBL",)
    # round 12: serving-hot-path trees whose raw device dispatches
    # (module-jitted names, device_put, block_until_ready) must flow
    # through the x.devguard seam (device-guard rule).  parallel/ is
    # out of scope by design: its shard_map bodies compose raw() ops
    # in-trace, and its host wrappers are themselves the guarded seam.
    device_prefixes: tuple = ("m3_tpu/server/", "m3_tpu/storage/",
                              "m3_tpu/aggregator/")
    # files that ARE the guard plumbing (nothing today; the seam lives
    # in x/devguard.py, outside the scoped prefixes)
    device_helper_files: tuple = ()
    # round 17: trees whose run_guarded/membudget literals must be
    # declared in registry_rule.FAMILIES (registry-complete rule); the
    # costwatch registry file additionally cross-checks the inverse
    # direction (every family has a cost leg or a reviewed waiver)
    registry_prefixes: tuple = ("m3_tpu/storage/", "m3_tpu/aggregator/",
                                "m3_tpu/encoding/", "m3_tpu/server/")
    registry_cost_file: str = "m3_tpu/x/costwatch.py"
    # round 18: the blessed homes of control-plane mutation verbs
    # (actuator-typed rule): the controller's actuator registry itself,
    # devguard (force_fallback drives force_open — plumbing under the
    # seam), and assembly (boot-time configuration from validated
    # config is initialization, not runtime mutation)
    controller_files: tuple = ("m3_tpu/x/controller.py",
                               "m3_tpu/x/devguard.py",
                               "m3_tpu/server/assembly.py")
    # round 20: trees whose durable write ops (fsync/replace/write-mode
    # opens) must run inside capacity_guard (enospc-typed rule); the
    # guard module itself is the blessed classification seam and exempt
    capacity_prefixes: tuple = ("m3_tpu/persist/",
                                "m3_tpu/aggregator/checkpoint.py")
    capacity_helper_files: tuple = ("m3_tpu/persist/capacity.py",)

    def is_wire_module(self, path: str) -> bool:
        return (path in self.wire_files
                or any(path.startswith(p) for p in self.wire_prefixes))

    def wants_dtype(self, path: str) -> bool:
        return any(path.startswith(p) for p in self.dtype_prefixes)

    def is_persist_module(self, path: str) -> bool:
        return any(path.startswith(p) for p in self.persist_prefixes)

    def wants_jax(self, path: str) -> bool:
        return any(path.startswith(p) for p in self.jax_prefixes)

    def is_host_boundary(self, path: str) -> bool:
        return any(path.startswith(p) for p in self.jax_host_boundary)

    def wants_timed(self, path: str) -> bool:
        return any(path.startswith(p) for p in self.timed_prefixes)

    def is_capacity_module(self, path: str) -> bool:
        if path in self.capacity_helper_files:
            return False
        return any(path.startswith(p) for p in self.capacity_prefixes)


@dataclass
class FileUnit:
    """One parsed file handed to every rule."""

    path: str            # repo-relative posix
    tree: ast.AST
    source: str
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.source.splitlines()


Rule = Callable[[FileUnit, Context], List[Finding]]


def _suppressions(unit: FileUnit):
    per_line: dict[int, set] = {}
    file_wide: set = set()
    for i, text in enumerate(unit.lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            rules = set(m.group(1).split(","))
            per_line.setdefault(i, set()).update(rules)
            # a comment-only line also suppresses the line below it
            if text.lstrip().startswith("#"):
                per_line.setdefault(i + 1, set()).update(rules)
        if i <= 10:
            mf = _SUPPRESS_FILE_RE.search(text)
            if mf:
                file_wide.update(mf.group(1).split(","))
    return per_line, file_wide


def apply_suppressions(unit: FileUnit, findings: Iterable[Finding]) -> List[Finding]:
    per_line, file_wide = _suppressions(unit)
    out = []
    for f in findings:
        if f.rule in file_wide or "all" in file_wide:
            continue
        rules = per_line.get(f.line, ())
        if f.rule in rules or "all" in rules:
            continue
        out.append(f)
    return out


def default_rules() -> List[Rule]:
    from m3_tpu.x.lint import (
        actuator_rule, capacity_rule, corruption, deadline_aware,
        devguard_rule, faultcov, jaxlint, locks, metrics_rule,
        placement, purity, registry_rule, resources, wirecheck,
    )

    return [
        locks.check,
        purity.check_jit_purity,
        purity.check_explicit_dtype,
        wirecheck.check,
        faultcov.check,
        resources.check,
        corruption.check,
        placement.check,
        deadline_aware.check,
        jaxlint.check_retrace,
        jaxlint.check_transfer,
        jaxlint.check_dtype_stability,
        jaxlint.check_constant_bloat,
        metrics_rule.check,
        devguard_rule.check,
        registry_rule.check,
        actuator_rule.check,
        capacity_rule.check,
    ]


def explain(rule: str) -> dict | None:
    """{why, bad, good} for a rule name, harvested from the rule
    modules' EXPLAIN tables (``cli lint --explain`` renders it)."""
    from m3_tpu.x.lint import (
        actuator_rule, capacity_rule, corruption, deadline_aware,
        devguard_rule, faultcov, jaxlint, locks, metrics_rule,
        placement, purity, registry_rule, resources, wirecheck,
    )

    for mod in (jaxlint, locks, purity, wirecheck, faultcov, resources,
                corruption, placement, deadline_aware, metrics_rule,
                devguard_rule, registry_rule, actuator_rule,
                capacity_rule):
        entry = getattr(mod, "EXPLAIN", {}).get(rule)
        if entry is not None:
            return entry
    return None


def lint_file(path: Path, rel_root: Path, ctx: Context,
              rules: List[Rule] | None = None) -> List[Finding]:
    source = path.read_text(encoding="utf-8")
    rel = path.relative_to(rel_root).as_posix()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [Finding("parse-error", rel, e.lineno or 0, str(e.msg))]
    unit = FileUnit(rel, tree, source)
    findings: List[Finding] = []
    for rule in (rules if rules is not None else default_rules()):
        findings.extend(rule(unit, ctx))
    return apply_suppressions(unit, findings)


def lint_tree(root: Path, rel_root: Path | None = None,
              ctx: Context | None = None,
              rules: List[Rule] | None = None) -> List[Finding]:
    """Lint every ``*.py`` under ``root``; paths reported relative to
    ``rel_root`` (default: root's parent, so scanning ``<repo>/m3_tpu``
    yields ``m3_tpu/...`` paths matching the Context prefixes)."""
    root = Path(root)
    rel_root = Path(rel_root) if rel_root is not None else root.parent
    ctx = ctx or Context()
    findings: List[Finding] = []
    for path in sorted(root.rglob("*.py")):
        findings.extend(lint_file(path, rel_root, ctx, rules))
    return sorted(findings)


# -- baseline ratchet --------------------------------------------------------


def default_baseline_path() -> Path:
    import m3_tpu.tools as _tools

    return Path(_tools.__file__).resolve().parent / "lint_baseline.json"


def load_baseline(path: Path) -> List[Finding]:
    if not Path(path).exists():
        return []
    raw = json.loads(Path(path).read_text())
    return [Finding(f["rule"], f["path"], int(f.get("line", 0)), f["message"])
            for f in raw.get("findings", [])]


def save_baseline(path: Path, findings: Iterable[Finding]) -> None:
    payload = {
        "version": 1,
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "message": f.message}
            for f in sorted(findings)
        ],
    }
    Path(path).write_text(json.dumps(payload, indent=1) + "\n")


def diff_baseline(findings: Iterable[Finding], baseline: Iterable[Finding]):
    """Returns (new, fixed): findings not in the baseline, and baseline
    entries that no longer fire.  Multiset semantics — two identical
    findings in one file need two baseline entries."""
    cur = Counter(f.key for f in findings)
    base = Counter(f.key for f in baseline)
    by_key: dict = {}
    for f in findings:
        by_key.setdefault(f.key, f)
    for f in baseline:
        by_key.setdefault(f.key, f)
    new = []
    fixed = []
    for key in (cur - base):
        for _ in range((cur - base)[key]):
            new.append(by_key[key])
    for key in (base - cur):
        for _ in range((base - cur)[key]):
            fixed.append(by_key[key])
    return sorted(new), sorted(fixed)


# -- shared AST helpers ------------------------------------------------------


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def func_defs(tree: ast.AST):
    """Every FunctionDef/AsyncFunctionDef in the tree (any nesting)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
