"""Rule family 14 — instrument-callsite hygiene (``metric-hygiene``).

Round 10 moved the hot-path latency surfaces onto interned histograms;
this rule keeps the two ways instrument callsites rot from coming
back:

* **intern-in-hot-path** — creating an instrument
  (``.counter(...)``/``.gauge(...)``/``.timer(...)``/
  ``.histogram(...)``) inside a loop or a per-request handler method
  (``do_GET``/``do_POST``/``do_PUT``/``do_DELETE``/``handle``).
  Registry interning makes it *correct*, but every call pays a name
  build + registry-lock intern on the hot path — the waste
  ``ingest_tcp._IngestMetrics`` exists to avoid.  Intern once at
  construction, use the handle in the loop.
* **unbounded-tag-cardinality** — ``.tagged({...})`` (or
  ``.scope(prefix, {...})``) whose tag VALUES are f-strings, string
  concatenation/formatting, or arbitrary variables.  Every distinct
  tag value is a new interned series that lives forever in the
  registry: a peer address or user id as a tag value is an unbounded
  series leak on /metrics.  Tag values must be string literals (bounded
  by the code itself); derived values belong in log lines, not label
  sets.

Scope: ``Context.metric_prefixes`` (the request-serving trees —
``server/``, ``query/`` — plus, since round 14,
``instrument/selfmon.py`` and ``coordinator/``: the self-monitoring
loop converts SCRAPED samples every tick, where a per-sample intern or
a scraped-label tag value is exactly the leak above) — maintenance-path
modules may intern lazily.
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding

_INSTRUMENT_FACTORIES = {"counter", "gauge", "timer", "histogram"}
_HANDLER_METHODS = {"do_GET", "do_POST", "do_PUT", "do_DELETE", "handle"}
_TAGGING = {"tagged", "scope"}


def _applies(path: str, ctx: Context) -> bool:
    return any(path.startswith(p) for p in ctx.metric_prefixes)


def _is_instrument_call(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr in _INSTRUMENT_FACTORIES
            and len(node.args) >= 1
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str))


def _loops_and_handlers(tree: ast.AST):
    """Yield (container node, kind) for every loop body and per-request
    handler method."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            yield node, "loop"
        elif (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in _HANDLER_METHODS):
            yield node, f"per-request handler {node.name}()"


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    if not _applies(unit.path, ctx):
        return []
    findings: List[Finding] = []
    seen: set = set()
    # (a) instrument interning inside loops / request handlers
    for container, kind in _loops_and_handlers(unit.tree):
        for node in ast.walk(container):
            if (isinstance(node, ast.Call) and _is_instrument_call(node)
                    and id(node) not in seen):
                seen.add(id(node))
                name = node.args[0].value
                findings.append(Finding(
                    "metric-hygiene", unit.path, node.lineno,
                    f".{node.func.attr}({name!r}) interned inside a "
                    f"{kind} — per-call name build + registry-lock "
                    f"intern on a hot path; intern the instrument once "
                    f"at construction and reuse the handle"))
    # (b) unbounded tag cardinality
    for node in ast.walk(unit.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _TAGGING):
            continue
        dicts = [a for a in list(node.args) + [kw.value
                                               for kw in node.keywords]
                 if isinstance(a, ast.Dict)]
        for d in dicts:
            for v in d.values:
                if v is None:
                    continue
                if isinstance(v, ast.Constant):
                    continue  # literal: bounded by the code
                desc = ("f-string" if isinstance(v, ast.JoinedStr)
                        else type(v).__name__)
                findings.append(Finding(
                    "metric-hygiene", unit.path, v.lineno,
                    f"unbounded tag cardinality: .{node.func.attr}() "
                    f"tag value is a {desc}, not a string literal — "
                    f"every distinct value interns a new series that "
                    f"lives forever on /metrics"))
    return findings


EXPLAIN = {
    "metric-hygiene": {
        "why": (
            "Two instrument-callsite rots: (1) interning an instrument "
            "per call inside a loop/request handler pays a name build "
            "+ registry-lock intern on the hot path (interning makes "
            "it correct, not free) — hoist to construction; (2) tag "
            "values derived from variables/f-strings (peer addresses, "
            "ids) intern a new series per distinct value — an "
            "unbounded /metrics leak.  Tag values must be literals."),
        "bad": ("while frames:\n"
                "    scope.counter('frames').inc()     # intern per frame\n"
                "scope.tagged({'peer': f'{host}:{port}'})  # unbounded\n"),
        "good": ("self._frames = scope.counter('frames')  # in __init__\n"
                 "while frames:\n"
                 "    self._frames.inc()\n"
                 "scope.tagged({'path': 'ingest'})         # literal\n"),
    },
}
