"""Rule family 5 — resource hygiene (``resource-hygiene``).

Sockets and files opened and then *worked on* before anyone owns their
cleanup: if a statement between the open and the ownership transfer
raises, the handle leaks (the ``create_connection`` → ``setsockopt`` →
raise shape in the wire clients, where a failed HELLO leaks the
half-constructed socket until GC).

The model flags ``x = open(...)`` / ``x = socket.create_connection(...)``
/ ``x = socket.socket(...)`` assignments where:

* the value is not consumed by a ``with`` statement, and
* further fallible statements follow in the same block before the
  function ends (anything but a bare ``return``/``return x``/``pass``),
  and
* no ``try`` in the function closes the handle in an ``except`` or
  ``finally`` (``x.close()`` — including via the attribute the handle
  was stored to), and
* the target is not a plain ``self.<attr>`` store outside ``__init__``
  (a constructed object owns its handle via its ``close()``; in
  ``__init__`` the object may never finish existing, so the store does
  NOT transfer ownership yet).

``setattr(self, <name>, x)`` immediately after the open counts as a
self store (the fileset mmap-init idiom).
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

_OPENERS = {"open", "socket.socket", "socket.create_connection",
            "socket.socketpair",
            # the shared wire dial helper (msg/protocol.connect) hands
            # back a live socket — call sites carry the same close duty
            "connect", "wire.connect", "protocol.connect", "wire_connect"}


def _is_open_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dotted(node.func) in _OPENERS


def _target_names(target: ast.AST):
    """('local', name) / ('self', attr) / None."""
    if isinstance(target, ast.Name):
        return ("local", target.id)
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return ("self", target.attr)
    return None


def _closes(fn: ast.AST, kind: str, name: str) -> bool:
    """Does any except/finally in the function close the handle?"""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        cleanup = list(node.finalbody)
        for h in node.handlers:
            cleanup.extend(h.body)
        for stmt in cleanup:
            for sub in ast.walk(stmt):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "close"):
                    owner = _target_names(sub.func.value)
                    if owner == (kind, name):
                        return True
    return False


def _transfers(stmt: ast.AST, name: str) -> bool:
    """The handle's ownership moves somewhere with a close() duty:
    returned to the caller, assigned to ``self``/another binding, or
    ``setattr(self, ..., x)`` (the fileset mmap-init idiom)."""
    if isinstance(stmt, ast.Return):
        return (isinstance(stmt.value, ast.Name)
                and stmt.value.id == name)
    if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Name):
        return stmt.value.id == name
    if (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
            and dotted(stmt.value.func) == "setattr"):
        args = stmt.value.args
        return (len(args) == 3 and isinstance(args[0], ast.Name)
                and args[0].id == "self"
                and isinstance(args[2], ast.Name) and args[2].id == name)
    return False


def _tail_leaks(tail, name: str) -> bool:
    """Walk the statements after the open in order: the first transfer
    ends the at-risk window safely; any other fallible statement before
    a transfer is the leak window."""
    for stmt in tail:
        if _transfers(stmt, name):
            return False
        if isinstance(stmt, (ast.Pass, ast.Return)):
            continue  # bare return: refcount closes the local
        return True
    return False


def _scan_block(body, fn, in_init: bool, unit, findings):
    for i, stmt in enumerate(body):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # nested defs are scanned as their own fn
        if isinstance(stmt, ast.Assign) and _is_open_call(stmt.value):
            tgt = _target_names(stmt.targets[0]) if len(stmt.targets) == 1 else None
            if tgt is not None:
                kind, name = tgt
                if kind == "self" and not in_init:
                    pass  # long-lived member; close() owns it
                elif not _tail_leaks(body[i + 1:], name):
                    pass
                elif _closes(fn, kind, name):
                    pass
                else:
                    what = ("file" if dotted(stmt.value.func) == "open"
                            else "socket")
                    findings.append(Finding(
                        "resource-hygiene", unit.path, stmt.lineno,
                        f"{what} opened in {fn.name}() leaks if a later "
                        f"statement raises — wrap in try/finally (close "
                        f"on error) or a context manager"))
        # recurse into nested blocks (if/for/while/with/try bodies)
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if sub:
                _scan_block(sub, fn, in_init, unit, findings)
        for h in getattr(stmt, "handlers", ()):
            _scan_block(h.body, fn, in_init, unit, findings)


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for fn in [n for n in ast.walk(unit.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        in_init = fn.name == "__init__"
        _scan_block(fn.body, fn, in_init, unit, findings)
    return findings


EXPLAIN = {
    "resource-hygiene": {
        "why": (
            "A socket/file opened with no owner on the error path leaks "
            "on every exception between open and the first close — "
            "under retry storms that exhausts fds exactly when the "
            "system is least able to afford it."),
        "bad": ("s = socket.create_connection(addr)\n"
                "s.sendall(hello)                 # raises -> s leaks\n"),
        "good": ("s = socket.create_connection(addr)\n"
                 "try:\n"
                 "    s.sendall(hello)\n"
                 "except BaseException:\n"
                 "    s.close()\n"
                 "    raise\n"),
    },
}
