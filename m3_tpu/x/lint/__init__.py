"""m3lint: codebase-aware static analysis for the m3-tpu tree.

Eighteen rule families, each encoding a contract this repo already
pays for at runtime (race tier, fault tier, bit-exactness goldens,
bench steady-state) as a static gate:

* ``lock-discipline``  — mixed locked/unlocked access to ``self._*``
  state (the race class ``tests/test_race.py`` stress-tests).
* ``jit-purity``       — clocks/randomness/locks/sockets/file I/O in
  functions reached from jit/shard_map callsites.
* ``explicit-dtype``   — array constructors without ``dtype=`` in the
  bit-exactness modules (``encoding/``, ``parallel/``).
* ``wire-exhaustive``  — frame-type dispatchers missing family members
  without an explicit default branch.
* ``fault-coverage``   — raw socket/fsync primitives in wire modules
  outside a faultpoint-wrapped helper (PR 1's invariant).
* ``resource-hygiene`` — sockets/files opened with no owner on the
  error path.
* ``corruption-typed`` — digest/checksum/magic verify sites under
  ``m3_tpu/persist/`` raising bare ``ValueError`` instead of the typed
  ``CorruptionError`` hierarchy (the quarantine/repair contract).
* ``placement-cas``    — raw ``kv.set``/``check_and_set`` of the
  placement key outside ``cluster/placement.py`` (mutations must go
  through ``PlacementService`` so concurrent admin edits and node
  cutovers CAS-serialize).
* ``deadline-aware``   — blocking ``send_frame``/``recv_frame``/
  ``connect`` calls in query-path modules (``query/remote.py``, the
  ``server/rpc.py`` client classes, ``client/session.py``) outside a
  deadline-accepting helper (the read-path overload contract: wire
  hops derive their timeouts from ``x.deadline``).
* ``retrace-risk`` / ``transfer-hygiene`` / ``dtype-stability`` /
  ``constant-bloat`` — the jax compile-stability families
  (``jaxlint.py``): traced Python control flow, trace-frozen env
  reads, host transfers under the tracer, unsynchronized timed
  regions, weak/narrowing dtype seams, and large arrays
  constant-folded into jitted HLO.  Static twin of the runtime
  sanitizer ``m3_tpu/x/tracewatch.py``; see TESTING.md "Compile
  stability & transfer hygiene".
* ``device-guard``      — raw hot-path device dispatches (module-jitted
  calls, ``device_put``, ``block_until_ready``) outside the
  ``x.devguard`` seam in the serving trees (round 12's fault-tier
  reachability invariant).
* ``registry-complete`` — devguard entry points × membudget components
  × costwatch registry stages must describe the same device-program
  set (``registry_rule.FAMILIES``); a program present in one registry
  but missing from another — or a family with no cost leg and no
  reviewed waiver — is a coverage hole (round 17).
* ``actuator-typed``    — control-plane knobs (admission capacity,
  membudget budget, breaker thresholds/state, forced device fallback)
  mutated outside ``x/controller.py``'s typed actuator registry — the
  placement-cas pattern for control state: mutations must be
  bounds-clamped, rate-limited, and emitted as ``controller_action``
  samples (round 18).
* ``enospc-typed``      — durable write ops (``os.fsync``/``os.replace``/
  write-mode ``open``/``.write_bytes``) in ``persist/`` and the
  aggregator checkpoint outside a ``capacity_guard`` block, or
  capacity-shaped ``raise OSError(ENOSPC/EDQUOT...)`` instead of the
  typed ``DiskCapacityError`` — a full disk must classify, clean its
  temp files, and count, never crash the flush that hit it (round 20).
* ``metric-hygiene``    — instrument interning inside loops/per-request
  handlers in the request-serving trees (``server/``, ``query/``) —
  registry interning makes it correct but per-call lock+intern is
  hot-path waste — and unbounded tag cardinality (tag values from
  f-strings/variables: every distinct value interns a series that
  lives forever on /metrics).

Run: ``python -m m3_tpu.tools.cli lint`` (gates against
``m3_tpu/tools/lint_baseline.json``; see TESTING.md "Static analysis &
lock sanitizer" for the ratchet workflow and inline suppressions;
``lint --explain <rule>`` prints any rule's rationale + examples).
"""

from m3_tpu.x.lint.core import (
    Context, Finding, default_baseline_path, default_rules, diff_baseline,
    lint_file, lint_tree, load_baseline, save_baseline,
)

__all__ = [
    "Context", "Finding", "default_baseline_path", "default_rules",
    "diff_baseline", "lint_file", "lint_tree", "load_baseline",
    "save_baseline", "run_repo",
]


def run_repo():
    """(findings, new, fixed) for the checked-in package vs the
    committed baseline — the exact computation the CI gate runs."""
    from pathlib import Path

    import m3_tpu

    pkg = Path(m3_tpu.__file__).resolve().parent
    findings = lint_tree(pkg, pkg.parent)
    baseline = load_baseline(default_baseline_path())
    new, fixed = diff_baseline(findings, baseline)
    return findings, new, fixed
