"""Rule family 9 — query-path deadline coverage (``deadline-aware``).

The read-side twin of the ``fault-coverage`` rule: the query path's
overload contract (PR 5) says every blocking wire primitive reachable
from a query — ``send_frame``/``recv_frame``/``connect`` in
``query/remote.py``, the ``server/rpc.py`` client classes, and the
``client/session.py`` read path — must flow through a
deadline-accepting helper, so a slow peer can never hold a query past
its budget.  A bare ``recv_frame`` added next quarter would be a wire
hop the deadline cannot bound — this rule makes that a gate failure,
not a review catch.

A function is **deadline-aware** when it visibly threads the budget:

* it calls into the deadline module (any dotted callee containing
  ``deadline`` — ``xdeadline.socket_timeout``, ``deadline.current``,
  ``xdeadline.check_current``, ``xdeadline.bind`` ...), or
* it calls a deadline-budget method (``.remaining()`` /
  ``.remaining_ms()`` / ``.socket_timeout()``) on some object, or
* it takes the deadline explicitly (a parameter named ``deadline`` or
  ``dl``).

Scope: files listed in ``Context.deadline_files`` (plus
``deadline_prefixes`` so the seeded corpus can opt in wholesale).
Server-side frame loops satisfy the rule naturally — they decode and
bind the frame's deadline trailer, which is exactly the awareness the
rule wants to see.
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

# blocking wire primitives, matched by final dotted component
_BLOCKING = {"send_frame": "frame send", "recv_frame": "frame recv",
             "connect": "dial", "wire_connect": "dial"}
# budget-deriving attribute calls that mark a function deadline-aware
_BUDGET_ATTRS = {"remaining", "remaining_ms", "socket_timeout"}
_DEADLINE_PARAMS = {"deadline", "dl"}


def _applies(path: str, ctx: Context) -> bool:
    return (path in ctx.deadline_files
            or any(path.startswith(p) for p in ctx.deadline_prefixes))


def _is_aware(fn: ast.AST) -> bool:
    args = getattr(fn, "args", None)
    if args is not None:
        names = [a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)]
        if any(n in _DEADLINE_PARAMS for n in names):
            return True
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted(node.func) or ""
        if "deadline" in callee:
            return True
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _BUDGET_ATTRS):
            return True
    return False


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    if not _applies(unit.path, ctx):
        return []
    findings: List[Finding] = []
    funcs = [n for n in ast.walk(unit.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    aware = {id(fn) for fn in funcs if _is_aware(fn)}
    # innermost enclosing function per call (nested defs walk later and
    # overwrite their enclosing def — same trick as fault-coverage)
    enclosing: dict = {}
    for fn in funcs:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                enclosing[id(node)] = fn
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted(node.func) or ""
        leaf = callee.rpartition(".")[2]
        what = _BLOCKING.get(leaf)
        if what is None:
            continue
        fn = enclosing.get(id(node))
        if fn is not None and id(fn) in aware:
            continue
        where = f"{fn.name}()" if fn is not None else "module level"
        findings.append(Finding(
            "deadline-aware", unit.path, node.lineno,
            f"blocking {what} ({leaf}) in {where} without deadline "
            f"plumbing — query-path wire I/O must derive its timeout "
            f"from x.deadline"))
    return findings


EXPLAIN = {
    "deadline-aware": {
        "why": (
            "Blocking send_frame/recv_frame/connect in query-path "
            "modules must derive their socket timeouts from the "
            "riding x.deadline budget: a wire hop that blocks on its "
            "own 30s constant keeps burning a peer's time long after "
            "the caller's deadline expired (the overload contract: "
            "spent budget maps to 504, not a wedged worker)."),
        "bad": "frame = recv_frame(sock)         # blocks past the deadline\n",
        "good": ("sock.settimeout(deadline.current().socket_timeout())\n"
                 "frame = recv_frame(sock)\n"),
    },
}
