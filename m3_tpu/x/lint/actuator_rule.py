"""Rule family 17 — self-healing actuator discipline (``actuator-typed``).

Round 18's invariant, made permanent (the placement-cas pattern applied
to control state): every runtime mutation of a control-plane knob —
admission capacity (``admission.resize``), the device-memory budget
(``membudget.set_budget``), breaker thresholds/state
(``devguard.configure``, ``breaker.force_open``), forced device
evacuation (``devguard.force_fallback``) — must go through
``x/controller.py``'s typed actuator registry, where it is
bounds-clamped, rate-limited, hysteresis-bounded, and emitted as a
``controller_action`` series.  A direct ``membudget.set_budget(0)``
added next quarter would be an invisible, unbounded, un-audited
mutation racing the controller's own relax path — exactly the class of
change this gate turns into a build failure.

A call is flagged when it matches one of the mutation verbs:

* ``.resize(...)`` on an admission-named receiver (``admission.resize``,
  ``self.admission.resize`` — membudget reservations' ``_mem.resize``
  is a different, ledger-internal verb and stays clean);
* ``set_budget(...)`` bare or on a membudget-named receiver;
* ``force_fallback(...)`` / ``force_open(...)`` on any receiver;
* ``.configure(...)`` on a devguard-named receiver.

Files under ``Context.controller_files`` are exempt: the controller
itself (the blessed mutation path), ``x/devguard.py`` (whose
``force_fallback`` drives ``force_open`` — the plumbing under the
seam), and ``server/assembly.py`` (boot-time configuration from the
validated config is initialization, not runtime mutation).
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

_VIA = ("go through x/controller.py's actuator registry so the change "
        "is bounds-clamped, rate-limited, and emitted as a "
        "controller_action series")


def _match(chain: str | None, attr: str) -> str | None:
    """The violation message for one callee, or None when clean."""
    chain = chain or ""
    if attr == "resize" and "admission" in chain:
        return f"direct admission mutation {chain}(...) — {_VIA}"
    if attr == "set_budget" and ("membudget" in chain
                                 or chain == "set_budget"):
        return f"direct membudget mutation {chain or attr}(...) — {_VIA}"
    if attr == "force_fallback":
        return f"direct forced-fallback mutation {chain or attr}(...) — {_VIA}"
    if attr == "force_open":
        return f"direct breaker force-open {chain or attr}(...) — {_VIA}"
    if attr == "configure" and "devguard" in chain:
        return f"direct breaker-threshold mutation {chain}(...) — {_VIA}"
    return None


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    if unit.path in ctx.controller_files:
        return []
    findings: List[Finding] = []
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            attr = fn.attr
        elif isinstance(fn, ast.Name):
            attr = fn.id
        else:
            continue
        msg = _match(dotted(fn), attr)
        if msg is not None:
            findings.append(Finding(
                "actuator-typed", unit.path, node.lineno, msg))
    return findings


EXPLAIN = {
    "actuator-typed": {
        "why": (
            "Control-plane knobs (admission capacity, membudget budget, "
            "breaker thresholds/state, forced device fallback) mutated "
            "outside x/controller.py's actuator registry are unbounded, "
            "un-rate-limited, and invisible on the controller_action "
            "history — and they race the controller's own shed/relax "
            "steps over the same state."),
        "bad": "membudget.set_budget(0)       # unbounded, un-audited\n",
        "good": (
            "reg.register(membudget_actuator(floor, step))\n"
            "# the controller sheds/relaxes it: clamped, rate-limited,\n"
            "# every step a controller_action sample\n"),
    },
}
