"""Rule family 16 — device-program registry completeness
(``registry-complete``).

Round 17's cross-check: the repo now has THREE registries that must
describe the same set of device programs — the ``x.devguard`` entry
points (``run_guarded``/``transfer_point`` stage names: fault
classification + breakers), the ``x.membudget`` footprint components
(HBM admission), and the ``x.costwatch`` stage registry (the costs +
irlint compile gates).  A device program present in one but missing
from another is a coverage hole nothing else detects: a guarded stage
with no costwatch row is invisible to both IR gates, a budgeted
component with no guard can OOM untyped, a costwatch family with no
budget registration is unadmitted HBM.

The agreement is declared ONCE, in the :data:`FAMILIES` table below,
and this rule enforces it per file:

* a ``run_guarded("X", ...)`` / ``transfer_point("X")`` string literal
  whose stage is not declared by any family is a finding (an
  unregistered device entry point);
* a ``membudget.reserve("X", ...)`` / ``membudget.transient("X", ...)``
  literal whose component is not declared by any family is a finding;
* in the costwatch registry file, a ``Stage("p/...", ...)`` whose
  prefix no family covers is a finding — and the inverse: a family
  whose declared ``cost_prefixes`` match no Stage, or that has neither
  a cost leg nor a reviewed ``cost_waiver``, is a finding;
* in each family's declared home file, every declared guard /
  membudget component must actually appear as a literal (the table
  drifting from the code is itself the bug).

Real gap found while seeding this rule: the buffer family
(``storage.buffer_append``/``storage.buffer_drain`` +
``storage.buffer``) has NO costwatch stage.  Recorded as a reviewed
``cost_waiver`` rather than new stages: the COSTS_r13 stage set is
frozen this round (ISSUE 17 satellite: zero hot-path behavior), and
the buffer's device programs take engine-dependent shapes that pin
only when the item-1 rebuild lands its pinned-shape buffer stages.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

# The one declaration of "these three registries agree".  Each family
# names its devguard stages, membudget components, costwatch stage-name
# prefixes, and the home files where the guard/membudget literals live.
# ``cost_waiver`` documents a REVIEWED absence of a cost leg — without
# it, a family with no costwatch coverage is a finding.
FAMILIES: Dict[str, dict] = {
    "codec.decode": {
        "guards": ("decode",),
        "guard_files": ("m3_tpu/encoding/m3tsz_jax.py",),
        "membudget": ("decode.lanes", "decode.ctrl_table"),
        "membudget_files": ("m3_tpu/encoding/m3tsz_jax.py",),
        "cost_prefixes": ("decode/",),
    },
    "codec.encode": {
        "guards": ("encode",),
        "guard_files": ("m3_tpu/encoding/m3tsz_jax.py",),
        "membudget": ("encode.lanes",),
        "membudget_files": ("m3_tpu/encoding/m3tsz_jax.py",),
        "cost_prefixes": ("encode/",),
    },
    "arena": {
        "guards": ("arena.ingest", "arena.consume"),
        "guard_files": ("m3_tpu/aggregator/arena.py",),
        "membudget": ("aggregator.counter", "aggregator.gauge",
                      "aggregator.timer"),
        "membudget_files": ("m3_tpu/aggregator/arena.py",),
        "cost_prefixes": ("arena/", "timer/"),
    },
    "buffer": {
        "guards": ("storage.buffer_append", "storage.buffer_drain"),
        "guard_files": ("m3_tpu/storage/buffer.py",),
        "membudget": ("storage.buffer",),
        "membudget_files": ("m3_tpu/storage/buffer.py",),
        "cost_prefixes": (),
        "cost_waiver": (
            "COSTS_r13 stage set is frozen (round-17 zero-hot-path "
            "contract) and the buffer programs' shapes are "
            "engine-dependent; pinned-shape buffer stages land with "
            "the ROADMAP item-1 device-resident rebuild"),
    },
}

_GUARD_CALLS = {"devguard.run_guarded", "run_guarded",
                "devguard.transfer_point", "transfer_point"}
_BUDGET_CALLS = {"membudget.reserve", "membudget.transient"}


def _declared(field: str) -> set:
    out: set = set()
    for fam in FAMILIES.values():
        out.update(fam.get(field, ()))
    return out


def _str_arg0(node: ast.Call):
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    cost_file = getattr(ctx, "registry_cost_file",
                        "m3_tpu/x/costwatch.py")
    prefixes = getattr(ctx, "registry_prefixes",
                       ("m3_tpu/storage/", "m3_tpu/aggregator/",
                        "m3_tpu/encoding/", "m3_tpu/server/"))
    in_scope = any(unit.path.startswith(p) for p in prefixes)
    is_cost_file = unit.path == cost_file
    is_home = any(
        unit.path in fam.get("guard_files", ())
        or unit.path in fam.get("membudget_files", ())
        for fam in FAMILIES.values())
    if not (in_scope or is_cost_file or is_home):
        return []

    findings: List[Finding] = []
    guards = _declared("guards")
    budgets = _declared("membudget")
    cost_prefixes = _declared("cost_prefixes")
    seen_guards: set = set()
    seen_budgets: set = set()
    stage_names: List[tuple] = []

    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted(node.func)
        lit = _str_arg0(node)
        if lit is None:
            continue
        if callee in _GUARD_CALLS:
            seen_guards.add(lit)
            if in_scope and lit not in guards:
                findings.append(Finding(
                    "registry-complete", unit.path, node.lineno,
                    f"device entry point '{lit}' is not declared by any "
                    "registry family — a guarded stage outside "
                    "registry_rule.FAMILIES has no membudget/costwatch "
                    "cross-check (declare it, with its budget and cost "
                    "legs, or it is a coverage hole)"))
        elif callee in _BUDGET_CALLS:
            seen_budgets.add(lit)
            if in_scope and lit not in budgets:
                findings.append(Finding(
                    "registry-complete", unit.path, node.lineno,
                    f"membudget component '{lit}' is not declared by any "
                    "registry family — a budgeted footprint outside "
                    "registry_rule.FAMILIES has no devguard/costwatch "
                    "cross-check"))
        elif callee == "Stage" and is_cost_file and "/" in lit:
            stage_names.append((lit, node.lineno))
            prefix = lit.split("/", 1)[0] + "/"
            if prefix not in cost_prefixes:
                findings.append(Finding(
                    "registry-complete", unit.path, node.lineno,
                    f"costwatch stage '{lit}' has prefix '{prefix}' no "
                    "registry family covers — a fingerprinted program "
                    "with no devguard/membudget family is a coverage "
                    "hole"))

    # table -> code direction: every declared name must exist in its
    # declared home file (the table drifting from the code is the bug)
    for fam_name, fam in sorted(FAMILIES.items()):
        if unit.path in fam.get("guard_files", ()):
            for g in fam["guards"]:
                if g not in seen_guards:
                    findings.append(Finding(
                        "registry-complete", unit.path, 1,
                        f"family '{fam_name}' declares device entry "
                        f"point '{g}' in this file but no run_guarded/"
                        "transfer_point literal registers it"))
        if unit.path in fam.get("membudget_files", ()):
            for b in fam["membudget"]:
                if b not in seen_budgets:
                    findings.append(Finding(
                        "registry-complete", unit.path, 1,
                        f"family '{fam_name}' declares membudget "
                        f"component '{b}' in this file but no "
                        "membudget.reserve/transient literal registers "
                        "it"))
        if is_cost_file:
            covered = [s for s, _ in stage_names
                       if any(s.startswith(p)
                              for p in fam.get("cost_prefixes", ()))]
            for p in fam.get("cost_prefixes", ()):
                if not any(s.startswith(p) for s, _ in stage_names):
                    findings.append(Finding(
                        "registry-complete", unit.path, 1,
                        f"family '{fam_name}' declares costwatch prefix "
                        f"'{p}' but the registry has no such stage"))
            if not fam.get("cost_prefixes") and not covered \
                    and not fam.get("cost_waiver"):
                findings.append(Finding(
                    "registry-complete", unit.path, 1,
                    f"family '{fam_name}' has no costwatch leg and no "
                    "reviewed cost_waiver — its device programs are "
                    "invisible to the costs and irlint gates"))
    return findings


EXPLAIN = {
    "registry-complete": {
        "why": (
            "Three registries must describe the same device programs: "
            "x.devguard entry points (fault classification + "
            "breakers), x.membudget components (HBM admission), and "
            "the x.costwatch stage registry (the costs/irlint compile "
            "gates).  A program present in one but missing from "
            "another is a hole nothing else detects — a guarded stage "
            "with no costwatch row dodges both IR gates; a budgeted "
            "component with no guard OOMs untyped.  The agreement is "
            "declared once (registry_rule.FAMILIES) and cross-checked "
            "per file in both directions; a family with no cost leg "
            "must carry a reviewed cost_waiver."),
        "bad": ("devguard.run_guarded(\"rollup.flush\", device, host)  "
                "# stage not in any FAMILIES entry\n"),
        "good": ("declare the family: guards + membudget components + "
                 "costwatch prefixes (or a reviewed cost_waiver) in "
                 "registry_rule.FAMILIES, then register all three "
                 "legs\n"),
    },
}
