"""Rule family 2 — tracer/jit purity and dtype discipline.

``jit-purity``: functions reached from ``jax.jit``/``pjit``/
``shard_map`` callsites run under a tracer — once, at trace time, on an
arbitrary host thread.  A ``time.time()`` there bakes one wall-clock
into the compiled program forever; a lock or socket call runs at trace
time and never again; ``np.random`` silently freezes one draw.  The
rule seeds from jit decorators/callsites, propagates through the
module-level call graph (a helper called only from jitted code is
jitted code), and flags impure calls inside the reachable set.

``explicit-dtype``: in ``encoding/`` and ``parallel/`` every
``jnp/np.array|zeros|ones|full|empty|arange`` must pass an explicit
dtype.  The M3TSZ contract is defined over float64/int64/uint64 BIT
PATTERNS (DeXOR-style bit-exact float encoding); a constructor that
silently follows ``jax_enable_x64``'s default — or a future change to
it — is a bit-exactness bug waiting for a flag flip.  ``asarray`` and
``*_like`` preserve their input dtype and are exempt.
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

# dotted-call prefixes that must not run under a tracer, with the why
_IMPURE = {
    "time.time": "wall clock frozen at trace time",
    "time.time_ns": "wall clock frozen at trace time",
    "time.monotonic": "wall clock frozen at trace time",
    "time.perf_counter": "wall clock frozen at trace time",
    "time.sleep": "host sleep inside a traced function",
    "threading.Lock": "lock created at trace time, never at run time",
    "threading.RLock": "lock created at trace time, never at run time",
    "threading.Condition": "lock created at trace time, never at run time",
    "socket.socket": "socket I/O inside a traced function",
    "socket.create_connection": "socket I/O inside a traced function",
    "os.fsync": "file I/O inside a traced function",
    "os.urandom": "host randomness frozen at trace time",
}
_IMPURE_PREFIXES = {
    "random.": "host randomness frozen at trace time",
    "np.random.": "host randomness frozen at trace time",
    "numpy.random.": "host randomness frozen at trace time",
}
_JIT_NAMES = ("jit", "pjit")
_JIT_WRAPPERS = ("shard_map", "shard_map_compat", "pmap", "xmap")


def _last_attr(name: str) -> str:
    return name.rpartition(".")[2]


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / jit / functools.partial(jax.jit, ...) as a decorator or
    a call target."""
    d = dotted(node)
    if d is not None and _last_attr(d) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fn = dotted(node.func)
        if fn is not None and _last_attr(fn) == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        return _is_jit_expr(node.func)
    return False


def _jit_seeds(tree: ast.AST):
    """(function name or def node) seeds: decorated defs and Name args
    of jit/shard_map callsites."""
    seed_defs = []
    seed_names = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(d) for d in node.decorator_list):
                seed_defs.append(node)
        elif isinstance(node, ast.Call):
            fn = dotted(node.func)
            if fn is None:
                continue
            last = _last_attr(fn)
            if last in _JIT_NAMES or last in _JIT_WRAPPERS:
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Name):
                        seed_names.add(arg.id)
                    elif isinstance(arg, ast.Lambda):
                        seed_defs.append(arg)
    return seed_defs, seed_names


def _called_names(fn: ast.AST) -> set:
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            out.add(node.func.id)
    return out


def check_jit_purity(unit: FileUnit, ctx: Context) -> List[Finding]:
    tree = unit.tree
    module_defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            module_defs.setdefault(node.name, node)
    seed_defs, seed_names = _jit_seeds(tree)
    reachable = {id(d): d for d in seed_defs}
    frontier = list(seed_defs)
    for name in seed_names:
        d = module_defs.get(name)
        if d is not None and id(d) not in reachable:
            reachable[id(d)] = d
            frontier.append(d)
    while frontier:
        fn = frontier.pop()
        for name in _called_names(fn):
            d = module_defs.get(name)
            if d is not None and id(d) not in reachable:
                reachable[id(d)] = d
                frontier.append(d)

    findings: List[Finding] = []
    seen = set()
    for fn in reachable.values():
        fname = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted(node.func)
            if callee is None:
                continue
            why = _IMPURE.get(callee)
            if why is None:
                for prefix, pwhy in _IMPURE_PREFIXES.items():
                    if callee.startswith(prefix):
                        why = pwhy
                        break
            if why is None:
                continue
            key = (fname, callee, node.lineno)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "jit-purity", unit.path, node.lineno,
                f"{fname}() is reached from a jit/shard_map callsite but "
                f"calls {callee} ({why})"))
    return findings


# -- explicit-dtype ----------------------------------------------------------

# constructor -> index of the positional dtype slot (None: keyword-only
# in practice — arange's 4th positional is legal but unused here)
_CTORS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "array": 1,
          "arange": 3}
_ARRAY_MODULES = {"jnp", "np", "numpy", "jax.numpy"}


def check_explicit_dtype(unit: FileUnit, ctx: Context) -> List[Finding]:
    if not ctx.wants_dtype(unit.path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(unit.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        mod = dotted(node.func.value)
        if mod not in _ARRAY_MODULES:
            continue
        ctor = node.func.attr
        slot = _CTORS.get(ctor)
        if slot is None:
            continue
        if any(k.arg == "dtype" for k in node.keywords):
            continue
        if len(node.args) > slot:
            continue  # dtype passed positionally
        findings.append(Finding(
            "explicit-dtype", unit.path, node.lineno,
            f"{mod}.{ctor}(...) without an explicit dtype= in a "
            f"bit-exactness module (the x64 default is a flag, not a "
            f"contract)"))
    return findings
