"""Rule family 2 — tracer/jit purity and dtype discipline.

``jit-purity``: functions reached from ``jax.jit``/``pjit``/
``shard_map`` callsites run under a tracer — once, at trace time, on an
arbitrary host thread.  A ``time.time()`` there bakes one wall-clock
into the compiled program forever; a lock or socket call runs at trace
time and never again; ``np.random`` silently freezes one draw.  The
rule seeds from jit decorators/callsites, propagates through the
module-level call graph (a helper called only from jitted code is
jitted code), and flags impure calls inside the reachable set.

``explicit-dtype``: in ``encoding/`` and ``parallel/`` every
``jnp/np.array|zeros|ones|full|empty|arange`` must pass an explicit
dtype.  The M3TSZ contract is defined over float64/int64/uint64 BIT
PATTERNS (DeXOR-style bit-exact float encoding); a constructor that
silently follows ``jax_enable_x64``'s default — or a future change to
it — is a bit-exactness bug waiting for a flag flip.  ``asarray`` and
``*_like`` preserve their input dtype and are exempt.
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

# dotted-call prefixes that must not run under a tracer, with the why
_IMPURE = {
    "time.time": "wall clock frozen at trace time",
    "time.time_ns": "wall clock frozen at trace time",
    "time.monotonic": "wall clock frozen at trace time",
    "time.perf_counter": "wall clock frozen at trace time",
    "time.sleep": "host sleep inside a traced function",
    "threading.Lock": "lock created at trace time, never at run time",
    "threading.RLock": "lock created at trace time, never at run time",
    "threading.Condition": "lock created at trace time, never at run time",
    "socket.socket": "socket I/O inside a traced function",
    "socket.create_connection": "socket I/O inside a traced function",
    "os.fsync": "file I/O inside a traced function",
    "os.urandom": "host randomness frozen at trace time",
}
_IMPURE_PREFIXES = {
    "random.": "host randomness frozen at trace time",
    "np.random.": "host randomness frozen at trace time",
    "numpy.random.": "host randomness frozen at trace time",
}
_JIT_NAMES = ("jit", "pjit")
_JIT_WRAPPERS = ("shard_map", "shard_map_compat", "pmap", "xmap")


def _last_attr(name: str) -> str:
    return name.rpartition(".")[2]


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / jit / functools.partial(jax.jit, ...) as a decorator or
    a call target."""
    d = dotted(node)
    if d is not None and _last_attr(d) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fn = dotted(node.func)
        if fn is not None and _last_attr(fn) == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        return _is_jit_expr(node.func)
    return False


def jit_static_params(call: ast.AST, fn: ast.AST | None) -> frozenset:
    """Static parameter NAMES declared by a jit decorator/callsite
    expression (``static_argnames`` strings, plus ``static_argnums``
    indices resolved against ``fn``'s positional parameters when the
    def is at hand).  Non-literal specs yield nothing — the
    retrace-risk rule flags those separately."""
    names: set = set()
    if not isinstance(call, ast.Call):
        return frozenset()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
        elif kw.arg == "static_argnums" and fn is not None:
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            args = getattr(fn, "args", None)
            pos = (list(args.posonlyargs) + list(args.args)) if args else []
            for e in elts:
                if (isinstance(e, ast.Constant) and isinstance(e.value, int)
                        and 0 <= e.value < len(pos)):
                    names.add(pos[e.value].arg)
    return frozenset(names)


def _jit_seeds(tree: ast.AST):
    """(function name or def node) seeds: decorated defs (with their
    declared static parameter names) and Name args of jit/shard_map
    callsites."""
    seed_defs = []   # (def node, frozenset static names | None)
    seed_names = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                if _is_jit_expr(d):
                    seed_defs.append((node, jit_static_params(d, node)))
                    break
        elif isinstance(node, ast.Call):
            fn = dotted(node.func)
            if fn is None:
                continue
            last = _last_attr(fn)
            if last in _JIT_NAMES or last in _JIT_WRAPPERS:
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Name):
                        seed_names.add(arg.id)
                    elif isinstance(arg, ast.Lambda):
                        seed_defs.append((arg, frozenset()))
    return seed_defs, seed_names


def _called_names(fn: ast.AST, include_partial_args: bool = False) -> set:
    out = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name):
            out.add(node.func.id)
        if include_partial_args:
            # functools.partial(helper, ...) / jax.vmap(helper) /
            # lax.scan(step, ...): the Name args are (or wrap) functions
            # that will run under the same tracer.
            callee = dotted(node.func)
            if callee is not None and _last_attr(callee) in (
                    "partial", "vmap", "scan", "associative_scan", "cond",
                    "while_loop", "fori_loop", "checkpoint", "remat"):
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Name):
                        out.add(arg.id)
    return out


def jit_reachable(tree: ast.AST, include_partial_args: bool = False):
    """Every function that can run under a jit/shard_map tracer, by
    module-level call-graph propagation from the jit seeds.

    Returns ``[(fn_node, statics, direct)]`` where ``statics`` is the
    frozenset of the def's declared static parameter names (only
    meaningful for ``direct=True`` decorated defs — helpers reached
    through the call graph get ``None``: their parameters may be
    static values partial-bound by the caller, so rules must not
    assume they are traced).  ``include_partial_args=True`` extends
    propagation through ``functools.partial``/``vmap``/``lax.scan``
    function arguments (the jax rule families use this; the original
    jit-purity family keeps the narrower graph its corpus pins)."""
    module_defs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            module_defs.setdefault(node.name, node)
    seed_defs, seed_names = _jit_seeds(tree)
    entries: dict = {}
    frontier = []
    for d, statics in seed_defs:
        if id(d) not in entries:
            entries[id(d)] = (d, statics, True)
            frontier.append(d)
    for name in seed_names:
        d = module_defs.get(name)
        if d is not None and id(d) not in entries:
            entries[id(d)] = (d, frozenset(), True)
            frontier.append(d)
    while frontier:
        fn = frontier.pop()
        for name in _called_names(fn, include_partial_args):
            d = module_defs.get(name)
            if d is not None and id(d) not in entries:
                entries[id(d)] = (d, None, False)
                frontier.append(d)
    return list(entries.values())


def check_jit_purity(unit: FileUnit, ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()
    for fn, _statics, _direct in jit_reachable(unit.tree):
        fname = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted(node.func)
            if callee is None:
                continue
            why = _IMPURE.get(callee)
            if why is None:
                for prefix, pwhy in _IMPURE_PREFIXES.items():
                    if callee.startswith(prefix):
                        why = pwhy
                        break
            if why is None:
                continue
            key = (fname, callee, node.lineno)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "jit-purity", unit.path, node.lineno,
                f"{fname}() is reached from a jit/shard_map callsite but "
                f"calls {callee} ({why})"))
    return findings


# -- explicit-dtype ----------------------------------------------------------

# constructor -> index of the positional dtype slot (None: keyword-only
# in practice — arange's 4th positional is legal but unused here)
_CTORS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "array": 1,
          "arange": 3}
_ARRAY_MODULES = {"jnp", "np", "numpy", "jax.numpy"}


def check_explicit_dtype(unit: FileUnit, ctx: Context) -> List[Finding]:
    if not ctx.wants_dtype(unit.path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(unit.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        mod = dotted(node.func.value)
        if mod not in _ARRAY_MODULES:
            continue
        ctor = node.func.attr
        slot = _CTORS.get(ctor)
        if slot is None:
            continue
        if any(k.arg == "dtype" for k in node.keywords):
            continue
        if len(node.args) > slot:
            continue  # dtype passed positionally
        findings.append(Finding(
            "explicit-dtype", unit.path, node.lineno,
            f"{mod}.{ctor}(...) without an explicit dtype= in a "
            f"bit-exactness module (the x64 default is a flag, not a "
            f"contract)"))
    return findings


EXPLAIN = {
    "jit-purity": {
        "why": (
            "Functions reached from jit/pjit/shard_map callsites run "
            "under a tracer — once, at trace time, on an arbitrary "
            "host thread.  A time.time() there bakes one wall-clock "
            "into the compiled program forever; a lock or socket call "
            "runs at trace time and never again; np.random silently "
            "freezes one draw."),
        "bad": ("@jax.jit\n"
                "def f(x):\n"
                "    return x + time.time()   # frozen at trace time\n"),
        "good": ("@jax.jit\n"
                 "def f(x, now):              # clock passed as data\n"
                 "    return x + now\n"),
    },
    "explicit-dtype": {
        "why": (
            "The M3TSZ contract is defined over float64/int64/uint64 "
            "BIT PATTERNS.  A constructor that silently follows "
            "jax_enable_x64's default — or a future change to it — is "
            "a bit-exactness bug waiting for a flag flip.  asarray and "
            "*_like preserve their input dtype and are exempt."),
        "bad": "a = jnp.zeros(n)             # width decided by a flag\n",
        "good": "a = jnp.zeros(n, jnp.int64)  # width decided by the code\n",
    },
}
