"""Rule family 18 — typed disk-capacity errors (``enospc-typed``).

The disk-pressure round's invariant, made permanent (corruption-typed's
pattern, one seam over): a full disk must surface as
:class:`m3_tpu.persist.capacity.DiskCapacityError`, never as a raw
``OSError`` that kills the flush/tick/drain that hit it.  The
classification lives in ONE place — ``capacity_guard`` — which also
unlinks atomic-write temp files on the error path and feeds the
``disk_capacity_errors_total`` counters; a durable write op added next
quarter outside the guard would silently reopen the raw-ENOSPC hole at
exactly the site most likely to fire under pressure.

Two triggers, scoped to the capacity modules (``persist/`` plus the
aggregator checkpoint; ``persist/capacity.py`` itself is the blessed
helper and exempt):

* a *durable write op* — ``os.fsync`` / ``os.fdatasync`` /
  ``os.replace`` / ``os.fdopen``, ``.write_bytes(``/``.write_text(``,
  or ``open(...)`` in a write mode — lexically outside any ``with``
  whose items include a ``capacity_guard(...)`` call;
* a ``raise OSError(...)`` carrying ENOSPC/EDQUOT markers (the errno
  constants, or no-space/quota wording) — hand-built capacity errors
  must be the typed class so ``except OSError`` fallbacks and the
  shed/cleanup handlers agree on what they saw.

Read-mode opens and file-object ``.write()`` calls (too generic — the
guard wraps the statement, not the handle) stay legal.
"""

from __future__ import annotations

import ast
import re
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

_OS_DURABLE = {"os.fsync", "os.fdatasync", "os.replace", "os.fdopen"}
_PATH_WRITERS = {"write_bytes", "write_text"}
_ENOSPC_MSG_RE = re.compile(r"enospc|edquot|no space|quota exceed", re.I)
_ERRNO_NAMES = {"ENOSPC", "EDQUOT"}


def _open_write_mode(call: ast.Call) -> bool:
    """True for ``open(..., 'w'/'a'/'x'/'+')`` (positional or mode=)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return any(c in mode.value for c in "wax+")
    return False


def _guarded_with(node: ast.With | ast.AsyncWith) -> bool:
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            callee = dotted(expr.func)
            if callee and callee.rsplit(".", 1)[-1] == "capacity_guard":
                return True
    return False


def _capacity_markers(call: ast.Call) -> bool:
    for sub in ast.walk(call):
        if isinstance(sub, ast.Attribute) and sub.attr in _ERRNO_NAMES:
            return True
        if isinstance(sub, ast.Name) and sub.id in _ERRNO_NAMES:
            return True
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and _ENOSPC_MSG_RE.search(sub.value)):
            return True
    return False


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    if not ctx.is_capacity_module(unit.path):
        return []
    findings: List[Finding] = []

    def visit(node: ast.AST, guarded: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            guarded = guarded or _guarded_with(node)
        if isinstance(node, ast.Call) and not guarded:
            callee = dotted(node.func)
            site = None
            if callee in _OS_DURABLE:
                site = callee
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _PATH_WRITERS):
                site = f".{node.func.attr}()"
            elif (isinstance(node.func, ast.Name) and node.func.id == "open"
                    and _open_write_mode(node)):
                site = "open(.., write mode)"
            if site is not None:
                findings.append(Finding(
                    "enospc-typed", unit.path, node.lineno,
                    f"durable write op {site} outside capacity_guard — "
                    "an ENOSPC here escapes as a raw OSError (no typed "
                    "classification, no temp cleanup, no counter); wrap "
                    "the write in m3_tpu.persist.capacity.capacity_guard"))
        if isinstance(node, ast.Raise):
            exc = node.exc
            if isinstance(exc, ast.Call):
                callee = dotted(exc.func)
                name = callee.rsplit(".", 1)[-1] if callee else None
                if name == "OSError" and _capacity_markers(exc):
                    findings.append(Finding(
                        "enospc-typed", unit.path, node.lineno,
                        "capacity-shaped OSError raised untyped — raise "
                        "m3_tpu.persist.capacity.DiskCapacityError (an "
                        "OSError subclass) so shed/cleanup handlers "
                        "dispatch on it"))
        for child in ast.iter_child_nodes(node):
            visit(child, guarded)

    visit(unit.tree, False)
    return findings


EXPLAIN = {
    "enospc-typed": {
        "why": (
            "Durable write ops under persist/ (and the aggregator "
            "checkpoint) must run inside capacity_guard: it classifies "
            "ENOSPC/EDQUOT into the typed DiskCapacityError hierarchy, "
            "unlinks atomic-write temp files on the error path, and "
            "feeds the disk_capacity_errors_total counters.  A raw "
            "fsync/replace outside the guard turns a full disk into an "
            "undiagnosed crash of the flush/tick/drain that hit it."),
        "bad": ("def _write_atomic(path, data):\n"
                "    with open(tmp, 'wb') as f:\n"
                "        f.write(data)\n"
                "        os.fsync(f.fileno())\n"
                "    os.replace(tmp, path)\n"),
        "good": ("def _write_atomic(path, data):\n"
                 "    with capacity_guard(path=path, component='fileset',\n"
                 "                        op='write', cleanup=(tmp,)):\n"
                 "        with open(tmp, 'wb') as f:\n"
                 "            f.write(data)\n"
                 "            os.fsync(f.fileno())\n"
                 "        os.replace(tmp, path)\n"),
    },
}
