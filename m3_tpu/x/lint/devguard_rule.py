"""Rule family 15 — device-boundary guard coverage (``device-guard``).

Round 12's invariant, made permanent: every device dispatch on the
serving hot path — calling a module-level jitted function, a raw
``jax.device_put``, a ``.block_until_ready()`` — in ``server/``,
``storage/`` and ``aggregator/`` must flow through the ``x.devguard``
seam (``run_guarded``/``transfer_point``, or the arena wrappers'
``_guarded_ingest``/``_guarded_consume`` helpers built on it).  A bare
dispatch added next quarter is a device boundary the fault tier cannot
reach (``device.compile``/``device.dispatch``/``device.transfer``
faultpoints fire inside the seam) and a failure the per-stage breakers
cannot degrade — an XlaRuntimeError there is a node crash, exactly the
class of loss ISSUE 13 exists to remove.

Mechanics (the fault-coverage rule's shape, with ancestor coverage):

* a module's *jitted names* are defs decorated ``@jax.jit`` /
  ``@functools.partial(jax.jit, ...)`` (assignments of ``jax.jit(f)``
  count too);
* a call to a jitted name, ``jax.device_put``, or
  ``.block_until_ready`` is COVERED when any enclosing function (the
  innermost def or an ancestor — guarded primaries are closures passed
  INTO the seam) calls a seam name;
* calls *inside* a jit-decorated def are tracing, not dispatching —
  the dispatch happens at that def's callers, so they are exempt;
* ``x/`` itself (the seam's home) and ``parallel/`` (in-jit
  composition via ``raw()``) are out of scope.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

_SEAM_CALLS = {
    "devguard.run_guarded", "run_guarded",
    "devguard.transfer_point", "transfer_point",
    "_guarded_ingest", "_guarded_consume", "_guarded_state_op",
}
_RAW_DOTTED = {"jax.device_put": "device_put"}
_RAW_METHODS = {"block_until_ready": "block_until_ready"}


def _is_jit_decorator(dec: ast.AST) -> bool:
    d = dotted(dec)
    if d in ("jax.jit", "jit"):
        return True
    if isinstance(dec, ast.Call):
        callee = dotted(dec.func)
        if callee in ("jax.jit", "jit"):
            return True
        if callee in ("functools.partial", "partial") and dec.args:
            return dotted(dec.args[0]) in ("jax.jit", "jit")
    return False


def _jitted_names(tree: ast.AST) -> Set[str]:
    """Module-level names bound to jitted callables: decorated defs
    plus ``name = jax.jit(f)`` assignments."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                names.add(node.name)
        elif isinstance(node, ast.Assign):
            v = node.value
            if (isinstance(v, ast.Call)
                    and dotted(v.func) in ("jax.jit", "jit")):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _calls_seam(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and dotted(node.func) in _SEAM_CALLS:
            return True
    return False


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    if not any(unit.path.startswith(p) for p in ctx.device_prefixes):
        return []
    if unit.path in getattr(ctx, "device_helper_files", ()):
        return []
    jitted = _jitted_names(unit.tree)
    funcs = [n for n in ast.walk(unit.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    # parent chain: innermost enclosing def per node, and def -> parent
    # def, so coverage flows OUTWARD (a guarded primary is a nested
    # closure whose seam call sits in the parent)
    parent: Dict[int, ast.AST] = {}
    enclosing: Dict[int, ast.AST] = {}
    for fn in funcs:
        for node in ast.walk(fn):
            if node is fn:
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                parent[id(node)] = fn  # innermost wins on later visits
            if isinstance(node, ast.Call):
                enclosing[id(node)] = fn

    fires = {id(fn) for fn in funcs if _calls_seam(fn)}
    is_jit_def = {id(fn) for fn in funcs
                  if any(_is_jit_decorator(d) for d in fn.decorator_list)}

    def covered(fn: ast.AST | None) -> bool:
        seen = 0
        while fn is not None and seen < 64:
            if id(fn) in fires or id(fn) in is_jit_def:
                return True
            fn = parent.get(id(fn))
            seen += 1
        return False

    findings: List[Finding] = []
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        what = None
        callee = dotted(node.func)
        if callee in _RAW_DOTTED:
            what = _RAW_DOTTED[callee]
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr in _RAW_METHODS):
            what = _RAW_METHODS[node.func.attr]
        elif (isinstance(node.func, ast.Name) and node.func.id in jitted):
            what = f"jit dispatch of {node.func.id}()"
        if what is None:
            continue
        fn = enclosing.get(id(node))
        if covered(fn):
            continue
        where = f"{fn.name}()" if fn is not None else "module level"
        findings.append(Finding(
            "device-guard", unit.path, node.lineno,
            f"raw {what} in {where} outside the devguard seam — hot-path "
            "device dispatches must run behind x.devguard.run_guarded so "
            "device faults classify, degrade and stay injectable"))
    return findings


EXPLAIN = {
    "device-guard": {
        "why": (
            "A bare jit dispatch / device_put / block_until_ready on the "
            "serving hot path is a device boundary the fault tier cannot "
            "reach and the per-stage breakers cannot degrade: a real XLA "
            "OOM there is a node crash and acked-sample loss instead of "
            "a typed, counted fallback (x/devguard.py — ISSUE 13's "
            "detect -> degrade -> keep-serving -> recover contract)."),
        "bad": "self.state = buffer_append(self.state, rows, ...)\n",
        "good": ("devguard.run_guarded(\"storage.buffer_append\",\n"
                 "    lambda: buffer_append(self.state, rows, ...),\n"
                 "    self._host_stage)\n"),
    },
}
