"""Rule family 8 — placement CAS discipline (``placement-cas``).

The topology PR's invariant, made permanent: every mutation of the
placement KV key must go through ``cluster.placement.PlacementService``
(whose ``update()`` is a get→mutate→CAS loop with bounded
version-conflict retry).  A raw ``kv.set("placement", ...)`` added next
quarter would blow straight past concurrent admin mutations AND the
node-side ``mark_available`` cutover CAS — a lost placement update is a
cluster that silently believes two different topologies.  This rule
turns that regression into a gate failure.

A call is flagged when BOTH hold:

* the callee is a ``set`` / ``set_if_not_exists`` / ``check_and_set``
  attribute call (any receiver — ``kv.set``, ``self.kv.check_and_set``,
  ``store.set_if_not_exists``...);
* its first positional argument is the string literal ``"placement"``
  (or an f-string/concat containing it as a fragment — key-prefix
  schemes must not dodge the rule).

``delete`` is deliberately legal: deleting the key is the operator's
reset verb (admin DELETE /placement), not a lost-update hazard.  Files
under ``Context.placement_files`` (the PlacementService home) are
exempt — that IS the blessed mutation path.
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding

_MUTATORS = {"set", "set_if_not_exists", "check_and_set"}


def _string_fragments(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def _names_placement_key(arg: ast.AST) -> bool:
    return any(s == "placement" or s.startswith("placement/")
               for s in _string_fragments(arg))


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    if unit.path in ctx.placement_files:
        return []
    findings: List[Finding] = []
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS):
            continue
        if not node.args or not _names_placement_key(node.args[0]):
            continue
        findings.append(Finding(
            "placement-cas", unit.path, node.lineno,
            f"raw kv.{fn.attr} of the placement key — go through "
            "cluster.placement.PlacementService (update() for the "
            "CAS-retried get→mutate→set) so concurrent mutations and "
            "node cutovers serialize"))
    return findings


EXPLAIN = {
    "placement-cas": {
        "why": (
            "Raw kv.set/check_and_set of the placement key outside "
            "cluster/placement.py bypasses PlacementService's CAS "
            "retry loop — a concurrent admin edit racing a node "
            "cutover loses one of the writes and the cluster's shard "
            "map forks."),
        "bad": "kv.set(PLACEMENT_KEY, blob)      # clobbers concurrent CAS\n",
        "good": "PlacementService(kv).update(mutate_fn)  # serialized CAS\n",
    },
}
