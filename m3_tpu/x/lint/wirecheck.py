"""Rule family 3 — wire-frame dispatch exhaustiveness (``wire-exhaustive``).

PR 1 added three frame types (INGEST_HELLO/ACK/BACKOFF) and had to
touch every dispatcher by hand; the next frame type must not be
half-wired.  The rule models the protocol's frame constants as
*families* (a bus dispatcher owes nothing to ingest frames) and checks
every dispatcher — a function comparing one expression against two or
more constants of a family — for exhaustiveness:

* the function mentions EVERY constant of the family (directly or via a
  module-level tuple alias like ``_BATCH_FRAMES``), or
* it carries an explicit default: a ``not in``/``!=`` guard against the
  family, or a terminal ``else:`` on its if/elif dispatch chain.

Anything else is a dispatcher that silently ignores a frame type the
peer is allowed to send — the half-wired case.

The family table below is the analyzer's copy of ``msg/protocol.py``'s
constants.  A consistency pass over protocol.py itself flags any frame
constant that is missing from the table, so ADDING a frame type fails
the gate until the family (and therefore every dispatcher) is updated.
"""

from __future__ import annotations

import ast
from typing import List

from m3_tpu.x.lint.core import Context, FileUnit, Finding, dotted

FAMILIES = {
    "bus": frozenset({"BUS_HELLO", "BUS_PUBLISH", "BUS_DELIVER", "BUS_ACK"}),
    "ingest": frozenset({"METRIC_BATCH", "TIMED_BATCH", "PASSTHROUGH_BATCH",
                         "FORWARDED_BATCH", "INGEST_HELLO", "INGEST_ACK",
                         "INGEST_BACKOFF", "INGEST_TRACE"}),
    "reply": frozenset({"OK", "ERROR"}),
    # frame families owned by other wire modules (server/rpc.py,
    # cluster/kv_remote.py, query/remote.py) — their dispatchers get the
    # same exhaustiveness treatment as protocol.py's
    "rpc": frozenset({"RPC_REQ", "RPC_REQ_DL", "RPC_REQ_TR", "RPC_OK",
                      "RPC_ERR"}),
    "kv": frozenset({"KV_REQ", "KV_OK", "KV_ERR"}),
    "query": frozenset({"QUERY_FETCH", "QUERY_RESULT"}),
    "rpc-method": frozenset({"M_WRITE_BATCH", "M_WRITE_TAGGED", "M_READ",
                             "M_QUERY_IDS", "M_LIST_BLOCKS", "M_BLOCK_META",
                             "M_READ_BLOCK", "M_WRITE_BLOCK", "M_TICK",
                             "M_HEALTH", "M_READ_BATCH"}),
    "kv-method": frozenset({"M_GET", "M_SET", "M_SET_NX", "M_CAS",
                            "M_DELETE", "M_KEYS"}),
}
_ALL_FAMILY_CONSTANTS = frozenset().union(*FAMILIES.values())

# wire-module module-level ints that are NOT frame/method types
_NON_FRAME_CONSTANTS = frozenset({"MAX_FRAME", "HELLO_WANT_ACKS"})


# modules frame constants are legitimately referenced through; guards
# against generic names (logging.ERROR, HTTPStatus.OK) polluting the
# "reply" family
_WIRE_MODULES = ("wire", "protocol")


def _const_name(node: ast.AST) -> str | None:
    """BUS_ACK / wire.BUS_ACK / protocol.BUS_ACK -> 'BUS_ACK'; None for
    attribute chains rooted anywhere else (logging.ERROR)."""
    if isinstance(node, ast.Name):
        return node.id if node.id in _ALL_FAMILY_CONSTANTS else None
    d = dotted(node)
    if d is None:
        return None
    prefix, _, name = d.rpartition(".")
    if prefix and prefix.rpartition(".")[2] not in _WIRE_MODULES:
        return None
    return name if name in _ALL_FAMILY_CONSTANTS else None


def _family_of(name: str) -> str | None:
    for fam, members in FAMILIES.items():
        if name in members:
            return fam
    return None


def _tuple_aliases(tree: ast.AST) -> dict:
    """Module-level ``_X = (wire.A, wire.B, ...)`` -> {_X: {A, B, ...}}."""
    aliases = {}
    for node in getattr(tree, "body", []):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, (ast.Tuple, ast.List, ast.Set))
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            names = set()
            for elt in node.value.elts:
                n = _const_name(elt)
                if n and _family_of(n):
                    names.add(n)
            if names:
                aliases[node.targets[0].id] = frozenset(names)
    return aliases


def _expr_constants(node: ast.AST, aliases: dict) -> set:
    """Family constants referenced by an expression (resolving tuple
    aliases and tuple/list/set literals)."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in aliases:
            out.update(aliases[sub.id])
            continue
        n = _const_name(sub)
        if n is not None and _family_of(n):
            out.add(n)
    return out


def _analyze_function(fn: ast.AST, aliases: dict):
    """Per family: (constants mentioned, has_default)."""
    mentioned: dict = {}
    defaults: set = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if isinstance(getattr(node, "ctx", None), ast.Load):
                n = _const_name(node)
                fam = _family_of(n) if n else None
                if fam:
                    mentioned.setdefault(fam, set()).add(n)
                elif isinstance(node, ast.Name) and node.id in aliases:
                    for c in aliases[node.id]:
                        f = _family_of(c)
                        if f:
                            mentioned.setdefault(f, set()).add(c)
        if isinstance(node, ast.Compare):
            consts = _expr_constants(node, aliases)
            fams = {_family_of(c) for c in consts} - {None}
            for op in node.ops:
                if isinstance(op, (ast.NotIn, ast.NotEq)):
                    # `ftype not in _BATCH_FRAMES` / `frame[0] != BUS_X`:
                    # an explicit everything-else branch exists
                    defaults.update(fams)
        if isinstance(node, ast.If):
            # terminal `else:` on an if/elif chain that dispatches on a
            # family constant
            consts = _expr_constants(node.test, aliases)
            fams = {_family_of(c) for c in consts} - {None}
            if fams:
                tail = node
                while (len(tail.orelse) == 1
                       and isinstance(tail.orelse[0], ast.If)):
                    tail = tail.orelse[0]
                    consts = _expr_constants(tail.test, aliases)
                    fams |= {_family_of(c) for c in consts} - {None}
                if tail.orelse:  # non-empty, non-elif terminal else
                    defaults.update(fams)
    return mentioned, defaults


def check(unit: FileUnit, ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    aliases = _tuple_aliases(unit.tree)
    for fn in [n for n in ast.walk(unit.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        mentioned, defaults = _analyze_function(fn, aliases)
        for fam, consts in mentioned.items():
            if len(consts) < 2 or fam in defaults:
                continue
            missing = FAMILIES[fam] - consts
            if missing:
                findings.append(Finding(
                    "wire-exhaustive", unit.path, fn.lineno,
                    f"{fn.name}() dispatches on {fam} frames "
                    f"{sorted(consts)} without a default branch and "
                    f"without handling {sorted(missing)}"))
    if unit.path in ctx.constant_files:
        findings.extend(_check_protocol_constants(unit))
    return findings


def _check_protocol_constants(unit: FileUnit) -> List[Finding]:
    """Every small-int module constant in a wire-constant file must
    belong to a family (or the known non-frame set) — adding
    INGEST_WHATEVER = 19 (or RPC_PING = 19 in rpc.py) fails the gate
    until FAMILIES (and so every dispatcher) learns it."""
    findings = []
    for node in getattr(unit.tree, "body", []):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if not name.isupper():
            continue
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
                and 0 < node.value.value < 256):
            continue
        if name in _NON_FRAME_CONSTANTS or _family_of(name):
            continue
        findings.append(Finding(
            "wire-exhaustive", unit.path, node.lineno,
            f"frame constant {name} is not assigned to a dispatch family "
            f"in m3_tpu/x/lint/wirecheck.py — dispatchers cannot be "
            f"checked for it"))
    return findings


EXPLAIN = {
    "wire-exhaustive": {
        "why": (
            "A frame-type dispatcher missing a family member (without "
            "an explicit default branch) silently drops the frame and "
            "desyncs the connection — the half-wired-frame-type class "
            "of bug.  The constant<->family table ratchet keeps new "
            "wire constants from being declared but never dispatched."),
        "bad": ("if ftype == MSG_A:\n"
                "    ...\n"
                "elif ftype == MSG_B:\n"
                "    ...                      # MSG_C exists; no default\n"),
        "good": ("elif ftype == MSG_C:\n"
                 "    ...\n"
                 "else:\n"
                 "    conn.close()             # explicit default\n"),
    },
}
