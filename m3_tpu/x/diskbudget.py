"""Per-root disk budget ledger: membudget's twin for the other finite
resource.

The reference runs ``storage/cleanup.go`` + commitlog retention because
a dbnode that fills its disk dies mid-flush; this module gives the
node the numbers to act BEFORE that happens.  One ledger per process
(the node owns one root), refreshed on the mediator tick: a walk of the
root classifies every byte by artifact family (filesets / commitlog /
snapshots / quarantine / checkpoints), headroom comes from
``os.statvfs`` — or from a configured ``disk.capacity`` quota when the
root shares a filesystem with other tenants (every dtest node on one
disk) or an operator wants a bound tighter than the device.

Watermarks, coarse on purpose (two thresholds an operator can reason
about, not a PID controller):

* **OK** — free ratio above ``low_ratio``: nothing changes.
* **LOW** — free ratio at/below ``low_ratio``: the mediator runs the
  cleanup machinery EAGERLY (superseded volumes, stale snapshots,
  retention-aged quarantine, fully-flushed commitlog segments) instead
  of waiting for its cadence.
* **CRITICAL** — free ratio at/below ``critical_ratio`` OR absolute
  free bytes inside the ``reserve`` band: NEW ingest is shed with the
  typed :class:`~m3_tpu.persist.capacity.DiskCapacityError` (the PR-1
  backoff contract: never acked = never lost), while reads, flushes,
  WAL appends and the final-drain snapshot keep running — the reserve
  exists precisely so the writes that make data durable always have
  room to complete.

The ledger is **advisory accounting, host-side only** (the membudget
discipline): it does not intercept writes, it informs the shed/reclaim
machinery and the /metrics + /health surfaces.  Gauges:
``disk_free_ratio`` / ``disk_free_bytes`` / ``disk_total_bytes`` /
``disk_used_bytes`` / ``disk_reserve_bytes`` / ``disk_level`` (0/1/2) /
``disk_ingest_shed_total`` plus per-family ``disk_component_bytes``.
Selfmon stores them like any gauge, so ``disk_free_ratio`` history is
PromQL-queryable and the ``disk-pressure`` SLO rule closes the loop
through the controller's ``emergency_cleanup`` actuator.
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Dict, Optional

from m3_tpu.persist.capacity import DiskCapacityError
from m3_tpu.x.membudget import parse_bytes

__all__ = [
    "LEVELS", "check_ingest", "components", "configure", "counters",
    "enabled", "level", "refresh", "reset", "shedding", "snapshot",
]

# Watermark levels, exported as the ``disk_level`` gauge value.
LEVELS = ("ok", "low", "critical")

_lock = threading.Lock()
_root: Optional[Path] = None
_capacity = 0          # 0 = statvfs headroom, >0 = configured quota bytes
_reserve = 0
_low_ratio = 0.25
_critical_ratio = 0.10
_shed_total = 0
_last: Optional[dict] = None

# Top-level directory → artifact family.  Anything else under the root
# (node.json, chaos ballast, stray files) lands in "other" so the ledger
# always sums to the bytes actually present.
_FAMILIES = {
    "data": "filesets",
    "commitlogs": "commitlog",
    "snapshots": "snapshots",
    "quarantine": "quarantine",
    "checkpoint": "checkpoints",
}


def configure(root, capacity=0, reserve="64M", low_ratio: float = 0.25,
              critical_ratio: float = 0.10) -> None:
    """Arm the ledger for ``root``.  ``capacity`` (bytes or suffixed
    string) of 0 means headroom comes from ``os.statvfs``; non-zero
    treats the root as a quota of that many bytes (the dtest/multi-
    tenant mode).  ``reserve`` is the flush-headroom band: free bytes
    at/below it are CRITICAL regardless of ratio."""
    global _root, _capacity, _reserve, _low_ratio, _critical_ratio, _last
    if not (0.0 <= critical_ratio <= low_ratio <= 1.0):
        raise ValueError(
            f"want 0 <= critical_ratio <= low_ratio <= 1, got "
            f"critical={critical_ratio} low={low_ratio}")
    with _lock:
        _root = Path(root)
        _capacity = parse_bytes(capacity)
        _reserve = parse_bytes(reserve)
        _low_ratio = float(low_ratio)
        _critical_ratio = float(critical_ratio)
        _last = None


def enabled() -> bool:
    with _lock:
        return _root is not None


def _walk_components(root: Path) -> Dict[str, int]:
    by: Dict[str, int] = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        rel = os.path.relpath(dirpath, root)
        top = rel.split(os.sep, 1)[0]
        family = _FAMILIES.get(top, "other")
        total = 0
        for name in filenames:
            try:
                total += os.lstat(os.path.join(dirpath, name)).st_size
            except OSError:
                continue
        if total:
            by[family] = by.get(family, 0) + total
    return by


def refresh() -> dict:
    """Re-walk the root and recompute the watermark verdict; returns
    (and caches) the snapshot dict.  Called from the mediator tick —
    /metrics and /health read the cache, so a scrape never walks."""
    with _lock:
        root, capacity, reserve = _root, _capacity, _reserve
        low, crit = _low_ratio, _critical_ratio
    if root is None:
        return snapshot()
    by = _walk_components(root)
    used = sum(by.values())
    if capacity > 0:
        total = capacity
        free = max(0, capacity - used)
    else:
        try:
            st = os.statvfs(root)
            total = st.f_blocks * st.f_frsize
            free = st.f_bavail * st.f_frsize
        except OSError:
            total, free = 0, 0
    ratio = (free / total) if total > 0 else 1.0
    if ratio <= crit or (reserve > 0 and free <= reserve):
        lvl = 2
    elif ratio <= low:
        lvl = 1
    else:
        lvl = 0
    snap = {
        "enabled": True,
        "root": str(root),
        "capacity_bytes": capacity,
        "total_bytes": total,
        "used_bytes": used,
        "free_bytes": free,
        "free_ratio": ratio,
        "reserve_bytes": reserve,
        "low_ratio": low,
        "critical_ratio": crit,
        "level": LEVELS[lvl],
        "level_value": lvl,
        "components": by,
    }
    global _last
    with _lock:
        snap["shed_total"] = _shed_total
        _last = snap
    return snap


def snapshot() -> dict:
    """Last refreshed view (the /health ``disk`` section).  Before the
    first mediator tick — or with the ledger unconfigured — a benign
    OK stub, so surfaces never block on a walk."""
    with _lock:
        if _last is not None:
            return dict(_last, shed_total=_shed_total)
        return {
            "enabled": _root is not None,
            "root": str(_root) if _root is not None else None,
            "capacity_bytes": _capacity,
            "total_bytes": 0,
            "used_bytes": 0,
            "free_bytes": 0,
            "free_ratio": 1.0,
            "reserve_bytes": _reserve,
            "low_ratio": _low_ratio,
            "critical_ratio": _critical_ratio,
            "level": "ok",
            "level_value": 0,
            "components": {},
            "shed_total": _shed_total,
        }


def level() -> str:
    """Current watermark verdict ("ok" / "low" / "critical")."""
    return snapshot()["level"]


def shedding() -> bool:
    """True when NEW ingest should be refused (CRITICAL)."""
    return snapshot()["level_value"] >= 2


def components() -> Dict[str, int]:
    """Per-family byte accounting from the last refresh."""
    return dict(snapshot()["components"])


def check_ingest() -> None:
    """Admission gate for NEW ingest: at CRITICAL raise the typed
    capacity error (counted) so the RPC/wire layers refuse the batch
    un-acked — the replica set absorbs it, nothing acked is lost."""
    snap = snapshot()
    if snap["level_value"] < 2:
        return
    global _shed_total
    with _lock:
        _shed_total += 1
    raise DiskCapacityError(
        f"ingest shed: disk critical ({snap['free_bytes']} bytes free of "
        f"{snap['total_bytes']}, ratio {snap['free_ratio']:.3f} <= "
        f"{snap['critical_ratio']}, reserve {snap['reserve_bytes']}) — "
        "retry after cleanup reclaims space",
        path=snap["root"], component="ingest", op="admit")


def counters() -> Dict[str, int]:
    with _lock:
        return {"diskbudget.shed_total": _shed_total}


def reset() -> None:
    """Test hygiene: disarm the ledger and zero the counters."""
    global _root, _capacity, _reserve, _low_ratio, _critical_ratio
    global _shed_total, _last
    with _lock:
        _root = None
        _capacity = 0
        _reserve = 0
        _low_ratio = 0.25
        _critical_ratio = 0.10
        _shed_total = 0
        _last = None
