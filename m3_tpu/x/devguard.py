"""Device-boundary guard: typed device errors + per-stage fallback.

ROADMAP item 1 moves the whole ingest hot path into device-resident
buffers, which turns every device failure — XLA OOM, compile error,
lost device, poisoned arena state — into a process crash unless the
device boundary gets the same detect → degrade → keep-serving →
recover contract the wire (PR 1), disk (PR 3), and query (PR 5) edges
already have.  This module is that contract's seam:

* **Typed errors** — :class:`DeviceError` hierarchy mirroring
  ``persist.CorruptionError``'s role for the disk edge:
  :class:`DeviceOOM` (RESOURCE_EXHAUSTED / allocation failures),
  :class:`CompileFailure` (XLA/Mosaic compilation),
  :class:`DeviceLost` (runtime/transport to the accelerator gone),
  :class:`DeviceStateError` (resident state unusable — e.g. the packed
  arena's sticky overflow flag).  :func:`classify` maps raw jax/XLA
  exception *shapes* (class name + status substrings — jaxlib moves the
  class between releases, the grpc-style status vocabulary is stable)
  to these types; anything it cannot place is NOT a device error and
  propagates raw (a programming bug must never trip a breaker).

* **The guarded seam** — :func:`run_guarded(stage, primary, fallback)`
  wraps every hot-path device entry point (arena ingest/consume, the
  series buffer append/drain, ``encode_batch_device`` /
  ``decode_batch_device`` and their sharded variants).  A classified
  failure is counted per (stage, kind), recorded on the stage's
  circuit breaker (``x.breaker`` with ``kind="stage"``), and the SAME
  batch re-runs through ``fallback`` — the stage's host/jnp
  implementation riding the already-static seams (``M3_ENCODE_PLACE``,
  ``M3_DECODE_CHAINS``, ``M3_ARENA_INGEST`` resolve in host wrappers
  since PR 7, so the fallback choice is a static argument: zero
  retraces, bit-parity already pinned).  Once the breaker trips open
  the primary is skipped entirely; after the cool-down ONE half-open
  probe re-tries the device path and success closes the breaker.

* **Faultpoints** — ``device.compile`` (fired before a stage's first
  device call in this process), ``device.dispatch`` (before every
  device call), ``device.transfer`` (at declared device→host
  materialization boundaries, via :func:`transfer_point`).  Error-mode
  triggers raise the class a real failure at that boundary would
  classify to (compile → CompileFailure, dispatch → DeviceOOM,
  transfer → DeviceLost), so synthetic OOM/compile failures are
  injectable on LIVE nodes through ``POST /api/v1/debug/faults`` — no
  real TPU needed to exercise any of this.  Faultpoints fire ONLY on
  the primary (device) path: the fallback is by definition not the
  device boundary, which is what makes the zero-acked-loss dtest
  meaningful on a CPU-only box.

Happy-path cost is observation only: one registry dict lookup per
faultpoint (free while nothing is armed) plus counter/breaker
bookkeeping — no device work, no transfers, no retraces (``cli hops
--check`` against PIPELINE_r09.json is the enforcement hook).

Stage-breaker knobs: ``M3_DEVICE_BREAKER_FAILURES`` (consecutive
classified failures to trip, default 5) and
``M3_DEVICE_BREAKER_RESET_S`` (open → half-open cool-down, default 10)
read on the HOST at stage creation; :func:`configure` is the config
plumbing (`device:` section) and applies to stages created after it —
the same create-time semantics as ``breaker_for``.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict

from m3_tpu.x import fault
from m3_tpu.x.breaker import BreakerOpenError, breaker_for

__all__ = [
    "DeviceError", "DeviceOOM", "CompileFailure", "DeviceLost",
    "DeviceStateError", "classify", "run_guarded", "transfer_point",
    "configure", "counters", "reset_counters", "reset_stages", "status",
    "stage_breaker", "force_fallback", "fallback_forced",
]


class DeviceError(RuntimeError):
    """A classified accelerator-boundary failure.  ``RuntimeError`` (not
    OSError) so the wire retry classifier never treats a device fault
    as a transport blip to retry into."""

    kind = "device"

    def __init__(self, stage: str, message: str = "",
                 cause: BaseException | None = None):
        detail = message or (f"{type(cause).__name__}: {cause}" if cause
                             else "")
        super().__init__(
            f"device {self.kind} at stage {stage!r}"
            + (f": {detail}" if detail else ""))
        self.stage = stage
        self.cause = cause


class DeviceOOM(DeviceError):
    """Device memory exhausted (RESOURCE_EXHAUSTED / failed allocation)."""

    kind = "oom"


class CompileFailure(DeviceError):
    """XLA/Mosaic compilation failed for this program."""

    kind = "compile"


class DeviceLost(DeviceError):
    """The accelerator (or its runtime/relay) went away mid-flight."""

    kind = "lost"


class DeviceStateError(DeviceError):
    """Device-resident state is unusable (poisoned arena, failed
    restore) — the caller should restore from checkpoint or reset."""

    kind = "state"


# Classifier vocabulary: grpc-style status words + the stable message
# fragments jax/XLA emit.  Matched lowercase, FIRST family wins — OOM
# before compile (a compile-time RESOURCE_EXHAUSTED is still an OOM).
_OOM_PAT = ("resource_exhausted", "out of memory", "failed to allocate",
            "allocation failure", "oom")
_COMPILE_PAT = ("compil",  # compile / compilation / compiler
                "mosaic", "unimplemented", "unsupported hlo",
                "invalid_argument")
_LOST_PAT = ("unavailable", "device lost", "data_loss", "data loss",
             "aborted", "connection to device", "device disconnected",
             "failed_precondition")
# Host-raised device-state shapes (not XlaRuntimeError): the packed
# arena's sticky overflow raise, and jax's deleted-buffer error (a
# donated input invalidated by a failed dispatch — the state is gone).
_STATE_HOST_PAT = ("overflow-pool error", "arena state",
                   "array has been deleted")

_XLA_CLASS_NAMES = ("XlaRuntimeError", "JaxRuntimeError")


def classify(exc: BaseException) -> type | None:
    """The DeviceError subclass a raw exception maps to, or None when
    it is not a device failure (programming errors — tracing
    TypeErrors, shape ValueErrors — propagate raw and never count
    toward a stage breaker)."""
    if isinstance(exc, DeviceError):
        return type(exc)
    name = type(exc).__name__
    msg = str(exc).lower()
    if name in _XLA_CLASS_NAMES or any(
            base.__name__ in _XLA_CLASS_NAMES
            for base in type(exc).__mro__):
        if any(p in msg for p in _OOM_PAT):
            return DeviceOOM
        if any(p in msg for p in _COMPILE_PAT):
            return CompileFailure
        if any(p in msg for p in _LOST_PAT):
            return DeviceLost
        # An XlaRuntimeError we cannot place more precisely: the device
        # answered with a runtime error about ITS state, not a Python
        # bug — degrade, don't crash.
        return DeviceStateError
    if isinstance(exc, RuntimeError) and any(
            p in msg for p in _STATE_HOST_PAT):
        return DeviceStateError
    return None


# ---------------------------------------------------------------------------
# Stage registry + counters (the x/fault.py shape: thread-safe, cheap,
# counters survive everything short of reset_counters()).
# ---------------------------------------------------------------------------

_FAILURES = int(os.environ.get("M3_DEVICE_BREAKER_FAILURES", "") or 5)
_RESET_S = float(os.environ.get("M3_DEVICE_BREAKER_RESET_S", "") or 10.0)

_lock = threading.Lock()
_counters: Dict[str, int] = {}
_compiled: Dict[str, bool] = {}  # stage -> first device call done
_forced = False  # controller-imposed evacuation: all stages on fallback


def configure(failures: int | None = None,
              reset_s: float | None = None) -> None:
    """Config plumbing for the stage-breaker knobs.  Applies to stage
    breakers created AFTER the call (breaker_for create-time semantics)
    — run_node calls this before any guarded stage runs."""
    global _FAILURES, _RESET_S
    if failures is not None:
        _FAILURES = int(failures)
    if reset_s is not None:
        _RESET_S = float(reset_s)


def force_fallback(on: bool) -> None:
    """Controller-imposed device evacuation (the x/controller
    ``device_fallback`` actuator — the ONLY legal caller outside
    tests; the actuator-typed lint rule enforces that).

    Engaging sets the module flag AND force-opens every EXISTING stage
    breaker, so in-flight guard decisions and /metrics breaker state
    agree with the evacuation.  Disengaging clears only the flag: the
    breakers recover through their own half-open probes — forced
    entry, earned exit (x/breaker's half-open discipline)."""
    global _forced
    with _lock:
        _forced = bool(on)
    if on:
        from m3_tpu.x.breaker import all_breakers

        for name, br in all_breakers().items():
            if name.startswith("stage:"):
                br.force_open()


def fallback_forced() -> bool:
    with _lock:
        return _forced


def _bump(key: str, n: int = 1) -> None:
    with _lock:
        _counters[key] = _counters.get(key, 0) + n


def counters() -> Dict[str, int]:
    """Flat ``{"device.<stage>.calls": n, ".fallback_calls": n,
    ".errors.<kind>": n}`` — mirrored onto /metrics by
    ``m3_tpu.x.register_metrics``."""
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        _counters.clear()


def reset_stages() -> None:
    """Test hygiene: forget per-stage compile markers and counters.
    (Stage breakers live in the x.breaker registry — reset that too
    for full isolation.)"""
    global _forced
    with _lock:
        _counters.clear()
        _compiled.clear()
        _forced = False


def stage_breaker(stage: str):
    """The process-wide breaker for a guarded stage (shared via the
    x.breaker registry under ``stage:<name>``, kind="stage" — surfaces
    as ``breaker_state{kind="stage"}`` on /metrics)."""
    return breaker_for(f"stage:{stage}", failure_threshold=_FAILURES,
                       reset_timeout_s=_RESET_S, kind="stage")


def _fire_faultpoints(stage: str) -> None:
    """Evaluate the device faultpoints for one primary-path call,
    raising the typed class a real failure at that boundary would
    classify to."""
    if not _compiled.get(stage):
        try:
            fault.fire("device.compile")
        except fault.FaultInjected as e:
            raise CompileFailure(stage, cause=e) from e
        with _lock:
            _compiled[stage] = True
    try:
        fault.fire("device.dispatch")
    except fault.FaultInjected as e:
        raise DeviceOOM(stage, cause=e) from e


def transfer_point(stage: str) -> None:
    """The ``device.transfer`` faultpoint: call at a declared
    device→host materialization boundary INSIDE a guarded primary, so
    an injected (or classified real) transfer failure counts against
    the stage and falls back like any other device error."""
    try:
        fault.fire("device.transfer")
    except fault.FaultInjected as e:
        raise DeviceLost(stage, cause=e) from e


def run_guarded(stage: str, primary: Callable[[], object],
                fallback: Callable[[], object] | None = None):
    """``primary()`` behind the stage's device guard.

    Closed breaker (or no fallback): faultpoints fire, ``primary``
    runs; a classified failure is counted + recorded on the breaker,
    then the SAME batch re-runs through ``fallback`` (or the typed
    error raises when there is none — admission/typed-reject shape).
    Open breaker with a fallback: ``primary`` is skipped entirely
    until the half-open probe.  Unclassified exceptions propagate raw.

    ``primary``/``fallback`` are zero-arg closures so the static-seam
    choice (place/chains/impl) rides as an ordinary static argument of
    the jitted callee — nothing retraces, nothing reads env under a
    tracer."""
    br = stage_breaker(stage)
    on_device = True
    if fallback is not None:
        if fallback_forced():
            # Controller-imposed evacuation: skip the primary without
            # consuming a half-open probe slot.
            on_device = False
        else:
            try:
                br.allow()
            except BreakerOpenError:
                on_device = False
    if on_device:
        try:
            _fire_faultpoints(stage)
            result = primary()
        except BaseException as e:
            cls = classify(e)
            if cls is None:
                # Not a device failure: the device answered and OUR
                # code raised.  Record success (CircuitBreaker.call's
                # app-error rule) so a half-open probe that hit a
                # Python bug releases its probe slot instead of
                # wedging the breaker half-open forever.
                if fallback is not None:
                    br.record_success()
                raise
            err = e if isinstance(e, DeviceError) else cls(stage, cause=e)
            _bump(f"device.{stage}.errors.{err.kind}")
            br.record_failure()
            if fallback is None:
                raise err from (e if err is not e else None)
        else:
            br.record_success()
            _bump(f"device.{stage}.calls")
            return result
    _bump(f"device.{stage}.fallback_calls")
    try:
        return fallback()
    except BaseException as e:
        # A failure that persists through the fallback raises TYPED to
        # the engine (e.g. jax's deleted-buffer error when the primary
        # donated its input before dying) — but never touches the
        # breaker: it tracks the device path, and this is the host one.
        cls = classify(e)
        if cls is None:
            raise
        err = e if isinstance(e, DeviceError) else cls(stage, cause=e)
        _bump(f"device.{stage}.errors.{err.kind}")
        raise err from (e if err is not e else None)


def status() -> dict:
    """The /health ``device`` document: per-stage breaker state +
    counters (stages appear after their first guarded call)."""
    from m3_tpu.x.breaker import all_breakers

    cnt = counters()
    stages: Dict[str, dict] = {}
    for key, n in cnt.items():
        # device.<stage>.<what...> — stage names themselves contain
        # dots (arena.ingest), so split on the KNOWN suffixes
        rest = key[len("device."):]
        for suffix in ("calls", "fallback_calls"):
            if rest.endswith("." + suffix):
                st = rest[: -len(suffix) - 1]
                stages.setdefault(st, {})[suffix] = n
                break
        else:
            st, _, kind = rest.rpartition(".errors.")
            if st:
                stages.setdefault(st, {}).setdefault(
                    "errors", {})[kind] = n
    for name, br in all_breakers().items():
        if name.startswith("stage:"):
            stages.setdefault(name[len("stage:"):], {})["breaker"] = br.state
    out = {"stages": stages}
    if fallback_forced():
        out["forced_fallback"] = True
    return out
