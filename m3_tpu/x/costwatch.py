"""Machine-independent perf fingerprints from XLA cost/memory analysis.

Every wall-clock number this repo has ever committed came from a
1-physical-core box, and the TPU relay was down for four straight
rounds — the formulation work those rounds shipped (decode 1972→670
ops/dp, encode 7.8K→1485) is tracked only by hand-counted proxies
(tools/decode_profile.py) and timing loops noisy enough that the soak
gate had to quarantine its own setup phase.  XLA already computes what
a formulation-regression gate needs, at COMPILE time, deterministically,
on any box:

* ``jit(f).lower(args).compile().cost_analysis()`` — flops,
  transcendentals, bytes accessed of the optimized HLO;
* ``.memory_analysis()`` — argument/output/temp bytes (peak derives);
* the compiled module text — an HLO op-class histogram.

This module is the registry + extractor: every hot-path device program
is named as a **stage** with its pinned canonical shapes (the artifact
is only comparable at fixed shape — the ``cli hops`` precedent), and
:func:`run_stages` lowers + compiles each one (ShapeDtypeStructs only:
no data, no transfers, no timed loops) and extracts a fingerprint with
per-datapoint normalizations (flops/dp, bytes/dp, peak-bytes/dp) that
are comparable across boxes and backends.  ``cli costs`` commits the
artifact (COSTS_r13.json) and ``cli costs --check`` is the multiset
ratchet over it — the one perf trend line that keeps moving while the
relay is down, and the regression instrument ROADMAP items 1 and 2 are
judged against.

Honesty notes:

* The numbers are COST-MODEL numbers, not measurements: XLA's
  HloCostAnalysis counts a while-loop body ONCE (a ``lax.scan`` over T
  steps reports one body's flops), and counts only the op classes it
  models (integer/bitwise ops — most of a codec — are not "flops").
  That is exactly why they make a good ratchet (deterministic, box-
  independent) and a bad throughput predictor; the drift between these
  counts and the jaxpr-level hand counts is recorded in the artifact
  (``opsdp_crosscheck``), not papered over.
* Fingerprints are pinned per (platform, jax version): an XLA upgrade
  or a backend change legitimately moves them, which is a re-baseline,
  not a regression — the check refuses cross-platform comparison.
* Pallas stages lower in interpret mode off-TPU (the kernels' own
  clean-fallback contract), so their CPU fingerprints describe the
  interpreter's HLO; the TPU child (``cli tpu_backlog``) records the
  Mosaic numbers head-to-head when a relay window opens.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple

from m3_tpu.x import hlotext

__all__ = [
    "CANONICAL", "CompiledStage", "DOCUMENTED_OPS_PER_DP", "GATED_METRICS",
    "STAGES", "Stage", "clear_stage_cache", "compiled_stage",
    "compiled_stages", "count_jaxpr_ops", "fingerprint_compiled",
    "fingerprint_lowered", "hlo_op_histogram", "run_stages",
    "stage_names", "step_ops_crosscheck",
]


# ---------------------------------------------------------------------------
# Canonical shapes — the registry's pinned geometry.  Small enough that
# the full registry compiles in well under a minute (tier-1 runs the
# gate every round), large enough that XLA's layout/fusion choices are
# the hot path's, not a toy's.  CHANGING ANY OF THESE IS A RE-BASELINE.
# ---------------------------------------------------------------------------

CANONICAL = {
    "S": 256,           # codec series axis
    "T": 128,           # codec datapoints per series
    "W": 4,             # arena window ring
    "C": 4096,          # arena slot capacity
    "SCAP": 16384,      # timer sample capacity
    "N": 8192,          # arena ingest batch size
    "QUANTILES": (0.5, 0.95, 0.99),   # engine default
    "SHARD_DEVICES": 2,  # sharded-wrapper mesh width (needs >= 2 devices)
}

# The hand-counted per-datapoint element-op attributions the profile
# harness reports (jaxpr equation counts of one scan step — see
# tools/decode_profile.py).  Recorded here so the HLO-derived counts the
# costs artifact carries are CROSS-CHECKED against them every run: the
# two attributions drifting silently would invalidate both.
DOCUMENTED_OPS_PER_DP = {
    "decode_step": 670,    # PROFILE_decode_r06 (fused chains tail)
    "encode_step": 1485,   # PROFILE_encode_r08 (phase-1 lane emission)
}

# Per-stage metrics the ratchet gates (growth OR shrinkage past
# tolerance fails — improvements re-baseline, the lint/hops tradition).
# argument/output bytes only move when the program's interface changes
# (shapes are pinned by the config equality check), which is precisely
# the constant-bloat class: the 1MB decode control table sliding from
# an argument into the HLO shows up here first.
GATED_METRICS = (
    "flops", "transcendentals", "bytes_accessed", "hlo_op_total",
    "memory.argument_bytes", "memory.output_bytes",
    "memory.temp_bytes", "memory.peak_bytes",
)


# ---------------------------------------------------------------------------
# Extractors
# ---------------------------------------------------------------------------


def count_jaxpr_ops(jaxpr) -> int:
    """Total equation count of a jaxpr including nested sub-jaxprs —
    THE one home of the profile harness' "element ops per datapoint"
    counter (tools/decode_profile.py imports it; a drifted second copy
    would let the two attributions diverge silently)."""
    n = 0
    for e in jaxpr.eqns:
        n += 1
        for v in e.params.values():
            if hasattr(v, "jaxpr"):
                n += count_jaxpr_ops(v.jaxpr)
    return n


# The instruction grammar moved to its one home (x/hlotext.py) when
# irlint grew a second reader of the same texts; this name stays as the
# seam costwatch's callers import.
_HLO_INSTR_RE = hlotext.HLO_INSTR_RE


def hlo_op_histogram(hlo_text: str) -> Dict[str, int]:
    """Opcode-class histogram of a compiled HLO module — delegates to
    :func:`m3_tpu.x.hlotext.op_histogram`, the shared parsing home."""
    return hlotext.op_histogram(hlo_text)


def _cost_dict(compiled) -> dict:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca or {}


def fingerprint_compiled(compiled, datapoints: int, hlo_text=None) -> dict:
    """Extract one stage's fingerprint from a compiled executable.

    ``peak_bytes`` is the derived live-set bound argument + output +
    temp − alias (donated inputs alias their outputs and must not be
    double-counted); XLA exposes no finer peak on this seam, and the
    bound is the number an admission check needs — what the program
    can touch at once.  ``hlo_text`` lets a caller that already holds
    ``compiled.as_text()`` (the stage cache) skip re-rendering it."""
    ca = _cost_dict(compiled)
    ma = compiled.memory_analysis()
    hist = hlo_op_histogram(compiled.as_text() if hlo_text is None
                            else hlo_text)
    arg = int(ma.argument_size_in_bytes)
    out = int(ma.output_size_in_bytes)
    temp = int(ma.temp_size_in_bytes)
    alias = int(ma.alias_size_in_bytes)
    peak = arg + out + temp - alias
    flops = int(ca.get("flops", 0) or 0)
    by = int(ca.get("bytes accessed", 0) or 0)
    dp = max(int(datapoints), 1)
    return {
        "datapoints": int(datapoints),
        "flops": flops,
        "transcendentals": int(ca.get("transcendentals", 0) or 0),
        "bytes_accessed": by,
        "flops_per_dp": round(flops / dp, 4),
        "bytes_per_dp": round(by / dp, 4),
        "hlo_ops": hist,
        "hlo_op_total": sum(hist.values()),
        "memory": {
            "argument_bytes": arg,
            "output_bytes": out,
            "temp_bytes": temp,
            "alias_bytes": alias,
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
            "peak_bytes": peak,
        },
        "peak_bytes_per_dp": round(peak / dp, 2),
    }


def fingerprint_lowered(lowered, datapoints: int) -> dict:
    """Compile a ``jit(...).lower(...)`` result and fingerprint it —
    the seam bench.py's per-stage ``cost`` blocks use."""
    return fingerprint_compiled(lowered.compile(), datapoints)


# ---------------------------------------------------------------------------
# Stage registry
# ---------------------------------------------------------------------------


class Stage(NamedTuple):
    """One named hot-path device program at pinned canonical shapes.

    ``build()`` returns ``(lowered, datapoints, config)``: the AOT-
    lowered program (``.compile()`` not yet called — the caller owns
    the one compile), the per-datapoint normalization divisor, and the
    config dict the check gate pins (shapes + statics: two artifacts
    are only comparable when their configs are equal)."""

    name: str
    build: Callable[[], tuple]


def _sds(shape, dtype):
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(shape, np.dtype(dtype))


def _codec_shapes():
    import numpy as np

    S, T = CANONICAL["S"], CANONICAL["T"]
    W = T * 24 // 64 + 4  # stream words/series at the corpus bit rate
    return {
        "S": S, "T": T, "max_points": T + 1, "stream_words": W,
        "words": _sds((S, W + 1), np.uint64),
        "nbits": _sds((S,), np.int64),
        "tbl": _sds((1 << 18,), np.uint32),
        "ts": _sds((S, T), np.int64),
        "vbits": _sds((S, T), np.uint64),
        "start": _sds((S,), np.int64),
        "valid": _sds((S, T), np.bool_),
        "out_words": T * 16 // 64 + 4,
    }


def _build_decode(chains: str, extract: str):
    from m3_tpu.encoding import m3tsz_jax as mj

    g = _codec_shapes()
    lowered = mj._decode_batch_device.lower(
        g["words"], g["nbits"], g["tbl"], max_points=g["max_points"],
        default_unit=1, chains=chains, scan_major=True, extract=extract)
    cfg = {"S": g["S"], "T": g["T"], "max_points": g["max_points"],
           "stream_words": g["stream_words"], "chains": chains,
           "extract": extract, "scan_major": True}
    return lowered, g["S"] * g["T"], cfg


def _build_decode_sharded():
    import jax

    from m3_tpu.encoding import m3tsz_jax as mj  # noqa: F401 (codec import)
    from m3_tpu.parallel import sharded_decode

    g = _codec_shapes()
    n_dev = min(CANONICAL["SHARD_DEVICES"], jax.device_count())
    lowered = sharded_decode._sharded_fn(
        n_dev, g["max_points"], 1, "fused", True, "jnp").lower(
            g["words"], g["nbits"], g["tbl"])
    cfg = {"S": g["S"], "T": g["T"], "max_points": g["max_points"],
           "stream_words": g["stream_words"], "chains": "fused",
           "extract": "jnp", "devices": n_dev}
    return lowered, g["S"] * g["T"], cfg


def _build_encode(place: str):
    from m3_tpu.encoding import m3tsz_jax as mj

    g = _codec_shapes()
    lowered = mj._encode_batch_device.lower(
        g["ts"], g["vbits"], g["start"], g["valid"], unit=1,
        out_words=g["out_words"], prefix_bits=None, place=place)
    cfg = {"S": g["S"], "T": g["T"], "out_words": g["out_words"],
           "place": place}
    return lowered, g["S"] * g["T"], cfg


def _build_encode_sharded():
    import jax

    from m3_tpu.parallel import sharded_encode

    g = _codec_shapes()
    n_dev = min(CANONICAL["SHARD_DEVICES"], jax.device_count())
    lowered = sharded_encode._sharded_fn(
        n_dev, 1, g["out_words"], "gather", False).lower(
            g["ts"], g["vbits"], g["start"], g["valid"])
    cfg = {"S": g["S"], "T": g["T"], "out_words": g["out_words"],
           "place": "gather", "devices": n_dev}
    return lowered, g["S"] * g["T"], cfg


def _arena_shapes():
    import numpy as np

    N = CANONICAL["N"]
    return {
        "idx": _sds((N,), np.int64),
        "slots": _sds((N,), np.int32),
        "windows": _sds((N,), np.int32),
        "ivals": _sds((N,), np.int64),
        "fvals": _sds((N,), np.float64),
        "times": _sds((N,), np.int64),
        "window": _sds((), np.int64),
    }


def _arena_cfg(**extra) -> dict:
    cfg = {"W": CANONICAL["W"], "C": CANONICAL["C"], "N": CANONICAL["N"]}
    cfg.update(extra)
    return cfg


def _state_shape(initfn, *args):
    """Abstract state pytree of an arena init — no allocation (the
    registry never materializes data; eval_shape keeps the int
    geometry static by closing over it)."""
    import jax

    return jax.eval_shape(lambda: initfn(*args))


def _build_rollup_ingest_packed():
    from m3_tpu.aggregator import packed

    W, C = CANONICAL["W"], CANONICAL["C"]
    a = _arena_shapes()
    cs = _state_shape(packed.counter_init, W, C)
    gs = _state_shape(packed.gauge_init, W, C)
    lowered = packed.rollup_ingest.lower(
        cs, gs, a["idx"], a["ivals"], a["fvals"], a["times"],
        num_windows=W, capacity=C)
    return lowered, CANONICAL["N"], _arena_cfg(layout="packed",
                                               op="rollup_ingest")


def _build_arena_f64(kind: str, op: str):
    from m3_tpu.aggregator import arena

    W, C, SCAP = CANONICAL["W"], CANONICAL["C"], CANONICAL["SCAP"]
    a = _arena_shapes()
    if kind == "counter":
        st = _state_shape(arena.counter_init, W, C)
        if op == "ingest":
            lowered = arena.counter_ingest.lower(
                st, a["idx"], a["slots"], a["ivals"], a["times"],
                impl="scatter")
        else:
            lowered = arena.counter_consume.lower(st, a["window"],
                                                  capacity=C)
    elif kind == "gauge":
        st = _state_shape(arena.gauge_init, W, C)
        if op == "ingest":
            lowered = arena.gauge_ingest.lower(
                st, a["idx"], a["slots"], a["fvals"], a["times"],
                impl="scatter")
        else:
            lowered = arena.gauge_consume.lower(st, a["window"], capacity=C)
    else:  # timer
        st = _state_shape(arena.timer_init, W, C, SCAP)
        if op == "ingest":
            lowered = arena.timer_ingest.lower(
                st, a["windows"], a["slots"], a["fvals"], a["times"],
                capacity=C, impl="scatter")
        else:
            lowered = arena.timer_consume.lower(
                st, a["window"], capacity=C,
                quantiles=CANONICAL["QUANTILES"], packed32=False)
    dp = CANONICAL["N"] if op == "ingest" else (
        SCAP if kind == "timer" else C)
    cfg = _arena_cfg(layout="f64", op=f"{kind}_{op}")
    if kind == "timer":
        cfg["SCAP"] = SCAP
        if op == "consume":
            cfg["quantiles"] = list(CANONICAL["QUANTILES"])
    return lowered, dp, cfg


def _build_arena_packed(kind: str, op: str):
    from m3_tpu.aggregator import packed

    W, C, SCAP = CANONICAL["W"], CANONICAL["C"], CANONICAL["SCAP"]
    a = _arena_shapes()
    if kind == "counter":
        st = _state_shape(packed.counter_init, W, C)
        lowered = packed.counter_consume.lower(st, a["window"], capacity=C)
    elif kind == "gauge":
        st = _state_shape(packed.gauge_init, W, C)
        lowered = packed.gauge_consume.lower(st, a["window"], capacity=C)
    else:  # timer
        st = _state_shape(packed.timer_init, W, C, SCAP)
        if op == "ingest":
            lowered = packed.timer_ingest.lower(
                st, a["windows"], a["slots"], a["fvals"], a["times"],
                capacity=C)
        else:
            lowered = packed.timer_consume.lower(
                st, a["window"], capacity=C,
                quantiles=CANONICAL["QUANTILES"])
    dp = CANONICAL["N"] if op == "ingest" else (
        SCAP if kind == "timer" else C)
    cfg = _arena_cfg(layout="packed", op=f"{kind}_{op}")
    if kind == "timer":
        cfg["SCAP"] = SCAP
        if op == "consume":
            cfg["quantiles"] = list(CANONICAL["QUANTILES"])
    return lowered, dp, cfg


# Every hot-path device program, by name.  Order is evidence priority
# (the tpu_backlog costs stage walks it under a relay-window budget).
STAGES: tuple = (
    # decode: both chains tails and both extract impls
    Stage("decode/fused",
          functools.partial(_build_decode, "fused", "jnp")),
    Stage("decode/gather",
          functools.partial(_build_decode, "gather", "jnp")),
    Stage("decode/gather_pallas",
          functools.partial(_build_decode, "gather", "pallas")),
    Stage("decode/sharded", _build_decode_sharded),
    # encode: all three placement tails
    Stage("encode/gather", functools.partial(_build_encode, "gather")),
    Stage("encode/scatter", functools.partial(_build_encode, "scatter")),
    Stage("encode/pallas", functools.partial(_build_encode, "pallas")),
    Stage("encode/sharded", _build_encode_sharded),
    # arena hot path: packed (the production layout) and f64 (oracle)
    Stage("arena/rollup_ingest_packed", _build_rollup_ingest_packed),
    Stage("arena/counter_ingest_f64",
          functools.partial(_build_arena_f64, "counter", "ingest")),
    Stage("arena/gauge_ingest_f64",
          functools.partial(_build_arena_f64, "gauge", "ingest")),
    Stage("arena/counter_consume_packed",
          functools.partial(_build_arena_packed, "counter", "consume")),
    Stage("arena/counter_consume_f64",
          functools.partial(_build_arena_f64, "counter", "consume")),
    Stage("arena/gauge_consume_packed",
          functools.partial(_build_arena_packed, "gauge", "consume")),
    Stage("arena/gauge_consume_f64",
          functools.partial(_build_arena_f64, "gauge", "consume")),
    # timer ingest/drain, both layouts
    Stage("timer/ingest_packed",
          functools.partial(_build_arena_packed, "timer", "ingest")),
    Stage("timer/ingest_f64",
          functools.partial(_build_arena_f64, "timer", "ingest")),
    Stage("timer/consume_packed",
          functools.partial(_build_arena_packed, "timer", "consume")),
    Stage("timer/consume_f64",
          functools.partial(_build_arena_f64, "timer", "consume")),
)


def stage_names() -> tuple:
    return tuple(s.name for s in STAGES)


# ---------------------------------------------------------------------------
# Lowering cache — ONE compile per registered program per process.
#
# Two tier-1 gates walk the full registry every round (``cli costs
# --check`` fingerprints it, ``cli irlint --check`` lints its IR), and
# round-14 tier-1 ran 856s against the 870s envelope: a second
# full-registry lowering does not fit.  The cache is keyed by stage
# name only, which is sound because CANONICAL is module-constant and
# builders are pure functions of it — same process, same program.
# ---------------------------------------------------------------------------


class CompiledStage(NamedTuple):
    """One registry stage, lowered + compiled once, with both module
    texts rendered once (irlint's rules and costwatch's histogram read
    the same strings instead of re-rendering per consumer)."""

    name: str
    lowered: Any       # jax .lower(...) result
    compiled: Any      # .compile() executable
    stablehlo: str     # lowered.as_text() — formulation-level MLIR
    hlo: str           # compiled.as_text() — post-optimization HLO
    datapoints: int
    config: dict


_STAGE_CACHE: Dict[str, CompiledStage] = {}


def clear_stage_cache() -> None:
    """Drop all cached executables (tests that reconfigure devices)."""
    _STAGE_CACHE.clear()


def compiled_stage(name: str) -> CompiledStage:
    """The cached :class:`CompiledStage` for one registry stage,
    building + compiling it on first use."""
    cs = _STAGE_CACHE.get(name)
    if cs is not None:
        return cs
    by_name = {s.name: s for s in STAGES}
    if name not in by_name:
        raise KeyError(f"unknown costwatch stage(s): {[name]}; "
                       f"known: {list(stage_names())}")
    lowered, datapoints, cfg = by_name[name].build()
    compiled = lowered.compile()
    cs = CompiledStage(name=name, lowered=lowered, compiled=compiled,
                       stablehlo=lowered.as_text(), hlo=compiled.as_text(),
                       datapoints=int(datapoints), config=dict(cfg))
    _STAGE_CACHE[name] = cs
    return cs


def compiled_stages(names=None, on_stage=None) -> Dict[str, CompiledStage]:
    """Cached :class:`CompiledStage` map in registry order (or a
    subset).  Unknown names fail in milliseconds, before any compile.
    ``on_stage(name, seconds)`` reports per-stage wall of THIS call —
    near-zero on cache hits, which is the observable proof the
    costs/irlint gates share one lowering."""
    import time

    want = set(names) if names is not None else None
    if want is not None:
        missing = want - set(stage_names())
        if missing:
            raise KeyError(f"unknown costwatch stage(s): {sorted(missing)}; "
                           f"known: {list(stage_names())}")
    out: Dict[str, CompiledStage] = {}
    for stage in STAGES:
        if want is not None and stage.name not in want:
            continue
        t0 = time.perf_counter()
        out[stage.name] = compiled_stage(stage.name)
        if on_stage is not None:
            on_stage(stage.name, time.perf_counter() - t0)
    return out


def run_stages(names=None, on_stage=None) -> Dict[str, dict]:
    """Lower + compile + fingerprint the registry (or a subset).

    Compile-only by construction: builders hand ``.lower()``
    ShapeDtypeStructs, so no data is materialized, nothing transfers,
    and nothing executes — immune to box noise, safe under the tier-1
    envelope.  Programs come from the process-wide stage cache, so a
    later ``cli irlint`` pass (or a repeated costs run) pays zero
    additional compiles.  ``on_stage(name, seconds)`` reports per-stage
    compile wall (observability of the gate's own cost, not part of
    any fingerprint)."""
    out: Dict[str, dict] = {}
    for name, cs in compiled_stages(names, on_stage=on_stage).items():
        fp = fingerprint_compiled(cs.compiled, cs.datapoints,
                                  hlo_text=cs.hlo)
        fp["config"] = dict(cs.config)
        out[name] = fp
    return out


# ---------------------------------------------------------------------------
# ops/dp cross-check: the profile harness' jaxpr hand counts vs the
# HLO-derived numbers, recorded so neither attribution drifts silently.
# ---------------------------------------------------------------------------


def _decode_step_jaxpr_ops() -> int:
    import jax
    import jax.numpy as jnp

    from m3_tpu.encoding import m3tsz_jax as mj

    S = CANONICAL["S"]
    W = CANONICAL["T"] * 24 // 64 + 4
    wpad = jnp.zeros((S, W + 1 + mj._PAD_WORDS), jnp.uint64)
    step = functools.partial(
        mj._decode_step, words=wpad, nbits=jnp.zeros(S, mj.I32),
        unit0=jnp.zeros(S, mj.I32),
        ctrl_tbl=jnp.zeros(1 << 18, jnp.uint32), emit_chains=True)
    carry0 = mj._decode_carry0(S, jnp.zeros(S, mj.I64))
    return count_jaxpr_ops(jax.make_jaxpr(step)(carry0, None).jaxpr)


def _encode_step_jaxpr_ops() -> int:
    import jax
    import jax.numpy as jnp

    from m3_tpu.encoding import m3tsz_jax as mj

    S = CANONICAL["S"]
    step = functools.partial(mj._encode_step, unit=1,
                             default_unit_is_32bit=True)
    carry0 = mj._encode_carry0(S, jnp.zeros(S, mj.I64), 1)
    xs = (jnp.zeros(S, mj.I64), jnp.zeros(S, mj.U64),
          jnp.ones(S, jnp.bool_))
    return count_jaxpr_ops(jax.make_jaxpr(step)(carry0, xs).jaxpr)


def step_ops_crosscheck(stage_fps: Dict[str, dict]) -> dict:
    """The two attributions side by side, with the drift explained.

    ``jaxpr_step_ops`` is the live hand-count (decode_profile's method:
    equations in one scan step's jaxpr); ``documented_ops_per_dp`` is
    the number the committed PROFILE artifacts report; ``hlo_flops_per
    _dp`` is XLA's own count from the compiled module.  They measure
    different things BY DESIGN — the explanation string is part of the
    artifact so the gap can't be misread as a bug."""
    out: dict = {}
    for key, live_fn, stage in (
            ("decode", _decode_step_jaxpr_ops, "decode/fused"),
            ("encode", _encode_step_jaxpr_ops, "encode/gather")):
        doc = DOCUMENTED_OPS_PER_DP[f"{key}_step"]
        live = live_fn()
        rec = {
            "documented_ops_per_dp": doc,
            "jaxpr_step_ops": live,
            "jaxpr_vs_documented": round(live / doc, 3),
        }
        fp = stage_fps.get(stage)
        if fp:
            rec["hlo_flops_per_dp"] = fp["flops_per_dp"]
            rec["hlo_bytes_per_dp"] = fp["bytes_per_dp"]
            rec["hlo_flops_vs_jaxpr_ops"] = round(
                fp["flops_per_dp"] / max(live, 1), 4)
        out[key] = rec
    out["explanation"] = (
        "jaxpr_step_ops counts EVERY equation in one scan step's jaxpr "
        "(integer/bitwise/select/gather included — the branchless "
        "formulation's real per-datapoint element work, the number the "
        "PROFILE artifacts attribute); XLA's cost analysis counts a "
        "lax.scan's while-body ONCE for the whole program and models "
        "only the op classes it prices (flops ~ floating/elementwise "
        "arithmetic; gathers and bit ops are bytes, not flops).  The "
        "ratio between them is therefore a FINGERPRINT to ratchet, not "
        "a unit conversion; jaxpr_vs_documented near 1.0 is the "
        "cross-check that the hand-counted attribution still describes "
        "the live step.")
    return out
