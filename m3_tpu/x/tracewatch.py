"""Runtime retrace/transfer sanitizer: the dynamic half of jaxlint.

The static jax families (``m3_tpu/x/lint/jaxlint.py``) catch the
*patterns* that cause silent recompiles and hidden host↔device copies;
this module catches the *events*.  A jitted function that retraces per
call — a Python scalar riding a shape-affecting position, a weak-type
flip, an unhashable static — costs 100-10000x its steady-state time and
shows up in a benchmark as "the kernel got slower", which is how perf
regressions hide (the exact failure mode ISSUE 7 exists for).  While
armed:

* **Compile counting** — every XLA compile in the process is observed
  through the ``jax_log_compiles`` seam (a logging handler on the
  ``Compiling <fn> with global shapes and types [...]`` record jax's
  pjit path emits once per real cache miss) and counted per function
  name, with the abstract argument shapes/dtypes of each compile
  recorded.  When a function compiles past its budget the handler
  raises :class:`RetraceError` *inside the offending call* — the
  traceback points at the callsite and the message carries every
  distinct signature seen, so the shape/dtype that churned is named,
  not guessed.  Because the seam observes the process, functions jitted
  BEFORE arming are covered too (unlike a ``jax.jit`` wrapper alone).
* **jit/pjit wrapping** — while armed, ``jax.jit``/``jax.pjit`` are
  swapped for a transparent factory that registers each new function's
  declared budget (``@tracewatch.retrace_budget(n)``) before delegating
  to the real jit; the returned object IS jax's jitted callable
  (``__wrapped__``, ``clear_cache``, ``lower`` all intact).
* **Transfer guard** — :func:`no_transfers` arms ``jax.transfer_guard``
  ("disallow") for real device backends AND a tracewatch-level guard
  that intercepts ``jax.Array.__array__`` (the ``np.asarray`` /
  ``np.array`` device→host seam) and ``jax.device_get``, raising
  :class:`TransferError` with the array's shape/dtype.  The software
  half exists because the CPU backend has no device boundary, so
  ``jax.transfer_guard`` never fires under ``JAX_PLATFORMS=cpu`` — the
  tier the test suite runs on.  :func:`allow_transfers` re-opens the
  guard for a declared host boundary inside a guarded region.

Arming (mirrors ``x/lockcheck.py``):

* code — ``tracewatch.install()`` / ``uninstall()`` (the race/dtest
  conftest fixture; bench children install in record mode);
* env — ``M3_TRACEWATCH=1`` arms at import with fail-fast raises,
  ``M3_TRACEWATCH=record`` counts without raising (``m3_tpu.x``
  imports this module, so dtest node subprocesses inherit arming
  through their environment exactly like lockcheck/faultpoints).
  ``M3_TRACEWATCH_BUDGET`` overrides the default per-function compile
  budget (default 32 — roomy: legit recompiles happen per distinct
  shape, and a shape-churning callsite blows past it immediately).

Honesty notes:

* Budgets are per *function name* as jax reports it: two same-named
  lambdas share a count.  Name real hot-path functions.
* A persistent-compilation-cache hit still counts as a compile: the
  trace ran and a new executable was installed — exactly the per-shape
  cost the sanitizer exists to surface (only the XLA backend time was
  saved).
* The ``__array__`` patch is process-global while installed but checks
  a thread-local arm flag, so only threads inside ``no_transfers()``
  are guarded.
"""

from __future__ import annotations

import contextlib
import logging
import os
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List

__all__ = [
    "RetraceError", "TransferError", "RetraceFinding", "install",
    "uninstall", "installed", "reset", "compiles", "total_compiles",
    "compile_signatures", "findings", "set_budget", "retrace_budget",
    "no_transfers", "allow_transfers", "snapshot", "retraces_since",
]

DEFAULT_BUDGET = 32

# Greedy to the LAST ']' in the record: the avals list itself contains
# one ']' per array argument ("[ShapedArray(f64[2,2]), ShapedArray(
# i32[5])]"), and the trailing "Argument mapping: (...)" carries none —
# a non-greedy match would truncate at the first shape's ']' and
# collapse every multi-argument signature to one broken entry.
_COMPILE_RE = re.compile(r"^Compiling ([^\s]+) with global shapes and types "
                         r"(\[.*\])", re.S)

_installed = False
_raise_on_violation = True
_mu = threading.Lock()
_counts: Dict[str, int] = {}
_signatures: Dict[str, List[str]] = {}
_budgets: Dict[str, int] = {}
_total = 0
_findings: List["RetraceFinding"] = []

_tls = threading.local()

_ORIG = {}


class RetraceError(RuntimeError):
    """A jitted function compiled past its retrace budget.  Raised
    inside the offending call, carrying every distinct argument
    signature the function compiled for."""


class TransferError(RuntimeError):
    """A device→host transfer happened inside a ``no_transfers()``
    guarded region (e.g. np.asarray on a device array in a timed
    loop)."""


@dataclass
class RetraceFinding:
    """One budget violation: ``name`` compiled ``count`` times against
    a budget of ``budget``; ``signatures`` lists the distinct abstract
    shapes/dtypes observed — the churning axis is the one that differs
    between entries."""

    name: str
    count: int
    budget: int
    signatures: List[str] = field(default_factory=list)

    def __str__(self) -> str:
        sigs = "\n  ".join(self.signatures) or "<none recorded>"
        return (
            f"{self.name} compiled {self.count}x (budget {self.budget}) — "
            f"a shape/dtype/static is churning per call.  Signatures "
            f"seen:\n  {sigs}\n"
            f"Fix the unstable axis (pad shapes, mark the argument "
            f"static, pin the dtype) or declare a budget with "
            f"tracewatch.set_budget({self.name!r}, n)."
        )


def _default_budget() -> int:
    try:
        return max(1, int(os.environ.get("M3_TRACEWATCH_BUDGET",
                                         str(DEFAULT_BUDGET))))
    except ValueError:
        return DEFAULT_BUDGET


class _CompileHandler(logging.Handler):
    """Counts the one-per-cache-miss pxla "Compiling <fn> ..." record.

    Raising from ``emit`` is deliberate: ``Logger.callHandlers`` does
    not catch handler exceptions (the swallowing convention lives in
    the stdlib emit() implementations), so a budget violation
    propagates out of jax's own logging call and surfaces AT the
    callsite that triggered the compile — fail fast, like lockcheck
    raising before the deadlocking acquire."""

    def emit(self, record: logging.LogRecord) -> None:
        m = _COMPILE_RE.match(record.getMessage())
        if not m:
            return
        name, avals = m.group(1), m.group(2)
        global _total
        with _mu:
            _total += 1
            n = _counts[name] = _counts.get(name, 0) + 1
            sigs = _signatures.setdefault(name, [])
            if avals not in sigs:
                sigs.append(avals)
            budget = _budgets.get(name, _default_budget())
            over = n > budget
            if over:
                finding = RetraceFinding(name, n, budget, list(sigs))
                _findings.append(finding)
        if over and _raise_on_violation:
            raise RetraceError(str(finding))


_handler = _CompileHandler(level=logging.WARNING)
# The one logger that emits the per-cache-miss record in jax 0.4.x.
_PXLA_LOGGER = "jax._src.interpreters.pxla"


# numpy module entry points wrapped by the guard: np.asarray on a jax
# array does NOT route through a patchable ``__array__`` (numpy takes
# the C buffer-protocol fast path), so the interception must happen at
# the numpy call itself.  Each wrapper delegates untouched unless the
# calling thread is inside no_transfers() AND the operand is a jax
# device array.
_NP_SEAMS = ("asarray", "array", "ascontiguousarray", "asanyarray")


def _patch_array_seam() -> None:
    """Swap in the transfer-guard seams (idempotent)."""
    import jax
    import numpy as np

    if "device_get" in _ORIG:
        return
    _ORIG["device_get"] = jax.device_get

    def guarded_device_get(x):
        _check_transfer("jax.device_get", x)
        return _ORIG["device_get"](x)

    jax.device_get = guarded_device_get

    try:
        import jaxlib.xla_extension as xe

        _ORIG["_array_cls"] = xe.ArrayImpl
    except Exception:  # pragma: no cover - exotic jaxlib layout
        _ORIG["_array_cls"] = jax.Array

    def _wrap_np(name: str):
        orig = getattr(np, name)

        def guarded(a, *args, **kw):
            if (getattr(_tls, "guard_depth", 0) > 0
                    and isinstance(a, _ORIG["_array_cls"])):
                _check_transfer(f"np.{name}", a)
            return orig(a, *args, **kw)

        guarded.__name__ = name
        guarded.__wrapped__ = orig
        return orig, guarded

    for name in _NP_SEAMS:
        orig, guarded = _wrap_np(name)
        _ORIG[f"np.{name}"] = orig
        setattr(np, name, guarded)

    # ``.item()``/dunder-driven conversions still route through the
    # per-class __array__ where numpy's fast path does not apply.
    try:
        arr = _ORIG["_array_cls"]
        _ORIG["__array__"] = arr.__array__

        def guarded_array(self, *a, **kw):
            _check_transfer("__array__", self)
            return _ORIG["__array__"](self, *a, **kw)

        arr.__array__ = guarded_array
    except Exception:  # pragma: no cover
        _ORIG.pop("__array__", None)


def _unpatch_array_seam() -> None:
    import jax
    import numpy as np

    if "device_get" in _ORIG:
        jax.device_get = _ORIG.pop("device_get")
    for name in _NP_SEAMS:
        orig = _ORIG.pop(f"np.{name}", None)
        if orig is not None:
            setattr(np, name, orig)
    if "__array__" in _ORIG:
        _ORIG["_array_cls"].__array__ = _ORIG.pop("__array__")
    _ORIG.pop("_array_cls", None)


def _check_transfer(kind: str, x) -> None:
    if getattr(_tls, "guard_depth", 0) <= 0:
        return
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", "?")
    desc = f"{dtype}{list(shape)}" if shape is not None else repr(type(x))
    raise TransferError(
        f"device->host transfer ({kind}) of {desc} inside a "
        f"no_transfers() region — move it out of the timed/guarded "
        f"section or wrap the host boundary in "
        f"tracewatch.allow_transfers()")


def _wrap_jit_factories() -> None:
    import jax

    if "jit" in _ORIG:
        return
    _ORIG["jit"] = jax.jit
    _ORIG["pjit"] = getattr(jax, "pjit", None)

    def _register(fun) -> None:
        budget = getattr(fun, "_tracewatch_budget", None)
        if budget is not None:
            name = getattr(fun, "__name__", None)
            if name:
                with _mu:
                    _budgets[name] = int(budget)

    def watched_jit(fun=None, **kw):
        if fun is None:  # jax.jit(static_argnames=...) usage
            def deco(f):
                _register(f)
                return _ORIG["jit"](f, **kw)
            return deco
        _register(fun)
        return _ORIG["jit"](fun, **kw)

    jax.jit = watched_jit
    if _ORIG["pjit"] is not None:
        def watched_pjit(fun=None, **kw):
            if fun is None:
                def deco(f):
                    _register(f)
                    return _ORIG["pjit"](f, **kw)
                return deco
            _register(fun)
            return _ORIG["pjit"](fun, **kw)

        jax.pjit = watched_pjit


def _unwrap_jit_factories() -> None:
    import jax

    if "jit" in _ORIG:
        jax.jit = _ORIG.pop("jit")
        pjit = _ORIG.pop("pjit")
        if pjit is not None:
            jax.pjit = pjit


def retrace_budget(n: int):
    """Decorator declaring a per-function compile budget, read by the
    armed jit factory: ``@tracewatch.retrace_budget(2)`` above the
    ``@jax.jit``-decorated def.  Inert when tracewatch is not armed."""
    def deco(fun):
        fun._tracewatch_budget = int(n)
        name = getattr(fun, "__name__", None)
        if name:
            with _mu:
                _budgets[name] = int(n)
        return fun
    return deco


def set_budget(name: str, n: int) -> None:
    """Declare the compile budget for the jit-reported function name."""
    with _mu:
        _budgets[name] = int(n)


def install(raise_on_violation: bool = True) -> None:
    """Arm the sanitizer: count every compile, enforce budgets, swap
    the jit factories, and stage the transfer-guard seams.  Idempotent."""
    global _installed, _raise_on_violation
    import jax

    _raise_on_violation = raise_on_violation
    if _installed:
        return
    _ORIG["log_compiles"] = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    pxla = logging.getLogger(_PXLA_LOGGER)
    pxla.addHandler(_handler)
    # jax_log_compiles also flips the dispatch module's per-phase
    # timing logs ("Finished tracing + transforming ...") to WARNING —
    # 3+ stderr lines per compile that nobody consumes and that drown
    # the armed process' real output (bench stage logs, dtest node
    # stderr).  Only the pxla "Compiling" record feeds the counter:
    # quiet the dispatch logger and keep the pxla record from
    # propagating to the root last-resort printer while armed.
    dispatch = logging.getLogger("jax._src.dispatch")
    _ORIG["dispatch_level"] = dispatch.level
    dispatch.setLevel(logging.ERROR)
    _ORIG["pxla_propagate"] = pxla.propagate
    pxla.propagate = False
    _wrap_jit_factories()
    _patch_array_seam()
    _installed = True


def uninstall() -> None:
    """Disarm and restore every seam (counters/findings survive for
    inspection; ``reset()`` clears them)."""
    global _installed
    if not _installed:
        return
    import jax

    pxla = logging.getLogger(_PXLA_LOGGER)
    pxla.removeHandler(_handler)
    if "pxla_propagate" in _ORIG:
        pxla.propagate = _ORIG.pop("pxla_propagate")
    if "dispatch_level" in _ORIG:
        logging.getLogger("jax._src.dispatch").setLevel(
            _ORIG.pop("dispatch_level"))
    if "log_compiles" in _ORIG:
        jax.config.update("jax_log_compiles", _ORIG.pop("log_compiles"))
    _unwrap_jit_factories()
    _unpatch_array_seam()
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    """Clear counters, signatures, findings and ad-hoc budgets
    (per-test hygiene, mirrors lockcheck.reset)."""
    global _total
    with _mu:
        _counts.clear()
        _signatures.clear()
        _findings.clear()
        _budgets.clear()
        _total = 0


def compiles() -> Dict[str, int]:
    with _mu:
        return dict(_counts)


def total_compiles() -> int:
    with _mu:
        return _total


def compile_signatures() -> Dict[str, List[str]]:
    with _mu:
        return {k: list(v) for k, v in _signatures.items()}


def findings() -> List[RetraceFinding]:
    with _mu:
        return list(_findings)


def snapshot() -> int:
    """Opaque marker for :func:`retraces_since` — bench timed regions
    bracket their steady-state loops with these two calls and assert
    the delta is ZERO, so a retrace regression fails the stage instead
    of masquerading as a throughput change."""
    return total_compiles()


def retraces_since(snap: int) -> int:
    return total_compiles() - snap


@contextlib.contextmanager
def no_transfers():
    """Forbid device→host transfers in this thread for the duration:
    ``np.asarray``/``np.array`` on device arrays and ``jax.device_get``
    raise :class:`TransferError`; on a real device backend
    ``jax.transfer_guard("disallow")`` additionally covers the implicit
    paths jax itself can see.  Installs the seams on demand if
    tracewatch is not armed."""
    import jax

    if "device_get" not in _ORIG:
        _patch_array_seam()
    _tls.guard_depth = getattr(_tls, "guard_depth", 0) + 1
    try:
        with jax.transfer_guard("disallow"):
            yield
    finally:
        _tls.guard_depth -= 1
        if not _installed and _tls.guard_depth <= 0:
            _unpatch_array_seam()


@contextlib.contextmanager
def allow_transfers():
    """Escape hatch for a declared host boundary inside a
    ``no_transfers()`` region (e.g. fetching a final result after the
    timed loop closed)."""
    import jax

    prev = getattr(_tls, "guard_depth", 0)
    _tls.guard_depth = 0
    try:
        with jax.transfer_guard("allow"):
            yield
    finally:
        _tls.guard_depth = prev


# dtest node subprocesses inherit arming through their environment,
# exactly like M3_LOCKCHECK/M3_FAULTPOINTS (m3_tpu.x imports this
# module).
if os.environ.get("M3_TRACEWATCH"):
    install(raise_on_violation=os.environ.get("M3_TRACEWATCH") != "record")
