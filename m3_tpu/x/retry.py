"""Retry with exponential backoff, jitter, attempt caps and budgets.

Equivalent of the reference's ``src/x/retry`` (``retry.go``: initial/max
backoff, backoff factor, jitter, max retries, forever, a retryable
classifier) plus its shared retry *budget* — the M3 production stance
that every network edge retries transient failures, but the aggregate
retry volume is bounded so a dying dependency cannot amplify load.

Design points for this tree:

* **Pure math first** — :meth:`Retrier.backoff_for` is a deterministic
  function of (attempt, rng) so tests pin the schedule without sleeping;
  the clock and sleep are injectable everywhere.
* **Classifier default** — transport failures only (``ConnectionError``,
  ``TimeoutError``, other ``OSError``).  Application errors (CAS
  conflicts as ``ValueError``, ``RemoteError`` as ``RuntimeError``)
  never retry: the reference's ``xerrors.IsRetryableError`` contract.
* **Budget** — a token bucket shared across retriers if desired: each
  retry consumes one token; an empty bucket fails fast instead of
  stacking backoff sleeps on a dead peer.
* **Counters** — per-retrier-name module counters (attempts, retries,
  successes, exhausted, budget_exhausted, not_retryable), mirrored into
  a node's instrument registry by ``m3_tpu.x.register_metrics`` and
  asserted by the dtest scenarios.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict

__all__ = ["RetryOptions", "RetryBudget", "Retrier", "default_retryable",
           "counters", "reset_counters"]


def default_retryable(e: BaseException) -> bool:
    """Transport-shaped failures only.  ``ProtocolError`` and
    ``FaultInjected`` subclass ``ConnectionError`` so they match."""
    return isinstance(e, (ConnectionError, TimeoutError, OSError))


@dataclass(frozen=True)
class RetryOptions:
    """Reference ``retry.Options`` surface (retry.go:40-78)."""

    initial_backoff_s: float = 0.05
    backoff_factor: float = 2.0
    max_backoff_s: float = 5.0
    max_attempts: int = 4        # total attempts including the first
    forever: bool = False
    jitter: bool = True          # uniform in [backoff/2, backoff]


class RetryBudget:
    """Token bucket bounding aggregate retry volume (x/retry budget
    role).  ``allow()`` refills by elapsed time and consumes one token;
    False means the retry is denied and the caller fails fast."""

    def __init__(self, capacity: float = 16.0, refill_per_s: float = 4.0,
                 clock: Callable[[], float] = time.monotonic):
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._tokens = float(capacity)
        self._clock = clock
        self._last = clock()
        self._lock = threading.Lock()

    def allow(self) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.capacity,
                self._tokens + (now - self._last) * self.refill_per_s)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        return self._tokens


_lock = threading.Lock()
_counters: Dict[str, int] = {}


def _bump(name: str, key: str, delta: int = 1) -> None:
    with _lock:
        k = f"{name}.{key}"
        _counters[k] = _counters.get(k, 0) + delta


def counters() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        _counters.clear()


class Retrier:
    """``run(fn)`` calls ``fn`` until it returns, raises a
    non-retryable error, or the policy is exhausted (last error
    re-raised).  One Retrier is safe for concurrent use."""

    def __init__(self, opts: RetryOptions = RetryOptions(),
                 name: str = "default",
                 is_retryable: Callable[[BaseException], bool] | None = None,
                 budget: RetryBudget | None = None,
                 sleep: Callable[[float], None] = time.sleep,
                 seed: int | None = None):
        self.opts = opts
        self.name = name
        self.is_retryable = is_retryable or default_retryable
        self.budget = budget
        self._sleep = sleep
        # Seeded rng -> reproducible jitter schedules in tests; the
        # default stays wall-entropy like the reference.
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()

    def backoff_for(self, retry_index: int) -> float:
        """Backoff before retry number ``retry_index`` (1-based): pure
        ``initial * factor**(i-1)`` capped at max, jittered to
        [backoff/2, backoff] when enabled (retry.go:150-170)."""
        if retry_index < 1:
            return 0.0
        # Exponent capped BEFORE exponentiation: an unbounded caller
        # (e.g. a reconnect loop counting failed rounds for hours)
        # must asymptote to max_backoff_s, not overflow float pow.
        b = self.opts.initial_backoff_s * (
            self.opts.backoff_factor ** min(retry_index - 1, 64))
        b = min(b, self.opts.max_backoff_s)
        if self.opts.jitter:
            with self._rng_lock:
                b = b / 2.0 + self._rng.random() * (b / 2.0)
        return b

    def run(self, fn: Callable[[], object], abort: Callable[[], bool] | None = None):
        """Run ``fn`` under the policy.  ``abort()`` (optional) is
        checked before each retry so callers can stop retrying a
        deliberately closed client without waiting out the schedule."""
        retry_index = 0
        while True:
            _bump(self.name, "attempts")
            try:
                result = fn()
            except Exception as e:  # noqa: BLE001 — classified below
                if not self.is_retryable(e):
                    _bump(self.name, "not_retryable")
                    raise
                retry_index += 1
                if (not self.opts.forever
                        and retry_index >= self.opts.max_attempts):
                    _bump(self.name, "exhausted")
                    raise
                if abort is not None and abort():
                    _bump(self.name, "aborted")
                    raise
                if self.budget is not None and not self.budget.allow():
                    _bump(self.name, "budget_exhausted")
                    raise
                _bump(self.name, "retries")
                self._sleep(self.backoff_for(retry_index))
                continue
            if retry_index:
                _bump(self.name, "recovered")
            _bump(self.name, "successes")
            return result
