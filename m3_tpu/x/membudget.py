"""Process-level device-memory (HBM) budget: admission, not autopsy.

``make_arenas`` at C=10M on a real chip OOM-crashes inside XLA with the
allocation half-landed; nothing upstream can catch it usefully because
the failure arrives as a runtime abort mid-dispatch.  This ledger moves
the failure to ADMISSION time, exactly like PR 11's SlotAllocator
contract for series capacity: every long-lived device structure — the
aggregation arenas (24B/slot packed counter, 40B/slot f64 — footprints
are compile-time constants of the layout), the series buffer ring, the
decode control table — and the big transient stage buffers (encoder
lane tables, decoder lane tables) REGISTER a byte reservation before
any XLA allocation happens.  Over budget raises the typed
:class:`DeviceBudgetExceeded` (a :class:`~m3_tpu.x.devguard.DeviceOOM`,
so the device guard classifies and counts it) and bumps the rejected
counter — reject-and-count, never die-in-XLA.

The budget is **advisory accounting, host-side only**: it tracks the
bytes THIS process asked for through the seam, not the allocator's
ground truth (XLA workspaces, compiled executables and framework
overhead are outside it).  Size the budget with headroom; the gauges
(``device_mem_budget_bytes`` / ``device_mem_used_bytes`` /
``device_mem_rejected_total`` on /metrics) make the high-water mark
visible.

Configuration: ``M3_DEVICE_MEM_BUDGET`` ("0"/unset = unlimited; plain
bytes or K/M/G/T suffix, binary units) read at import, or the node
config's ``device.mem_budget`` applied by run_node via
:func:`set_budget` before any reservation is taken.

Reservations release on ``release()``/context-manager exit, or
automatically when their ``owner`` object is garbage-collected (a
``weakref.finalize``, the lockcheck registry's pattern) — arena and
buffer objects have no close() and must not leak ledger bytes when an
engine drops them.
"""

from __future__ import annotations

import os
import re
import threading
import weakref
from typing import Dict

from m3_tpu.x.devguard import DeviceOOM

__all__ = [
    "DeviceBudgetExceeded", "Reservation", "budget", "used", "parse_bytes",
    "reserve", "transient", "set_budget", "snapshot", "counters",
    "reset", "arena_bytes", "buffer_bytes", "counter_arena_bytes",
    "gauge_arena_bytes", "timer_arena_bytes",
]


class DeviceBudgetExceeded(DeviceOOM):
    """Typed admission reject: the reservation would exceed
    ``M3_DEVICE_MEM_BUDGET``.  A DeviceOOM subclass so the devguard
    classifier/breakers treat it as the OOM it prevents."""

    kind = "budget"

    def __init__(self, component: str, nbytes: int, budget: int, used: int):
        super().__init__(
            component,
            f"reserving {nbytes} bytes would exceed the device memory "
            f"budget ({used} of {budget} in use) — raise "
            "M3_DEVICE_MEM_BUDGET/device.mem_budget or shrink the "
            "arena/buffer geometry")
        self.component = component
        self.nbytes = nbytes
        self.budget = budget
        self.used = used


_SIZE_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*([KMGT]i?)?B?$", re.IGNORECASE)
_SIZE_MULT = {None: 1, "K": 1 << 10, "M": 1 << 20, "G": 1 << 30,
              "T": 1 << 40}


def parse_bytes(v) -> int:
    """"512M" / "2GiB" / 1048576 → bytes (binary units; 0 = unlimited)."""
    if isinstance(v, (int, float)):
        return int(v)
    m = _SIZE_RE.match(str(v).strip())
    if not m:
        raise ValueError(
            f"bad byte size {v!r} (want e.g. '512M', '2GiB', or bytes)")
    suffix = m.group(2)
    mult = _SIZE_MULT[suffix[0].upper() if suffix else None]
    return int(float(m.group(1)) * mult)


_lock = threading.Lock()
_budget = parse_bytes(os.environ.get("M3_DEVICE_MEM_BUDGET", "") or 0)
_used = 0
_peak = 0
_rejected = 0
_by_component: Dict[str, int] = {}


def set_budget(nbytes) -> None:
    """Set the process budget (bytes or suffixed string; 0 disables
    admission).  Existing reservations stay — shrinking below current
    use only affects NEW reservations."""
    global _budget
    _budget = parse_bytes(nbytes)


def budget() -> int:
    return _budget


def used() -> int:
    with _lock:
        return _used


def counters() -> Dict[str, int]:
    with _lock:
        return {"membudget.used_bytes": _used,
                "membudget.peak_bytes": _peak,
                "membudget.rejected_total": _rejected}


def snapshot() -> dict:
    """The /health view: budget/used/peak/rejected + per-component
    bytes currently reserved."""
    with _lock:
        return {
            "budget_bytes": _budget,
            "used_bytes": _used,
            "peak_bytes": _peak,
            "rejected_total": _rejected,
            "components": dict(_by_component),
        }


def reset() -> None:
    """Test hygiene: zero the ledger (live Reservations become no-ops
    for the bytes they release — only use between isolated tests)."""
    global _used, _peak, _rejected
    with _lock:
        _used = 0
        _peak = 0
        _rejected = 0
        _by_component.clear()


class Reservation:
    """One admitted byte reservation; release is idempotent."""

    def __init__(self, component: str, nbytes: int):
        self.component = component
        self.nbytes = int(nbytes)
        self._released = False
        self._finalizer = None

    def resize(self, nbytes: int) -> None:
        """Grow/shrink in place (buffer ``_grow`` paths): the DELTA is
        admitted against the budget; an over-budget grow raises typed
        and leaves the reservation unchanged."""
        nbytes = int(nbytes)
        delta = nbytes - self.nbytes
        if self._released or delta == 0:
            return
        _admit(self.component, delta)
        self.nbytes = nbytes

    def release(self) -> None:
        global _used
        if self._released:
            return
        self._released = True
        if self._finalizer is not None:
            self._finalizer.detach()
            self._finalizer = None
        _admit(self.component, -self.nbytes, count_reject=False)

    def __enter__(self) -> "Reservation":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _admit(component: str, delta: int, count_reject: bool = True) -> None:
    global _used, _peak, _rejected
    with _lock:
        if delta > 0 and _budget > 0 and _used + delta > _budget:
            if count_reject:
                _rejected += 1
            raise DeviceBudgetExceeded(component, delta, _budget, _used)
        _used += delta
        _peak = max(_peak, _used)
        _by_component[component] = _by_component.get(component, 0) + delta
        if _by_component[component] <= 0:
            del _by_component[component]


def reserve(component: str, nbytes: int, owner=None) -> Reservation:
    """Admit ``nbytes`` for ``component`` or raise
    :class:`DeviceBudgetExceeded` (counted).  With ``owner`` given the
    reservation auto-releases when the owner is collected."""
    _admit(component, int(nbytes))
    res = Reservation(component, nbytes)
    if owner is not None:
        res._finalizer = weakref.finalize(owner, _finalize_release, res)
    return res


def _finalize_release(res: Reservation) -> None:
    # module-level (not a bound method) so the finalizer holds no cycle
    res._finalizer = None
    res.release()


def transient(component: str, nbytes: int) -> Reservation:
    """Context-managed reservation for a stage's transient device
    buffers (encoder/decoder lane tables): admitted for the duration
    of the call, released on exit either way."""
    return reserve(component, nbytes)


# ---------------------------------------------------------------------------
# Footprint formulas — the known constants the admission check uses.
# These mirror the state NamedTuples field-by-field; a layout change
# that alters a dtype/lane set must update its formula (the checkpoint
# round-trip tests cover the same shapes).  Since round 13 the formulas
# are verified against XLA's OWN memory_analysis() at the costwatch
# canonical shapes (cli costs: membudget_crosscheck;
# tests/test_membudget_xla.py pins formula >= actual and <= 2x actual)
# instead of hand-derived lane nbytes alone.
# ---------------------------------------------------------------------------

# XLA's memory_analysis() reports a few dozen bytes of tuple/alignment
# overhead per state pytree beyond the raw lane nbytes (measured 24-104B
# across the six arena states at canonical shapes).  The formulas fold
# a flat allowance in so "formula >= XLA actual" holds exactly, not
# approximately.
_XLA_STATE_OVERHEAD = 512


def counter_arena_bytes(layout: str, num_windows: int, capacity: int,
                        pool_capacity: int | None = None) -> int:
    """packed: 24B/slot (base u64 + sq i64 + minmax u32 + pool_idx i32)
    + 44B per overflow-pool row (default P = max(64, W*C/16)) + the two
    i32 scalar lanes (pool_n, err); f64: 40B/slot (5 i64 lanes).  Both
    carry the per-slot i64 last_at."""
    wc = num_windows * capacity
    if layout == "packed":
        P = pool_capacity if pool_capacity is not None else max(64, wc // 16)
        return 24 * wc + 44 * P + 8 * capacity + 8 + _XLA_STATE_OVERHEAD
    return 40 * wc + 8 * capacity + _XLA_STATE_OVERHEAD


def gauge_arena_bytes(layout: str, num_windows: int, capacity: int) -> int:
    """56B/slot on both layouts (7 f64/i64 lanes) + per-slot last_at."""
    return 56 * num_windows * capacity + 8 * capacity + _XLA_STATE_OVERHEAD


def timer_arena_bytes(layout: str, num_windows: int, capacity: int,
                      sample_capacity: int) -> int:
    """packed: one u64 word per buffered sample; f64: 24B/slot moments
    + 12B (i32 slot + f64 value) per buffered sample.  Plus the
    per-window write heads and per-slot last_at."""
    W, C, S = num_windows, capacity, sample_capacity
    if layout == "packed":
        return 8 * W * S + 8 * W + 8 * C + _XLA_STATE_OVERHEAD
    return 24 * W * C + 12 * W * S + 8 * W + 8 * C + _XLA_STATE_OVERHEAD


def arena_bytes(layout: str, num_windows: int, capacity: int,
                sample_capacity: int) -> int:
    """Total device bytes of one (counter, gauge, timer) arena triple —
    the sum of the per-arena formulas above (the admission constants
    ISSUE 13 names: 24B/slot packed counter, 40B/slot f64)."""
    return (counter_arena_bytes(layout, num_windows, capacity)
            + gauge_arena_bytes(layout, num_windows, capacity)
            + timer_arena_bytes(layout, num_windows, capacity,
                                sample_capacity))


def buffer_bytes(num_windows: int, sample_capacity: int) -> int:
    """Series-buffer ring bytes: slot i32 + ts i64 + val f64 per
    (window, sample) plus the per-window i64 write heads."""
    return 20 * num_windows * sample_capacity + 8 * num_windows


# Per-datapoint TEMP coefficients for the codec passes, by placement /
# chains tail.  Derived from XLA memory_analysis temp bytes at the
# costwatch canonical shapes (S=256, T=128: encode gather 204 B/dp,
# scatter 168, pallas 216; decode fused 11, gather+jnp 85,
# gather+pallas 128) with ~25-30% headroom — the admission contract is
# formula >= XLA actual and <= 2x actual, pinned by
# tests/test_membudget_xla.py and surfaced per run in the COSTS
# artifact's membudget_crosscheck.
_ENCODE_TEMP_PER_DP = {"gather": 260, "scatter": 220, "pallas": 280}
_DECODE_TEMP_PER_DP = {"fused": 16, "gather": 110, "gather_pallas": 170}


def encode_lane_bytes(S: int, T: int, out_words: int,
                      place: str = "gather") -> int:
    """Transient device bytes of one encode pass through placement tail
    ``place``: the exact argument footprint (ts i64 + value bits u64 +
    valid bool + start i64), the exact output (words + total_bits +
    fallback), and a per-tail temp coefficient covering the (T, 4, S)
    lane tables, offset cumsums and (4T, S) fragment planes XLA
    actually materializes."""
    args = 17 * S * T + 8 * S
    out = 8 * S * out_words + 9 * S
    per_dp = _ENCODE_TEMP_PER_DP.get(place, _ENCODE_TEMP_PER_DP["pallas"])
    return args + out + per_dp * S * T


def decode_lane_bytes(S: int, W: int, max_points: int,
                      chains: str = "fused", extract: str = "jnp") -> int:
    """Transient device bytes of one decode pass through the ``chains``
    tail (``W`` = padded stream words per series): exact arguments
    (words + nbits + the 1MiB value-control table), exact outputs
    (ts i64 + payload u64 + meta u8 per point, plus err/prec/ann), and
    a per-tail temp coefficient for the phase-2 lane tables the gather
    tails materialize (the fused tail carries its chains in the scan
    and pays almost none)."""
    args = 8 * S * W + 8 * S + (1 << 20)
    out = 17 * S * max_points + 24 * S
    key = ("fused" if chains == "fused"
           else ("gather_pallas" if extract == "pallas" else "gather"))
    return args + out + _DECODE_TEMP_PER_DP[key] * S * max_points
