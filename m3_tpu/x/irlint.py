"""irlint: typed StableHLO/HLO-level rules over the device-program registry.

The stack already guards three layers — m3lint reads source AST,
tracewatch/hopwatch watch runtime, costwatch reduces compiled modules
to numeric fingerprints — and the bug classes that slipped through all
of them were IR-shaped: the silent i32→i64 cumsum promotion of PR 9
(a ±5%% costwatch bytes drift, not a named finding), the 1MB
``_VALUE_CTRL_TBL`` const-folded into every decode HLO in PR 7
(invisible to AST constant-bloat once a builder fn folds it), and
scatter ops creeping back into the "zero hot-path scatter" packed
arena of PR 8.  This pass closes the layer: it lowers every stage in
the costwatch registry (ShapeDtypeStructs only — no data, no
execution, no transfers; relay-independent by construction) through
the shared stage cache and runs typed rule families over the module
texts, reporting lint-shaped findings under the same empty-baseline
multiset ratchet as m3lint.

Rule families
-------------

* ``transfer-free``   — host custom-calls / infeed / outfeed / send /
  recv / host callbacks in any hot-path program.  The host-call
  whitelist is EMPTY; only known device directives (SPMD partitioner
  markers, Mosaic kernels) are exempt, so an unknown custom-call
  target is a finding until it is classified.
* ``scatter-budget``  — per-stage StableHLO scatter-op budget.  The
  packed arena allows only its bounded ``lax.cond`` promotion
  scatters, the encode ``scatter`` placement tail is whitelisted by
  stage name, everything else is 0.  Counted on the StableHLO
  (formulation level): CPU XLA happens to rewrite every scatter out of
  the optimized HLO, which would make a compiled-HLO census vacuously
  pass — and the formulation is what a TPU backend will lower.
* ``width-discipline`` — 64-bit tensor-type census (i64/ui64/f64
  tokens in the StableHLO) vs each stage's declared width contract;
  codec stages additionally forbid f64 outright.  A silent i32→i64 or
  f32→f64 promotion moves the census even when the op count does not.
* ``ir-const-bloat``  — constants ≥ threshold elements that XLA kept
  in the compiled module AFTER folding — the class AST constant-bloat
  cannot see once a builder fn folds them.
* ``residency-composition`` — the ROADMAP item-1 gate: the declared
  seam chain arena_ingest → window_drain → encode phase 1 → placement
  is probed as COMPOSED programs under ``jax.eval_shape`` (a host
  materialization in the glue raises ``TracerArrayConversionError`` —
  a typed, zero-execution proof of a host crossing), and every host
  crossing between adjacent stages is a finding.  The CURRENT
  crossings (e.g. the 583KB drain→encode re-upload recorded in
  PIPELINE_r13) are committed in the baseline artifact
  ``IRLINT_r17.json``; a new crossing FAILS; item 1 burns the list
  down to empty, re-baselining each win.

Honesty notes: scatter/width censuses are taken on the StableHLO the
CURRENT backend lowers — pallas stages lower in interpret mode off-TPU
(their clean-fallback contract), so their CPU budgets describe the
interpreter's formulation; the artifact pins (platform, jax version)
and the check refuses cross-platform comparison, and ``cli
tpu_backlog``'s irlint stage records the Mosaic-side findings
head-to-head when a relay window opens.

Run: ``python -m m3_tpu.tools.cli irlint [--json|--check [BASELINE]|
--explain RULE]``; see TESTING.md "IR lint & residency composition".
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Callable, Dict, List, NamedTuple

from m3_tpu.x import hlotext
from m3_tpu.x.lint.core import Finding

__all__ = [
    "CONST_BLOAT_MIN_ELEMENTS", "CONST_WHITELIST", "Crossing",
    "DEVICE_DIRECTIVE_TARGETS", "EXPLAIN", "PIPE", "ProgramIR", "RULES",
    "SCATTER_BUDGETS", "SCHEMA", "Seam", "SEAMS", "WIDE_FORBIDDEN",
    "WIDTH_CONTRACTS", "analyze_program", "build_artifact",
    "check_against_baseline", "check_artifact", "default_baseline_path",
    "program_ir", "residency_report",
]

SCHEMA = 1

RULES = ("transfer-free", "scatter-budget", "width-discipline",
         "ir-const-bloat", "residency-composition")


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parents[2] / "IRLINT_r17.json"


# ---------------------------------------------------------------------------
# Contracts.  Every registered stage MUST have a row in SCATTER_BUDGETS
# and WIDTH_CONTRACTS (tests pin table keys == costwatch.stage_names());
# a program that is NOT in the tables gets the zero contract — new
# stages start maximally strict and declare their budgets explicitly.
# All numbers are measured on (cpu, the pinned jax) at the costwatch
# canonical shapes; the artifact records both so the check can refuse
# a cross-platform comparison instead of mis-ratcheting it.
# ---------------------------------------------------------------------------

# Per-stage StableHLO scatter budgets (exact ceilings, census > budget
# is a finding).  Non-zero rows are the REVIEWED allowances:
#
# * arena/timer ingest stages: the bounded lax.cond promotion scatters
#   of the packed layout (PR 8's one sanctioned scatter class) and the
#   f64 oracle's slot-update scatters — per-lane, capacity-bounded;
# * encode/*: the stream-word placement tail — ``place="scatter"`` is
#   whitelisted by stage name per the costwatch registry, and every
#   placement variant carries the 2-scatter bounded carry promotion;
# * decode/gather_pallas: pallas interpret-mode internals on CPU (the
#   kernel itself has no scatter; Mosaic numbers land via tpu_backlog).
SCATTER_BUDGETS: Dict[str, int] = {
    "decode/fused": 0,
    "decode/gather": 0,
    "decode/gather_pallas": 4,
    "decode/sharded": 0,
    "encode/gather": 2,
    "encode/scatter": 6,
    "encode/pallas": 6,
    "encode/sharded": 2,
    "arena/rollup_ingest_packed": 32,
    "arena/counter_ingest_f64": 12,
    "arena/gauge_ingest_f64": 16,
    "arena/counter_consume_packed": 0,
    "arena/counter_consume_f64": 0,
    "arena/gauge_consume_packed": 0,
    "arena/gauge_consume_f64": 0,
    "timer/ingest_packed": 4,
    "timer/ingest_f64": 12,
    "timer/consume_packed": 0,
    "timer/consume_f64": 0,
}

# Per-stage 64-bit tensor-type token ceilings ({} entries implicitly 0
# for every wide type).  The codec's i64/ui64 budget is its DESIGN
# (i64 timestamps, u64 stream words); the contract catches the census
# GROWING — the shape a silent promotion takes.
WIDTH_CONTRACTS: Dict[str, Dict[str, int]] = {
    "decode/fused": {"i64": 229, "ui64": 661},
    "decode/gather": {"i64": 286, "ui64": 693},
    "decode/gather_pallas": {"i64": 301, "ui64": 710},
    "decode/sharded": {"i64": 248, "ui64": 674},
    "encode/gather": {"i64": 755, "ui64": 1701},
    "encode/scatter": {"i64": 734, "ui64": 1616},
    "encode/pallas": {"i64": 739, "ui64": 1620},
    "encode/sharded": {"i64": 773, "ui64": 1720},
    "arena/rollup_ingest_packed": {"i64": 2703, "ui64": 52, "f64": 1635},
    "arena/counter_ingest_f64": {"i64": 118},
    "arena/gauge_ingest_f64": {"i64": 173, "f64": 77},
    "arena/counter_consume_packed": {"i64": 162, "ui64": 11, "f64": 89},
    "arena/counter_consume_f64": {"i64": 84, "f64": 89},
    "arena/gauge_consume_packed": {"i64": 69, "f64": 105},
    "arena/gauge_consume_f64": {"i64": 65, "f64": 107},
    "timer/ingest_packed": {"i64": 125, "ui64": 40, "f64": 2},
    "timer/ingest_f64": {"i64": 148, "f64": 35},
    "timer/consume_packed": {"i64": 187, "ui64": 41, "f64": 1059},
    "timer/consume_f64": {"i64": 207, "f64": 170},
}

# Wide types a stage may not use AT ALL, regardless of ceiling: the
# codec's bit-exactness contract is integer/bit ops end to end — one
# f64 token in a decode/encode module is a correctness smell (an
# accidental float path through timestamps or value bits), not a
# budget question.
WIDE_FORBIDDEN: Dict[str, tuple] = {
    name: ("f64",) for name in WIDTH_CONTRACTS
    if name.startswith(("decode/", "encode/"))
}

WIDE_TYPES = ("i64", "ui64", "f64")

# Custom-call targets that are DEVICE directives, not host calls: the
# SPMD partitioner's sharding markers and the Mosaic/TPU kernel call.
# Everything else — including every callback flavor this jax emits
# (xla_python_cpu_callback etc.) — is a transfer-free finding.  The
# HOST whitelist is deliberately empty.
DEVICE_DIRECTIVE_TARGETS = frozenset({
    "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
    "tpu_custom_call", "annotate_device_placement",
})

_TRANSFER_OPS = ("infeed", "outfeed", "send", "recv")

CONST_BLOAT_MIN_ELEMENTS = 4096

# (stage, "dtype[shape]") -> reviewed rationale.  The irlint analogue
# of an m3lint inline suppression: the literal is load-bearing, the
# reason is recorded here AND in the artifact's suppressions section.
CONST_WHITELIST: Dict[tuple, str] = {
    ("arena/gauge_ingest_f64", "s32[8192]"):
        "descending-iota tie-breaker operand of the last-wins stable "
        "sort over the N=8192 ingest batch (gauge semantics: later "
        "sample wins the slot) — 32KB, batch-shaped not capacity-"
        "shaped, folded at trace time by design; reformulating it as a "
        "computed iota would move the frozen COSTS_r13 fingerprints "
        "for zero functional gain (reviewed round 17)",
}


# ---------------------------------------------------------------------------
# Rule engines.  Each takes a ProgramIR (any object with .name,
# .stablehlo, .hlo — costwatch.CompiledStage qualifies) and returns
# lint-core Findings keyed (rule, path=stage-name, message): line
# numbers are meaningless in generated IR, so key stability lives in
# the message strings, which are built ONLY from census numbers and
# contract values (deterministic per platform+jax pin).
# ---------------------------------------------------------------------------


class ProgramIR(NamedTuple):
    """One lowered program's texts, decoupled from the registry so the
    corpus tests can lint ad-hoc jitted programs."""

    name: str
    stablehlo: str
    hlo: str


def program_ir(name: str, lowered) -> ProgramIR:
    """Build a :class:`ProgramIR` from a ``jit(f).lower(...)`` result
    (compiles it — the corpus-test seam; registry programs come from
    the costwatch stage cache instead and compile once per process)."""
    return ProgramIR(name=name, stablehlo=lowered.as_text(),
                     hlo=lowered.compile().as_text())


def _find(rule: str, path: str, message: str) -> Finding:
    return Finding(rule, path, 0, message)


def rule_transfer_free(p) -> List[Finding]:
    out: List[Finding] = []
    targets: Dict[str, int] = {}
    for src in (hlotext.stablehlo_custom_call_targets(p.stablehlo),
                hlotext.custom_call_targets(p.hlo)):
        for t, n in src.items():
            targets[t] = max(targets.get(t, 0), n)
    for t in sorted(targets):
        if t in DEVICE_DIRECTIVE_TARGETS:
            continue
        out.append(_find(
            "transfer-free", p.name,
            f"host-side custom call target '{t}' in a hot-path program "
            "(host-call whitelist is empty; a device directive must be "
            "classified in DEVICE_DIRECTIVE_TARGETS)"))
    hist = hlotext.op_histogram(p.hlo, include_tuple_shaped=True)
    for op in _TRANSFER_OPS:
        n = hist.get(op, 0) + hlotext.stablehlo_op_count(p.stablehlo, op)
        if n:
            out.append(_find(
                "transfer-free", p.name,
                f"host transfer op '{op}' x{n} in a hot-path program"))
    return out


def rule_scatter_budget(p, budget=None) -> List[Finding]:
    if budget is None:
        budget = SCATTER_BUDGETS.get(p.name, 0)
    n = hlotext.stablehlo_op_count(p.stablehlo, "scatter")
    if n <= budget:
        return []
    return [_find(
        "scatter-budget", p.name,
        f"stablehlo.scatter census {n} exceeds the stage budget "
        f"{budget} (only reviewed bounded-promotion scatters are "
        "budgeted; everything else is 0)")]


def rule_width_discipline(p, contract=None, forbidden=None) -> List[Finding]:
    if contract is None:
        contract = WIDTH_CONTRACTS.get(p.name, {})
    if forbidden is None:
        forbidden = WIDE_FORBIDDEN.get(p.name, ())
    census = hlotext.stablehlo_type_census(p.stablehlo)
    out: List[Finding] = []
    for t in WIDE_TYPES:
        n = census.get(t, 0)
        if t in forbidden and n:
            out.append(_find(
                "width-discipline", p.name,
                f"forbidden wide type {t} present (census {n}) — this "
                "stage's contract is no-{t} (codec bit-exactness is "
                "integer/bit ops end to end)".replace("{t}", t)))
            continue
        ceil = int(contract.get(t, 0))
        if n > ceil:
            out.append(_find(
                "width-discipline", p.name,
                f"64-bit census {t} = {n} exceeds the declared width "
                f"contract {ceil} — a silent promotion "
                "(i32-to-i64 / f32-to-f64) widens the census before it "
                "moves any costwatch byte metric past tolerance"))
    return out


def rule_ir_const_bloat(p, min_elements=CONST_BLOAT_MIN_ELEMENTS,
                        whitelist=None):
    """Returns (findings, suppressions) — whitelisted literals are
    reported as applied suppressions, never silently dropped."""
    if whitelist is None:
        whitelist = CONST_WHITELIST
    out: List[Finding] = []
    sups: List[dict] = []
    for c in hlotext.folded_constants(p.hlo, min_elements):
        what = f"{c['dtype']}[{c['shape']}]"
        rationale = whitelist.get((p.name, what))
        if rationale is not None:
            sups.append({"rule": "ir-const-bloat", "stage": p.name,
                         "what": what, "elements": c["elements"],
                         "rationale": rationale})
            continue
        out.append(_find(
            "ir-const-bloat", p.name,
            f"folded constant {what} ({c['elements']} elements >= "
            f"{min_elements}) embedded in the compiled module — big "
            "literals belong in arguments (the PR 7 ctrl-table class), "
            "or in CONST_WHITELIST with a reviewed rationale"))
    return out, sups


def analyze_program(p, **overrides):
    """All four IR rules over one program: (findings, suppressions).
    ``overrides`` (budget / contract / forbidden / min_elements /
    whitelist) are the corpus-test seam."""
    findings = list(rule_transfer_free(p))
    findings += rule_scatter_budget(p, budget=overrides.get("budget"))
    findings += rule_width_discipline(
        p, contract=overrides.get("contract"),
        forbidden=overrides.get("forbidden"))
    cb, sups = rule_ir_const_bloat(
        p, min_elements=overrides.get(
            "min_elements", CONST_BLOAT_MIN_ELEMENTS),
        whitelist=overrides.get("whitelist"))
    findings += cb
    return findings, sups


# ---------------------------------------------------------------------------
# Residency composition — the item-1 gate.
#
# The declared chain is probed, not asserted: each seam's probe
# composes producer → glue → consumer under ``jax.eval_shape`` (shapes
# only — zero data, zero execution).  If the glue materializes a
# tracer on the host (the ``np.asarray`` in engine._emit / the hops
# tmat assembly), jax raises TracerArrayConversionError — a TYPED
# static proof of a host crossing.  A non-composed seam contributes
# its transfer ledger as findings: avals from eval_shape on the
# producer's outputs, multiplied by the PIPE window count, byte-exact
# against PIPELINE_r13's hop ledger (tests pin the equality).
# ---------------------------------------------------------------------------

# The `cli hops` pipeline geometry the crossings are declared at (NOT
# the costwatch canonical shapes: crossings are cross-checked against
# the committed PIPELINE artifact, which runs this geometry).
PIPE = {
    "S": 1024,              # series
    "T": 320,               # datapoints per series
    "resolution_s": 10,     # rollup window seconds
    "windows_drained": 33,  # closed windows the pass drains
    "W": 4,                 # arena window ring
    "C": 1024,              # arena slot capacity (1 << ceil(log2 S))
    "quantiles": [0.5, 0.95, 0.99],
}


class Crossing(NamedTuple):
    """One host crossing at a seam: a named array that leaves (d2h) or
    re-enters (h2d) the device between two chain stages."""

    direction: str      # "d2h" | "h2d"
    name: str           # e.g. "counter.lanes"
    dtype: str          # numpy dtype name
    shape: tuple
    bytes_each: int
    transfers: int      # per full pipeline pass
    via: str            # the glue site that forces the crossing

    @property
    def total_bytes(self) -> int:
        return self.bytes_each * self.transfers

    @property
    def message(self) -> str:
        dims = "x".join(str(d) for d in self.shape) or "scalar"
        return (f"{self.direction} {self.name} {self.dtype}[{dims}] "
                f"{self.bytes_each}B x{self.transfers} = "
                f"{self.total_bytes}B via {self.via}")


class Seam(NamedTuple):
    """One adjacency in the declared chain.  ``probe()`` returns
    ``(composed, evidence)``; ``crossings()`` is the transfer ledger
    charged when the probe says NOT composed (a composed seam charges
    nothing — that is how item 1 burns the list down)."""

    name: str
    producer: str
    consumer: str
    probe: Callable[[], tuple]
    crossings: Callable[[], List[Crossing]]


def _sds(shape, dtype):
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(shape, np.dtype(dtype))


def _aval_crossing(direction, name, aval, transfers, via) -> Crossing:
    import numpy as np

    dt = np.dtype(aval.dtype)
    size = int(dt.itemsize)
    for d in aval.shape:
        size *= int(d)
    return Crossing(direction=direction, name=name, dtype=dt.name,
                    shape=tuple(int(d) for d in aval.shape),
                    bytes_each=size, transfers=int(transfers), via=via)


def _probe_ingest_to_drain():
    """arena_ingest → window_drain: ingest's output STATE is consume's
    input state — composing them under eval_shape succeeds iff the
    ring stays device-resident across the seam (it does; the arena
    classes thread jax arrays, engine only materializes on emit)."""
    import jax

    from m3_tpu.aggregator import packed

    W, C, B = PIPE["W"], PIPE["C"], PIPE["S"]
    cs = jax.eval_shape(lambda: packed.counter_init(W, C))
    gs = jax.eval_shape(lambda: packed.gauge_init(W, C))

    def composed(cs, gs, idx, iv, fv, tm, w):
        cs2, gs2 = packed.rollup_ingest(cs, gs, idx, iv, fv, tm,
                                        num_windows=W, capacity=C)
        return (packed.counter_consume(cs2, w, capacity=C),
                packed.gauge_consume(gs2, w, capacity=C))

    try:
        jax.eval_shape(composed, cs, gs, _sds((B,), "int64"),
                       _sds((B,), "int64"), _sds((B,), "float64"),
                       _sds((B,), "int64"), _sds((), "int64"))
    except jax.errors.TracerArrayConversionError as e:
        return False, f"TracerArrayConversionError: {e}"
    return True, ("rollup_ingest -> consume composes under eval_shape: "
                  "the arena state pytree stays device-resident across "
                  "the seam")


def _probe_drain_to_encode():
    """window_drain → encode phase 1: the glue mirrors what the live
    pipeline does between them — engine._emit materializes drained
    lanes/counts with np.asarray, hops assembles host tmat/vmat
    matrices, encode_batch re-uploads.  Under eval_shape that
    np.asarray raises on the tracer: the typed proof this seam is NOT
    composed today (the exact gap ROADMAP item 1 closes)."""
    import jax
    import numpy as np

    from m3_tpu.aggregator import packed

    W, C = PIPE["W"], PIPE["C"]
    st = jax.eval_shape(lambda: packed.counter_init(W, C))

    def glued(st, w):
        lanes, counts = packed.counter_consume(st, w, capacity=C)
        # the live glue: engine._emit's host materialization, then the
        # hops-pass host matrix assembly feeding encode_batch
        lanes = np.asarray(lanes)
        counts = np.asarray(counts)
        return lanes.sum() + counts.sum()

    try:
        jax.eval_shape(glued, st, _sds((), "int64"))
    except jax.errors.TracerArrayConversionError:
        return False, ("TracerArrayConversionError composing consume "
                       "-> emit glue -> encode: engine._emit "
                       "np.asarray(lanes/counts) materializes the "
                       "drain on the host, and encode_batch re-uploads "
                       "host tmat/vmat (m3_tpu/tools/hops.py _run_pass)")
    return True, ("drain -> encode composes under eval_shape: the emit "
                  "glue no longer materializes on the host — "
                  "re-baseline the burned-down crossings")


def _probe_encode_to_placement():
    """encode phase 1 → placement: both phases live in ONE jitted
    program (``_encode_batch_device`` with its ``place=`` tail), so the
    seam is composed by construction; the probe lowers it at PIPE
    shapes to keep that an observation, not an assumption."""
    import jax

    from m3_tpu.encoding import m3tsz_jax as mj

    S, nw = PIPE["S"], PIPE["windows_drained"]
    out_words = max(16, nw * 40 // 64 + 8)

    def composed(ts, vb, start, valid):
        return mj._encode_batch_device(ts, vb, start, valid, unit=1,
                                       out_words=out_words,
                                       prefix_bits=None, place="gather")

    try:
        jax.eval_shape(composed, _sds((S, nw), "int64"),
                       _sds((S, nw), "uint64"), _sds((S,), "int64"),
                       _sds((S, nw), "bool"))
    except jax.errors.TracerArrayConversionError as e:
        return False, f"TracerArrayConversionError: {e}"
    return True, ("lane emission and word placement are one jitted "
                  "program (_encode_batch_device place tail)")


def _drain_crossings() -> List[Crossing]:
    """The drain→encode transfer ledger, derived (not hand-typed): d2h
    avals come from eval_shape on the consume programs at PIPE
    geometry × the drained-window count; h2d avals are the host
    matrices the hops pass assembles for encode_batch.  Tests pin the
    totals byte-exact against PIPELINE_r13's hop ledger."""
    import jax

    from m3_tpu.aggregator import packed

    W, C, nw = PIPE["W"], PIPE["C"], PIPE["windows_drained"]
    S = PIPE["S"]
    via_d2h = "engine._emit np.asarray on drained lanes/counts"
    via_h2d = "hops _run_pass encode_batch(host tmat/vmat) re-upload"
    # engine drains COUNTER, GAUGE, TIMER per closed window
    emitters = (
        ("counter", lambda: packed.counter_init(W, C),
         lambda st, w: packed.counter_consume(st, w, capacity=C)),
        ("gauge", lambda: packed.gauge_init(W, C),
         lambda st, w: packed.gauge_consume(st, w, capacity=C)),
        ("timer", lambda: packed.timer_init(W, C, 1 << 24),
         lambda st, w: packed.timer_consume(
             st, w, capacity=C, quantiles=tuple(PIPE["quantiles"]))),
    )
    out: List[Crossing] = []
    for kind, init, consume in emitters:
        st = jax.eval_shape(init)
        lanes, counts = jax.eval_shape(consume, st, _sds((), "int64"))
        out.append(_aval_crossing("d2h", f"{kind}.lanes", lanes, nw,
                                  via_d2h))
        out.append(_aval_crossing("d2h", f"{kind}.counts", counts, nw,
                                  via_d2h))
    for name, shape, dtype in (
            ("encode.ts", (S, nw), "int64"),
            ("encode.vbits", (S, nw), "uint64"),
            ("encode.valid", (S, nw), "bool"),
            ("encode.start", (S,), "int64")):
        out.append(_aval_crossing("h2d", name, _sds(shape, dtype), 1,
                                  via_h2d))
    return out


def _no_crossings() -> List[Crossing]:
    return []


SEAMS: tuple = (
    Seam("arena_ingest->window_drain", "arena_ingest", "window_drain",
         _probe_ingest_to_drain, _no_crossings),
    Seam("window_drain->encode_phase1", "window_drain", "encode_phase1",
         _probe_drain_to_encode, _drain_crossings),
    Seam("encode_phase1->placement", "encode_phase1", "placement",
         _probe_encode_to_placement, _no_crossings),
)

CHAIN = ("arena_ingest", "window_drain", "encode_phase1", "placement")


def residency_report():
    """(findings, seam_records): probe every declared seam; a
    non-composed seam charges its crossing ledger as findings."""
    findings: List[Finding] = []
    records: List[dict] = []
    for seam in SEAMS:
        composed, evidence = seam.probe()
        crossings = [] if composed else seam.crossings()
        for c in crossings:
            findings.append(_find("residency-composition",
                                  f"seam:{seam.name}", c.message))
        records.append({
            "seam": seam.name,
            "producer": seam.producer,
            "consumer": seam.consumer,
            "composed": bool(composed),
            "evidence": evidence,
            "crossings": [c._asdict() for c in crossings],
            "transfers": sum(c.transfers for c in crossings),
            "bytes": sum(c.total_bytes for c in crossings),
        })
    return findings, records


# ---------------------------------------------------------------------------
# Artifact + ratchet (the costs refusal discipline over the m3lint
# multiset diff)
# ---------------------------------------------------------------------------


def _platform() -> dict:
    import jax

    dev = jax.devices()[0]
    return {"platform": dev.platform, "device_kind": dev.device_kind,
            "devices": jax.device_count(), "jax": jax.__version__}


def build_artifact(stage_names=None, log=None) -> dict:
    """Lint the registry's IR (or a subset) + probe the residency
    chain, and assemble the IRLINT document.  Programs come from the
    costwatch stage cache: after a ``cli costs`` run in the same
    process this performs ZERO additional compiles."""
    from m3_tpu.x import costwatch

    def on_stage(name, seconds):
        if log is not None:
            log(f"irlint: {name} lowered in {seconds:.1f}s")

    findings: List[Finding] = []
    suppressions: List[dict] = []
    stages = costwatch.compiled_stages(stage_names, on_stage=on_stage)
    for name, cs in stages.items():
        f, s = analyze_program(cs)
        findings += f
        suppressions += s
    res_findings, seam_records = residency_report()
    findings += res_findings
    counts = {rule: 0 for rule in RULES}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "artifact": "IRLINT",
        "schema": SCHEMA,
        "generated_by": "python -m m3_tpu.tools.cli irlint",
        "config": dict(_platform(), canonical={
            k: (list(v) if isinstance(v, tuple) else v)
            for k, v in costwatch.CANONICAL.items()}, pipe=dict(PIPE)),
        "rules": list(RULES),
        "stages": sorted(stages),
        "counts": counts,
        "findings": [
            {"rule": f.rule, "path": f.path, "message": f.message}
            for f in sorted(findings)],
        "suppressions": suppressions,
        "residency": {"chain": list(CHAIN), "seams": seam_records},
    }


def _finding_objs(artifact: dict) -> List[Finding]:
    return [Finding(f["rule"], f["path"], 0, f["message"])
            for f in artifact.get("findings", [])]


def check_artifact(artifact: dict, baseline: dict) -> list:
    """The ratchet: typed refusals first (comparing across a schema /
    platform / jax / geometry change would mis-attribute legitimate IR
    movement to a rule violation), then the m3lint multiset diff over
    finding keys — a new finding fails, a stale baseline entry fails
    the other way (an improvement must re-baseline so the ratchet only
    ever tightens; item 1 burns the residency section down this way)."""
    from m3_tpu.x.lint.core import diff_baseline

    errs: list = []

    def err(kind, msg, **extra):
        errs.append(dict({"kind": kind, "message": msg}, **extra))

    if baseline.get("schema") != artifact.get("schema"):
        err("schema", f"schema mismatch: baseline "
            f"{baseline.get('schema')} vs current "
            f"{artifact.get('schema')} — regenerate the baseline")
        return errs
    for key, kind, why in (
            ("platform", "platform",
             "IR censuses only ratchet within one backend (the Mosaic "
             "lowering of the same registry is a head-to-head, see cli "
             "tpu_backlog)"),
            ("jax", "jax-version",
             "an XLA/jaxlib upgrade legitimately moves lowered IR; "
             "re-baseline (cli irlint --out) in a dedicated PR")):
        b = baseline.get("config", {}).get(key)
        c = artifact.get("config", {}).get(key)
        if b != c:
            err(kind, f"{key} mismatch: baseline {b!r} vs current {c!r}"
                f" — {why}")
            return errs
    for key in ("canonical", "pipe"):
        b = baseline.get("config", {}).get(key)
        c = artifact.get("config", {}).get(key)
        if b != c:
            err("config", f"{key} geometry changed: baseline {b} vs "
                f"current {c} — pinned shapes moved; re-baseline "
                "deliberately")
            return errs

    new, fixed = diff_baseline(_finding_objs(artifact),
                               _finding_objs(baseline))
    for f in new:
        err("new-finding", f"[{f.rule}] {f.path}: {f.message}",
            rule=f.rule, path=f.path)
    for f in fixed:
        err("stale-baseline", f"[{f.rule}] {f.path}: baseline entry no "
            f"longer fires ({f.message}) — commit the improvement: cli "
            "irlint --out and re-baseline", rule=f.rule, path=f.path)
    return errs


def check_against_baseline(artifact: dict, baseline_path) -> list:
    base = json.loads(Path(baseline_path).read_text())
    return check_artifact(artifact, base)


# ---------------------------------------------------------------------------
# --explain
# ---------------------------------------------------------------------------

EXPLAIN = {
    "transfer-free": {
        "why": (
            "The hot path's contract is device-resident end to end: a "
            "host callback, infeed/outfeed, or send/recv inside a "
            "registered program is a synchronous host round-trip per "
            "dispatch — the exact class hopwatch meters at runtime, "
            "caught here at lower time with the whitelist EMPTY.  Only "
            "classified device directives (SPMD partitioner markers, "
            "Mosaic kernel calls) are exempt."),
        "bad": ("jax.pure_callback(np_fn, aval, x) inside a registered "
                "stage -> custom-call target 'xla_python_cpu_callback' "
                "in both module texts"),
        "good": ("keep host work outside the jitted program (the "
                 "engine drain/emit seam), or land it as a device "
                 "kernel and classify the target"),
    },
    "scatter-budget": {
        "why": (
            "PR 8 rebuilt the arena around 'zero hot-path scatter'; "
            "the survivors are the bounded lax.cond promotion "
            "scatters, and encode's scatter placement tail is "
            "whitelisted by stage name.  Budgets are exact ceilings on "
            "the StableHLO census — compiled CPU HLO is vacuous here "
            "(XLA rewrites every scatter away on cpu), and the "
            "formulation is what a TPU backend lowers."),
        "bad": ("state.at[idx].add(v) creeping into a consume stage: "
                "stablehlo.scatter census 1 > budget 0"),
        "good": ("dense one-hot/segment formulations (the PR 8 "
                 "rewrite), or a reviewed budget row in "
                 "irlint.SCATTER_BUDGETS with the bound's rationale"),
    },
    "width-discipline": {
        "why": (
            "PR 9's i32->i64 cumsum promotion cost a silent 2x on a "
            "lane buffer and surfaced only as a costwatch bytes drift "
            "within tolerance.  Each stage declares its 64-bit census "
            "ceiling (i64/ui64/f64 tensor-type tokens in the "
            "StableHLO); codec stages forbid f64 outright — timestamps "
            "and value bits are integer/bit ops end to end, so ANY f64 "
            "token there is an accidental float path."),
        "bad": ("jnp.cumsum(i32_lanes) without dtype= -> i64 census "
                "jumps past the stage ceiling"),
        "good": ("jnp.cumsum(x, dtype=jnp.int32), explicit dtypes at "
                 "every accumulation seam (the m3lint explicit-dtype "
                 "rule's IR-level twin)"),
    },
    "ir-const-bloat": {
        "why": (
            "PR 7 found the 1MB decode control table const-folded into "
            "every decode module.  AST-level constant-bloat cannot see "
            "a literal once a builder fn folds it; this rule censuses "
            "the COMPILED module's constants >= 4096 elements, so the "
            "class is caught wherever it is produced.  Whitelisting is "
            "by (stage, dtype[shape]) with a reviewed rationale, "
            "recorded in the artifact's suppressions section."),
        "bad": ("tbl = jnp.asarray(np.arange(65536)) inside a jitted "
                "builder -> s32[65536] constant in the compiled HLO"),
        "good": ("pass big tables as arguments (device-placed once, "
                 "like _VALUE_CTRL_TBL after PR 7), or whitelist with "
                 "rationale in irlint.CONST_WHITELIST"),
    },
    "residency-composition": {
        "why": (
            "ROADMAP item 1 rebuilds wire->rollup->encode->flush "
            "device-resident.  This rule declares that chain as seams "
            "and PROBES each one under jax.eval_shape: composing "
            "producer -> live glue -> consumer either traces through "
            "(composed: state never leaves the device) or raises "
            "TracerArrayConversionError at the host materialization — "
            "a typed, zero-execution proof of a crossing.  Current "
            "crossings (the drain's 8.1MB d2h and the 583KB encode "
            "re-upload, byte-exact vs PIPELINE_r13) are committed in "
            "IRLINT_r17.json; new crossings FAIL; item 1 burns the "
            "list to empty, re-baselining each win."),
        "bad": ("lanes = np.asarray(consume(state, w)) between two "
                "chain stages -> every drained array becomes a d2h "
                "crossing finding"),
        "good": ("feed consume's output avals straight into the next "
                 "stage's jitted program (one composed module, the "
                 "item-1 shape) and re-baseline the burned-down list"),
    },
}
