"""Runtime lock-order sanitizer: the dynamic half of the race tier.

CPython has no ``-race``; ``tests/test_race.py`` asserts conservation
invariants but a latent lock-order inversion only trips when the
scheduler happens to interleave the two acquire chains — the classic
deadlock that survives a thousand green runs.  This module makes
ordering violations deterministic: while armed, every lock created via
``threading.Lock()``/``threading.RLock()`` is wrapped, each thread's
held-lock stack is tracked, and the cross-thread acquisition graph
(``A held while acquiring B`` ⇒ edge A→B) is checked on every NEW edge.
An edge that closes a cycle is an inversion: some execution acquired
A→B, this one acquires B→A, and the interleaving of the two deadlocks.
The report carries BOTH stacks — the recorded stack of the first
ordering and the live stack of the reversal — and fails fast
(:class:`LockOrderError`) in the acquiring thread *before* blocking.

Arming (mirrors ``M3_FAULTPOINTS``):

* code — ``lockcheck.install()`` / ``lockcheck.uninstall()`` (the
  race/dtest conftest fixture);
* env — ``M3_LOCKCHECK=1`` arms at import (``m3_tpu.x`` imports this
  module, so dtest node subprocesses inherit arming through their
  environment exactly like faultpoints).

Scope and honesty notes:

* Only locks CREATED while armed are tracked (the factory is swapped,
  existing lock objects are untouched).  The fixture installs before
  the test body, so every lock the test constructs is covered; library
  singletons created at import time are not.
* Edges are keyed per lock *instance* — two different instance pairs
  acquired in opposite orders are different edges, so there are no
  false cycles from unrelated objects sharing a class.
* A lock acquired in one thread and released in another (legal, rare)
  leaves a stale held-stack entry; the release side ignores it.  If
  such a handoff ever produced a spurious edge, suppress by acquiring
  via the raw ``_thread`` primitives.
* Only unbounded blocking acquires participate in ordering checks:
  trylocks and timeout-bounded acquires cannot deadlock (they are
  often deliberate inversion-avoidance back-off) and record no edges.
* Wrapped locks keep working after ``uninstall()`` — bookkeeping
  beyond the held-stack push/pop is gated on the armed flag.
"""

from __future__ import annotations

import itertools
import os
import threading
import traceback
import weakref
import _thread
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Set, Tuple

__all__ = [
    "LockOrderError", "LockInversion", "install", "uninstall", "reset",
    "installed", "findings", "sanitized_lock", "sanitized_rlock",
]

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

_armed = False
_raise_on_cycle = True
_seq = itertools.count(1)

# registry state, guarded by a RAW lock (never a wrapped one)
_mu = _thread.allocate_lock()
_adj: Dict[int, Set[int]] = {}                  # a -> {b}: a held while acquiring b
_edge_stacks: Dict[Tuple[int, int], str] = {}   # (a, b) -> acquisition stack
_names: Dict[int, str] = {}                     # seq -> "kind @ file:line"
_findings: List["LockInversion"] = []
_reported: Set[tuple] = set()                   # dedup: cycle seq paths
# seqs of GC'd wrapper locks, drained under _mu.  The weakref finalizer
# appends WITHOUT taking _mu (deque.append is atomic): a finalizer can
# fire from an allocation made while _mu is already held by this very
# thread, and a raw lock is not reentrant.
_dead: deque = deque()

_tls = threading.local()


def _prune_dead_locked() -> None:
    """Drop registry entries for GC'd locks.  Caller holds _mu."""
    while _dead:
        seq = _dead.popleft()
        _names.pop(seq, None)
        _adj.pop(seq, None)
        for peers in _adj.values():
            peers.discard(seq)
        for key in [k for k in _edge_stacks if seq in k]:
            del _edge_stacks[key]


class LockOrderError(RuntimeError):
    """Raised in the acquiring thread when a new edge closes a cycle —
    BEFORE the real acquire, so the sanitizer reports instead of
    deadlocking."""


@dataclass
class LockInversion:
    """One detected inversion: this thread acquired ``cycle[0]`` while
    holding ``cycle[-1]``, and recorded edges already chain
    ``cycle[0]`` → ... → ``cycle[-1]``."""

    cycle: Tuple[str, ...]          # lock names along the existing path
    forward_stack: str              # stack that recorded the first edge
    reversal_stack: str             # live stack performing the reversal
    thread: str

    def __str__(self) -> str:
        chain = " -> ".join(self.cycle)
        return (
            f"lock-order inversion in {self.thread}: acquiring "
            f"{self.cycle[0]} while holding {self.cycle[-1]}, but the "
            f"order {chain} was already established\n"
            f"--- stack that established {self.cycle[0]} -> "
            f"{self.cycle[1]} ---\n{self.forward_stack}"
            f"--- stack performing the reversal ---\n{self.reversal_stack}"
        )


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _creation_site() -> str:
    # nearest frame outside this module and threading.py
    for frame in reversed(traceback.extract_stack(limit=12)[:-2]):
        fn = frame.filename
        if not (fn.endswith("lockcheck.py") or fn.endswith("threading.py")):
            return f"{fn}:{frame.lineno}"
    return "<unknown>"


def _find_path(src: int, dst: int) -> list | None:
    """DFS path src → dst over recorded edges (iterative; graphs are
    tiny — a handful of locks per scenario)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _adj.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _before_acquire(seq: int) -> None:
    """Record edges held→seq and fail fast on a cycle.  Runs BEFORE the
    real acquire so the inversion is reported, not deadlocked on."""
    held = _held()
    if not held or seq in held:
        return  # nothing held, or re-entrant RLock acquire
    cur_stack = None
    for holder in dict.fromkeys(held):  # preserve order, dedup
        if holder == seq:
            continue
        with _mu:
            _prune_dead_locked()
            if seq in _adj.get(holder, ()):
                continue  # known-good edge
            path = _find_path(seq, holder)
            if path is not None:
                key = tuple(path)
                if key in _reported:
                    if _raise_on_cycle:
                        raise LockOrderError(
                            f"lock-order inversion (repeat): "
                            f"{' -> '.join(_names.get(s, '?') for s in path)}")
                    continue  # record mode: one finding per cycle
                _reported.add(key)
                if cur_stack is None:
                    cur_stack = "".join(traceback.format_stack(limit=24)[:-2])
                inv = LockInversion(
                    cycle=tuple(_names.get(s, f"lock#{s}") for s in path),
                    forward_stack=_edge_stacks.get(
                        (path[0], path[1]), "<stack unavailable>"),
                    reversal_stack=cur_stack,
                    thread=threading.current_thread().name,
                )
                _findings.append(inv)
                if _raise_on_cycle:
                    raise LockOrderError(str(inv))
                continue
            if cur_stack is None:
                cur_stack = "".join(traceback.format_stack(limit=24)[:-2])
            _adj.setdefault(holder, set()).add(seq)
            _edge_stacks[(holder, seq)] = cur_stack


class _SanitizedLock:
    """Wrapper over a raw lock; ``_kind`` distinguishes Lock/RLock for
    the self-deadlock check.  Unknown attributes (``_is_owned``,
    ``_acquire_restore``...) forward to the inner lock so
    ``threading.Condition`` keeps its RLock fast paths."""

    _kind = "Lock"

    def __init__(self, inner):
        self._inner = inner
        self._seq = next(_seq)
        with _mu:
            _names[self._seq] = f"{self._kind}@{_creation_site()}"
        # registry entries die with the lock (env-armed long-lived
        # processes create locks per connection/thread forever); the
        # finalizer only touches the lock-free dead queue
        weakref.finalize(self, _dead.append, self._seq)

    def acquire(self, blocking=True, timeout=-1):
        # Only unbounded blocking acquires participate in ordering:
        # a trylock (blocking=False) or timeout-bounded acquire cannot
        # deadlock — it is often the back-off half of a deliberate
        # inversion-avoidance pattern, and recording its edges would
        # both false-positive here and poison the graph for later
        # legitimate blocking acquires.
        if _armed and blocking and timeout < 0:
            if self._kind == "Lock" and self._seq in _held():
                inv = LockInversion(
                    cycle=(_names.get(self._seq, "?"),) * 2,
                    forward_stack="<self-deadlock: same non-reentrant "
                                  "lock>\n",
                    reversal_stack="".join(
                        traceback.format_stack(limit=24)[:-1]),
                    thread=threading.current_thread().name,
                )
                with _mu:
                    _findings.append(inv)
                # ALWAYS raise, even in record mode: unlike an order
                # inversion (which only deadlocks under the adverse
                # interleaving), re-acquiring a held non-reentrant lock
                # hangs this thread with CERTAINTY — proceeding would
                # convert the report into the deadlock it reports.
                raise LockOrderError(str(inv))
            else:
                _before_acquire(self._seq)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _held().append(self._seq)
        return ok

    def release(self):
        self._inner.release()
        held = _held()
        # remove the most recent occurrence; tolerate cross-thread
        # releases (entry simply isn't in this thread's stack)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self._seq:
                del held[i]
                break

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<sanitized {self._inner!r}>"

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _SanitizedRLock(_SanitizedLock):
    _kind = "RLock"


def sanitized_lock():
    return _SanitizedLock(_ORIG_LOCK())


def sanitized_rlock():
    return _SanitizedRLock(_ORIG_RLOCK())


def install(raise_on_cycle: bool = True) -> None:
    """Swap the ``threading.Lock``/``RLock`` factories and start
    checking.  Idempotent."""
    global _armed, _raise_on_cycle
    _raise_on_cycle = raise_on_cycle
    threading.Lock = sanitized_lock
    threading.RLock = sanitized_rlock
    _armed = True


def uninstall() -> None:
    """Restore the factories and stop checking (already-wrapped locks
    keep working, unchecked)."""
    global _armed
    _armed = False
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK


def installed() -> bool:
    return _armed


def reset() -> None:
    """Clear the acquisition graph and findings (per-test hygiene: a
    fresh test's lock instances are fresh seqs, but module-singleton
    locks would otherwise accumulate edges across tests)."""
    with _mu:
        _adj.clear()
        _edge_stacks.clear()
        _findings.clear()
        _reported.clear()
        _prune_dead_locked()


def findings() -> List[LockInversion]:
    with _mu:
        return list(_findings)


# dtest node subprocesses inherit arming through their environment,
# exactly like M3_FAULTPOINTS (m3_tpu.x imports this module).
if os.environ.get("M3_LOCKCHECK"):
    install(raise_on_cycle=os.environ.get("M3_LOCKCHECK") != "record")
