"""SLO-burn-driven self-healing: the guarded control plane.

PRs 10-17 built every sensor (selfmon burn-rate verdicts queryable in
PromQL, devguard stage breakers, membudget gauges) and every actuator
(admission slots, ingest backoff, membudget budgets, the
TopologyWatcher/ShardMigrator path) — but no wire connected them: a
sustained fault degraded the node until a human read ``/health`` and
turned a knob.  This module is that wire, built SRE-workbook style
(multi-window multi-burn-rate mitigation, the same framework
``query/slo.py``'s rules implement) with SALSA-style self-adjustment
(arXiv:2102.12531) as the precedent for state that resizes itself under
observed load.  Guardrails ARE the feature:

* **Typed actuators.**  Every mutable knob is an :class:`Actuator`
  with declared bounds — ``baseline`` (the configured resting value),
  ``shed_limit`` (the furthest mitigation may push it) and ``step``
  (one tick's movement).  Every application is clamped to
  ``[lo, hi] = sorted(baseline, shed_limit)``; nothing the controller
  does can leave the declared envelope.  The m3lint ``actuator-typed``
  rule makes this the ONLY legal mutation path (the placement-cas
  pattern for control state).
* **Hysteresis + hold.**  A binding fires only after ``fire_ticks``
  CONSECUTIVE firing verdicts and relaxes only after ``clear_ticks``
  consecutive ticks with burn at or below ``clear_burn`` (distinct
  thresholds: the SLO fires on ``factor x budget``, the controller
  clears strictly below it) AND after ``hold_ticks`` post-action hold
  — a flapping verdict moves nothing.
* **Rate limit.**  Each actuator moves at most once per
  ``min_interval_s`` (wall clock, injectable), shed or relax.
* **Unknown means HOLD.**  A rule whose verdict is missing, errored
  (``burn: None``) or NaN — PR 14's explicit-unknown contract — freezes
  its binding exactly where it is: no shed, no relax, counted
  ``held_unknown``.  A controller acting on data it does not have is
  worse than no controller.
* **Half-open relax.**  Recovery is x/breaker's half-open discipline
  applied to levels: one probe step back toward baseline per qualifying
  tick; a re-firing verdict re-sheds immediately (the probe failed),
  a quiet one keeps stepping until every actuator rests at baseline.
* **Every decision is a series.**  Each action updates a
  ``controller_action{rule=,actuator=,action=}`` gauge (value = the
  level after the action), which the next selfmon scrape stores into
  ``_m3_selfmon`` — the controller's behavior is retro-queryable PromQL
  exactly like the SLOs that drive it.  Gauges are interned lazily on
  FIRST action, so a healthy run emits zero ``controller_action``
  series (the tier-1 quiet invariant pins exactly that).

The controller reads verdicts from the node's own
:class:`~m3_tpu.query.slo.SLOEvaluator` (fresh each pass: the mediator
runs the controller stage right after ``selfmon.tick``), and —
for bindings that demand SUSTAINED burn (the placement rebalance) —
re-reads the stored burn history through the ordinary PromQL engine
under an ``x/deadline`` budget (:class:`BurnHistory`).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, Tuple

import numpy as np

from m3_tpu.x import deadline as xdeadline
from m3_tpu.x.deadline import Deadline

__all__ = [
    "Actuator", "ActuatorRegistry", "Binding", "BurnHistory", "Controller",
    "admission_actuator", "ingest_backoff_actuator", "membudget_actuator",
    "devguard_fallback_actuator", "checkpoint_actuator",
    "rebalance_actuator", "emergency_cleanup_actuator",
]


@dataclasses.dataclass
class Actuator:
    """One typed, bounds-clamped knob.

    ``apply(value)`` performs the mutation (the ONLY place the
    underlying limit/budget/flag is touched — the actuator-typed lint
    rule enforces that).  Level actuators step between ``baseline`` and
    ``shed_limit``; ``pulse`` actuators (checkpoint save, rebalance
    tick) fire ``apply`` as a one-shot on every shed and have nothing
    to relax — they always rest at baseline.
    """

    name: str
    kind: str                      # "admission"|"ingest"|"membudget"|...
    baseline: float
    shed_limit: float
    step: float
    apply: Callable[[float], None]
    pulse: bool = False
    unit: str = ""                 # for status()/docs readability

    def __post_init__(self):
        if not self.name:
            raise ValueError("actuator needs a name")
        if self.step <= 0:
            raise ValueError(f"actuator {self.name}: step must be > 0")
        self.lo = min(self.baseline, self.shed_limit)
        self.hi = max(self.baseline, self.shed_limit)
        self.value = float(self.baseline)
        self.sheds = 0
        self.relaxes = 0

    def clamp(self, v: float) -> float:
        return min(self.hi, max(self.lo, v))

    @property
    def at_baseline(self) -> bool:
        return self.pulse or self.value == self.baseline

    def _move(self, target: float) -> float | None:
        """One clamped step toward ``target``; returns the new value or
        None when already there (no mutation, no emission)."""
        if self.value == target:
            return None
        step = self.step if target > self.value else -self.step
        new = self.clamp(self.value + step)
        # overshoot lands exactly on the target bound
        if (step > 0) != (new <= target):
            new = target
        if new == self.value:
            return None
        self.apply(new)
        self.value = new
        return new

    def shed(self) -> float | None:
        """One step toward ``shed_limit`` (pulse: fire the one-shot).
        Returns the applied value, or None when nothing moved."""
        if self.pulse:
            self.apply(self.shed_limit)
            self.sheds += 1
            return self.shed_limit
        new = self._move(self.shed_limit)
        if new is not None:
            self.sheds += 1
        return new

    def relax(self) -> float | None:
        """One half-open probe step back toward ``baseline``."""
        if self.pulse:
            return None
        new = self._move(self.baseline)
        if new is not None:
            self.relaxes += 1
        return new

    def snapshot(self) -> dict:
        out = {
            "kind": self.kind,
            "baseline": self.baseline,
            "shed_limit": self.shed_limit,
            "step": self.step,
            "value": self.value,
            "at_baseline": self.at_baseline,
            "sheds": self.sheds,
            "relaxes": self.relaxes,
        }
        if self.pulse:
            out["pulse"] = True
        if self.unit:
            out["unit"] = self.unit
        return out


class ActuatorRegistry:
    """Name-keyed actuator set; the controller acts ONLY through it."""

    def __init__(self, actuators: Iterable[Actuator] = ()):
        self._acts: Dict[str, Actuator] = {}
        for a in actuators:
            self.register(a)

    def register(self, act: Actuator) -> Actuator:
        if act.name in self._acts:
            raise ValueError(f"duplicate actuator {act.name!r}")
        self._acts[act.name] = act
        return act

    def get(self, name: str) -> Actuator:
        return self._acts[name]

    def names(self) -> list:
        return sorted(self._acts)

    def __contains__(self, name: str) -> bool:
        return name in self._acts

    def snapshot(self) -> dict:
        return {n: a.snapshot() for n, a in sorted(self._acts.items())}


@dataclasses.dataclass(frozen=True)
class Binding:
    """One SLO rule wired to a set of actuators with its hysteresis."""

    rule: str                      # SLO rule name (query/slo.py)
    actuators: Tuple[str, ...]     # ActuatorRegistry names
    name: str = ""                 # unique; defaults to the rule name
    fire_ticks: int = 2            # consecutive firing verdicts to act
    clear_ticks: int = 3           # consecutive clear verdicts to relax
    clear_burn: float = 1.0        # burn multiple at/under which "clear"
    hold_ticks: int = 2            # post-shed ticks before relax starts
    # sustained-burn demand (the rebalance binding): shed additionally
    # requires min_over_time(burn[window]) >= sustain_burn from the
    # stored history — unknown history HOLDs like an unknown verdict
    sustain_window: str = ""
    sustain_burn: float = 0.0

    def __post_init__(self):
        if not self.rule:
            raise ValueError("binding needs a rule name")
        if not self.actuators:
            raise ValueError(f"binding {self.rule}: needs actuators")
        if self.fire_ticks < 1 or self.clear_ticks < 1:
            raise ValueError(
                f"binding {self.rule}: fire_ticks/clear_ticks must be >= 1")
        if self.hold_ticks < 0:
            raise ValueError(f"binding {self.rule}: hold_ticks must be >= 0")
        if self.clear_burn <= 0:
            raise ValueError(f"binding {self.rule}: clear_burn must be > 0")
        if not self.name:
            object.__setattr__(self, "name", self.rule)


class BurnHistory:
    """Sustained-burn reads over the STORED ``slo_burn`` history,
    through the ordinary PromQL engine under an ``x/deadline`` budget —
    the same retro-query an operator would issue.  Any failure (empty
    history, deadline, engine error) returns None: unknown, which the
    controller treats as HOLD."""

    def __init__(self, engine, metric: str = "m3tpu_slo_burn",
                 deadline_s: float = 1.0):
        self.engine = engine
        self.metric = metric
        self.deadline_s = float(deadline_s)

    def min_burn(self, rule: str, window: str,
                 now_nanos: int) -> float | None:
        """min-over-window burn for ``rule`` (worst instance): the
        burn multiple the rule NEVER dropped below across the window —
        the sustained-burn witness."""
        q = f'min_over_time({self.metric}{{rule="{rule}"}}[{window}])'
        try:
            with xdeadline.bind(Deadline(self.deadline_s)):
                block = self.engine.execute_instant(q, now_nanos)
            vals = np.asarray(block.values)
            if vals.size == 0:
                return None
            col = vals[:, -1]
            finite = col[~np.isnan(col)]
            if finite.size == 0:
                return None
            return float(finite.max())
        except Exception:  # noqa: BLE001 — unknown history means HOLD
            return None


class _BindingState:
    __slots__ = ("firing_streak", "clear_streak", "hold_left",
                 "held_unknown", "engaged")

    def __init__(self):
        self.firing_streak = 0
        self.clear_streak = 0
        self.hold_left = 0
        self.held_unknown = 0
        self.engaged = False


def _unknown(burn, firing) -> bool:
    return (firing is None or burn is None
            or (isinstance(burn, float) and math.isnan(burn)))


class Controller:
    """The mediator-tick control loop.

    ``burn_source()`` returns the SLO status document
    (``SLOEvaluator.status()``'s shape: ``{"rules": {name: {burn,
    firing, ...}}}``); ``clock`` is injectable for the fake-clock test
    matrix.  ``tick(now_nanos)`` runs one pass and returns its stats —
    the mediator records them like any other stage.  ``status()`` is
    the ``/health`` ``controller`` section (lock-cheap, no queries).
    """

    def __init__(self, registry: ActuatorRegistry,
                 bindings: Iterable[Binding],
                 burn_source: Callable[[], dict],
                 clock: Callable[[], float] = time.monotonic,
                 instrument=None, min_interval_s: float = 5.0,
                 history: BurnHistory | None = None):
        self.registry = registry
        self.bindings: Tuple[Binding, ...] = tuple(bindings)
        names = [b.name for b in self.bindings]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate binding names {names}")
        for b in self.bindings:
            for a in b.actuators:
                if a not in registry:
                    raise ValueError(
                        f"binding {b.name}: unknown actuator {a!r}")
        self.burn_source = burn_source
        self.min_interval_s = float(min_interval_s)
        self.history = history
        self._clock = clock
        self._scope = instrument
        self._gauges: dict = {}   # (rule, actuator, action) -> gauge,
        #                           interned lazily on FIRST action so a
        #                           quiet controller stores zero series
        self._lock = threading.Lock()
        self._states = {b.name: _BindingState() for b in self.bindings}
        self._last_action: Dict[str, float] = {}  # actuator -> clock()
        self.ticks = 0
        self.actions_total = 0
        self.held_unknown = 0
        self.rate_limited = 0
        self.actions = deque(maxlen=256)

    # -- emission ----------------------------------------------------------

    def _emit(self, rule: str, actuator: str, action: str,
              value: float) -> None:
        # reached only from tick(), which holds _lock for the whole pass
        self.actions_total += 1  # m3lint: disable=lock-discipline
        self.actions.append({
            "unix": round(time.time(), 3), "rule": rule,
            "actuator": actuator, "action": action,
            "value": round(float(value), 6),
        })
        if self._scope is None:
            return
        key = (rule, actuator, action)
        g = self._gauges.get(key)
        if g is None:
            # tag values are config-bounded (rules x actuators x two
            # verbs), never request-derived
            g = self._scope.tagged({
                "rule": rule, "actuator": actuator, "action": action,
            }).gauge("controller_action")
            self._gauges[key] = g
        g.update(float(value))

    def _allowed(self, actuator: str) -> bool:
        last = self._last_action.get(actuator)
        if last is not None and self._clock() - last < self.min_interval_s:
            # reached only from tick(), which holds _lock for the pass
            self.rate_limited += 1  # m3lint: disable=lock-discipline
            return False
        return True

    # -- the pass ----------------------------------------------------------

    def tick(self, now_nanos: int | None = None) -> dict:
        if now_nanos is None:
            now_nanos = time.time_ns()
        with self._lock:
            self.ticks += 1
            doc = self.burn_source() or {}
            rules = doc.get("rules", {}) or {}
            stats = {"sheds": 0, "relaxes": 0, "held_unknown": 0,
                     "rate_limited_before": self.rate_limited}
            for b in self.bindings:
                st = self._states[b.name]
                verdict = rules.get(b.rule)
                burn = verdict.get("burn") if verdict else None
                firing = verdict.get("firing") if verdict else None
                if _unknown(burn, firing):
                    # explicit-unknown contract: freeze the binding
                    st.held_unknown += 1
                    self.held_unknown += 1
                    stats["held_unknown"] += 1
                    continue
                if firing:
                    st.firing_streak += 1
                    st.clear_streak = 0
                    if st.firing_streak >= b.fire_ticks:
                        stats["sheds"] += self._shed(b, st, now_nanos)
                else:
                    st.firing_streak = 0
                    if burn <= b.clear_burn:
                        st.clear_streak += 1
                    else:
                        st.clear_streak = 0
                    if st.hold_left > 0:
                        st.hold_left -= 1
                    elif st.engaged and st.clear_streak >= b.clear_ticks:
                        stats["relaxes"] += self._relax(b, st)
                st.engaged = any(
                    not self.registry.get(a).at_baseline
                    for a in b.actuators)
            stats["rate_limited"] = (self.rate_limited
                                     - stats.pop("rate_limited_before"))
            return stats

    def _shed(self, b: Binding, st: _BindingState, now_nanos: int) -> int:
        if b.sustain_window:
            sustained = (self.history.min_burn(b.rule, b.sustain_window,
                                               now_nanos)
                         if self.history is not None else None)
            if sustained is None:
                # no queryable history yet: unknown, HOLD (reached only
                # from tick(), which holds _lock for the whole pass)
                st.held_unknown += 1
                self.held_unknown += 1  # m3lint: disable=lock-discipline
                return 0
            if sustained < b.sustain_burn:
                return 0
        moved = 0
        for name in b.actuators:
            if not self._allowed(name):
                continue
            new = self.registry.get(name).shed()
            if new is not None:
                self._last_action[name] = self._clock()
                self._emit(b.rule, name, "shed", new)
                moved += 1
        if moved:
            st.engaged = True
            st.hold_left = b.hold_ticks
        return moved

    def _relax(self, b: Binding, st: _BindingState) -> int:
        moved = 0
        for name in b.actuators:
            act = self.registry.get(name)
            if act.at_baseline or not self._allowed(name):
                continue
            new = act.relax()
            if new is not None:
                self._last_action[name] = self._clock()
                self._emit(b.rule, name, "relax", new)
                moved += 1
        return moved

    # -- read surface ------------------------------------------------------

    def status(self) -> dict:
        """The ``/health`` ``controller`` section: configuration,
        per-binding state, actuator envelope + positions, and the
        recent action tail (cheap: no queries, no engine)."""
        with self._lock:
            return {
                "enabled": True,
                "ticks": self.ticks,
                "actions_total": self.actions_total,
                "held_unknown": self.held_unknown,
                "rate_limited": self.rate_limited,
                "min_interval_s": self.min_interval_s,
                "bindings": {
                    b.name: {
                        "rule": b.rule,
                        "actuators": list(b.actuators),
                        "fire_ticks": b.fire_ticks,
                        "clear_ticks": b.clear_ticks,
                        "clear_burn": b.clear_burn,
                        "hold_ticks": b.hold_ticks,
                        **({"sustain_window": b.sustain_window,
                            "sustain_burn": b.sustain_burn}
                           if b.sustain_window else {}),
                        "firing_streak": self._states[b.name].firing_streak,
                        "clear_streak": self._states[b.name].clear_streak,
                        "hold_left": self._states[b.name].hold_left,
                        "held_unknown": self._states[b.name].held_unknown,
                        "engaged": self._states[b.name].engaged,
                    }
                    for b in self.bindings
                },
                "actuators": self.registry.snapshot(),
                "recent": list(self.actions)[-20:],
            }


# ---------------------------------------------------------------------------
# Actuator factories — the blessed mutation closures.  Every direct
# write to an admission limit / backoff hint / membudget budget /
# devguard force flag lives HERE (x/controller.py), which is exactly
# the scope the m3lint actuator-typed rule exempts.
# ---------------------------------------------------------------------------


def admission_actuator(admission, floor: int, step: int = 1,
                       name: str = "query_slots") -> Actuator:
    """Query-slot shedding: step ``max_concurrent`` down toward
    ``floor`` under query burn, back up to the configured baseline on
    recovery.  A baseline of 0 (gating off) sheds INTO gating — the
    controller imposes a temporary slot cap on an otherwise ungated
    node and removes it again at baseline."""
    return Actuator(
        name, "admission",
        baseline=float(admission.max_concurrent),
        shed_limit=float(floor), step=float(step), unit="slots",
        apply=lambda v: admission.resize(max_concurrent=int(v)))


def ingest_backoff_actuator(server, ceiling_ms: int, step_ms: int,
                            name: str = "ingest_backoff") -> Actuator:
    """Ingest shedding: raise the wire BACKOFF hint toward
    ``ceiling_ms`` under ingest burn so well-behaved clients slow down
    before the queue sheds for them."""
    def apply(v: float) -> None:
        server.backoff_hint_ms = int(v)

    return Actuator(
        name, "ingest", baseline=float(server.backoff_hint_ms),
        shed_limit=float(ceiling_ms), step=float(step_ms), unit="ms",
        apply=apply)


def membudget_actuator(floor_bytes: int, step_bytes: int,
                       name: str = "membudget") -> Actuator:
    """Device-memory tightening: step the admission budget down toward
    ``floor_bytes`` under device burn — NEW device structures admit
    against the tightened budget while existing reservations stand
    (membudget's shrink semantics)."""
    from m3_tpu.x import membudget

    return Actuator(
        name, "membudget", baseline=float(membudget.budget()),
        shed_limit=float(floor_bytes), step=float(step_bytes),
        unit="bytes", apply=lambda v: membudget.set_budget(int(v)))


def devguard_fallback_actuator(name: str = "device_fallback") -> Actuator:
    """Device-path evacuation: a 0/1 switch over
    ``devguard.force_fallback`` — engaged, every guarded stage takes
    its host fallback without waiting for its breaker to trip; on
    relax the flag clears and the (force-opened) stage breakers recover
    through their own half-open probes."""
    from m3_tpu.x import devguard

    return Actuator(
        name, "devguard", baseline=0.0, shed_limit=1.0, step=1.0,
        apply=lambda v: devguard.force_fallback(v >= 0.5))


def checkpoint_actuator(checkpointer, name: str = "checkpoint") -> Actuator:
    """Pre-emptive durability pulse: save the aggregator checkpoint NOW
    (device burn often precedes device loss — the checkpoint is the
    recovery substrate)."""
    return Actuator(
        name, "checkpoint", baseline=0.0, shed_limit=1.0, step=1.0,
        pulse=True, apply=lambda v: checkpointer.save())


def rebalance_actuator(migrator, name: str = "rebalance") -> Actuator:
    """Placement pulse: run one shard-migration pass now (the
    TopologyWatcher/ShardMigrator seam; ``tick()`` is
    ``_tick_mu``-serialized against the mediator's own pass)."""
    return Actuator(
        name, "placement", baseline=0.0, shed_limit=1.0, step=1.0,
        pulse=True, apply=lambda v: migrator.tick())


def emergency_cleanup_actuator(fn: Callable[[], object],
                               name: str = "emergency_cleanup") -> Actuator:
    """Space-reclaim pulse for disk burn: run the cleanup machinery NOW
    (superseded volumes, stale snapshots, retention-aged quarantine,
    fully-flushed commitlog segments) instead of waiting for its
    mediator cadence — the controller's answer to a filling disk, fired
    alongside ingest backoff so reclaim and shed act together."""
    return Actuator(
        name, "disk", baseline=0.0, shed_limit=1.0, step=1.0,
        pulse=True, apply=lambda v: fn())
