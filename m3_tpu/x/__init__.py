"""Cross-cutting substrate (the reference's ``src/x`` tree).

* ``m3_tpu.x.fault`` — process-global fault-injection registry: named
  faultpoints at every socket/disk boundary, armed via code or the
  ``M3_FAULTPOINTS`` env var, with deterministic seeding and per-point
  trigger counters.
* ``m3_tpu.x.retry`` — the reference ``src/x/retry`` equivalent:
  exponential backoff + jitter + attempt caps + a shared retry budget,
  adopted by every wire client in the tree.
* ``m3_tpu.x.deadline`` — end-to-end query deadlines + cooperative
  cancellation: one absolute expiry threaded HTTP → engine → fanout →
  wire (context-bound, serialized into the query/rpc frames), raising
  typed ``DeadlineExceeded`` the API maps to 504.
* ``m3_tpu.x.admission`` — bounded concurrent-query slots + wait queue
  with queue timeout; saturation sheds typed ``QueryShedError``
  (HTTP 503 + Retry-After) instead of queueing unboundedly.
* ``m3_tpu.x.breaker`` — per-peer circuit breakers
  (closed/open/half-open on consecutive transport failures or deadline
  blowouts) shared by the remote-query client, the session read
  fan-out, and the rpc client through one process registry.
* ``m3_tpu.x.lockcheck`` — runtime lock-order sanitizer: wraps
  ``threading.Lock``/``RLock`` behind an env-armed seam
  (``M3_LOCKCHECK``, like ``M3_FAULTPOINTS``) and fails fast on
  acquisition-order cycles; armed by the race/dtest conftest fixture.
* ``m3_tpu.x.tracewatch`` — runtime retrace/transfer sanitizer: counts
  XLA compiles per function through the ``jax_log_compiles`` seam and
  fails fast (with the offending shapes/dtypes) when a jitted function
  retraces past its budget; ``no_transfers()`` forbids device→host
  copies in timed/guarded regions.  Env-armed via ``M3_TRACEWATCH``
  (like lockcheck); bench steady-state loops assert zero retraces
  through it.
* ``m3_tpu.x.hopwatch`` — tracewatch's counting sibling: per-named-hop
  host↔device transfer (count + bytes), compile and dispatch
  accounting behind the same env-seam arming (``M3_HOPWATCH``);
  ``cli hops`` drives the wire→arena→drain→encode→fileset path under
  it and commits the PIPELINE artifact ROADMAP item 1 rebuilds
  against.
* ``m3_tpu.x.devguard`` — the device-boundary resilience seam: typed
  ``DeviceError`` classification over jax/XLA exception shapes,
  per-stage fallback breakers (``run_guarded``), and the
  ``device.compile``/``device.dispatch``/``device.transfer``
  faultpoints so synthetic device failures are injectable on live
  nodes through ``/api/v1/debug/faults``.
* ``m3_tpu.x.membudget`` — process-level device-memory ledger: arenas,
  series buffers and big transient stage buffers reserve bytes BEFORE
  XLA allocates; over ``M3_DEVICE_MEM_BUDGET`` rejects typed
  (``DeviceBudgetExceeded``) instead of dying inside the runtime.
* ``m3_tpu.x.diskbudget`` — membudget's disk twin: a per-root byte
  ledger (filesets / commitlog / snapshots / quarantine / checkpoints
  + statvfs or quota headroom) with OK/LOW/CRITICAL watermarks and a
  reserved flush-headroom band; LOW triggers eager cleanup, CRITICAL
  sheds NEW ingest typed (``DiskCapacityError``) while flush/WAL ride
  the reserve.
* ``m3_tpu.x.costwatch`` — machine-independent cost fingerprints: a
  registry of every hot-path device program at pinned canonical
  shapes, fingerprinted compile-only from XLA's cost/memory analysis
  (flops/bytes/op-histogram/peak per datapoint); ``cli costs --check``
  ratchets the committed COSTS artifact, box-noise-immune and
  relay-independent.  (Imported lazily — it pulls the codec/arena
  modules in, so it is not part of the m3_tpu.x import set.)
* ``m3_tpu.x.lint`` — m3lint, the codebase-aware static analyzer
  (``python -m m3_tpu.tools.cli lint``); its rule families are the
  static mirror of what fault/retry/lockcheck/tracewatch enforce at
  runtime (the jax families — retrace-risk, transfer-hygiene,
  dtype-stability, constant-bloat — are tracewatch's static twin).

``register_metrics(registry)`` mirrors the fault and retry counters
into an instrument registry at scrape time, so a node's ``/metrics``
exposes ``fault_*`` and ``retry_*`` series dtest scenarios can assert
on.
"""

from __future__ import annotations

# lockcheck first: importing it evaluates the M3_LOCKCHECK env seam, so
# a node subprocess wraps its locks before fault/retry (or anything
# else) constructs one.  tracewatch next, for the same reason: its
# M3_TRACEWATCH seam must swap the jit factories before any module
# decorates a hot-path function.  hopwatch (the counting sibling,
# M3_HOPWATCH) follows the same rule: its jit proxy only sees functions
# jitted after arming.
from m3_tpu.x import lockcheck  # noqa: F401  (env-armed seam)
from m3_tpu.x import tracewatch  # noqa: F401  (env-armed seam)
from m3_tpu.x import hopwatch  # noqa: F401  (env-armed seam)
from m3_tpu.x import breaker, deadline, fault, retry
from m3_tpu.x import devguard, membudget  # noqa: F401  (device guard)


def register_metrics(registry, prefix: str = "") -> object:
    """Register a scrape-time collector mirroring the fault, retry,
    deadline and breaker counters into ``registry`` gauges (tagged by
    point/retrier/peer name).  Returns the collector so callers with a
    shutdown path can ``registry.unregister_collector`` it."""
    scope = registry.scope(prefix)

    def collect() -> None:
        for name, value in fault.counters().items():
            point, _, key = name.rpartition(".")
            scope.tagged({"point": point}).gauge(f"fault.{key}").update(value)
        for name, value in retry.counters().items():
            rname, _, key = name.rpartition(".")
            scope.tagged({"retrier": rname}).gauge(f"retry.{key}").update(value)
        dl = deadline.counters()
        scope.gauge("query_deadline_exceeded_total").update(
            dl.get("deadline.exceeded", 0))
        scope.gauge("query_cancelled_total").update(
            dl.get("deadline.cancelled", 0))
        for peer, br in breaker.all_breakers().items():
            scope.tagged({"peer": peer, "kind": br.kind}).gauge(
                "breaker_state").update(br.state_code)
        for name, value in breaker.counters().items():
            peer, _, key = name.rpartition(".")
            scope.tagged({"peer": peer}).gauge(f"breaker.{key}").update(value)
        # device-guard stage counters: device.<stage>.calls /
        # .fallback_calls / .errors.<kind> (stage names contain dots —
        # split on the known suffixes, the devguard.status() rule)
        for name, value in devguard.counters().items():
            rest = name[len("device."):]
            if rest.endswith(".calls") and not rest.endswith(
                    ".fallback_calls"):
                scope.tagged({"stage": rest[:-len(".calls")]}).gauge(
                    "device_guard_calls").update(value)
            elif rest.endswith(".fallback_calls"):
                scope.tagged(
                    {"stage": rest[:-len(".fallback_calls")]}).gauge(
                    "device_fallback_total").update(value)
            else:
                st, _, kind = rest.rpartition(".errors.")
                if st:
                    scope.tagged({"stage": st, "kind": kind}).gauge(
                        "device_error_total").update(value)
        mb = membudget.snapshot()
        scope.gauge("device_mem_budget_bytes").update(mb["budget_bytes"])
        scope.gauge("device_mem_used_bytes").update(mb["used_bytes"])
        scope.gauge("device_mem_peak_bytes").update(mb["peak_bytes"])
        scope.gauge("device_mem_rejected_total").update(
            mb["rejected_total"])
        # disk ledger + typed-capacity counters (lazy: diskbudget pulls
        # persist.capacity in, and most registry users never touch disk)
        from m3_tpu.persist import capacity
        from m3_tpu.x import diskbudget
        db = diskbudget.snapshot()
        if db["enabled"]:
            scope.gauge("disk_total_bytes").update(db["total_bytes"])
            scope.gauge("disk_used_bytes").update(db["used_bytes"])
            scope.gauge("disk_free_bytes").update(db["free_bytes"])
            scope.gauge("disk_free_ratio").update(db["free_ratio"])
            scope.gauge("disk_reserve_bytes").update(db["reserve_bytes"])
            scope.gauge("disk_level").update(db["level_value"])
            scope.gauge("disk_ingest_shed_total").update(db["shed_total"])
            for comp, nbytes in db["components"].items():
                scope.tagged({"component": comp}).gauge(
                    "disk_component_bytes").update(nbytes)
        for name, value in capacity.counters().items():
            comp, _, _key = name.rpartition(".")
            scope.tagged({"component": comp}).gauge(
                "disk_capacity_errors_total").update(value)

    registry.register_collector(collect)
    return collect
