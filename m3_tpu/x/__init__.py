"""Cross-cutting substrate (the reference's ``src/x`` tree).

Currently two members, both born for the robustness tier:

* ``m3_tpu.x.fault`` — process-global fault-injection registry: named
  faultpoints at every socket/disk boundary, armed via code or the
  ``M3_FAULTPOINTS`` env var, with deterministic seeding and per-point
  trigger counters.
* ``m3_tpu.x.retry`` — the reference ``src/x/retry`` equivalent:
  exponential backoff + jitter + attempt caps + a shared retry budget,
  adopted by every wire client in the tree.

``register_metrics(registry)`` mirrors both modules' counters into an
instrument registry at scrape time, so a node's ``/metrics`` exposes
``fault_*`` and ``retry_*`` series dtest scenarios can assert on.
"""

from __future__ import annotations

from m3_tpu.x import fault, retry


def register_metrics(registry, prefix: str = "") -> object:
    """Register a scrape-time collector mirroring the fault and retry
    counters into ``registry`` gauges (tagged by point/retrier name).
    Returns the collector so callers with a shutdown path can
    ``registry.unregister_collector`` it."""
    scope = registry.scope(prefix)

    def collect() -> None:
        for name, value in fault.counters().items():
            point, _, key = name.rpartition(".")
            scope.tagged({"point": point}).gauge(f"fault.{key}").update(value)
        for name, value in retry.counters().items():
            rname, _, key = name.rpartition(".")
            scope.tagged({"retrier": rname}).gauge(f"retry.{key}").update(value)

    registry.register_collector(collect)
    return collect
