"""Multi-source series merge: the one seam every read goes through.

Equivalent of the reference's iterator-merge stack
(`src/dbnode/encoding/multi_reader_iterator.go` merging replica/volume
streams, `series_iterator.go` merging block streams, and the buffer's
in-memory stream contribution `storage/series/buffer.go:705`) — but as a
single sorted dict-merge over (timestamp → value) instead of a k-way
heap of pull iterators: sources are small per-series point lists, and
batched decode already produced arrays.

Precedence: LATER sources win on duplicate timestamps.  Callers order
sources oldest-to-newest (fileset volume < open warm buffer < pending
cold overflow), giving last-write-wins — matching the reference's
version semantics where a higher fileset volume and newer buffer
versions supersede (`buffer.go:1016` BufferBucketVersions).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

Point = Tuple[int, float]


def merge_point_sources(sources: Iterable[Iterable[Point]]) -> List[Point]:
    """Merge per-source point lists into one time-sorted list with each
    timestamp appearing exactly once; later sources take precedence."""
    merged: dict[int, float] = {}
    for pts in sources:
        for t, v in pts:
            merged[t] = v
    return sorted(merged.items())
