"""Anti-entropy repair + peers bootstrap across replica databases.

Reference parity:

* `src/dbnode/storage/repair.go:115-246` — shardRepairer fetches block
  metadata (per-series checksums) from every replica, compares with
  `ReplicaMetadataComparer` (`repair.go:162`), and streams differing
  blocks, loading merged data back into the shard (`repair.go:348`).
* `src/dbnode/storage/bootstrap/bootstrapper/peers/source.go` — a node
  whose local filesets are missing (new node, wiped disk, placement
  add/replace) streams whole blocks from replica peers and persists
  them locally.

Replicas are *handles* exposing the block-level replication surface
(``list_block_filesets`` / ``block_metadata`` / ``read_block`` /
``write_block``): either local ``Database`` objects or
``server.rpc.RemoteDatabase`` connections to other node processes —
repair and peers bootstrap stream blocks over the wire exactly like the
reference's peer block streaming (`client/peer.go`,
`stream_blocks_*`), never by reading a peer's filesystem.  Metadata
compare is a dict diff over per-series adler32 digests — the digest the
reference filesets already carry (`src/dbnode/digest/digest.go:24-37`).
The device-side analogue (checksum compare across the replica mesh axis
as a ppermute collective) lives in ``m3_tpu/parallel/replication.py``.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

from m3_tpu.encoding.m3tsz import decode_series, encode_series
from m3_tpu.persist.corruption import CorruptionError
from m3_tpu.persist.digest import digest as checksum
from m3_tpu.server.rpc import RemoteError
from m3_tpu.storage.database import ShardNotOwnedError

# A replica is skipped/demoted on transport failure (ConnectionError),
# on application-level failure it reports (RemoteError: RPC_ERR frames
# — a remote replica's CorruptionError arrives as one of these), on a
# LOCAL handle's typed CorruptionError (a corrupt block under this very
# process), AND on the typed ShardNotOwnedError (the placement moved
# the shard off that replica — writing the merged block there would
# resurrect decommissioned data) — one bad replica must never abort the
# anti-entropy sweep, matching the reference's per-host fetch failure
# handling (src/dbnode/storage/repair.go:115-246).  The scrubber
# quarantines the local corruption separately; repair's job is only to
# keep sweeping.
_REPLICA_FAILURE = (ConnectionError, RemoteError, CorruptionError,
                    ShardNotOwnedError)


class RepairReport(dict):
    @property
    def converged(self) -> bool:
        return self["series_diff"] == 0 and self["blocks_missing"] == 0


def repair_shard_block(
    dbs: List[object], namespace: str, shard: int, block_start: int,
) -> RepairReport:
    """Compare one (shard, block) across replica handles; merge + rewrite
    where they diverge (repair.go:115-246 + the load at :348).

    Divergent replicas get a new fileset volume holding the union of all
    replicas' points (last-writer-wins per timestamp is unnecessary: the
    merge is per-timestamp first-seen, matching the session's read
    de-dup).  Returns counts; a second call reports convergence.
    Unreachable replicas are skipped (counted as blocks_missing) like
    the reference's per-host metadata fetch failures; a REACHABLE
    replica that merely lacks the fileset gets the merged block written
    so repair alone converges a blockless replica (the old behavior —
    peers bootstrap is only the startup fast path).
    """
    metas = []   # dict | None (reachable, no fileset) | DOWN
    DOWN = object()
    for db in dbs:
        try:
            metas.append(db.block_metadata(namespace, shard, block_start))
        except _REPLICA_FAILURE:
            metas.append(DOWN)
    present = [m for m in metas if m is not None and m is not DOWN]
    report = RepairReport(
        replicas=len(dbs),
        blocks_missing=sum(1 for m in metas if m is None or m is DOWN),
        series_checked=len(set().union(*present)) if present else 0,
        series_diff=0,
        repaired_replicas=0,
    )
    if not present:
        return report

    # Diff: any series whose checksum isn't identical on every replica
    # (missing counts as different) — ReplicaMetadataComparer semantics.
    # A series missing from one present replica yields {None, <ck>} here,
    # so missing-vs-present and checksum-mismatch are both caught.
    all_sids = sorted(set().union(*present))
    divergent = [
        sid
        for sid in all_sids
        if len({m.get(sid) for m in present}) > 1
    ]
    report["series_diff"] = len(divergent)
    # Stream + merge only when something repairable exists: a divergent
    # series, or a REACHABLE replica missing the block.  DOWN replicas
    # keep blocks_missing non-zero (convergence honestly unknown) but
    # cannot be written, so they must not trigger the expensive merge.
    reachable_missing = any(m is None for m in metas)
    if not divergent and not reachable_missing:
        return report

    # Merge pass: union every replica's points for the whole block
    # (streaming just the divergent series would also work; whole-block
    # union keeps the rewrite one volume bump, like the cold-flush merge).
    # A replica dying between metadata and streaming is demoted to DOWN.
    merged: Dict[bytes, Dict[int, float]] = {}
    for i, (db, meta) in enumerate(zip(dbs, metas)):
        if meta is None or meta is DOWN:
            continue
        try:
            block = db.read_block(namespace, shard, block_start)
        except _REPLICA_FAILURE:
            metas[i] = DOWN
            report["blocks_missing"] += 1
            continue
        for sid, seg in block:
            tgt = merged.setdefault(sid, {})
            for d in decode_series(seg):
                tgt.setdefault(d.timestamp, d.value)
    if not any(m is not None and m is not DOWN for m in metas):
        return report

    series = [
        (sid, encode_series(sorted(pts.items()), start=block_start))
        for sid, pts in sorted(merged.items())
    ]
    merged_ck = {sid: checksum(seg) for sid, seg in series}
    for db, meta in zip(dbs, metas):
        if meta is DOWN:
            continue  # unreachable: next sweep, after it rejoins
        if meta == merged_ck:
            continue  # already converged replica: no rewrite
        try:
            db.write_block(namespace, shard, block_start, series)
            report["repaired_replicas"] += 1
        except _REPLICA_FAILURE:
            continue
    return report


def repair_namespace(dbs: List[object], namespace: str,
                     num_shards: int | None = None) -> RepairReport:
    """Repair every flushed (shard, block) seen on any reachable replica.

    ``num_shards`` must be given when every handle is remote; otherwise
    it is read off the first local Database in ``dbs``."""
    if num_shards is None:
        num_shards = next(
            (db.namespaces[namespace].opts.num_shards
             for db in dbs if hasattr(db, "namespaces")), None,
        )
        if num_shards is None:
            raise ValueError(
                "repair_namespace: num_shards is required when every "
                "replica handle is remote"
            )
    total = RepairReport(
        replicas=len(dbs), blocks_missing=0, series_checked=0,
        series_diff=0, repaired_replicas=0,
    )
    for shard in range(num_shards):
        blocks = set()
        for db in dbs:
            try:
                blocks.update(
                    bs for bs, _ in db.list_block_filesets(namespace, shard)
                )
            except _REPLICA_FAILURE:
                continue
        for bs in sorted(blocks):
            rep = repair_shard_block(dbs, namespace, shard, bs)
            for k in ("blocks_missing", "series_checked", "series_diff",
                      "repaired_replicas"):
                total[k] += rep[k]
    return total


def peers_bootstrap(
    db, peers: List[object], namespace: str, num_shards: int | None = None,
    shards: "Iterable[int] | None" = None,
) -> Dict[str, int]:
    """Fill every (shard, block) fileset missing locally from a replica
    peer (bootstrapper/peers/source.go: stream blocks from peers and
    persist, used on node add/replace and after data loss).

    ``db`` is the local ``Database``; ``peers`` are replica handles
    (local or ``RemoteDatabase``).  Streams the peer's encoded segments
    verbatim — bit-identical blocks, so a follow-up repair pass reports
    convergence immediately.  Unreachable peers are skipped.

    Scope: only PLACEMENT-OWNED shards are copied.  ``shards`` names
    them explicitly; when None, the namespace's installed ownership
    (``Namespace.owned``) applies — a restarting node pulls exactly its
    shards, never every peer's full dataset (the reference's peers
    bootstrapper walks the topology's shard set for this node, not the
    shard space).  A namespace with no ownership installed (single-node
    / no placement) keeps the copy-everything behavior.
    """
    ns = db.namespaces[namespace]
    total = num_shards if num_shards is not None else ns.opts.num_shards
    if shards is None:
        shards = range(total) if ns.owned is None else sorted(ns.owned)
    copied_blocks = copied_series = 0
    for shard in sorted(shards):
        local = dict(db.list_block_filesets(namespace, shard))
        for peer in peers:
            if peer is None or peer is db:
                continue
            try:
                peer_blocks = peer.list_block_filesets(namespace, shard)
            except _REPLICA_FAILURE:
                continue
            for bs, _vol in peer_blocks:
                if bs in local:
                    continue
                try:
                    series = peer.read_block(namespace, shard, bs)
                except _REPLICA_FAILURE:
                    continue
                db.write_block(namespace, shard, bs, series)
                local[bs] = 0
                copied_blocks += 1
                copied_series += len(series)
    return {"blocks": copied_blocks, "series": copied_series}


def block_metadata(db, namespace: str, shard: int, block_start: int):
    """Back-compat shim over the handle method (old free-function API)."""
    return db.block_metadata(namespace, shard, block_start)
