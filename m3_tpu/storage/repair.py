"""Anti-entropy repair + peers bootstrap across replica databases.

Reference parity:

* `src/dbnode/storage/repair.go:115-246` — shardRepairer fetches block
  metadata (per-series checksums) from every replica, compares with
  `ReplicaMetadataComparer` (`repair.go:162`), and streams differing
  blocks, loading merged data back into the shard (`repair.go:348`).
* `src/dbnode/storage/bootstrap/bootstrapper/peers/source.go` — a node
  whose local filesets are missing (new node, wiped disk, placement
  add/replace) streams whole blocks from replica peers and persists
  them locally.

Here replicas are per-instance `Database` handles (the same in-process
topology the reference's integration tests use); metadata compare is a
dict diff over per-series adler32 digests — the digest the reference
filesets already carry (`src/dbnode/digest/digest.go:24-37`).  The
device-side analogue (checksum compare across the replica mesh axis as
a ppermute collective) lives in `m3_tpu/parallel/replication.py`.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from m3_tpu.encoding.m3tsz import decode_series, encode_series
from m3_tpu.persist.digest import digest as checksum
from m3_tpu.persist.fs import DataFileSetReader, DataFileSetWriter, list_filesets


def block_metadata(
    db, namespace: str, shard: int, block_start: int
) -> Dict[bytes, int] | None:
    """Per-series stream checksums for one flushed block, or None when
    the replica has no fileset for it (reference
    FetchBlocksMetadataRawV2, the metadata half of repair)."""
    filesets = dict(list_filesets(db.opts.root, namespace, shard))
    if block_start not in filesets:
        return None
    r = DataFileSetReader(
        db.opts.root, namespace, shard, block_start, filesets[block_start]
    )
    return {sid: checksum(seg) for sid, seg in r.read_all()}


class RepairReport(dict):
    @property
    def converged(self) -> bool:
        return self["series_diff"] == 0 and self["blocks_missing"] == 0


def repair_shard_block(
    dbs: List[object], namespace: str, shard: int, block_start: int
) -> RepairReport:
    """Compare one (shard, block) across replicas; merge + rewrite where
    they diverge (repair.go:115-246 + the load at :348).

    Divergent replicas get a new fileset volume holding the union of all
    replicas' points (last-writer-wins per timestamp is unnecessary: the
    merge is per-timestamp first-seen, matching the session's read
    de-dup).  Returns counts; a second call reports convergence.
    """
    metas = [block_metadata(db, namespace, shard, block_start) for db in dbs]
    present = [m for m in metas if m is not None]
    report = RepairReport(
        replicas=len(dbs),
        blocks_missing=sum(1 for m in metas if m is None),
        series_checked=len(set().union(*present)) if present else 0,
        series_diff=0,
        repaired_replicas=0,
    )
    if not present:
        return report

    # Diff: any series whose checksum isn't identical on every replica
    # (missing counts as different) — ReplicaMetadataComparer semantics.
    # A series missing from one present replica yields {None, <ck>} here,
    # so missing-vs-present and checksum-mismatch are both caught.
    all_sids = sorted(set().union(*present))
    divergent = [
        sid
        for sid in all_sids
        if len({m.get(sid) for m in metas if m is not None}) > 1
    ]
    report["series_diff"] = len(divergent)
    if not divergent and report["blocks_missing"] == 0:
        return report

    # Merge pass: union every replica's points for the whole block
    # (streaming just the divergent series would also work; whole-block
    # union keeps the rewrite one volume bump, like the cold-flush merge).
    merged: Dict[bytes, Dict[int, float]] = {}
    for db, meta in zip(dbs, metas):
        if meta is None:
            continue
        filesets = dict(list_filesets(db.opts.root, namespace, shard))
        r = DataFileSetReader(
            db.opts.root, namespace, shard, block_start, filesets[block_start]
        )
        for sid, seg in r.read_all():
            tgt = merged.setdefault(sid, {})
            for d in decode_series(seg):
                tgt.setdefault(d.timestamp, d.value)

    series = [
        (sid, encode_series(sorted(pts.items()), start=block_start))
        for sid, pts in sorted(merged.items())
    ]
    merged_ck = {sid: checksum(seg) for sid, seg in series}
    for db, meta in zip(dbs, metas):
        if meta == merged_ck:
            continue  # already converged replica: no rewrite
        filesets = dict(list_filesets(db.opts.root, namespace, shard))
        vol = filesets.get(block_start, -1) + 1
        ns = db.namespaces[namespace]
        DataFileSetWriter(
            db.opts.root, namespace, shard, block_start,
            ns.opts.block_size_nanos, volume=vol,
        ).write_all(series)
        ns.shards[shard].flushed_blocks.add(block_start)
        report["repaired_replicas"] += 1
    return report


def repair_namespace(dbs: List[object], namespace: str) -> RepairReport:
    """Repair every flushed (shard, block) seen on any replica."""
    num_shards = dbs[0].namespaces[namespace].opts.num_shards
    total = RepairReport(
        replicas=len(dbs), blocks_missing=0, series_checked=0,
        series_diff=0, repaired_replicas=0,
    )
    for shard in range(num_shards):
        blocks = set()
        for db in dbs:
            blocks.update(
                bs for bs, _ in list_filesets(db.opts.root, namespace, shard)
            )
        for bs in sorted(blocks):
            rep = repair_shard_block(dbs, namespace, shard, bs)
            for k in ("blocks_missing", "series_checked", "series_diff",
                      "repaired_replicas"):
                total[k] += rep[k]
    return total


def peers_bootstrap(
    db, peers: List[object], namespace: str
) -> Dict[str, int]:
    """Fill every (shard, block) fileset missing locally from a replica
    peer (bootstrapper/peers/source.go: stream blocks from peers and
    persist, used on node add/replace and after data loss).

    Copies the peer's encoded streams verbatim — bit-identical blocks,
    so a follow-up repair pass reports convergence immediately.
    """
    ns = db.namespaces[namespace]
    copied_blocks = copied_series = 0
    for shard in range(ns.opts.num_shards):
        local = dict(list_filesets(db.opts.root, namespace, shard))
        for peer in peers:
            if peer is None or peer is db:
                continue
            for bs, vol in list_filesets(peer.opts.root, namespace, shard):
                if bs in local:
                    continue
                r = DataFileSetReader(
                    peer.opts.root, namespace, shard, bs, vol
                )
                series = list(r.read_all())
                DataFileSetWriter(
                    db.opts.root, namespace, shard, bs,
                    ns.opts.block_size_nanos, volume=0,
                ).write_all(series)
                ns.shards[shard].flushed_blocks.add(bs)
                local[bs] = 0
                copied_blocks += 1
                copied_series += len(series)
    return {"blocks": copied_blocks, "series": copied_series}
