"""The TSDB engine: database → namespace → shard (→ device buffers).

Structural equivalent of the reference's storage hierarchy
(`src/dbnode/storage/database.go:739 db.Write`, `namespace.go:698`,
`shard.go:867-1008 writeAndIndex`, read `shard.go:1079 ReadEncoded`,
flush orchestration `mediator.go:284 ongoingTick` + `flush.go`), with the
TPU-shaped substitutions:

* per-series encoder objects → one per-shard device append-log ring
  (`storage/buffer.py`) + batched M3TSZ encode at seal time;
* the lock-free series map + insert queue → a host `SlotAllocator`;
* warm flush → `DataFileSetWriter.write_all` of batch-encoded streams;
* cold writes → host overflow lists flushed as higher fileset volumes
  (reference `coldflush.go` + `fs/merger.go`: we merge the existing
  volume's streams with the cold points and write volume+1);
* commit log → WAL appends per ingest batch before buffering.

Reads serve from sealed filesets (scalar/batched decode) merged with the
open in-memory window — the same two-source merge the reference does with
`series buffer streams` + `block retriever` (`shard.go:1079`).
"""

from __future__ import annotations

import dataclasses
import threading
from pathlib import Path

from m3_tpu.core.hash import shard_for as hash_shard_for
from typing import Dict, Iterable, List, Sequence

import numpy as np

from m3_tpu.core.slots import SlotAllocator
from m3_tpu.index.doc import Document, decode_tags, encode_tags
from m3_tpu.index.namespace_index import NamespaceIndex
from m3_tpu.index.search import Query
from m3_tpu.encoding.m3tsz import decode_series, encode_series
from m3_tpu.encoding.m3tsz_jax import decode_batch, encode_batch
from m3_tpu.persist.commitlog import (
    CommitLogEntry, CommitLogWriter, commitlog_seq, list_commitlogs,
    read_commitlog,
)
from m3_tpu.persist import capacity as cap
from m3_tpu.persist.corruption import CorruptionError
from m3_tpu.persist.fs import (
    DataFileSetReader, DataFileSetWriter, list_fileset_volumes, list_filesets,
    remove_fileset,
)
from m3_tpu.persist import quarantine as quar
from m3_tpu.persist import snapshot as snap
from m3_tpu.instrument import logger
from m3_tpu.instrument.tracing import Tracepoint
from m3_tpu.storage.limits import NO_LIMITS, NewSeriesLimiter, QueryLimits
from m3_tpu.storage.buffer import ShardBuffer, dedupe_last_write_wins
from m3_tpu.storage.series_merge import merge_point_sources

_LOG = logger("storage.database")


@dataclasses.dataclass(frozen=True)
class NamespaceOptions:
    """Retention/block options (reference `src/dbnode/namespace/options.go`:
    RetentionOptions blockSize/retentionPeriod/bufferPast/bufferFuture)."""

    block_size_nanos: int = 2 * 3600 * 10**9
    retention_nanos: int = 48 * 3600 * 10**9
    buffer_past_nanos: int = 10 * 60 * 10**9
    buffer_future_nanos: int = 2 * 60 * 10**9
    cold_writes_enabled: bool = True
    num_shards: int = 4
    slot_capacity: int = 1 << 17
    sample_capacity: int = 1 << 18


@dataclasses.dataclass(frozen=True)
class DatabaseOptions:
    root: str = "m3tpu_data"
    commitlog_enabled: bool = True
    # Active-segment size bound: the WAL rotates once a segment crosses
    # this many bytes, so cleanup can reclaim fully-flushed segments on
    # nodes whose snapshot cadence (the only other rotation driver) is
    # long.  0 = rotate only on snapshot (the pre-round-20 behavior).
    commitlog_rotate_bytes: int = 64 << 20
    # 0 = unlimited; live-tunable via the write_new_series_limit_per_sec
    # runtime option (reference dbnode/kvconfig/keys.go).
    write_new_series_limit_per_sec: float = 0.0


class ShardNotOwnedError(RuntimeError):
    """A write or read addressed a shard this node does not own under
    the current placement (reference dbnode's per-shard state check in
    `storage/shard.go` — writes to a shard the topology moved away are
    errors, not silent drops).  Wire-mapped by server/rpc.py so a
    remote caller gets the SAME typed error; the replicated session
    counts it as a per-replica routing miss (stale placement) and
    refreshes its topology, never as a data error."""

    def __init__(self, namespace: str | None, shard: int | None):
        super().__init__(
            f"shard {shard} not owned by this node (namespace {namespace!r})"
        )
        self.namespace = namespace
        self.shard = shard


class WriteResult(int):
    """Cold-write count (plain int for back-compat) carrying the typed
    ingest-rejection info: ``rejected`` = samples dropped because their
    series creation exceeded the new-series rate limit; ``accepted`` =
    per-input-sample bool mask (None when nothing was rejected —
    everything landed)."""

    rejected: int
    accepted = None
    # Samples dropped because their shard is not owned under the
    # current placement (mixed direct-ingest batches only — an
    # ALL-unowned batch raises ShardNotOwnedError instead, which is
    # what the per-shard session fan-out sees).
    not_owned: int

    def __new__(cls, ncold: int, rejected: int = 0, not_owned: int = 0):
        obj = super().__new__(cls, ncold)
        obj.rejected = rejected
        obj.not_owned = not_owned
        return obj


def shard_for_id(sid: bytes, num_shards: int) -> int:
    """murmur3(id) % N, bit-for-bit the reference's router
    (`sharding/shardset.go:148-163`).

    NOTE: data directories written before the crc32→murmur3 switch route
    differently and are not readable by this build (no deployed data
    exists; there is no migration path by design).
    """
    return hash_shard_for(sid, num_shards)


class Shard:
    def __init__(self, namespace: str, shard_id: int, opts: NamespaceOptions, root: str,
                 block_cache=None, new_series_limiter=None, corruption_cb=None):
        self.namespace = namespace
        self.shard_id = shard_id
        self.opts = opts
        self.root = root
        self.block_cache = block_cache
        # Called (namespace, shard, block_start, volume, err) after a
        # corrupt volume is quarantined — the Database's counter/log hook.
        self._corruption_cb = corruption_cb
        self.slots = SlotAllocator(opts.slot_capacity,
                                   limiter=new_series_limiter)
        self.new_series_rejected = 0
        # Ring must cover (bufferPast + bufferFuture) / blockSize + 2 blocks.
        span = opts.buffer_past_nanos + opts.buffer_future_nanos
        num_windows = max(2, span // opts.block_size_nanos + 2)
        self.buffer = ShardBuffer(
            opts.block_size_nanos, int(num_windows), opts.sample_capacity,
            opts.slot_capacity,
        )
        self.flushed_blocks: set[int] = set()
        for bs, _vol in list_filesets(root, namespace, shard_id):
            self.flushed_blocks.add(bs)

    # -- write path --------------------------------------------------------

    def open_starts(self, now_nanos: int) -> set[int]:
        """Block starts accepting warm writes at `now` (reference
        buffer.go:311-398: [now-bufferPast, now+bufferFuture])."""
        bsz = self.opts.block_size_nanos
        lo = (now_nanos - self.opts.buffer_past_nanos) // bsz * bsz
        hi = (now_nanos + self.opts.buffer_future_nanos) // bsz * bsz
        return {bs for bs in range(lo, hi + bsz, bsz) if bs not in self.flushed_blocks}

    def write_batch(self, ids: Sequence[bytes], ts: np.ndarray, vals: np.ndarray,
                    now_nanos: int) -> int:
        slots = self.slots.resolve(ids)
        rejected = slots < 0
        nrej = 0
        if rejected.any():
            # New-series rate limit hit: drop ONLY the rejected
            # creations (existing series in the batch still land) and
            # count them — graceful degradation under churn, never
            # unbounded state growth (dbnode/kvconfig/keys.go
            # write-new-series limits).
            nrej = int(rejected.sum())
            self.new_series_rejected += nrej
            keep = ~rejected
            slots, ts, vals = slots[keep], ts[keep], vals[keep]
        ncold = self.buffer.write(slots, ts, vals, self.open_starts(now_nanos))
        res = WriteResult(ncold, nrej)
        res.accepted = ~rejected
        return res

    # -- flush path --------------------------------------------------------

    def _encode_runs(self, slots: np.ndarray, ts: np.ndarray, vals: np.ndarray,
                     block_start: int) -> list[tuple[bytes, bytes]]:
        """(sorted, deduped) flat runs -> [(id, m3tsz stream)] via the
        batched device encoder; fallback series use the scalar oracle."""
        if len(slots) == 0:
            return []
        uniq, starts_idx, counts = np.unique(slots, return_index=True, return_counts=True)
        S, T = len(uniq), int(counts.max())
        tmat = np.zeros((S, T), np.int64)
        vmat = np.zeros((S, T), np.float64)
        for r, (i0, c) in enumerate(zip(starts_idx, counts)):
            tmat[r, :c] = ts[i0 : i0 + c]
            vmat[r, :c] = vals[i0 : i0 + c]
            if c < T:  # pad with the last sample (ignored via counts)
                tmat[r, c:] = tmat[r, c - 1]
                vmat[r, c:] = vmat[r, c - 1]
        starts = np.full(S, block_start, np.int64)
        streams, fallback = encode_batch(
            tmat, vmat, starts, counts=counts, out_words=max(16, T * 40 // 64 + 8)
        )
        out = []
        for r, slot in enumerate(uniq):
            sid = self.slots.id_of(int(slot))
            if sid is None:
                continue
            if fallback[r]:
                pts = list(zip(tmat[r, : counts[r]].tolist(), vmat[r, : counts[r]].tolist()))
                stream = encode_series(pts, start=block_start)
            else:
                stream = streams[r]
            out.append((sid, stream))
        return out

    def warm_flush(self, block_start: int) -> int:
        """Seal + persist one block (reference buffer.go:634 WarmFlush →
        persist_manager flush).  Returns series flushed.

        The window clears only AFTER the volume is durably on disk
        (peek → write → discard): a DiskCapacityError mid-write leaves
        every sample buffered and readable, and the next tick retries
        the flush against whatever space the cleanup freed."""
        slots, ts, vals = self.buffer.peek(block_start)
        series = self._encode_runs(slots, ts, vals, block_start)
        DataFileSetWriter(
            self.root, self.namespace, self.shard_id, block_start,
            self.opts.block_size_nanos, volume=0,
        ).write_all(series)
        self.buffer.discard(block_start)
        self.flushed_blocks.add(block_start)
        return len(series)

    def cold_flush(self, skip_open: frozenset = frozenset()) -> int:
        """Merge cold overflow writes with the existing volume and write
        volume+1 (reference coldflush.go + fs/merger.go).

        ``skip_open`` holds block starts still inside the warm window:
        their overflow entries are DEGRADED-MODE staging from the
        guarded buffer append (warm samples host-routed while the
        device path is down), and flushing them before the block seals
        would race the later warm flush for volume numbering.  They
        stay readable from the overflow lists and are merged by the
        cold flush that follows the seal."""
        flushed = 0
        for block_start in sorted(self.buffer.cold.keys()):
            if block_start in skip_open:
                continue
            slots, ts, vals = self.buffer.peek_cold(block_start)
            if len(slots) == 0:
                self.buffer.discard_cold(block_start)
                continue
            vol = -1
            for bs, v in list_filesets(self.root, self.namespace, self.shard_id):
                if bs == block_start:
                    vol = v

            # Merge from the highest INTACT volume (corrupt ones are
            # quarantined and the next-lower tried); the rewrite still
            # lands at max_vol+1 so volume numbering stays monotonic
            # across a quarantine.
            def _decode_volume(merge_vol):
                r = DataFileSetReader(
                    self.root, self.namespace, self.shard_id,
                    block_start, merge_vol
                )
                return {
                    sid: {d.timestamp: d.value for d in decode_series(seg)}
                    for sid, seg in r.read_all()
                }

            merged: Dict[bytes, Dict[int, float]] = (
                self._fold_intact_volumes(block_start, _decode_volume) or {}
            )
            for slot, t, v in zip(slots, ts, vals):
                sid = self.slots.id_of(int(slot))
                if sid is None:
                    continue
                merged.setdefault(sid, {})[int(t)] = float(v)
            series = []
            for sid, pts in merged.items():
                items = sorted(pts.items())
                series.append((sid, encode_series(items, start=block_start)))
            DataFileSetWriter(
                self.root, self.namespace, self.shard_id, block_start,
                self.opts.block_size_nanos, volume=vol + 1,
            ).write_all(series)
            # staged overflow clears only once volume+1 is on disk —
            # same no-loss-on-ENOSPC ordering as warm_flush
            self.buffer.discard_cold(block_start)
            self.flushed_blocks.add(block_start)
            if self.block_cache is not None:
                # volume+1 supersedes the cached volume's blocks
                self.block_cache.invalidate_block(
                    self.namespace, self.shard_id, block_start
                )
            flushed += len(series)
        return flushed

    def snapshot_blocks(self, snap_root: str) -> int:
        """Persist every un-flushed block (open warm window + pending cold
        overflow) as a snapshot fileset under `snap_root` without touching
        the live buffers (reference buffer.go:537 Snapshot).  Returns
        series-blocks written."""
        written = 0
        for bs in sorted(set(self.buffer.open_blocks) | set(self.buffer.cold)):
            slots, ts, vals = self.buffer.peek(bs)
            parts = self.buffer.cold.get(bs, ())
            if len(parts):
                slots = np.concatenate([slots] + [p[0] for p in parts]).astype(np.int32)
                ts = np.concatenate([ts] + [p[1] for p in parts]).astype(np.int64)
                vals = np.concatenate([vals] + [p[2] for p in parts]).astype(np.float64)
                slots, ts, vals = dedupe_last_write_wins(slots, ts, vals)
            if len(slots) == 0:
                continue
            series = self._encode_runs(slots, ts, vals, bs)
            DataFileSetWriter(
                snap_root, self.namespace, self.shard_id, bs,
                self.opts.block_size_nanos, volume=0,
            ).write_all(series)
            written += len(series)
        return written

    # -- corruption handling ----------------------------------------------

    def quarantine_volume(self, block_start: int, volume: int, err) -> None:
        """Pull one corrupt fileset volume out of the live tree
        (persist/quarantine), drop its cached readers/blocks, and — when
        no intact volume remains for the block — un-mark it flushed so
        buffers/replay may serve it again (the corrupt volume is now
        *missing*, not half-readable)."""
        qdir = quar.quarantine_fileset(self.root, self.namespace,
                                       self.shard_id, block_start, volume, err)
        if self.block_cache is not None:
            self.block_cache.invalidate_block(
                self.namespace, self.shard_id, block_start
            )
        if not any(bs == block_start for bs, _ in list_filesets(
                self.root, self.namespace, self.shard_id)):
            self.flushed_blocks.discard(block_start)
        _LOG.warning(
            "quarantined corrupt fileset ns=%s shard=%d block=%d vol=%d: %s",
            self.namespace, self.shard_id, block_start, volume, err,
        )
        if self._corruption_cb is not None:
            self._corruption_cb(self.namespace, self.shard_id, block_start,
                                volume, err, quarantined=qdir is not None)

    def _fold_intact_volumes(self, block_start: int, consume):
        """Apply ``consume(volume)`` to the block's volumes, highest
        first, returning the first result that reads clean.  A corrupt
        volume is quarantined and the next-lower one tried; a missing
        one (raced cleanup/quarantine) is skipped.  ``consume`` must
        build any partial state fresh per call — a mid-read
        CorruptionError discards that attempt wholesale.  This is the
        ONE place the quarantine-and-fall-back contract lives (read
        path, cold-flush merge, and WAL-replay dedupe all fold through
        it)."""
        vols = sorted(
            (v for bs, v in list_fileset_volumes(
                self.root, self.namespace, self.shard_id)
             if bs == block_start),
            reverse=True,
        )
        for vol in vols:
            try:
                return consume(vol)
            except FileNotFoundError:
                continue
            except CorruptionError as e:
                self.quarantine_volume(block_start, vol, e)
                continue
        return None

    def _read_fileset_series(self, block_start: int, sid: bytes,
                             volume: int | None = None):
        """Points for ``sid`` from the highest INTACT volume of a block,
        or None.  A corrupt volume is quarantined and the next-lower
        volume tried — corruption degrades this one source (buffers and
        replicas still answer), it never fails the read (the reference's
        checksum-verify-and-skip read path, persist/fs/read.go +
        repair.go's expected-corruption contract).

        ``volume`` is the caller's already-known latest volume: the hot
        path reads it directly (no extra directory glob); only a
        corrupt/vanished volume falls back to enumerating what remains
        on disk."""
        def consume(vol):
            if self.block_cache is not None:
                return self.block_cache.read_series(
                    self.root, self.namespace, self.shard_id,
                    block_start, vol, sid,
                )
            r = DataFileSetReader(
                self.root, self.namespace, self.shard_id, block_start, vol
            )
            seg = r.read(sid)
            return ([(d.timestamp, d.value) for d in decode_series(seg)]
                    if seg else None)

        if volume is not None:
            try:
                return consume(volume)
            except FileNotFoundError:
                pass
            except CorruptionError as e:
                self.quarantine_volume(block_start, volume, e)
            # quarantined/vanished: whatever remains on disk, if anything
        return self._fold_intact_volumes(block_start, consume)

    # -- read path ---------------------------------------------------------

    def read_sources(
        self, sid: bytes, start_nanos: int, end_nanos: int
    ) -> list[list[tuple[int, float]]]:
        """Every source holding points for this series over the range,
        ordered oldest-precedence-first for the merge seam
        (series_merge.merge_point_sources): sealed fileset volume, open
        warm buffer, pending cold overflow.  This is the seam the
        reference builds from MultiReaderIterator + buffer streams
        (`shard.go:1079` ReadEncoded gathering disk + memory streams)."""
        bsz = self.opts.block_size_nanos
        slot = self.slots.get(sid)
        lo = start_nanos // bsz * bsz
        filesets = dict(list_filesets(self.root, self.namespace, self.shard_id))
        sources: list[list[tuple[int, float]]] = []
        for bs in range(lo, end_nanos + bsz, bsz):
            if bs in filesets:
                pts = self._read_fileset_series(bs, sid, volume=filesets[bs])
                if pts:
                    sources.append(pts)
            if slot is not None and bs in self.buffer.open_blocks:
                ts, vals = self.buffer.read_window(bs, slot)
                sources.append(list(zip(ts.tolist(), vals.tolist())))
            if slot is not None and bs in self.buffer.cold:
                # Cold writes awaiting flush are readable immediately
                # (the reference reads cold buckets too — versioned
                # buckets in buffer.go:1016 serve un-flushed cold data).
                pts: list[tuple[int, float]] = []
                for cslots, cts, cvals in self.buffer.cold[bs]:
                    m = cslots == slot
                    pts.extend(zip(cts[m].tolist(), cvals[m].tolist()))
                sources.append(pts)
        return sources

    def read(self, sid: bytes, start_nanos: int, end_nanos: int) -> list[tuple[int, float]]:
        merged = merge_point_sources(
            self.read_sources(sid, start_nanos, end_nanos)
        )
        return [(t, v) for t, v in merged if start_nanos <= t < end_nanos]

    def read_many(self, sids: Sequence[bytes], start_nanos: int,
                  end_nanos: int) -> list[list[tuple[int, float]]]:
        """Batched :meth:`read`: one result list per requested id, same
        merge/range contract as the single-id path.  The win is
        amortization — per BLOCK this pays one sorted-window snapshot
        (buffer.read_window_many) and one cold-overflow sort instead of
        per-id O(window) work, which is what makes verifying a
        million-series soak ledger (and serving batched fetches under
        load) feasible.  Fileset sources stay per-id: the block cache
        already amortizes the disk read across ids."""
        bsz = self.opts.block_size_nanos
        lo = start_nanos // bsz * bsz
        filesets = dict(list_filesets(self.root, self.namespace, self.shard_id))
        slots = np.asarray(
            [s if (s := self.slots.get(sid)) is not None else -1
             for sid in sids], np.int64)
        sources_per: list[list] = [[] for _ in sids]
        for bs in range(lo, end_nanos + bsz, bsz):
            if bs in filesets:
                vol = filesets[bs]
                for i, sid in enumerate(sids):
                    pts = self._read_fileset_series(bs, sid, volume=vol)
                    if pts:
                        sources_per[i].append(pts)
            if bs in self.buffer.open_blocks:
                for i, (wts, wvals) in enumerate(
                        self.buffer.read_window_many(bs, slots)):
                    if len(wts):
                        sources_per[i].append(
                            list(zip(wts.tolist(), wvals.tolist())))
            if bs in self.buffer.cold:
                parts = self.buffer.cold[bs]
                cslots = np.concatenate([p[0] for p in parts]).astype(np.int64)
                cts = np.concatenate([p[1] for p in parts])
                cvals = np.concatenate([p[2] for p in parts])
                # arrival-stable sort by slot so per-id extraction is a
                # binary search, with arrival order (the cold merge
                # rule's tie-break input) preserved within each slot
                order = np.argsort(cslots, kind="stable")
                cslots, cts, cvals = cslots[order], cts[order], cvals[order]
                los = np.searchsorted(cslots, slots)
                his = np.searchsorted(cslots, slots + 1)
                for i, (slo, shi) in enumerate(zip(los.tolist(), his.tolist())):
                    if shi > slo and slots[i] >= 0:
                        sources_per[i].append(
                            list(zip(cts[slo:shi].tolist(),
                                     cvals[slo:shi].tolist())))
        return [
            [(t, v) for t, v in merge_point_sources(srcs)
             if start_nanos <= t < end_nanos]
            for srcs in sources_per
        ]


class Namespace:
    def __init__(self, name: str, opts: NamespaceOptions, root: str,
                 block_cache=None, new_series_limiter=None,
                 corruption_cb=None):
        self.name = name
        self.opts = opts
        self.root = root
        self.shards = [
            Shard(name, i, opts, root, block_cache,
                  new_series_limiter=new_series_limiter,
                  corruption_cb=corruption_cb)
            for i in range(opts.num_shards)
        ]
        # Placement-driven ownership: None = own every shard (the
        # single-node / no-placement default, bit-compatible with the
        # pre-topology behavior); a set restricts writes AND reads to
        # exactly those shards — everything else raises the typed
        # ShardNotOwnedError (reference dbnode shard state gating).
        self.owned: frozenset | None = None
        self.index = NamespaceIndex(opts.block_size_nanos, root, name)

    def check_owned(self, shard: int) -> None:
        if self.owned is not None and shard not in self.owned:
            raise ShardNotOwnedError(self.name, shard)

    def write_tagged_batch(self, docs: Sequence[Document], ts: np.ndarray,
                           vals: np.ndarray, now_nanos: int) -> int:
        """Write + index tagged series (reference WriteTagged
        `database.go:771` → shard writeAndIndex → nsIndex.WriteBatch).
        The index only learns documents whose series were ACCEPTED —
        rate-limited churn must not grow the reverse index either (that
        is the unbounded-memory failure the limit exists to stop)."""
        res = self.write_batch([d.id for d in docs], ts, vals, now_nanos)
        if res.accepted is None:
            self.index.write_batch(list(docs), ts)
        else:
            acc = res.accepted
            kept = [d for d, a in zip(docs, acc) if a]
            if kept:
                self.index.write_batch(kept, ts[acc])
        return res

    def query_ids(self, q: Query, start: int, end: int,
                  inc_docs=None) -> list[Document]:
        """Index query → matching series documents (reference db.QueryIDs
        → nsIndex.Query `storage/index.go:1483`)."""
        return self.index.query(q, start, end, inc_docs=inc_docs)

    def write_batch(self, ids: Sequence[bytes], ts: np.ndarray, vals: np.ndarray,
                    now_nanos: int) -> int:
        by_shard: Dict[int, List[int]] = {}
        for i, sid in enumerate(ids):
            by_shard.setdefault(shard_for_id(sid, self.opts.num_shards), []).append(i)
        # Ownership gate BEFORE any shard buffers a sample.  An
        # ALL-unowned batch rejects atomically with the typed error —
        # the session fans single-shard sub-batches, so that maps to
        # one routing miss.  A MIXED direct-ingest batch (carbon/HTTP
        # front doors hash one flush across many shards) must NOT lose
        # its owned samples to one stray id: owned shards land, the
        # unowned remainder is dropped into the accepted mask like a
        # limiter rejection (counted as ``not_owned``; never
        # WAL-logged, never indexed).
        owned_set = self.owned
        unowned = ([] if owned_set is None
                   else sorted(sh for sh in by_shard if sh not in owned_set))
        if unowned and len(unowned) == len(by_shard):
            raise ShardNotOwnedError(self.name, unowned[0])
        ncold = nrej = ndropped = 0
        full = np.ones(len(ids), bool)
        for sh, idxs in by_shard.items():
            sel = np.asarray(idxs)
            if owned_set is not None and sh not in owned_set:
                full[sel] = False
                ndropped += len(idxs)
                continue
            res = self.shards[sh].write_batch(
                [ids[i] for i in idxs], ts[sel], vals[sel], now_nanos
            )
            ncold += int(res)
            nrej += res.rejected
            if res.accepted is not None:
                full[sel] = res.accepted
        out = WriteResult(ncold, nrej, ndropped)
        if nrej or ndropped:
            out.accepted = full
        return out

    @property
    def new_series_rejected(self) -> int:
        return sum(sh.new_series_rejected for sh in self.shards)

    def read(self, sid: bytes, start: int, end: int) -> list[tuple[int, float]]:
        shard = shard_for_id(sid, self.opts.num_shards)
        self.check_owned(shard)
        return self.shards[shard].read(sid, start, end)

    def read_many(self, sids: Sequence[bytes], start: int,
                  end: int) -> list[list[tuple[int, float]]]:
        """Batched read: group by shard, amortize the per-window sort
        (Shard.read_many), return point lists aligned with ``sids``.
        The ownership gate is per SHARD and atomic like write_batch's
        all-unowned case: any unowned shard in the batch raises typed
        (the session fans single-shard sub-batches, so this maps to one
        routing miss, never a partially-silent read)."""
        by_shard: Dict[int, List[int]] = {}
        for i, sid in enumerate(sids):
            by_shard.setdefault(shard_for_id(sid, self.opts.num_shards),
                                []).append(i)
        for sh in by_shard:
            self.check_owned(sh)
        out: list = [None] * len(sids)
        for sh, idxs in by_shard.items():
            for i, pts in zip(idxs, self.shards[sh].read_many(
                    [sids[i] for i in idxs], start, end)):
                out[i] = pts
        return out

    def tick(self, now_nanos: int) -> dict:
        """Seal + warm-flush every open block that has left the warm
        window (mediator.go tick → flush), then cold-flush overflow."""
        stats = {"warm_flushed": 0, "cold_flushed": 0, "index_sealed": 0}
        sealed_blocks: set[int] = set()
        for shard in self.shards:
            open_now = shard.open_starts(now_nanos)
            for bs in sorted(set(shard.buffer.open_blocks) - open_now):
                stats["warm_flushed"] += shard.warm_flush(bs)
                sealed_blocks.add(bs)
            if self.opts.cold_writes_enabled:
                stats["cold_flushed"] += shard.cold_flush(
                    skip_open=frozenset(open_now))
        # Index blocks seal alongside their data blocks (reference index
        # flush rides the same mediator file-system pass, mediator.go:318).
        for bs in sorted(sealed_blocks):
            if self.index.seal_block(bs) is not None:
                stats["index_sealed"] += 1
        # Background segment compaction: bound per-block segment counts
        # under churn (reference multi_segments_builder compaction).
        stats["index_compactions"] = self.index.compact()
        return stats


class Database:
    """Top-level engine (reference storage/database.go db struct;
    `Write` :739, `ReadEncoded` via namespaces, `Bootstrap` :1199)."""

    def __init__(self, opts: DatabaseOptions | None = None,
                 namespaces: Dict[str, NamespaceOptions] | None = None,
                 instrument=None, tracer=None, limits: QueryLimits | None = None,
                 new_series_limiter: NewSeriesLimiter | None = None):
        from m3_tpu.instrument.tracing import NOOP_TRACER

        self.opts = opts or DatabaseOptions()
        self._scope = instrument.scope("db") if instrument is not None else None
        # flush/snapshot latency: windowed mergeable histograms (the
        # /health ``latency`` section), interned once — these paths run
        # per mediator tick and must not pay a registry intern each time
        self._hist_tick = (self._scope.histogram("tick_seconds")
                           if self._scope is not None else None)
        self._hist_snapshot = (self._scope.histogram("snapshot_seconds")
                               if self._scope is not None else None)
        # per-batch ingest latency at the STORAGE boundary (covers every
        # front door: rpc write fan-out, HTTP json, carbon, WAL replay
        # excluded by construction) — the fleet-mergeable lane the soak
        # harness scrapes for its per-phase ingest p50/p99
        self._hist_write = (self._scope.histogram("write_batch_seconds")
                            if self._scope is not None else None)
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        self.limits = limits if limits is not None else NO_LIMITS
        # One engine-wide reentrant lock serializing state mutation:
        # ingest batches (HTTP threads), the mediator's tick/snapshot/
        # cleanup thread, bootstrap, and reads that walk buffer state.
        # The reference uses fine-grained per-shard/series locks
        # (shard.go RLock ladders); here every operation is already a
        # whole-batch array program, so one coarse lock adds no
        # meaningful serialization beyond what the batched design has.
        self._mu = threading.RLock()
        Path(self.opts.root).mkdir(parents=True, exist_ok=True)
        from m3_tpu.storage.block_cache import BlockCache

        self.block_cache = BlockCache(instrument=instrument)
        # Engine-wide new-series rate limiter shared by every shard's
        # allocator (0 = unlimited; runtime-tuned through the
        # write_new_series_limit_per_sec KV option, kvconfig/keys.go).
        self.new_series_limiter = (
            new_series_limiter if new_series_limiter is not None
            else NewSeriesLimiter(self.opts.write_new_series_limit_per_sec))
        self.namespaces: Dict[str, Namespace] = {}
        for name, nopts in (namespaces or {"default": NamespaceOptions()}).items():
            self.namespaces[name] = Namespace(
                name, nopts, self.opts.root, self.block_cache,
                new_series_limiter=self.new_series_limiter,
                corruption_cb=self._note_corruption,
            )
        self.commitlog = (
            CommitLogWriter(
                self.opts.root,
                rotate_bytes=self.opts.commitlog_rotate_bytes,
                # fsync wall time on the db scope: a stalling disk is
                # SLO-visible long before it is full
                fsync_histogram=(
                    self._scope.histogram("commitlog_fsync_seconds")
                    if self._scope is not None else None),
            ) if self.opts.commitlog_enabled else None
        )
        # (num_shards, owned) the topology watcher last installed:
        # inherited by namespaces created later (see ensure_namespace).
        self._ownership_template: tuple | None = None
        self.bootstrapped = False

    def _note_corruption(self, namespace: str, shard: int, block_start: int,
                         volume: int, err, quarantined: bool = True) -> None:
        """Counter hook every shard's quarantine path reports through —
        the ``corruption_*`` series on a node's /metrics.  ``detected``
        counts every corruption event; ``quarantined`` only those where
        files were actually moved (a volume whose files vanished before
        the move detects without quarantining)."""
        if self._scope is not None:
            self._scope.counter("corruption_detected").inc()
            if quarantined:
                self._scope.counter("corruption_quarantined").inc()

    def quarantine_inventory(self) -> list:
        """Reason dicts of everything under <root>/quarantine/ (served
        in /health detail)."""
        return quar.list_quarantined(self.opts.root)

    def quarantine_fileset_volume(self, namespace: str, shard: int,
                                  block_start: int, volume: int,
                                  err=None) -> None:
        """Engine-locked quarantine of one fileset volume (the
        scrubber's entry point — flushed-block bookkeeping must not
        race ingest/tick)."""
        with self._mu:
            self.namespaces[namespace].shards[shard].quarantine_volume(
                block_start, volume, err
            )

    # ---- placement-driven shard ownership -------------------------------

    def set_ownership_template(self, num_shards: int,
                               owned: Iterable[int] | None) -> None:
        """Ownership applied to namespaces created AFTER the placement
        was observed (dynamic namespace add, downsampler
        ensure_namespace): a new namespace sharing the placement's
        shard space must start placement-scoped, not own-all — without
        this it would silently bypass the ownership invariant until the
        next placement version bump."""
        with self._mu:
            self._ownership_template = (
                int(num_shards), None if owned is None else frozenset(owned))

    def set_shard_ownership(self, namespace: str | None,
                            owned: Iterable[int] | None) -> None:
        """Install the placement-derived shard set this node serves
        (None = own everything, the no-placement default).  Applies to
        one namespace, or to every namespace when ``namespace`` is None
        (the topology watcher's shape: one placement governs the node).
        Takes effect atomically under the engine lock — a mid-batch
        ingest either wholly precedes or wholly follows the swap."""
        with self._mu:
            targets = (self.namespaces.values() if namespace is None
                       else [self.namespaces[namespace]])
            for ns in targets:
                ns.owned = None if owned is None else frozenset(owned)

    def owned_shards(self, namespace: str) -> frozenset | None:
        ns = self.namespaces[namespace]
        return ns.owned

    def drop_shard(self, namespace: str, shard_id: int) -> int:
        """Discard one shard's local state: every fileset volume on
        disk, the in-memory buffers/slots, and cached blocks — the
        post-cutover cleanup of a LEAVING shard (reference dbnode
        closes and deletes shards the topology moved away).  Returns
        the number of fileset volumes removed.  The caller (migrator)
        is responsible for grace: by the time this runs, ownership has
        already been revoked and clients re-routed."""
        with self._mu:
            ns = self.namespaces[namespace]
            sh = ns.shards[shard_id]
            removed = 0
            for bs, vol in list_fileset_volumes(self.opts.root, namespace,
                                                shard_id):
                remove_fileset(self.opts.root, namespace, shard_id, bs, vol)
                self.block_cache.invalidate_block(namespace, shard_id, bs)
                removed += 1
            # A fresh Shard starts empty (the fileset scan above left
            # nothing) — buffers, slots and flushed-block bookkeeping
            # all reset in one swap.
            ns.shards[shard_id] = Shard(
                namespace, shard_id, ns.opts, self.opts.root,
                self.block_cache,
                new_series_limiter=self.new_series_limiter,
                corruption_cb=self._note_corruption,
            )
            _LOG.info("dropped shard ns=%s shard=%d (%d fileset volumes)",
                      namespace, shard_id, removed)
            if self._scope is not None:
                self._scope.counter("shards_dropped").inc()
            return removed

    def ensure_namespace(self, name: str,
                         opts: NamespaceOptions | None = None) -> Namespace:
        """Create-if-missing (the reference adds namespaces dynamically
        through KV-watched namespace metadata, dbnode/namespace/dynamic.go;
        the coordinator provisions aggregated namespaces per policy)."""
        with self._mu:  # racing the mediator's namespace iteration
            ns = self.namespaces.get(name)
            if ns is None:
                ns = self.namespaces[name] = Namespace(
                    name, opts or NamespaceOptions(), self.opts.root,
                    self.block_cache,
                    new_series_limiter=self.new_series_limiter,
                    corruption_cb=self._note_corruption,
                )
                tpl = self._ownership_template
                if tpl is not None and tpl[0] == ns.opts.num_shards:
                    ns.owned = tpl[1]  # placement-scoped from birth
            return ns

    def write_batch(self, namespace: str, ids: Sequence[bytes], ts, vals,
                    now_nanos: int | None = None) -> int:
        import time as _time

        ns = self.namespaces[namespace]
        ts = np.asarray(ts, np.int64)
        vals = np.asarray(vals, np.float64)
        if now_nanos is None:
            now_nanos = int(ts.max())
        t0 = _time.perf_counter()
        with self._mu, self.tracer.start_span(
            Tracepoint.DB_WRITE_BATCH, {"n": len(ids), "ns": namespace}
        ):
            if self._scope is not None:
                self._scope.counter("writes").inc(len(ids))
            try:
                res = ns.write_batch(ids, ts, vals, now_nanos)
            except ShardNotOwnedError:
                if self._scope is not None:
                    self._scope.counter("shard_not_owned").inc()
                raise
            if self._scope is not None and getattr(res, "not_owned", 0):
                self._scope.counter("shard_not_owned").inc(res.not_owned)
            if self._scope is not None and res.rejected:
                self._scope.counter("new_series_rejected").inc(res.rejected)
            # Log AFTER acceptance so the WAL never contains
            # rate-limit-rejected samples (the reference writes the
            # commitlog after the in-memory write succeeds, as an async
            # enqueue - commit_log.go:716).  Bootstrap replay then
            # re-admits exactly the accepted set, bypassing the limiter.
            if self.commitlog is not None:
                if res.accepted is None:
                    self.commitlog.write_batch(list(ids), ts, vals,
                                               namespace=namespace.encode())
                else:
                    acc = res.accepted
                    self.commitlog.write_batch(
                        [sid for sid, a in zip(ids, acc) if a],
                        ts[acc], vals[acc], namespace=namespace.encode())
            if self._hist_write is not None:
                self._hist_write.record(_time.perf_counter() - t0)
            return res

    def write_tagged_batch(self, namespace: str, docs: Sequence[Document], ts, vals,
                           now_nanos: int | None = None) -> int:
        import time as _time

        ns = self.namespaces[namespace]
        ts = np.asarray(ts, np.int64)
        vals = np.asarray(vals, np.float64)
        if now_nanos is None:
            now_nanos = int(ts.max())
        t0 = _time.perf_counter()
        with self._mu, self.tracer.start_span(
            Tracepoint.DB_WRITE_BATCH, {"n": len(docs), "ns": namespace,
                                        "tagged": True}
        ):
            if self._scope is not None:
                self._scope.counter("writes_tagged").inc(len(docs))
            try:
                res = ns.write_tagged_batch(docs, ts, vals, now_nanos)
            except ShardNotOwnedError:
                if self._scope is not None:
                    self._scope.counter("shard_not_owned").inc()
                raise
            if self._scope is not None and getattr(res, "not_owned", 0):
                self._scope.counter("shard_not_owned").inc(res.not_owned)
            if self._scope is not None and res.rejected:
                self._scope.counter("new_series_rejected").inc(res.rejected)
            if self.commitlog is not None:
                # Tags ride the annotation field so WAL replay can rebuild
                # index documents (the reference's commitlog entries carry
                # the series metadata for the same reason).  Only the
                # ACCEPTED samples are logged - see write_batch.
                if res.accepted is None:
                    kept = list(docs)
                    kts, kvs = ts, vals
                else:
                    kept = [d for d, a in zip(docs, res.accepted) if a]
                    kts, kvs = ts[res.accepted], vals[res.accepted]
                if kept:
                    self.commitlog.write_batch(
                        [d.id for d in kept], kts, kvs,
                        namespace=namespace.encode(),
                        annotations=[encode_tags(d) for d in kept],
                    )
            if self._hist_write is not None:
                self._hist_write.record(_time.perf_counter() - t0)
            return res

    def query_ids(self, namespace: str, q: Query, start: int, end: int):
        with self._mu, self.tracer.start_span(
            Tracepoint.DB_QUERY_IDS, {"ns": namespace}
        ):
            # windowed per-query limit, incremented DURING matching so a
            # heavy query aborts mid-match (reference storage/limits)
            return self.namespaces[namespace].query_ids(
                q, start, end, inc_docs=self.limits.inc_docs
            )

    def read(self, namespace: str, sid: bytes, start: int, end: int):
        if self._scope is not None:
            self._scope.counter("reads").inc()
        self.limits.inc_series(1)
        # bytes pre-check: an already-exhausted window rejects the read
        # BEFORE decoding; the exact size still accounts afterwards (it
        # is unknowable until decoded).
        self.limits.inc_bytes(0)
        with self._mu, self.tracer.start_span(Tracepoint.DB_READ):
            pts = self.namespaces[namespace].read(sid, start, end)
        # 16 bytes per (ts, value) sample — the bytes-read accounting unit
        self.limits.inc_bytes(16 * len(pts))
        return pts

    def read_batch(self, namespace: str, sids: Sequence[bytes],
                   start: int, end: int) -> list[list[tuple[int, float]]]:
        """Batched :meth:`read` (one engine-lock acquisition, one
        sorted-window snapshot per open block instead of per id): the
        RPC ``read_batch`` / session ``fetch_batch`` storage entry.
        Same limits accounting units as the single-id path."""
        if self._scope is not None:
            self._scope.counter("reads").inc(len(sids))
        self.limits.inc_series(len(sids))
        self.limits.inc_bytes(0)
        with self._mu, self.tracer.start_span(
                Tracepoint.DB_READ, {"n": len(sids)}):
            out = self.namespaces[namespace].read_many(sids, start, end)
        self.limits.inc_bytes(16 * sum(len(p) for p in out))
        return out

    def tick(self, now_nanos: int) -> dict:
        import time as _time

        t0 = _time.perf_counter()
        with self._mu, self.tracer.start_span(Tracepoint.DB_TICK):
            stats = {}
            for name, ns in self.namespaces.items():
                stats[name] = ns.tick(now_nanos)
        if self._hist_tick is not None:
            self._hist_tick.record(_time.perf_counter() - t0)
        return stats

    # ---- block-level replication surface -------------------------------
    # The handle interface repair and peers bootstrap run against; the
    # socket RPC (server/rpc.py) exports exactly these four methods so a
    # replica works the same whether it is this object or a remote node
    # (reference FetchBlocksMetadataRawV2 `node/service.go:1529` + the
    # peer block streaming in `client/peer.go`).

    def list_block_filesets(self, namespace: str, shard: int):
        """[(block_start, latest volume)] flushed for the shard."""
        from m3_tpu.persist.fs import list_filesets

        return sorted(list_filesets(self.opts.root, namespace, shard))

    def block_metadata(self, namespace: str, shard: int, block_start: int):
        """Per-series stream checksums for one flushed block, or None
        when no fileset exists for it.

        Served from the fileset's index entries alone (the writer stores
        adler32-of-segment per entry), never touching the data file —
        the metadata-only property of the reference's
        FetchBlocksMetadataRawV2."""
        from m3_tpu.persist.fs import DataFileSetReader, list_filesets

        filesets = dict(list_filesets(self.opts.root, namespace, shard))
        if block_start not in filesets:
            return None
        r = DataFileSetReader(
            self.opts.root, namespace, shard, block_start, filesets[block_start]
        )
        return {e.id: e.checksum for e in r.entries()}

    def read_block(self, namespace: str, shard: int, block_start: int):
        """All (series id, encoded stream) pairs of one flushed block;
        [] when the block has no fileset."""
        from m3_tpu.persist.fs import DataFileSetReader, list_filesets

        filesets = dict(list_filesets(self.opts.root, namespace, shard))
        if block_start not in filesets:
            return []
        r = DataFileSetReader(
            self.opts.root, namespace, shard, block_start, filesets[block_start]
        )
        return list(r.read_all())

    def write_block(self, namespace: str, shard: int, block_start: int,
                    series) -> None:
        """Persist a full block's series as the next fileset volume and
        mark it flushed (repair rewrite / peers-bootstrap load)."""
        from m3_tpu.persist.fs import DataFileSetWriter, list_filesets

        with self._mu:
            ns = self.namespaces[namespace]
            # A non-owner must not accept streamed blocks: repair
            # writing a merged block at a decommissioned replica would
            # resurrect data the topology moved away (callers treat
            # this like any per-replica failure and skip the replica).
            ns.check_owned(shard)
            filesets = dict(list_filesets(self.opts.root, namespace, shard))
            vol = filesets.get(block_start, -1) + 1
            DataFileSetWriter(
                self.opts.root, namespace, shard, block_start,
                ns.opts.block_size_nanos, volume=vol,
            ).write_all(sorted(series))
            ns.shards[shard].flushed_blocks.add(block_start)

    def snapshot(self) -> dict:
        """Capture every namespace's un-flushed buffers as snapshot
        filesets (reference mediator.go:318 runFileSystemProcesses →
        buffer.Snapshot; metadata commit gates visibility).  The commit
        log rotates first so the snapshot covers everything in the
        now-inactive logs — recovery then replays only seq >= the active
        log (`snapshot_metadata_write.go` commitlog-identifier role)."""
        import time as _time

        t0 = _time.perf_counter()
        with self._mu, self.tracer.start_span(Tracepoint.DB_SNAPSHOT):
            seq = snap.next_snapshot_seq(self.opts.root)
            if self.commitlog is not None:
                self.commitlog.rotate()
                cl_seq = self.commitlog.seq
            else:
                cl_seq = 0
            snap_root = str(snap.snapshot_data_root(self.opts.root, seq))
            written = 0
            index_segs = 0
            for ns in self.namespaces.values():
                for shard in ns.shards:
                    written += shard.snapshot_blocks(snap_root)
                index_segs += ns.index.snapshot_mutable(snap_root)
            snap.commit_snapshot(self.opts.root, seq, cl_seq)
        if self._hist_snapshot is not None:
            self._hist_snapshot.record(_time.perf_counter() - t0)
        return {"seq": seq, "series_blocks": written, "index_segments": index_segs}

    def cleanup(self, now_nanos: int) -> dict:
        """Expired-data cleanup (reference `storage/cleanup.go`):
        out-of-retention fileset volumes, superseded (non-max) volumes,
        all-but-latest snapshots, and commitlogs fully covered by the
        latest snapshot."""
        stats = {"filesets": 0, "snapshots": 0, "commitlogs": 0}
        with self._mu:
            return self._cleanup_locked(now_nanos, stats)

    def _cleanup_locked(self, now_nanos: int, stats: dict) -> dict:
        for ns in self.namespaces.values():
            cutoff = now_nanos - ns.opts.retention_nanos - ns.opts.block_size_nanos
            for shard in ns.shards:
                vols = list_fileset_volumes(self.opts.root, ns.name, shard.shard_id)
                max_vol = {}
                for bs, vol in vols:
                    max_vol[bs] = max(max_vol.get(bs, -1), vol)
                for bs, vol in vols:
                    if bs <= cutoff or vol < max_vol[bs]:
                        remove_fileset(self.opts.root, ns.name, shard.shard_id, bs, vol)
                        self.block_cache.invalidate_block(
                            ns.name, shard.shard_id, bs
                        )
                        stats["filesets"] += 1
                        if bs <= cutoff:
                            shard.flushed_blocks.discard(bs)
        # Quarantine entries age out WITH their data's retention: once
        # the block is out of retention everywhere, the evidence (and
        # the scrubber's repair worklist entry) has nothing left to
        # heal toward — without this the inventory and /health payload
        # grow forever.
        import shutil as _shutil

        max_keep = max(
            (ns.opts.retention_nanos + ns.opts.block_size_nanos
             for ns in self.namespaces.values()),
            default=48 * 3600 * 10**9,
        )
        for entry in quar.list_quarantined(self.opts.root):
            ns = self.namespaces.get(entry.get("namespace"))
            bs = entry.get("block_start")
            if ns is not None and isinstance(bs, int):
                expired = (bs <= now_nanos - ns.opts.retention_nanos
                           - ns.opts.block_size_nanos)
            else:
                # No retention anchor (quarantined snapshots, dropped
                # namespaces, unreadable reasons): age out on the
                # wall-clock quarantine time against the longest
                # retention any namespace keeps.
                qa = entry.get("quarantined_at")
                expired = (isinstance(qa, (int, float))
                           and qa * 1e9 <= now_nanos - max_keep)
            if expired:
                _shutil.rmtree(entry["dir"], ignore_errors=True)
                stats["quarantine_reaped"] = stats.get("quarantine_reaped", 0) + 1
        stats["snapshots"] = snap.prune_snapshots(self.opts.root, keep=1)
        latest = snap.latest_snapshot(self.opts.root)
        for log in list_commitlogs(self.opts.root):
            if self.commitlog is not None and log == self.commitlog.path:
                continue
            if latest is not None and commitlog_seq(log) < latest.commitlog_seq:
                log.unlink(missing_ok=True)
                stats["commitlogs"] += 1
            elif self._commitlog_fully_flushed(log):
                # Size-rotated segments (rotate_bytes) are not covered
                # by any snapshot, so without this check they live to
                # retention — a segment every entry of which is durable
                # in a checkpointed fileset protects nothing.
                log.unlink(missing_ok=True)
                stats["commitlogs"] += 1
        return stats

    def _commitlog_fully_flushed(self, log) -> bool:
        """True iff EVERY entry in the (inactive) segment is durable in
        a checkpointed fileset: its block is flushed and nothing for
        that block is still pending in the warm/cold buffers.  Entries
        for unknown namespaces or unflushed blocks keep the segment
        (conservative — replay may still need it)."""
        try:
            for e in read_commitlog(log):
                ns = self.namespaces.get(e.namespace.decode())
                if ns is None:
                    return False
                shard = ns.shards[
                    shard_for_id(e.series_id, ns.opts.num_shards)]
                bs = (e.timestamp // ns.opts.block_size_nanos
                      * ns.opts.block_size_nanos)
                if bs not in shard.flushed_blocks:
                    return False
                if (bs in shard.buffer.open_blocks
                        or bs in shard.buffer.cold):
                    return False
        except OSError:
            return False
        return True

    def _replay_entries(self, name: str, entries: list,
                        flushed_pts: Dict[tuple, dict] | None = None) -> int:
        """Write recovered entries into a namespace's buffers, skipping
        blocks already covered by a checkpointed fileset (the fs
        bootstrapper's unfulfilled-ranges rule).  Entries whose
        annotation carries encoded tags re-index their document too, so
        recovery rebuilds the (unsealed) reverse index.  Never re-logs."""
        ns = self.namespaces.get(name)
        if ns is None:
            return 0
        if ns.owned is not None:
            # Placement-scoped recovery: WAL/snapshot entries for shards
            # this node no longer owns are NOT re-buffered (a restarting
            # ex-donor must not resurrect handed-off shards; the new
            # owner already streamed or re-ingested them).
            entries = [
                e for e in entries
                if shard_for_id(e.series_id, ns.opts.num_shards) in ns.owned
            ]
            if not entries:
                return 0
        ts = np.asarray([e.timestamp for e in entries], np.int64)
        vals = np.asarray([e.value for e in entries], np.float64)
        ids = [e.series_id for e in entries]
        keep = np.ones(len(ts), bool)
        # Lazy cache of fileset contents for flushed blocks touched by
        # recovery: a point already in the fileset is a duplicate (drop);
        # a point absent from it is a pending cold write that crashed
        # before cold_flush — keep it, and write_batch re-routes it cold
        # because the flushed block is not in open_starts.  The caller
        # (bootstrap) shares one cache across all logs so each fileset
        # decodes once, not once per commitlog file.
        if flushed_pts is None:
            flushed_pts = {}
        for i, sid in enumerate(ids):
            shard_id = shard_for_id(sid, ns.opts.num_shards)
            sh = ns.shards[shard_id]
            bs = int(ts[i]) // ns.opts.block_size_nanos * ns.opts.block_size_nanos
            if bs not in sh.flushed_blocks:
                continue
            key = (name, shard_id, bs)
            if key not in flushed_pts:
                # Decode the highest INTACT volume; a corrupt one is
                # quarantined and a lower volume tried.  When nothing
                # intact remains the dedupe set is empty, so every WAL
                # entry for the block is KEPT and re-buffered — replay
                # re-covers exactly the data the corrupt fileset lost.
                def _decode_timestamps(vol, _bs=bs, _shard=shard_id):
                    r = DataFileSetReader(
                        self.opts.root, ns.name, _shard, _bs, vol
                    )
                    return {
                        fsid: {d.timestamp for d in decode_series(seg)}
                        for fsid, seg in r.read_all()
                    }

                flushed_pts[key] = (
                    sh._fold_intact_volumes(bs, _decode_timestamps) or {}
                )
            if int(ts[i]) in flushed_pts[key].get(sid, ()):
                keep[i] = False
        if not keep.any():
            return 0
        kept = np.nonzero(keep)[0]
        now = int(ts.max())
        tagged_idx = []
        tagged_docs = []
        for i in kept:
            ann = entries[i].annotation
            doc = decode_tags(ids[i], ann) if ann else None
            if doc is not None:
                tagged_idx.append(i)
                tagged_docs.append(doc)
        if tagged_docs:
            sel = np.asarray(tagged_idx)
            ns.write_tagged_batch(tagged_docs, ts[sel], vals[sel], now)
        tagged_set = set(tagged_idx)
        plain = [i for i in kept if i not in tagged_set]
        if plain:
            sel = np.asarray(plain)
            ns.write_batch([ids[i] for i in plain], ts[sel], vals[sel], now)
        return len(kept)

    def bootstrap(self) -> dict:
        """fs → snapshot → commitlog bootstrap chain (reference
        `storage/bootstrap/process.go` + bootstrapper/README.md: filesets
        first, then the latest snapshot, then WAL-tail replay for whatever
        isn't covered — `bootstrapper/commitlog` reads snapshots + WAL)."""
        with self._mu, self.tracer.start_span(Tracepoint.DB_BOOTSTRAP):
            # Replay re-admits previously-ACCEPTED series: the limiter
            # gates foreground churn only (the WAL never contains
            # rejected samples - see write_batch's log-after-accept).
            with self.new_series_limiter.bypass():
                return self._bootstrap_locked()

    def _bootstrap_locked(self) -> dict:
        # Torn-write sweep FIRST: a crash (or classified ENOSPC whose
        # unlink itself failed) between temp-write and rename leaves a
        # dead ``*.tmp*`` beside the real artifact — invisible to every
        # reader but holding disk the ledger would count forever.
        swept = cap.sweep_temp_files(self.opts.root)
        if swept:
            _LOG.info("bootstrap: swept %d torn temp file(s)", len(swept))
        restored = 0
        flushed_pts: Dict[tuple, dict] = {}  # shared fileset-decode cache
        latest = snap.latest_snapshot(self.opts.root)
        if latest is not None:
            snap_root = str(snap.snapshot_data_root(self.opts.root, latest.seq))
            for name, ns in self.namespaces.items():
                ns.index.restore_snapshot(snap_root)
                for shard in ns.shards:
                    entries: list[CommitLogEntry] = []
                    for bs, vol in list_filesets(snap_root, name, shard.shard_id):
                        try:
                            r = DataFileSetReader(
                                snap_root, name, shard.shard_id, bs, vol
                            )
                            for sid, seg in r.read_all():
                                entries.extend(
                                    CommitLogEntry(sid, d.timestamp, d.value,
                                                   namespace=name.encode())
                                    for d in decode_series(seg)
                                )
                        except CorruptionError as e:
                            # A rotted snapshot fileset must not abort
                            # node start: quarantine it (under the DB
                            # root) and keep whatever decoded cleanly —
                            # replicas/repair re-converge the remainder.
                            qdir = quar.quarantine_fileset(
                                snap_root, name, shard.shard_id, bs, vol, e,
                                qroot=self.opts.root,
                                label=f"snapshot-{latest.seq}",
                            )
                            _LOG.warning(
                                "quarantined corrupt snapshot fileset "
                                "seq=%d ns=%s shard=%d block=%d vol=%d: %s",
                                latest.seq, name, shard.shard_id, bs, vol, e,
                            )
                            self._note_corruption(
                                name, shard.shard_id, bs, vol, e,
                                quarantined=qdir is not None)
                    if entries:
                        restored += self._replay_entries(name, entries, flushed_pts)
        replayed = 0
        min_seq = latest.commitlog_seq if latest is not None else -1
        for log in list_commitlogs(self.opts.root):
            if self.commitlog is not None and log == self.commitlog.path:
                continue
            if commitlog_seq(log) < min_seq:
                continue  # fully covered by the snapshot
            per_ns: Dict[str, list] = {}
            for e in read_commitlog(log):
                per_ns.setdefault(e.namespace.decode(), []).append(e)
            for name, entries in per_ns.items():
                replayed += self._replay_entries(name, entries, flushed_pts)
        self.bootstrapped = True
        return {"commitlog_replayed": replayed, "snapshot_restored": restored,
                "temp_files_swept": len(swept)}

    def close(self) -> None:
        with self._mu:
            if self.commitlog is not None:
                self.commitlog.close()
