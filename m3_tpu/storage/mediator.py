"""Mediator: the background maintenance loop of the storage engine.

Equivalent of the reference's mediator (`src/dbnode/storage/mediator.go:74
struct, :159 Open, :284 ongoingTick, :318 runFileSystemProcesses`): one
orchestrator owning the periodic tick (seal + warm/cold flush), buffer
snapshots, and expired-data cleanup, so callers never drive those by hand.

Differences by design: the reference interleaves a tick pipeline over
every namespace/shard with per-step locking; here each `run_once` is a
single-threaded pass (the Database's engine work is batched array
programs, so the win is in the kernels, not goroutine interleaving).  A
deterministic `clock` injection point replaces the reference's
clock.Options for tests — the same controllable-clock trick its
integration harness uses (`integration/setup.go` nowFn overrides).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from m3_tpu.instrument import logger
from m3_tpu.storage.database import Database

_LOG = logger("storage.mediator")


def _wall_clock_nanos() -> int:
    return time.time_ns()


class Mediator:
    """Drives tick → snapshot → cleanup on an interval (or on demand)."""

    def __init__(
        self,
        db: Database,
        clock: Callable[[], int] = _wall_clock_nanos,
        tick_interval_s: float = 10.0,
        snapshot_every: int = 6,
        cleanup_every: int = 6,
        scrubber=None,
        scrub_every: int = 1,
        migrator=None,
        migrate_every: int = 1,
        downsampler=None,
        checkpointer=None,
        checkpoint_every: int = 0,
        selfmon=None,
        selfmon_every: int = 1,
        controller=None,
        controller_every: int = 1,
        diskpressure=None,
        instrument=None,
    ):
        self.db = db
        self.clock = clock
        self.tick_interval_s = tick_interval_s
        self.snapshot_every = max(1, snapshot_every)
        self.cleanup_every = max(1, cleanup_every)
        # Optional storage.scrub.Scrubber: the corruption sweep rides
        # the same maintenance loop as flush/snapshot/cleanup, budgeted
        # per pass so it never monopolizes a tick.
        self.scrubber = scrubber
        self.scrub_every = max(1, scrub_every)
        # Optional storage.migration.ShardMigrator: the shard lifecycle
        # (stream INITIALIZING, cut over, grace-drop LEAVING leftovers)
        # runs off this same thread, budgeted per tick like the scrub.
        self.migrator = migrator
        self.migrate_every = max(1, migrate_every)
        # Optional coordinator Downsampler: its window drain rides the
        # maintenance loop (the reference coordinator's flush manager
        # role) — without this, a live node's downsampled aggregates
        # would only ever flush on drain.
        self.downsampler = downsampler
        # Optional aggregator.checkpoint.AggregatorCheckpointer: the
        # arena checkpoint rides the tick cadence (plus SIGTERM drain),
        # so a SIGKILL loses at most checkpoint_every ticks of window
        # state; 0 disables the periodic save.
        self.checkpointer = checkpointer
        self.checkpoint_every = checkpoint_every
        # Optional instrument.selfmon.SelfMonitor: the self-scrape
        # (registry + fleet peers → the _m3_selfmon namespace through
        # the real write path) and the SLO burn-rate evaluation ride
        # the maintenance loop on their own cadence.
        self.selfmon = selfmon
        self.selfmon_every = max(1, selfmon_every)
        # Optional x.controller.Controller: the self-healing pass reads
        # the verdicts the selfmon stage just refreshed and acts through
        # its typed actuator registry — sensor before controller, every
        # pass, by construction.
        self.controller = controller
        self.controller_every = max(1, controller_every)
        # Optional disk-pressure stage (assembly closure over
        # x.diskbudget + Database.cleanup): refreshes the disk ledger
        # every pass and runs cleanup EAGERLY at/above the LOW
        # watermark — pressure-driven reclaim instead of cadence.
        self.diskpressure = diskpressure
        self._ticks = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._scope = (
            instrument.scope("mediator") if instrument is not None else None
        )
        # Timer (lifetime reservoir) is the RIGHT instrument here and
        # deliberately kept: the mediator ticks every few seconds, so a
        # windowed histogram would mostly be empty, and "how have ticks
        # behaved over the process's life" is the question an operator
        # asks.  Hot paths (ingest/query/flush) use Histogram instead —
        # see instrument.Timer's staleness caveat.
        self._timer_tick = (self._scope.timer("tick_wall_seconds")
                            if self._scope is not None else None)
        # Optional condition-triggered profiler (reference
        # triggering_profile.go): observe() gets each pass's wall
        # duration, so a slow tick auto-captures a debug bundle.
        self.profiler = None

    def run_once(self, now_nanos: int | None = None) -> dict:
        """One maintenance pass: tick (seal+flush) every call, snapshot and
        cleanup on their cadence (mediator.go:284 ongoingTick + :318
        runFileSystemProcesses)."""
        with self._lock:
            t0 = time.monotonic()
            now = self.clock() if now_nanos is None else now_nanos
            stats: dict = {"tick": self.db.tick(now)}
            self._ticks += 1
            if self._ticks % self.snapshot_every == 0:
                stats["snapshot"] = self.db.snapshot()
            if self._ticks % self.cleanup_every == 0:
                stats["cleanup"] = self.db.cleanup(now)
            if self.diskpressure is not None:
                # After flush/snapshot/cleanup (their writes are the
                # bytes being measured), before selfmon (so this pass's
                # scrape stores the watermark the ledger just computed).
                try:
                    stats["disk"] = self.diskpressure(now)
                except Exception:  # noqa: BLE001 — a failing ledger
                    # walk must not disable maintenance; counted so a
                    # silently-dead disk stage is visible on /metrics
                    _LOG.exception("mediator: disk-pressure stage failed")
                    if self._scope is not None:
                        self._scope.counter("disk_pressure_errors").inc()
            if (self.migrator is not None
                    and self._ticks % self.migrate_every == 0):
                # Shard lifecycle before the scrub stage: a freshly
                # streamed block is immediately eligible for verify,
                # and a due drop frees its volumes before the sweep
                # re-lists them.
                stats["topology"] = self.migrator.tick()
            if self.downsampler is not None:
                try:
                    stats["downsample_flushed"] = self.downsampler.flush(now)
                except Exception:  # noqa: BLE001 — one bad drain must
                    # not disable flush/snapshot/cleanup for the pass
                    _LOG.exception("mediator: downsampler flush failed")
                    if self._scope is not None:
                        self._scope.counter("downsample_flush_errors").inc()
            if (self.selfmon is not None
                    and self._ticks % self.selfmon_every == 0):
                # Self-scrape AFTER the flush stages so the cycle's
                # samples record this tick's flush counters; the writes
                # land in open buffers and seal on a later tick like
                # any other ingest.
                try:
                    stats["selfmon"] = self.selfmon.tick(now)
                except Exception:  # noqa: BLE001 — a failing scrape
                    # must not disable flush/snapshot/cleanup; counted
                    # so a silently-dead selfmon is visible on /metrics
                    _LOG.exception("mediator: selfmon tick failed")
                    if self._scope is not None:
                        self._scope.counter("selfmon_tick_errors").inc()
            if (self.controller is not None
                    and self._ticks % self.controller_every == 0):
                # Self-healing AFTER selfmon so each pass acts on the
                # verdicts evaluated THIS tick, never last tick's.
                try:
                    stats["controller"] = self.controller.tick(now)
                except Exception:  # noqa: BLE001 — a failing control
                    # pass must not disable maintenance; counted so a
                    # silently-dead controller is visible on /metrics
                    _LOG.exception("mediator: controller tick failed")
                    if self._scope is not None:
                        self._scope.counter("controller_tick_errors").inc()
            if (self.checkpointer is not None and self.checkpoint_every > 0
                    and self._ticks % self.checkpoint_every == 0):
                try:
                    stats["checkpoint"] = self.checkpointer.save()
                except Exception:  # noqa: BLE001 — counted by the
                    # checkpointer; the tick's remaining stages still run
                    _LOG.exception("mediator: aggregator checkpoint failed")
            if (self.scrubber is not None
                    and self._ticks % self.scrub_every == 0):
                # Non-blocking: an admin-triggered whole-disk scrub in
                # flight must not stall flush/snapshot/cleanup — the
                # tick just skips its scrub stage and retries next pass.
                stats["scrub"] = self.scrubber.run_once(wait=False)
            if self._scope is not None:
                self._scope.counter("ticks").inc()
                for ns_stats in stats["tick"].values():
                    self._scope.counter("warm_flushed").inc(
                        ns_stats.get("warm_flushed", 0)
                    )
                    self._scope.counter("cold_flushed").inc(
                        ns_stats.get("cold_flushed", 0)
                    )
            stats["duration_s"] = time.monotonic() - t0
            if self._timer_tick is not None:
                self._timer_tick.record(stats["duration_s"])
            if self.profiler is not None:
                stats["profile"] = self.profiler.observe(stats["duration_s"])
            return stats

    # -- background loop ---------------------------------------------------

    def open(self) -> None:
        """Start the background loop (mediator.go:159 Open)."""
        if self._thread is not None:
            raise RuntimeError("mediator already open")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                # A persistently failing tick silently disabling
                # flush/snapshot/cleanup would be invisible data-loss
                # risk — always log, count when metered.
                _LOG.exception("mediator tick failed")
                if self._scope is not None:
                    self._scope.counter("tick_errors").inc()
