"""Device series buffer: the in-memory mutable head of every series.

Re-design of the reference's per-series `dbBuffer`
(`src/dbnode/storage/series/buffer.go:221-247` BufferBucketVersions per
block start; `Write` classifies warm/cold vs bufferPast/bufferFuture
`buffer.go:290-413`; `WarmFlush` merges bucket streams `buffer.go:634`).
Instead of an encoder object per (series, block), the whole shard buffers
into a ring of **append logs on device** — one per open block window:

    slot (W, S) i32 | ts (W, S) i64 | val (W, S) f64 | n (W,)

Ingest is a single scatter per batch (same layout as the timer sample
arenas).  Seal/flush drains a window with one lex-sort by
(slot, ts, arrival) + last-write-wins dedupe — the analogue of the
reference's bucket-merge at flush, where later writes at the same
timestamp win (buffer.go conflict resolution on merge) — and hands the
host sorted runs ready for the batched M3TSZ encoder.

Out-of-window writes (cold writes / too-late / too-future) never touch the
device: the host routes them to a per-block overflow list, flushed as a
higher fileset volume (the reference's cold flush,
`storage/coldflush.go` + `fs_merge_with_mem.go`).

Device-fault contract (round 12): the two device entry points —
``buffer_append`` on the write path, ``buffer_drain`` on the
seal/snapshot/read path — run behind the ``x.devguard`` seam.  A
classified device failure (XLA OOM, lost device, an over-budget grow
rejected by ``x.membudget``) degrades instead of dropping acked
samples: the append falls back to staging the batch on the SAME host
overflow lists the cold path uses (readable immediately via
``read_sources``, snapshot-covered, merged in by the next cold flush
AFTER the block seals), and the drain falls back to a bit-identical
numpy sort+dedupe of the transferred columns.  The stage breakers
(``storage.buffer_append`` / ``storage.buffer_drain``) trip after
consecutive failures, skip the device entirely while open, and
half-open re-probe it — visible on /metrics and /health like every
other edge.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from m3_tpu.x import devguard, membudget


class BufferState(NamedTuple):
    slot: jnp.ndarray  # i32 (W, S); capacity = empty sentinel
    ts: jnp.ndarray  # i64 (W, S)
    val: jnp.ndarray  # f64 (W, S)
    n: jnp.ndarray  # i64 (W,)


def buffer_init(num_windows: int, sample_capacity: int, slot_capacity: int) -> BufferState:
    return BufferState(
        slot=jnp.full((num_windows, sample_capacity), slot_capacity, jnp.int32),
        ts=jnp.full((num_windows, sample_capacity), jnp.iinfo(jnp.int64).max, jnp.int64),
        val=jnp.zeros((num_windows, sample_capacity), jnp.float64),
        n=jnp.zeros(num_windows, jnp.int64),
    )


@functools.partial(jax.jit, donate_argnums=0)
def buffer_append(
    state: BufferState,
    windows: jnp.ndarray,  # i32 (N,) ring row per sample; OOB drops
    slots: jnp.ndarray,  # i32 (N,)
    ts: jnp.ndarray,  # i64 (N,)
    vals: jnp.ndarray,  # f64 (N,)
) -> BufferState:
    num_w, scap = state.slot.shape
    n = slots.shape[0]
    oob = (windows < 0) | (windows >= num_w)
    wkey = jnp.where(oob, num_w, windows)
    # Stable sort by window keeps arrival order within each window.
    s_w, s_slot, s_ts, s_val = jax.lax.sort(
        (wkey, slots, ts, vals), num_keys=1, is_stable=True
    )
    pos = jnp.arange(n, dtype=jnp.int64)
    rank = pos - jnp.searchsorted(s_w, s_w, side="left")
    base = state.n[jnp.clip(s_w, 0, num_w - 1)]
    dst = base + rank
    flat = jnp.where(
        (s_w < num_w) & (dst < scap), s_w.astype(jnp.int64) * scap + dst, num_w * scap
    )
    per_w = jnp.bincount(wkey, length=num_w)

    def _scatter(ops):
        fslot, fts, fval = ops
        return (fslot.at[flat].set(s_slot, mode="drop"),
                fts.at[flat].set(s_ts, mode="drop"),
                fval.at[flat].set(s_val, mode="drop"))

    flat_slot = state.slot.ravel()
    flat_ts = state.ts.ravel()
    flat_val = state.val.ravel()
    if n > 0 and n <= scap:
        # A batch whose samples ALL target one valid window and fit
        # appends CONTIGUOUSLY at that window's write head: one
        # dynamic_update_slice (memcpy) per column instead of a scatter
        # (~1us/element on TPU — TPU_RESULTS_r05.json window #3).  The
        # common dbnode shape: in-order writes land in one warm window
        # of the multi-window ring, so the gate is on the BATCH, not
        # the ring size.
        row = jnp.clip(windows[0], 0, num_w - 1).astype(jnp.int64)
        same = jnp.logical_not(oob.any()) & (windows == windows[0]).all()
        fits = same & (state.n[row] + n <= scap)

        def _dus(ops):
            fslot, fts, fval = ops
            start = row * scap + state.n[row]
            return (
                jax.lax.dynamic_update_slice_in_dim(fslot, s_slot, start, 0),
                jax.lax.dynamic_update_slice_in_dim(fts, s_ts, start, 0),
                jax.lax.dynamic_update_slice_in_dim(fval, s_val, start, 0),
            )

        new_slot, new_ts, new_val = jax.lax.cond(
            fits, _dus, _scatter, (flat_slot, flat_ts, flat_val))
    else:
        new_slot, new_ts, new_val = _scatter((flat_slot, flat_ts, flat_val))
    return BufferState(
        slot=new_slot.reshape(num_w, scap),
        ts=new_ts.reshape(num_w, scap),
        val=new_val.reshape(num_w, scap),
        n=state.n + per_w,
    )


@jax.jit
def buffer_drain(state: BufferState, window: jnp.ndarray):
    """One window -> (slot, ts, val, keep) sorted by (slot, ts).

    keep masks out empty sentinel entries and duplicate (slot, ts) pairs
    — the *last arrival* wins, matching the reference's merge rule where
    a later write at the same timestamp supersedes.
    """
    slot_w = jax.lax.dynamic_index_in_dim(state.slot, window, keepdims=False)
    ts_w = jax.lax.dynamic_index_in_dim(state.ts, window, keepdims=False)
    val_w = jax.lax.dynamic_index_in_dim(state.val, window, keepdims=False)
    scap = slot_w.shape[0]
    # arrival descending so the latest write sorts first within (slot, ts)
    arr_desc = jnp.arange(scap - 1, -1, -1, dtype=jnp.int64)
    s_slot, s_ts, _arr, s_val = jax.lax.sort(
        (slot_w, ts_w, arr_desc, val_w), num_keys=3
    )
    first = jnp.concatenate(
        [jnp.ones(1, bool), (s_slot[1:] != s_slot[:-1]) | (s_ts[1:] != s_ts[:-1])]
    )
    return s_slot, s_ts, s_val, first


def dedupe_last_write_wins(slots: np.ndarray, ts: np.ndarray, vals: np.ndarray):
    """Sort by (slot, ts) and keep the LAST-arriving sample per (slot, ts)
    — the one merge rule every host-side path shares (cold drain,
    snapshot merge), mirroring the device path in `buffer_drain`."""
    arrival = np.arange(len(slots))
    order = np.lexsort((-arrival, ts, slots))
    slots, ts, vals = slots[order], ts[order], vals[order]
    first = np.ones(len(slots), bool)
    first[1:] = (slots[1:] != slots[:-1]) | (ts[1:] != ts[:-1])
    return slots[first], ts[first], vals[first]


class ShardBuffer:
    """Host wrapper owning one shard's buffer ring + overflow lists."""

    def __init__(self, block_size_nanos: int, num_windows: int,
                 sample_capacity: int, slot_capacity: int):
        self.block_size = block_size_nanos
        self.num_windows = num_windows
        self.sample_capacity = sample_capacity
        self.slot_capacity = slot_capacity
        # Admission before allocation: an over-budget ring rejects
        # typed (DeviceBudgetExceeded) here instead of OOM-ing inside
        # XLA; released automatically when this buffer is collected.
        self._mem = membudget.reserve(
            "storage.buffer",
            membudget.buffer_bytes(num_windows, sample_capacity),
            owner=self)
        self.state = buffer_init(num_windows, sample_capacity, slot_capacity)
        # Warm samples routed to the host overflow lists while the
        # device path is degraded (the buffer_append fallback); counted
        # for /metrics-style visibility through devguard's counters and
        # surfaced per-buffer for tests.
        self.degraded_staged = 0
        self._n_host = np.zeros(num_windows, np.int64)
        # block_start -> ring row for open windows
        self.open_blocks: dict[int, int] = {}
        # block_start -> [(slot, ts, val)] host overflow (cold writes)
        self.cold: dict[int, list] = {}
        # Sorted-window snapshot cache: every read of an open window
        # (single-series, batched verify, snapshot peek) needs the SAME
        # device sort+dedupe of the whole window, which is O(window) —
        # at 1M buffered samples that is ~100ms of sort + a multi-MB
        # device→host transfer PER READ.  One version counter (bumped
        # on any mutation) makes the sorted snapshot reusable: K reads
        # between two writes pay ONE drain + K binary searches.
        self._version = 0
        self._snap: dict[int, tuple] = {}  # block_start -> (version, s, t, v)

    def _row_for(self, block_start: int) -> int:
        return (block_start // self.block_size) % self.num_windows

    def write(self, slots: np.ndarray, ts: np.ndarray, vals: np.ndarray,
              open_starts: set[int]) -> int:
        """Append a batch.  open_starts = block starts currently accepting
        warm writes (decided by the shard: retention/bufferPast/Future).
        Returns count of samples routed to the cold path."""
        block_starts = (ts // self.block_size) * self.block_size
        warm = np.isin(block_starts, list(open_starts))
        ncold = int((~warm).sum())
        if ncold:
            for bs in np.unique(block_starts[~warm]):
                sel = (~warm) & (block_starts == bs)
                self.cold.setdefault(int(bs), []).append(
                    (slots[sel].copy(), ts[sel].copy(), vals[sel].copy())
                )
        if warm.any():
            wslots, wts, wvals = slots[warm], ts[warm], vals[warm]
            wstarts = block_starts[warm]

            def _device_append():
                self._version += 1  # sorted snapshots are now stale
                rows = ((wstarts // self.block_size)
                        % self.num_windows).astype(np.int32)
                for bs in np.unique(wstarts):
                    self.open_blocks[int(bs)] = self._row_for(int(bs))
                per_row = np.bincount(rows, minlength=self.num_windows)
                if (self._n_host + per_row).max() > self.sample_capacity:
                    self._grow(int((self._n_host + per_row).max()))
                state = buffer_append(
                    self.state,
                    jnp.asarray(rows),
                    jnp.asarray(wslots.astype(np.int32)),
                    jnp.asarray(wts.astype(np.int64)),
                    jnp.asarray(wvals.astype(np.float64)),
                )
                self._n_host += per_row
                self.state = state

            def _host_stage():
                # Degraded path: warm samples land on the SAME host
                # overflow lists the cold path owns — acked samples
                # stay readable (read_sources serves the cold lists)
                # and snapshot-covered; cold_flush merges them in only
                # AFTER the block seals (Namespace.tick passes the
                # open-window skip set), so the sealed warm volume is
                # never overwritten by an early degraded flush.
                for bs in np.unique(wstarts):
                    sel = wstarts == bs
                    self.cold.setdefault(int(bs), []).append(
                        (wslots[sel].copy(), wts[sel].copy(),
                         wvals[sel].copy()))
                self.degraded_staged += len(wslots)

            devguard.run_guarded("storage.buffer_append",
                                 _device_append, _host_stage)
        return ncold

    def _grow(self, needed: int) -> None:
        new_cap = self.sample_capacity
        while new_cap < needed:
            new_cap *= 2
        # Admit the growth BEFORE padding: an over-budget grow raises
        # typed inside the guarded append, which degrades this batch to
        # the host staging path instead of OOM-ing in XLA.
        self._mem.resize(membudget.buffer_bytes(self.num_windows, new_cap))
        pad = new_cap - self.sample_capacity
        imax = np.iinfo(np.int64).max
        self.state = BufferState(
            slot=jnp.pad(self.state.slot, ((0, 0), (0, pad)),
                         constant_values=self.slot_capacity),
            ts=jnp.pad(self.state.ts, ((0, 0), (0, pad)), constant_values=imax),
            val=jnp.pad(self.state.val, ((0, 0), (0, pad))),
            n=self.state.n,
        )
        self.sample_capacity = new_cap

    def _drain_row(self, row: int):
        """One window's (slot, ts, val, first) as host arrays, behind
        the ``storage.buffer_drain`` guard: the device sort falls back
        to a bit-identical numpy lexsort of the transferred columns
        when the device path is degraded."""

        def _device():
            s_slot, s_ts, s_val, first = buffer_drain(
                self.state, jnp.int32(row))
            devguard.transfer_point("storage.buffer_drain")
            return (np.asarray(s_slot), np.asarray(s_ts),
                    np.asarray(s_val), np.asarray(first))

        return devguard.run_guarded("storage.buffer_drain", _device,
                                    lambda: self._host_drain(row))

    def _host_drain(self, row: int):
        """Numpy mirror of :func:`buffer_drain` — same (slot, ts,
        arrival-desc) order, same first mask; the degraded-mode tail."""
        slot_w = np.asarray(self.state.slot)[row]
        ts_w = np.asarray(self.state.ts)[row]
        val_w = np.asarray(self.state.val)[row]
        arrival = np.arange(len(slot_w))
        order = np.lexsort((-arrival, ts_w, slot_w))
        s_slot, s_ts, s_val = slot_w[order], ts_w[order], val_w[order]
        first = np.ones(len(s_slot), bool)
        first[1:] = (s_slot[1:] != s_slot[:-1]) | (s_ts[1:] != s_ts[:-1])
        return s_slot, s_ts, s_val, first

    def drain(self, block_start: int):
        """Seal one open block: device sort+dedupe, then host-side
        ragged split.  Returns (slots, ts, vals) sorted by (slot, ts)
        with duplicates resolved last-write-wins; clears the window."""
        row = self.open_blocks.pop(block_start, None)
        if row is None:
            return (np.empty(0, np.int32), np.empty(0, np.int64), np.empty(0))
        s_slot, s_ts, s_val, first = self._drain_row(row)
        keep = first & (s_slot < self.slot_capacity)
        out = (s_slot[keep], s_ts[keep], s_val[keep])
        self._reset_row(row)
        return out

    def _reset_row(self, row: int) -> None:
        self._version += 1
        imax = np.iinfo(np.int64).max
        self.state = BufferState(
            slot=self.state.slot.at[row].set(self.slot_capacity),
            ts=self.state.ts.at[row].set(imax),
            val=self.state.val,
            n=self.state.n.at[row].set(0),
        )
        self._n_host[row] = 0

    def discard(self, block_start: int) -> None:
        """Drop one open window WITHOUT the drain sort.  The flush path
        reads via :meth:`peek`, writes the volume, and discards only
        once the write is durably on disk — an ENOSPC mid-write leaves
        the window buffered and readable for the next tick's retry."""
        row = self.open_blocks.pop(block_start, None)
        if row is not None:
            self._reset_row(row)

    def drain_cold(self, block_start: int):
        """Pull the overflow list for one block (sorted, deduped)."""
        parts = self.cold.pop(block_start, None)
        return self._merge_cold(parts)

    def peek_cold(self, block_start: int):
        """Non-destructive :meth:`drain_cold` — pair with
        :meth:`discard_cold` after the merged volume lands on disk."""
        return self._merge_cold(self.cold.get(block_start))

    def discard_cold(self, block_start: int) -> None:
        self.cold.pop(block_start, None)

    @staticmethod
    def _merge_cold(parts):
        if not parts:
            return (np.empty(0, np.int32), np.empty(0, np.int64), np.empty(0))
        slots = np.concatenate([p[0] for p in parts]).astype(np.int32)
        ts = np.concatenate([p[1] for p in parts]).astype(np.int64)
        vals = np.concatenate([p[2] for p in parts]).astype(np.float64)
        return dedupe_last_write_wins(slots, ts, vals)

    def _sorted_window(self, block_start: int):
        """(slots, ts, vals) of one open window, sorted by (slot, ts),
        deduped last-write-wins, sentinel-stripped — served from the
        version-stamped snapshot cache (invalidated by any write/drain)
        so reads between mutations share ONE device sort instead of
        paying O(window) each."""
        row = self.open_blocks.get(block_start)
        if row is None:
            return None
        hit = self._snap.get(block_start)
        if hit is not None and hit[0] == self._version:
            return hit[1:]
        s_slot, s_ts, s_val, first = self._drain_row(row)
        keep = first & (s_slot < self.slot_capacity)
        out = (s_slot[keep], s_ts[keep], s_val[keep])
        # one snapshot per OPEN window (reads alternate between open
        # blocks per series — a single-entry cache would thrash back to
        # O(window) per read); closed windows' entries are pruned here
        self._snap = {
            bs: v for bs, v in self._snap.items() if bs in self.open_blocks
        }
        self._snap[block_start] = (self._version,) + out
        return out

    def peek(self, block_start: int):
        """Non-destructive drain of one open window: (slots, ts, vals)
        sorted+deduped, state untouched — the snapshot read
        (reference buffer.go:537 Snapshot streams the open buckets
        without evicting them)."""
        snap = self._sorted_window(block_start)
        if snap is None:
            return (np.empty(0, np.int32), np.empty(0, np.int64), np.empty(0))
        return snap

    def read_window(self, block_start: int, slot: int):
        """Read one series' points from an open (unsealed) block — the
        read path's buffer component (buffer.go:705 ReadEncoded).  A
        binary search over the sorted snapshot: O(log window) per call
        once the snapshot is warm."""
        snap = self._sorted_window(block_start)
        if snap is None:
            return np.empty(0, np.int64), np.empty(0)
        s_slot, s_ts, s_val = snap
        lo, hi = np.searchsorted(s_slot, [slot, slot + 1])
        return s_ts[lo:hi], s_val[lo:hi]

    def read_window_many(self, block_start: int, slots: np.ndarray):
        """Batched :meth:`read_window`: one sorted snapshot serves every
        requested slot (the bulk-verify / batched-fetch read path —
        without this, reading S series out of a window costs S full
        window sorts).  Returns ``[(ts, vals), ...]`` aligned with
        ``slots``; a slot < 0 (unknown series) yields empty arrays."""
        empty = (np.empty(0, np.int64), np.empty(0))
        snap = self._sorted_window(block_start)
        if snap is None:
            return [empty for _ in slots]
        s_slot, s_ts, s_val = snap
        slots = np.asarray(slots, np.int64)
        los = np.searchsorted(s_slot, slots)
        his = np.searchsorted(s_slot, slots + 1)
        return [
            (s_ts[lo:hi], s_val[lo:hi]) if (hi > lo and sl >= 0) else empty
            for sl, lo, hi in zip(slots.tolist(), los.tolist(), his.tolist())
        ]
