"""Block cache: LRU of open fileset readers and decoded series blocks.

Equivalent of the reference's two read-path caches: the seek manager's
open-seeker pools (`src/dbnode/persist/fs/seek_manager.go` — one open
reader per (shard, blockStart) reused across reads) and the WiredList
(`src/dbnode/storage/block` — a capacity-bounded LRU of decompressed
blocks evicted least-recently-used).  Without them every query re-reads
and re-decodes the fileset from disk (round-1 VERDICT #6 weakness).

Keys include the volume, so a cold flush writing volume+1 naturally
misses the stale entries; `invalidate_block` drops them eagerly so the
LRU doesn't pin dead volumes.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from m3_tpu.encoding.m3tsz import decode_series
from m3_tpu.persist.fs import DataFileSetReader


class BlockCache:
    def __init__(self, max_readers: int = 64, max_series_blocks: int = 8192,
                 instrument=None):
        self._readers: OrderedDict[tuple, DataFileSetReader] = OrderedDict()
        self._series: OrderedDict[tuple, list] = OrderedDict()
        self.max_readers = max_readers
        self.max_series_blocks = max_series_blocks
        self._lock = threading.Lock()
        self._scope = (
            instrument.scope("block_cache") if instrument is not None else None
        )

    # -- readers (seek manager role) ---------------------------------------

    def reader(self, root, namespace: str, shard: int, block_start: int,
               volume: int) -> DataFileSetReader:
        key = (str(root), namespace, shard, block_start, volume)
        with self._lock:
            r = self._readers.get(key)
            if r is not None:
                self._readers.move_to_end(key)
                return r
        r = DataFileSetReader(root, namespace, shard, block_start, volume)
        evicted = []
        with self._lock:
            self._readers[key] = r
            self._readers.move_to_end(key)
            while len(self._readers) > self.max_readers:
                evicted.append(self._readers.popitem(last=False)[1])
        for old in evicted:  # release the persistent data handles
            old.close()
        return r

    # -- decoded blocks (WiredList role) -----------------------------------

    def read_series(self, root, namespace: str, shard: int, block_start: int,
                    volume: int, sid: bytes) -> list | None:
        """Decoded [(ts, value)] for one series-block, or None when the
        fileset has no entry for `sid`."""
        key = (str(root), namespace, shard, block_start, volume, sid)
        with self._lock:
            if key in self._series:
                self._series.move_to_end(key)
                if self._scope is not None:
                    self._scope.counter("hits").inc()
                return self._series[key]
        if self._scope is not None:
            self._scope.counter("misses").inc()
        seg = self.reader(root, namespace, shard, block_start, volume).read(sid)
        pts = (
            [(d.timestamp, d.value) for d in decode_series(seg)]
            if seg else None
        )
        with self._lock:
            self._series[key] = pts
            self._series.move_to_end(key)
            while len(self._series) > self.max_series_blocks:
                self._series.popitem(last=False)
        return pts

    # -- invalidation ------------------------------------------------------

    def invalidate_block(self, namespace: str, shard: int,
                         block_start: int) -> None:
        """Drop every volume's entries for one block (cold flush wrote a
        superseding volume; cleanup removed the files)."""
        closing = []
        with self._lock:
            for store in (self._readers, self._series):
                dead = [
                    k for k in store
                    if k[1] == namespace and k[2] == shard and k[3] == block_start
                ]
                for k in dead:
                    item = store.pop(k)
                    if store is self._readers:
                        closing.append(item)
        for r in closing:
            r.close()

    def clear(self) -> None:
        with self._lock:
            readers = list(self._readers.values())
            self._readers.clear()
            self._series.clear()
        for r in readers:
            r.close()

    @property
    def stats(self) -> dict:
        with self._lock:
            return {
                "readers": len(self._readers),
                "series_blocks": len(self._series),
            }
