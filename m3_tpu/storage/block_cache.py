"""Block cache: LRU of open fileset readers and decoded series blocks.

Equivalent of the reference's two read-path caches: the seek manager's
open-seeker pools (`src/dbnode/persist/fs/seek_manager.go` — one open
reader per (shard, blockStart) reused across reads) and the WiredList
(`src/dbnode/storage/block` — a capacity-bounded LRU of decompressed
blocks evicted least-recently-used).  Without them every query re-reads
and re-decodes the fileset from disk (round-1 VERDICT #6 weakness).

Keys include the volume, so a cold flush writing volume+1 naturally
misses the stale entries; `invalidate_block` drops them eagerly so the
LRU doesn't pin dead volumes.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from m3_tpu.encoding.m3tsz import decode_series
from m3_tpu.persist.fs import DataFileSetReader


# Python-object cost of one cached (ts, value) tuple: the tuple header
# (~56 B) + an int and a float object (~60 B) + the list slot (8 B).
# Budgeting raw payload (16 B) would admit ~6x the configured memory.
_POINT_BYTES = 124
_ENTRY_OVERHEAD = 120  # key tuple + list object bookkeeping, approximate


def _entry_bytes(pts) -> int:
    return _ENTRY_OVERHEAD + (_POINT_BYTES * len(pts) if pts else 0)


class BlockCache:
    """Seek-manager + wired-list tier.

    * readers: open-fileset LRU capped by count (each pins an mmap and
      a parsed index — the seek manager's open-seeker pool).
    * decoded series-blocks: LRU bounded by a BYTE budget, the
      reference WiredList's capacity model (`storage/block` wires
      decompressed blocks up to a byte limit, evicting LRU), with
      single-flight decode so concurrent readers of one cold
      series-block pay one disk fetch (retriever.go request
      coalescing).
    """

    def __init__(self, max_readers: int = 64,
                 max_bytes: int = 64 << 20,
                 instrument=None):
        self._readers: OrderedDict[tuple, DataFileSetReader] = OrderedDict()
        self._series: OrderedDict[tuple, list] = OrderedDict()
        self._series_bytes = 0
        self.max_readers = max_readers
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._inflight: dict[tuple, threading.Event] = {}
        self._scope = (
            instrument.scope("block_cache") if instrument is not None else None
        )

    # -- readers (seek manager role) ---------------------------------------

    def reader(self, root, namespace: str, shard: int, block_start: int,
               volume: int) -> DataFileSetReader:
        key = (str(root), namespace, shard, block_start, volume)
        with self._lock:
            r = self._readers.get(key)
            if r is not None:
                self._readers.move_to_end(key)
                return r
        r = DataFileSetReader(root, namespace, shard, block_start, volume)
        with self._lock:
            self._readers[key] = r
            self._readers.move_to_end(key)
            while len(self._readers) > self.max_readers:
                # Drop the pool's reference only: a concurrent borrower
                # may still be mid-read on the evicted reader, and
                # closing its mmap under it would poison that read.
                # DataFileSetReader.close()/__del__ (persist/fs.py)
                # release the fd+mmap when the last borrower's reference
                # dies — immediate under CPython refcounting, the only
                # runtime this framework targets (the role of the seek
                # manager's borrow counts).
                self._readers.popitem(last=False)
        return r

    # -- decoded blocks (WiredList role) -----------------------------------

    def read_series(self, root, namespace: str, shard: int, block_start: int,
                    volume: int, sid: bytes) -> list | None:
        """Decoded [(ts, value)] for one series-block, or None when the
        fileset has no entry for `sid`."""
        key = (str(root), namespace, shard, block_start, volume, sid)
        while True:
            with self._lock:
                if key in self._series:
                    self._series.move_to_end(key)
                    if self._scope is not None:
                        self._scope.counter("hits").inc()
                    return self._series[key]
                ev = self._inflight.get(key)
                if ev is None:
                    # this thread owns the fetch (single-flight)
                    self._inflight[key] = threading.Event()
                    break
            # another thread is decoding the same series-block: wait and
            # re-check the cache instead of duplicating the disk read
            ev.wait()
        if self._scope is not None:
            self._scope.counter("misses").inc()
        try:
            seg = self.reader(root, namespace, shard, block_start,
                              volume).read(sid)
            pts = (
                [(d.timestamp, d.value) for d in decode_series(seg)]
                if seg else None
            )
            with self._lock:
                self._series[key] = pts
                self._series.move_to_end(key)
                self._series_bytes += _entry_bytes(pts)
                while (self._series_bytes > self.max_bytes
                       and len(self._series) > 1):
                    _, old = self._series.popitem(last=False)
                    self._series_bytes -= _entry_bytes(old)
                    if self._scope is not None:
                        self._scope.counter("evictions").inc()
            return pts
        finally:
            with self._lock:
                self._inflight.pop(key).set()

    # -- invalidation ------------------------------------------------------

    def invalidate_block(self, namespace: str, shard: int,
                         block_start: int) -> None:
        """Drop every volume's entries for one block (cold flush wrote a
        superseding volume; cleanup removed the files)."""
        with self._lock:
            for store in (self._readers, self._series):
                dead = [
                    k for k in store
                    if k[1] == namespace and k[2] == shard and k[3] == block_start
                ]
                for k in dead:
                    item = store.pop(k)
                    if store is not self._readers:
                        self._series_bytes -= _entry_bytes(item)
                    # evicted readers close via refcount (__del__), not
                    # here — a borrower may still be reading

    def clear(self) -> None:
        with self._lock:
            self._readers.clear()  # refcount close-deferral as above
            self._series.clear()
            self._series_bytes = 0

    @property
    def stats(self) -> dict:
        with self._lock:
            return {
                "readers": len(self._readers),
                "series_blocks": len(self._series),
                "series_bytes": self._series_bytes,
            }
