"""ShardMigrator: the node-side shard lifecycle state machine.

Reference parity: the dbnode pieces between "a placement you can edit"
and "a cluster you can grow/shrink/roll-restart under load" —
`src/dbnode/storage/bootstrap/bootstrapper/peers` (stream INITIALIZING
shards from the donor peer), `topology/dynamic.go` consumption in
`storage/database.go` (assign/close shards on every topology map), and
the coordinator's MarkShardsAvailable cutover.  One object owns the
whole lifecycle for one node:

* **Ownership install** — every observed placement version installs the
  node's owned shard set into the ``Database``
  (INITIALIZING ∪ AVAILABLE ∪ LEAVING; writes/reads outside it raise
  the typed ``ShardNotOwnedError``).  No placement yet = own all (the
  single-node bring-up default).
* **Streaming** — INITIALIZING shards pull missing flushed blocks from
  the donor named in the placement over the existing block replication
  RPC surface (``list_block_filesets``/``block_metadata``/
  ``read_block``/``write_block``), budgeted per tick so a big backfill
  never starves flush/snapshot/cleanup.  Every streamed segment is
  digest-verified against the donor's block metadata before it lands —
  a corrupt wire copy is rejected, counted, and retried next tick.
  When the donor is unreachable (replace of a dead node), streaming
  falls back to any AVAILABLE replica of the shard.
* **Cutover** — a fully streamed shard CAS-flips
  INITIALIZING→AVAILABLE through ``PlacementService.update`` (bounded
  retry on version conflict); the donor's LEAVING entry disappears in
  the same placement version.
* **Drop** — shards that leave this node's placement entry (cutover
  completed elsewhere, or the instance was removed) lose ownership
  immediately (clients re-route on their next placement observation)
  and their filesets/buffers are deleted after a grace period of ticks,
  so in-flight peer streams and repairs drain first.  Shards never
  observed as owned are NOT dropped — a mistyped instance id must not
  wipe a disk.

Faultpoints: ``topology.stream`` arms at the block-fetch boundary
(drop = the fetch is lost this tick, delay = slow donor, error = typed
transport failure, corrupt = byte-flip caught by digest verify).

Counters (``topology_*`` on /metrics): ``placement_changes``,
``blocks_streamed``, ``series_streamed``, ``stream_errors``,
``verify_failures``, ``cutovers``, ``cutover_failures``,
``shards_dropped``; gauges ``placement_version``,
``shards_initializing``/``_available``/``_leaving``, ``pending_drops``.
Progress is served in /health via :meth:`status`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from m3_tpu.cluster.placement import PlacementService, ShardState, mark_available
from m3_tpu.cluster.topology import TopologyView, TopologyWatcher
from m3_tpu.instrument import logger
from m3_tpu.persist.digest import digest as checksum
from m3_tpu.x import fault

_LOG = logger("storage.migration")


class ShardMigrator:
    """Drives one node's shard lifecycle off the mediator tick thread.

    ``resolve(instance)`` returns a Database-shaped handle for a
    placement instance; the default dials ``instance.endpoint`` with
    ``server.rpc.RemoteDatabase`` (in-process tests pass a dict-backed
    resolver instead).  Handles are cached and closed with the
    migrator."""

    def __init__(self, db, watcher: TopologyWatcher,
                 placements: PlacementService, resolve=None,
                 stream_blocks_per_tick: int = 4, grace_ticks: int = 2,
                 instrument=None):
        self.db = db
        self.watcher = watcher
        self.placements = placements
        self._resolve = resolve if resolve is not None else self._dial
        self.stream_blocks_per_tick = int(stream_blocks_per_tick)
        self.grace_ticks = max(0, int(grace_ticks))
        self._scope = (
            instrument.scope("topology") if instrument is not None else None
        )
        # per-shard stream-pass latency (hot during a node replace):
        # windowed histogram, interned once
        self._hist_stream = (self._scope.histogram("stream_seconds")
                             if self._scope is not None else None)
        self._mu = threading.Lock()
        # Serializes whole tick() passes: the admin's on-demand
        # POST /topology/migrate racing the mediator tick would stream
        # duplicate volumes and double-advance drop grace countdowns
        # (same mediator-vs-admin race the scrubber guards with its
        # sweep lock).
        self._tick_mu = threading.Lock()
        self._applied_version = -1
        self._prev_owned: Optional[frozenset] = None  # last installed set
        self._had_placement = False
        self._pending_drops: Dict[int, int] = {}      # shard -> ticks left
        self._progress: Dict[int, dict] = {}          # shard -> copied/total
        self._handles: Dict[tuple, object] = {}
        self._mismatch_warned: set = set()
        watcher.on_change(self._on_view)

    # -- instrumentation ---------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        if self._scope is not None and n:
            self._scope.counter(name).inc(n)

    def _gauge(self, name: str, v: float) -> None:
        if self._scope is not None:
            self._scope.gauge(name).update(v)

    # -- handle resolution -------------------------------------------------

    @staticmethod
    def _dial(instance):
        if not instance.endpoint:
            raise ConnectionError(
                f"instance {instance.id} has no endpoint in the placement"
            )
        from m3_tpu.server.rpc import RemoteDatabase

        host, _, port = instance.endpoint.rpartition(":")
        return RemoteDatabase((host, int(port)))

    def _handle_for(self, instance):
        key = (instance.id, instance.endpoint)
        with self._mu:
            h = self._handles.get(key)
        if h is not None:
            return h
        h = self._resolve(instance)
        with self._mu:
            self._handles.setdefault(key, h)
            return self._handles[key]

    # -- placement observation --------------------------------------------

    def _matching_namespaces(self, placement) -> List[str]:
        """Namespaces the placement's shard space governs.  A namespace
        sharded differently from the placement keeps own-all (the
        placement cannot describe it) — warned once, never silently
        half-applied."""
        # Namespace map snapshot under the engine lock: ensure_namespace
        # inserts concurrently on the ingest path (scrub._volume_list
        # takes the same precaution).
        with self.db._mu:
            items = list(self.db.namespaces.items())
        out = []
        for name, ns in items:
            if ns.opts.num_shards == placement.num_shards:
                out.append(name)
            elif name not in self._mismatch_warned:
                self._mismatch_warned.add(name)
                _LOG.warning(
                    "namespace %s has %d shards but the placement has %d; "
                    "ownership not applied to it", name, ns.opts.num_shards,
                    placement.num_shards,
                )
        return out

    def _on_view(self, view: TopologyView) -> None:
        """Watch listener: install ownership and schedule drops.  Cheap
        and non-blocking (runs inside the KV notification path); the
        heavy streaming/drop work happens on tick()."""
        if view.placement is None:
            return
        with self._mu:
            if view.version <= self._applied_version:
                return
            self._applied_version = view.version
            owned = view.owned_shards()
            prev = self._prev_owned
            had = self._had_placement
            self._prev_owned = owned
            self._had_placement = True
            if had and prev is not None and owned is not None:
                # Shards that left my entry between two observed
                # versions: revoke now, delete after grace.  First-ever
                # observation never drops (a node with a wrong
                # instance_id must not wipe its disk).
                for shard in prev - owned:
                    self._pending_drops.setdefault(shard, self.grace_ticks)
                for shard in owned:
                    # re-acquired mid-grace (operator reverted): keep data
                    self._pending_drops.pop(shard, None)
            self._progress = {
                s: self._progress.get(s, {"copied": 0, "total": None})
                for s in view.shards_in_state(ShardState.INITIALIZING)
            }
            # Ownership installs INSIDE the version-gated section: with
            # it outside, a tick-thread apply of v1 racing a
            # watch-thread apply of v2 could finish LAST and leave v1's
            # stale shard set installed forever (the gate would then
            # drop every re-delivery of v2).  Lock order here is
            # migrator._mu -> db._mu; nothing takes them in reverse.
            for name in self._matching_namespaces(view.placement):
                self.db.set_shard_ownership(name, owned)
            # Namespaces created AFTER this version (dynamic namespace
            # add, downsampler ensure_namespace) inherit the same set
            # at construction — they must never start own-all on a
            # placement-scoped node.
            self.db.set_ownership_template(view.placement.num_shards, owned)
        self._count("placement_changes")
        self._gauge("placement_version", view.version)
        for st, g in ((ShardState.INITIALIZING, "shards_initializing"),
                      (ShardState.AVAILABLE, "shards_available"),
                      (ShardState.LEAVING, "shards_leaving")):
            self._gauge(g, len(view.shards_in_state(st)))

    # -- streaming ---------------------------------------------------------

    def _stream_sources(self, view: TopologyView, shard: int) -> list:
        """Donor first, then any AVAILABLE replica (the dead-donor
        fallback).  Returns (instance, handle) pairs; unreachable
        resolves are skipped here, unreachable calls are skipped by the
        caller."""
        sources = []
        donor_id = view.donor_for(shard)
        insts = []
        if donor_id and view.placement is not None:
            donor = view.placement.instances.get(donor_id)
            if donor is not None:
                insts.append(donor)
        insts.extend(i for i in view.available_replicas(shard)
                     if not insts or i.id != insts[0].id)
        for inst in insts:
            try:
                sources.append((inst, self._handle_for(inst)))
            except Exception:  # noqa: BLE001 — unresolvable peer ≙ down
                self._count("stream_errors")
        return sources

    def _stream_shard(self, view: TopologyView, shard: int,
                      budget: int, stats: dict) -> bool:
        """Pull missing flushed blocks for one INITIALIZING shard.
        Returns True when the shard is KNOWN fully copied (some source
        answered and nothing is missing) — the cutover precondition."""
        import time as _time

        t0 = _time.perf_counter()
        try:
            return self._stream_shard_inner(view, shard, budget, stats)
        finally:
            if self._hist_stream is not None:
                self._hist_stream.record(_time.perf_counter() - t0)

    def _stream_shard_inner(self, view: TopologyView, shard: int,
                            budget: int, stats: dict) -> bool:
        complete = True
        answered = False
        copied = total = 0
        for name in self._matching_namespaces(view.placement):
            local = dict(self.db.list_block_filesets(name, shard))
            src_blocks = None
            for inst, handle in self._stream_sources(view, shard):
                try:
                    src_blocks = handle.list_block_filesets(name, shard)
                except Exception:  # noqa: BLE001 — source down: next one
                    self._count("stream_errors")
                    stats["stream_errors"] += 1
                    continue
                src = (inst, handle)
                break
            if src_blocks is None:
                # Nobody reachable knows this shard's blocks: cutting
                # over blind could present data loss as AVAILABLE.
                complete = False
                continue
            answered = True
            total += len(src_blocks)
            copied += sum(1 for bs, _ in src_blocks if bs in local)
            for bs, _vol in src_blocks:
                if bs in local:
                    continue
                if budget - stats["blocks_streamed"] <= 0:
                    complete = False
                    break
                ok = self._copy_block(src[1], name, shard, bs, stats)
                if ok:
                    copied += 1
                else:
                    complete = False
            else:
                continue
            complete = False  # budget broke the loop
        with self._mu:
            if shard in self._progress:
                self._progress[shard] = {"copied": copied, "total": total}
        return complete and answered

    def _copy_block(self, handle, name: str, shard: int, bs: int,
                    stats: dict) -> bool:
        """One block over the wire, digest-verified, behind the
        ``topology.stream`` faultpoint."""
        try:
            if fault.fire("topology.stream") == "drop":
                raise fault.FaultInjected("topology.stream: fetch dropped")
            meta = handle.block_metadata(name, shard, bs) or {}
            series = handle.read_block(name, shard, bs)
        except Exception:  # noqa: BLE001 — donor died mid-stream: the
            # shard stays INITIALIZING and next tick retries/falls back
            self._count("stream_errors")
            stats["stream_errors"] += 1
            return False
        verified = []
        for sid, seg in series:
            _, seg = fault.mangle("topology.stream", seg)
            want = meta.get(sid)
            if want is not None and checksum(seg) != want:
                # Wire/source corruption: refuse the whole block (a
                # half-verified block would cut over with holes).
                self._count("verify_failures")
                stats["verify_failures"] += 1
                return False
            verified.append((sid, seg))
        try:
            self.db.write_block(name, shard, bs, verified)
        except Exception:  # noqa: BLE001 — e.g. ownership revoked by a
            # racing placement move; next tick re-evaluates
            self._count("stream_errors")
            stats["stream_errors"] += 1
            return False
        self._count("blocks_streamed")
        self._count("series_streamed", len(verified))
        stats["blocks_streamed"] += 1
        stats["series_streamed"] += len(verified)
        return True

    # -- cutover -----------------------------------------------------------

    def _cutover(self, shard: int, stats: dict) -> None:
        iid = self.watcher.instance_id

        def mutate(p):
            if p is None:
                raise ValueError("placement vanished before cutover")
            return mark_available(p, iid, shard)

        try:
            self.placements.update(mutate)
        except (KeyError, ValueError) as e:
            # Not initializing anymore (operator raced us) or CAS
            # retries exhausted: the next observed placement version
            # tells us which; nothing to do now.
            self._count("cutover_failures")
            stats["cutover_failures"] += 1
            _LOG.warning("cutover of shard %d failed: %s", shard, e)
            return
        self._count("cutovers")
        stats["cutovers"] += 1
        _LOG.info("shard %d cut over to AVAILABLE on %s", shard, iid)

    # -- drop --------------------------------------------------------------

    def _process_drops(self, stats: dict) -> None:
        with self._mu:
            due = []
            for shard in sorted(self._pending_drops):
                self._pending_drops[shard] -= 1
                if self._pending_drops[shard] < 0:
                    due.append(shard)
            for shard in due:
                del self._pending_drops[shard]
        view = self.watcher.view()
        if view.placement is None:
            return
        for shard in due:
            for name in self._matching_namespaces(view.placement):
                try:
                    stats["fileset_volumes_dropped"] += self.db.drop_shard(
                        name, shard)
                except Exception:  # noqa: BLE001 — a failed delete
                    # retries via cleanup/retention, never kills the tick
                    _LOG.exception("drop of shard %d ns=%s failed",
                                   shard, name)
            self._count("shards_dropped")
            stats["shards_dropped"] += 1

    # -- the tick ----------------------------------------------------------

    def tick(self, wait: bool = True) -> dict:
        """One lifecycle pass (mediator-driven): stream INITIALIZING
        shards under the per-tick block budget, cut fully streamed ones
        over, then advance grace countdowns and drop expired shards.

        Whole passes are serialized; ``wait=False`` (nothing uses it
        yet, but it mirrors the scrubber's mediator shape) returns
        ``{"skipped": True}`` instead of queueing behind a pass already
        in flight."""
        if not self._tick_mu.acquire(blocking=wait):
            return {"skipped": True}
        try:
            stats = {"blocks_streamed": 0, "series_streamed": 0,
                     "stream_errors": 0, "verify_failures": 0, "cutovers": 0,
                     "cutover_failures": 0, "shards_dropped": 0,
                     "fileset_volumes_dropped": 0}
            view = self.watcher.view()
            if view.placement is not None:
                self._on_view(view)  # idempotent: covers a missed fire
                budget = (self.stream_blocks_per_tick
                          if self.stream_blocks_per_tick > 0 else 1 << 30)
                for shard in view.shards_in_state(ShardState.INITIALIZING):
                    if self._stream_shard(view, shard, budget, stats):
                        self._cutover(shard, stats)
            self._process_drops(stats)
            self._gauge("pending_drops", len(self._pending_drops))
            return stats
        finally:
            self._tick_mu.release()

    # -- introspection / drain --------------------------------------------

    def status(self) -> dict:
        """Migration progress for /health."""
        view = self.watcher.view()
        with self._mu:
            progress = {str(s): dict(p) for s, p in self._progress.items()}
            pending = sorted(self._pending_drops)
        out = {
            "instance": self.watcher.instance_id,
            "placement_version": view.version,
            "in_placement": view.in_placement,
            "shards": {
                "initializing": view.shards_in_state(ShardState.INITIALIZING),
                "available": view.shards_in_state(ShardState.AVAILABLE),
                "leaving": view.shards_in_state(ShardState.LEAVING),
            },
            "streaming": progress,
            "pending_drops": pending,
        }
        return out

    def wait_handed_off(self, timeout_s: float = 30.0,
                        poll_s: float = 0.2) -> bool:
        """Drain aid: block until none of this node's shards is LEAVING
        (every handoff cut over) or the timeout passes.  Driven purely
        by the placement watch — the newcomers do the actual work."""
        deadline = time.monotonic() + timeout_s
        while True:
            view = self.watcher.view()
            if (view.placement is None
                    or not view.shards_in_state(ShardState.LEAVING)):
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    def close(self) -> None:
        with self._mu:
            handles, self._handles = self._handles, {}
        for h in handles.values():
            if hasattr(h, "close"):
                try:
                    h.close()
                except Exception:  # noqa: BLE001
                    pass
