"""Per-query resource limits with lookback windows.

Equivalent of `src/dbnode/storage/limits`: global windowed limits on
docs matched and series/bytes read — each limit accumulates within a
lookback window and every query checks-and-adds before doing work;
exceeding returns a typed error the API maps to HTTP 429/400 rather
than letting one heavy query exhaust the node.
"""

from __future__ import annotations

import contextlib
import re
import threading
import time
from dataclasses import dataclass

_LIMIT_MSG_RE = re.compile(
    r"query limit exceeded: ([\w-]+) \((\d+) > (\d+) within window\)"
)


class QueryLimitExceeded(RuntimeError):
    def __init__(self, name: str, value: int, limit: int):
        super().__init__(
            f"query limit exceeded: {name} ({value} > {limit} within window)"
        )
        self.name = name

    @classmethod
    def from_message(cls, msg: str) -> "QueryLimitExceeded":
        """Rebuild from the stable message form — the wire layers
        (query/remote, server/rpc) ship errors as ``TypeName: message``
        strings and must re-raise the REAL class client-side so a
        remote limit trip still maps to HTTP 429, not 500."""
        m = _LIMIT_MSG_RE.search(msg)
        if m:
            return cls(m.group(1), int(m.group(2)), int(m.group(3)))
        return cls("remote", 0, 0)


class _WindowedLimit:
    """check-and-add within a rolling lookback window
    (reference limits/query_limits.go lookbackLimit)."""

    def __init__(self, name: str, limit: int, lookback_s: float,
                 now=time.monotonic):
        self.name = name
        self.limit = limit
        self.lookback_s = lookback_s
        self._now = now
        self._value = 0
        self._window_start = now()
        self._lock = threading.Lock()

    def inc(self, n: int) -> None:
        if self.limit <= 0:  # disabled
            return
        with self._lock:
            t = self._now()
            if t - self._window_start >= self.lookback_s:
                self._value = 0
                self._window_start = t
            self._value += n
            if self._value > self.limit:
                raise QueryLimitExceeded(self.name, self._value, self.limit)

    @property
    def current(self) -> int:
        return self._value


@dataclass(frozen=True)
class LimitsOptions:
    """0 disables a limit (the reference's default)."""

    max_docs_matched: int = 0
    max_series_read: int = 0
    max_bytes_read: int = 0
    lookback_s: float = 5.0


class QueryLimits:
    def __init__(self, opts: LimitsOptions | None = None, now=time.monotonic,
                 instrument=None):
        self.opts = opts or LimitsOptions()
        self.docs = _WindowedLimit(
            "docs-matched", self.opts.max_docs_matched, self.opts.lookback_s, now
        )
        self.series = _WindowedLimit(
            "series-read", self.opts.max_series_read, self.opts.lookback_s, now
        )
        self.bytes = _WindowedLimit(
            "bytes-read", self.opts.max_bytes_read, self.opts.lookback_s, now
        )
        self._scope = (
            instrument.scope("query_limits") if instrument is not None else None
        )

    def inc_docs(self, n: int) -> None:
        self._inc(self.docs, n)

    def inc_series(self, n: int) -> None:
        self._inc(self.series, n)

    def inc_bytes(self, n: int) -> None:
        self._inc(self.bytes, n)

    def _inc(self, lim: _WindowedLimit, n: int) -> None:
        try:
            lim.inc(n)
        except QueryLimitExceeded:
            if self._scope is not None:
                self._scope.counter(f"exceeded_{lim.name}").inc()
            raise


NO_LIMITS = QueryLimits(LimitsOptions())


class NewSeriesLimiter:
    """Token bucket refilled at ``per_sec`` (0 = unlimited) gating
    series/entry CREATION — the churn control of the reference's
    entry.go rate limits and the dbnode write-new-series runtime keys
    (kvconfig/keys.go).  Shared by every shard's allocator;
    runtime-tunable via set_rate (the kvconfig watch calls it live).
    The bucket capacity is one second's budget, so a quiet period
    cannot bank an unbounded burst.  Rejections surface as typed
    counts (WriteResult.rejected / new_series_rejected counters), not
    exceptions — partial batch acceptance is the contract."""

    def __init__(self, per_sec: float = 0, now=time.monotonic):
        self._now = now
        self._lock = threading.Lock()
        self._tokens = float(per_sec)
        self._last = now()
        self.per_sec = float(per_sec)
        self.rejected_total = 0
        # Bypass depth is THREAD-LOCAL: a bootstrap/follower-ingest
        # bypass window on one thread must not exempt concurrent
        # foreground writes on other threads from the limit.
        self._bypass = threading.local()

    def set_rate(self, per_sec: float) -> None:
        with self._lock:
            self.per_sec = float(per_sec)
            self._tokens = min(self._tokens, self.per_sec)

    @contextlib.contextmanager
    def bypass(self):
        """Temporarily disable the limit: bootstrap/WAL replay must
        re-admit every previously-accepted series (the reference limits
        only foreground writes), and multi-policy fan-out charges the
        budget once, with follower lists riding the first list's
        decision under this bypass.  Scoped to the CALLING THREAD only
        (nestable depth counter): other threads' foreground writes keep
        paying the limit while a replay runs."""
        depth = getattr(self._bypass, "depth", 0)
        self._bypass.depth = depth + 1
        try:
            yield self
        finally:
            self._bypass.depth = depth

    def acquire_up_to(self, n: int) -> int:
        """Take up to ``n`` tokens; returns how many were granted
        (n when unlimited or bypassed).  Callers reject the
        shortfall."""
        if n <= 0:
            return 0
        with self._lock:
            if self.per_sec <= 0 or getattr(self._bypass, "depth", 0):
                return n
            t = self._now()
            self._tokens = min(
                self.per_sec, self._tokens + (t - self._last) * self.per_sec)
            self._last = t
            granted = int(min(n, self._tokens))
            self._tokens -= granted
            self.rejected_total += n - granted
            return granted
