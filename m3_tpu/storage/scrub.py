"""Background integrity scrubber: detect → quarantine → repair from peers.

The wire layer (PR 1) already treats every network failure as expected
and recoverable; this module gives the *disk* edge the same contract.
Reference parity: the repair subsystem's checksum comparison
(`src/dbnode/storage/repair.go`) assumes somebody notices local rot —
real deployments pair it with periodic verification (the
`verify_data_files` tool run under cron).  Here that loop is in-process:

* **Budgeted sweep** — each mediator tick verifies at most
  ``budget_volumes`` fileset volumes (checkpoint → digest file →
  per-file adler32 → per-segment checksums, all via the existing
  ``DataFileSetReader`` open + ``read_all`` walk), resuming from a
  cursor so a large disk is scrubbed incrementally, a few volumes per
  tick, forever.
* **Quarantine** — a failed verify routes through
  ``Shard.quarantine_volume`` (atomic move + reason file + cache
  invalidation + flushed-block bookkeeping).
* **Peer-assisted recovery** — after the sweep, every quarantined
  (namespace, shard, block) with NO intact local volume is re-fetched
  through the existing anti-entropy surface
  (``repair.repair_shard_block`` over the replica handles): the local
  handle presents as a reachable-but-blockless replica, so the merged
  block is written straight back as a fresh fileset volume — the same
  convergence path a wiped node uses.

Counters (``scrub.*`` on a node's /metrics): ``volumes_checked``,
``corruptions_found``, ``repair_attempts``, ``repairs_completed``,
``sweeps``.

Also runnable on demand: ``POST /api/v1/database/scrub`` (admin API)
runs an unbudgeted sweep in-process, and ``python -m m3_tpu.tools.cli
scrub <root>`` (:func:`scrub_root`) sweeps a data root offline without
a running Database.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List

from m3_tpu.instrument import logger
from m3_tpu.persist import quarantine as quar
from m3_tpu.persist.corruption import CorruptionError
from m3_tpu.persist.fs import DataFileSetReader, list_fileset_volumes

_LOG = logger("storage.scrub")


def verify_volume(root, namespace: str, shard: int, block_start: int,
                  volume: int) -> int:
    """Full integrity walk of one fileset volume; raises
    :class:`CorruptionError` on the first failed check, returns the
    series count otherwise.  Open verifies checkpoint/digest/per-file
    adler32; draining ``read_all`` verifies every segment checksum."""
    r = DataFileSetReader(root, namespace, shard, block_start, volume)
    try:
        return sum(1 for _ in r.read_all())
    finally:
        r.close()


def _verify_outcome(root, namespace: str, shard: int, block_start: int,
                    volume: int):
    """Shared verify-and-classify step of the online and offline
    sweeps: ``("ok", series)``, ``("gone", None)`` (raced cleanup), or
    ``("corrupt", err)``.  ``corrupt`` covers the typed hierarchy AND
    untyped reader failures — bare decode errors and I/O-level rot
    (EIO on a failing sector) alike must flag the one volume, never
    kill the rest of the sweep."""
    try:
        return "ok", verify_volume(root, namespace, shard, block_start, volume)
    except FileNotFoundError:
        return "gone", None
    except (ValueError, EOFError, struct.error, OSError) as e:
        return "corrupt", e


class Scrubber:
    """Owns the sweep cursor and the repair worklist for one Database.

    ``peers`` are replica handles (local ``Database`` objects or
    ``server.rpc.RemoteDatabase``) used for post-quarantine recovery;
    with no peers the scrubber still detects and quarantines (a later
    WAL-covered flush or an operator restore fills the hole).
    """

    #: per-(ns, shard, block) ceiling on peer-repair attempts; a hole
    #: nobody can fill (no replica ever flushed it, or it aged out of
    #: retention everywhere) must not generate RPC traffic forever.
    #: In-memory, so a restart grants a fresh allowance — bounded both
    #: ways.
    REPAIR_ATTEMPT_CAP = 5

    def __init__(self, db, peers: List[object] | None = None,
                 budget_volumes: int = 4, instrument=None):
        self.db = db
        self.peers = list(peers or [])
        self.budget_volumes = int(budget_volumes)
        self._cursor = None  # last (ns, shard, block, vol) verified
        self._lock = threading.Lock()
        self._repair_lock = threading.Lock()  # one repair pass at a time
        self._hole_attempts: Dict[tuple, int] = {}
        self._scope = (
            instrument.scope("scrub") if instrument is not None else None
        )

    def _count(self, name: str, n: int = 1) -> None:
        if self._scope is not None and n:
            self._scope.counter(name).inc(n)

    def _volume_list(self) -> List[tuple]:
        # Namespace enumeration under the engine lock: ensure_namespace
        # inserts concurrently on the ingest path, and iterating a
        # resizing dict raises.  The (slow) per-shard globbing happens
        # OUTSIDE the lock.
        with self.db._mu:
            shards = [
                (name, shard.shard_id)
                for name in sorted(self.db.namespaces)
                for shard in self.db.namespaces[name].shards
            ]
        out = []
        for name, shard_id in shards:
            for bs, vol in list_fileset_volumes(
                    self.db.opts.root, name, shard_id):
                out.append((name, shard_id, bs, vol))
        return out

    def run_once(self, budget: int | None = None, repair: bool = True,
                 wait: bool = True) -> dict:
        """One scrub pass: verify up to ``budget`` volumes (None = the
        configured per-tick budget; 0 = the whole disk, the on-demand
        shape), quarantine what fails, then attempt peer repair of every
        open hole.  Returns the pass's stats.

        ``wait=False`` (the mediator's shape) returns ``{"skipped":
        True}`` instead of blocking when another sweep — e.g. an
        admin-triggered whole-disk scrub — already holds the sweep
        lock: a long on-demand scrub must never stall the maintenance
        tick behind it."""
        budget = self.budget_volumes if budget is None else int(budget)
        stats = {"checked": 0, "corrupt": 0, "repair_attempts": 0,
                 "repaired": 0, "wrapped": False}
        if not self._lock.acquire(blocking=wait):
            return {"skipped": True}
        try:
            vols = self._volume_list()
            if vols:
                # Resume strictly after the cursor, wrapping at the end
                # — every volume is eventually visited no matter how
                # small the per-tick budget.
                if self._cursor is not None:
                    after = [v for v in vols if v > self._cursor]
                    stats["wrapped"] = not after
                    vols = after + [v for v in vols if v <= self._cursor]
                take = vols if budget <= 0 else vols[:budget]
                for name, shard_id, bs, vol in take:
                    stats["checked"] += 1
                    self._cursor = (name, shard_id, bs, vol)
                    outcome, detail = _verify_outcome(
                        self.db.opts.root, name, shard_id, bs, vol)
                    if outcome == "corrupt":
                        stats["corrupt"] += 1
                        self.db.quarantine_fileset_volume(
                            name, shard_id, bs, vol, detail
                        )
        finally:
            self._lock.release()
        # Repair OUTSIDE the sweep lock — and, on the mediator's
        # non-blocking (wait=False) path, on a BACKGROUND thread: peer
        # fetches can block up to the RPC timeout on an unreachable
        # replica, and the maintenance tick must never stall behind
        # them (flush/snapshot/cleanup would back up for minutes).
        # On-demand callers (admin endpoint) keep the synchronous shape
        # so the HTTP response carries the repair outcome.
        if repair:
            if wait:
                # Serialize with any in-flight background pass: two
                # passes walking the same holes would double-rewrite
                # blocks cluster-wide and race _hole_attempts.
                with self._repair_lock:
                    self._repair_holes(stats)
            else:
                stats["repair_async"] = self._spawn_repair()
        self._count("volumes_checked", stats["checked"])
        self._count("corruptions_found", stats["corrupt"])
        self._count("sweeps")
        return stats

    def _spawn_repair(self) -> bool:
        """Start one background repair pass; False when no peers exist
        or a previous pass is still running (it will pick up any new
        holes next tick)."""
        if not self.peers:
            return False
        if not self._repair_lock.acquire(blocking=False):
            return False
        def run():
            try:
                self._repair_holes({"repair_attempts": 0, "repaired": 0})
            except Exception:  # noqa: BLE001 — background loop must survive
                _LOG.exception("background repair pass failed")
            finally:
                self._repair_lock.release()
        threading.Thread(target=run, daemon=True,
                         name="m3-scrub-repair").start()
        return True

    def _repair_holes(self, stats: dict) -> None:
        """Re-fetch every quarantined (ns, shard, block) that has no
        intact local volume from the replica set.  Stateless worklist:
        the quarantine inventory names the holes, the presence of a
        local fileset marks one healed — no extra bookkeeping files.
        Per-hole attempts are capped (REPAIR_ATTEMPT_CAP) so a hole no
        replica can fill stops generating RPC traffic."""
        if not self.peers:
            return
        holes = set()
        for entry in quar.list_quarantined(self.db.opts.root):
            if entry.get("kind") != "fileset" or entry.get("label") != "data":
                continue  # snapshot filesets re-converge via the WAL/peers
            name = entry.get("namespace")
            if name not in self.db.namespaces:
                continue
            holes.add((name, int(entry["shard"]), int(entry["block_start"])))
        from m3_tpu.storage.repair import repair_shard_block

        for name, shard_id, bs in sorted(holes):
            if bs in dict(self.db.list_block_filesets(name, shard_id)):
                self._hole_attempts.pop((name, shard_id, bs), None)
                continue  # healed (repair, re-flush, or intact lower volume)
            attempts = self._hole_attempts.get((name, shard_id, bs), 0)
            if attempts >= self.REPAIR_ATTEMPT_CAP:
                continue  # exhausted: operator restore / restart re-arms
            self._hole_attempts[(name, shard_id, bs)] = attempts + 1
            stats["repair_attempts"] += 1
            try:
                repair_shard_block([self.db] + self.peers, name, shard_id, bs)
            except Exception:  # noqa: BLE001 — scrub loop must survive
                _LOG.exception(
                    "peer repair failed ns=%s shard=%d block=%d",
                    name, shard_id, bs,
                )
                continue
            if bs in dict(self.db.list_block_filesets(name, shard_id)):
                stats["repaired"] += 1
                self._hole_attempts.pop((name, shard_id, bs), None)
                _LOG.info("peer repair healed ns=%s shard=%d block=%d",
                          name, shard_id, bs)
        self._count("repair_attempts", stats["repair_attempts"])
        self._count("repairs_completed", stats["repaired"])


def scrub_root(root, quarantine: bool = True) -> List[dict]:
    """Offline sweep of a data root (no Database required — the ops/CLI
    shape).  Verifies every checkpointed volume; corrupt ones are
    quarantined unless ``quarantine=False`` (report-only).  Returns one
    result dict per volume."""
    from pathlib import Path

    results = []
    d = Path(root) / "data"
    namespaces = sorted(p.name for p in d.iterdir() if p.is_dir()) if d.exists() else []
    for ns in namespaces:
        shards = sorted(
            int(p.name) for p in (d / ns).iterdir() if p.name.isdigit()
        )
        for shard in shards:
            for bs, vol in list_fileset_volumes(root, ns, shard):
                rec: Dict = {"namespace": ns, "shard": shard,
                             "block_start": bs, "volume": vol, "ok": True}
                outcome, detail = _verify_outcome(root, ns, shard, bs, vol)
                if outcome == "gone":
                    continue
                if outcome == "ok":
                    rec["series"] = detail
                else:
                    rec.update(ok=False, error=str(detail),
                               check=getattr(detail, "check", None))
                    if quarantine:
                        rec["quarantined"] = str(
                            quar.quarantine_fileset(root, ns, shard, bs, vol,
                                                    detail)
                        )
                results.append(rec)
    return results
