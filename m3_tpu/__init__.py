"""m3-tpu: a TPU-native time-series metrics platform.

A from-scratch redesign of the capabilities of M3 (distributed TSDB,
streaming aggregator, PromQL-compatible query engine) around JAX/XLA:
ingest hot paths (M3TSZ block compression, rollup/quantile pipelines,
temporal query functions) run as batched array programs over
(series x time) tensors on TPU, with a thin host control plane for
sharding, durability and cluster coordination.

This framework requires 64-bit JAX types throughout: timestamps are
int64 UnixNanos and the M3TSZ wire format is defined over float64 bit
patterns.  Enabling x64 here — at the framework root, as a documented
contract — is deliberate; every m3_tpu entry point depends on it.
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
